"""In-memory fake cluster.

The reference generated a fake clientset for tests but never used it
(reference pkg/client/clientset/versioned/fake/fake_trainingjob.go:29-36;
SURVEY §4).  This build makes the fake a first-class backend: an in-memory
implementation of :class:`Cluster` with nodes, capacity accounting, a tiny
pod scheduler, and hooks the elastic runtime uses to attach real local
worker processes.  All controller/scheduler tests run against it; it also
powers bench.py's multi-job elastic scenario.

Semantics mirrored from the reference:

* ``inquiry_resource`` accumulates allocatable totals over nodes and
  requests/limits over non-terminal pods, then subtracts per-node usage
  (reference cluster.go:176-242).
* trainer groups behave like a batch Job: a ``parallelism`` dial; the fake
  "kubelet" (:meth:`reconcile`) creates/deletes pods to match it, placing
  them on nodes with headroom else leaving them Pending
  (role of the k8s Job controller + kube-scheduler).
* pod counting is DeletionTimestamp-aware (reference cluster.go:117-136).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from edl_tpu.api.types import TrainingJob
from edl_tpu.cluster.base import Cluster, ConflictError, PodCounts, PodPhase
from edl_tpu.cluster.resource import ClusterResource, NodeResources


@dataclass
class FakeNode:
    name: str
    cpu_milli: int = 0
    memory_mega: int = 0
    tpu_chips: int = 0
    #: ICI domain: meshes must stay within one domain to ride ICI.
    ici_domain: str = ""


@dataclass
class FakePod:
    name: str
    job_uid: str  # namespace/name of the owning job ("" for system pods)
    role: str  # trainer | master | pserver
    seq: int = 0  # creation order, for newest-first surplus deletion
    cpu_request_milli: int = 0
    cpu_limit_milli: int = 0
    memory_request_mega: int = 0
    memory_limit_mega: int = 0
    tpu_limit: int = 0
    phase: PodPhase = PodPhase.PENDING
    node: Optional[str] = None
    deletion_timestamp: bool = False


@dataclass
class _TrainerGroup:
    """Role of the trainer batchv1.Job (parallelism dial + pods)."""

    job_uid: str
    parallelism: int
    resource_version: int = 0


class FakeCluster(Cluster):
    """Thread-safe in-memory cluster."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._nodes: dict[str, FakeNode] = {}
        self._pods: dict[str, FakePod] = {}
        # both keyed by job uid (namespace/name)
        self._groups: dict[str, _TrainerGroup] = {}
        self._job_specs: dict[str, TrainingJob] = {}
        self._aux_pods_seq = itertools.count()
        #: Called with (pod, "start"|"stop") when reconcile changes the world;
        #: the elastic runtime uses this to launch/kill real worker processes.
        self.pod_event_hook: Optional[Callable[[FakePod, str], None]] = None
        #: True → reconcile also keeps one live coordinator pod per
        #: fault-tolerant job (the master RS analogue); enabled by the
        #: process-backed kubelet, off for pure scheduler bookkeeping.
        self.materialize_aux_pods: bool = False
        #: Injected failure for conflict-retry tests.
        self.fail_next_updates: int = 0

    # -- topology setup ----------------------------------------------------

    def add_node(
        self,
        name: str,
        cpu_milli: int = 0,
        memory_mega: int = 0,
        tpu_chips: int = 0,
        ici_domain: str = "",
    ) -> FakeNode:
        with self._lock:
            node = FakeNode(name, cpu_milli, memory_mega, tpu_chips, ici_domain or name)
            self._nodes[name] = node
            return node

    def add_system_pod(self, name: str, node: str, cpu_request_milli: int = 0,
                       memory_request_mega: int = 0) -> None:
        """Background load (k8s system pods / the demo's nginx competitor,
        reference example/nginx.yaml)."""
        with self._lock:
            self._pods[name] = FakePod(
                name=name, job_uid="", role="system", seq=next(self._aux_pods_seq),
                cpu_request_milli=cpu_request_milli,
                cpu_limit_milli=cpu_request_milli,
                memory_request_mega=memory_request_mega,
                memory_limit_mega=memory_request_mega,
                phase=PodPhase.RUNNING, node=node,
            )

    def remove_system_pod(self, name: str) -> None:
        with self._lock:
            self._pods.pop(name, None)

    # -- Cluster interface -------------------------------------------------

    def inquiry_resource(self) -> ClusterResource:
        with self._lock:
            r = ClusterResource(node_count=len(self._nodes))
            nodes = NodeResources()
            for n in self._nodes.values():
                r.cpu_total_milli += n.cpu_milli
                r.memory_total_mega += n.memory_mega
                r.tpu_total += n.tpu_chips
                nodes.nodes_cpu_idle_milli[n.name] = n.cpu_milli
                nodes.nodes_memory_free_mega[n.name] = n.memory_mega
                nodes.nodes_tpu_free[n.name] = n.tpu_chips
                nodes.nodes_ici_domain[n.name] = n.ici_domain
            for p in self._pods.values():
                if p.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                    continue  # terminal pods hold nothing (cluster.go:202-210)
                r.cpu_request_milli += p.cpu_request_milli
                r.cpu_limit_milli += p.cpu_limit_milli
                r.memory_request_mega += p.memory_request_mega
                r.memory_limit_mega += p.memory_limit_mega
                r.tpu_request += p.tpu_limit
                r.tpu_limit += p.tpu_limit
                if p.node in nodes.nodes_cpu_idle_milli:
                    nodes.nodes_cpu_idle_milli[p.node] -= p.cpu_request_milli
                    nodes.nodes_memory_free_mega[p.node] -= p.memory_request_mega
                    nodes.nodes_tpu_free[p.node] -= p.tpu_limit
                if (p.tpu_limit > 0 and p.job_uid
                        and p.node in self._nodes
                        and not self._allows_multi_domain(p.job_uid)):
                    # chip pods pin their job to the domain they run in —
                    # the planner must keep growing the mesh there
                    # (DCN-spanning jobs are never pinned)
                    r.jobs_ici_domain.setdefault(
                        p.job_uid, self._nodes[p.node].ici_domain)
            r.nodes = nodes
            return r

    def get_trainer_parallelism(self, job: TrainingJob) -> int:
        with self._lock:
            return self._group(job).parallelism

    def update_trainer_parallelism(self, job: TrainingJob, parallelism: int) -> None:
        with self._lock:
            if self.fail_next_updates > 0:
                self.fail_next_updates -= 1
                raise ConflictError("injected conflict")
            g = self._group(job)
            g.parallelism = parallelism
            g.resource_version += 1
        self.reconcile()

    def job_pods(self, job: TrainingJob) -> PodCounts:
        role = getattr(job, "replica_role", "trainer")
        with self._lock:
            total = running = pending = succeeded = failed = 0
            for p in self._pods.values():
                if p.job_uid != job.full_name or p.role != role:
                    continue
                total += 1
                if p.deletion_timestamp:
                    continue  # Terminating counts in total only
                if p.phase == PodPhase.RUNNING:
                    running += 1
                elif p.phase == PodPhase.PENDING:
                    pending += 1
                elif p.phase == PodPhase.SUCCEEDED:
                    succeeded += 1
                elif p.phase == PodPhase.FAILED:
                    failed += 1
            return PodCounts(total, running, pending, succeeded, failed)

    def create_resources(self, job: TrainingJob) -> None:
        # works for both replica-group kinds: a TrainingJob's trainer
        # group and a ServingJob's server group are the same dial
        with self._lock:
            if job.full_name in self._groups:
                raise ConflictError(f"job {job.full_name} already exists")
            self._groups[job.full_name] = _TrainerGroup(
                job_uid=job.full_name, parallelism=job.group_range()[0]
            )
            self._job_specs[job.full_name] = job
        self.reconcile()

    def job_spec(self, job_uid: str) -> Optional[TrainingJob]:
        """The spec a pod's job was created from (the kubelet needs it to
        compile the pod's container command/env via the jobparser)."""
        with self._lock:
            return self._job_specs.get(job_uid)

    def report_pod_exit(self, name: str, returncode: int) -> None:
        """Kubelet status update: the pod's process exited.  rc 0 →
        Succeeded (work-queue Job: the job is done), else Failed (the Job
        controller replaces it on the next reconcile)."""
        with self._lock:
            p = self._pods.get(name)
            if p is None or p.phase not in (PodPhase.PENDING,
                                            PodPhase.RUNNING):
                return
            p.phase = (PodPhase.SUCCEEDED if returncode == 0
                       else PodPhase.FAILED)
        self.reconcile()

    def delete_resources(self, job: TrainingJob) -> None:
        stopped: list[FakePod] = []
        with self._lock:
            self._groups.pop(job.full_name, None)
            self._job_specs.pop(job.full_name, None)
            for name in [n for n, p in self._pods.items() if p.job_uid == job.full_name]:
                stopped.append(self._pods.pop(name))
        for p in stopped:
            self._emit(p, "stop")

    # -- the fake kubelet / job controller --------------------------------

    def reconcile(self) -> None:
        """Drive pods toward each group's parallelism: create missing pods,
        delete surplus ones, and try to place Pending pods on nodes."""
        started: list[FakePod] = []
        stopped: list[FakePod] = []
        with self._lock:
            for g in list(self._groups.values()):
                spec = self._job_specs.get(g.job_uid)
                if spec is None:
                    continue
                role = getattr(spec, "replica_role", "trainer")
                # coordinator ReplicaSet semantics for FT jobs (role of the
                # master RS, reference pkg/jobparser.go:167-227): keep ONE
                # live coordinator pod; a Failed one is replaced.  Off by
                # default: the pure-bookkeeping scheduler scenarios elide
                # aux pods (they hold no chips); the process-backed kubelet
                # turns it on to run the job's coordinator for real.
                if (getattr(spec.spec, "fault_tolerant", False)
                        and self.materialize_aux_pods):
                    coords = [
                        p for p in self._pods.values()
                        if p.job_uid == g.job_uid and p.role == "coordinator"
                        and p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
                        and not p.deletion_timestamp
                    ]
                    if not coords:
                        seq = next(self._aux_pods_seq)
                        mres = spec.spec.master.resources
                        self._pods[f"{spec.name}-coordinator-{seq}"] = FakePod(
                            name=f"{spec.name}-coordinator-{seq}",
                            job_uid=g.job_uid, role="coordinator", seq=seq,
                            cpu_request_milli=mres.cpu_request().milli_value(),
                            cpu_limit_milli=mres.cpu_limit().milli_value(),
                            memory_request_mega=(
                                mres.memory_request().scaled_value(6)),
                            memory_limit_mega=(
                                mres.memory_limit().scaled_value(6)),
                        )
                pods = [
                    p for p in self._pods.values()
                    if p.job_uid == g.job_uid and p.role == role
                ]
                live = [
                    p for p in pods
                    if p.phase in (PodPhase.PENDING, PodPhase.RUNNING)
                    and not p.deletion_timestamp
                ]
                # Work-queue Job semantics (completions unset): once any pod
                # has Succeeded the work is done — never spawn replacements.
                done = any(p.phase == PodPhase.SUCCEEDED for p in pods)
                if done:
                    continue
                # Non-fault-tolerant jobs have a zero-failure budget: the
                # updater's any-failure-is-fatal rule will tear the job
                # down, but until it does, spawning a replacement trainer
                # would hand it a frozen EDL_STATIC_PEERS list the
                # survivors disagree with (the dead pod is still in
                # theirs).  Enforce the budget at the Job-controller level
                # too: once any trainer Failed, never replace (ADVICE r5
                # item 3).  Serving replicas are ReplicaSet-semantics:
                # always replaceable.
                if (not spec.replaceable_on_failure()
                        and any(p.phase == PodPhase.FAILED for p in pods)):
                    continue
                # surplus: delete newest first (creation-order, not name-order)
                for p in sorted(live, key=lambda p: p.seq)[g.parallelism:]:
                    self._pods.pop(p.name, None)
                    stopped.append(p)
                # missing: create
                for i in range(g.parallelism - len(live)):
                    seq = next(self._aux_pods_seq)
                    name = f"{spec.name}-{role}-{seq}"
                    res = spec.group_resources()
                    pod = FakePod(
                        name=name, job_uid=g.job_uid, role=role, seq=seq,
                        cpu_request_milli=res.cpu_request().milli_value(),
                        cpu_limit_milli=res.cpu_limit().milli_value(),
                        memory_request_mega=res.memory_request().scaled_value(6),
                        memory_limit_mega=res.memory_limit().scaled_value(6),
                        tpu_limit=spec.tpu_chips_per_replica(),
                    )
                    self._pods[name] = pod
            # schedule Pending pods
            for p in self._pods.values():
                if p.phase == PodPhase.PENDING and not p.deletion_timestamp:
                    node = self._find_node_for(p)
                    if node is not None:
                        p.node = node
                        p.phase = PodPhase.RUNNING
                        started.append(p)
        for p in stopped:
            self._emit(p, "stop")
        for p in started:
            self._emit(p, "start")

    def kill_pod(self, name: str, phase: PodPhase = PodPhase.FAILED) -> None:
        """Chaos hook: fail a pod (the reference's manual kill-a-pod demo,
        doc/boss_tutorial.md:271-301, made programmatic)."""
        with self._lock:
            p = self._pods.get(name)
            if p is None:
                return
            p.phase = phase
        self._emit(p, "stop")
        self.reconcile()  # Job controller re-creates the replacement pod

    def list_pods(self, job_uid: Optional[str] = None, role: Optional[str] = None
                  ) -> list[FakePod]:
        with self._lock:
            return [
                p for p in self._pods.values()
                if (job_uid is None or p.job_uid == job_uid)
                and (role is None or p.role == role)
            ]

    # -- internals ---------------------------------------------------------

    def _group(self, job: TrainingJob) -> _TrainerGroup:
        g = self._groups.get(job.full_name)
        if g is None:
            raise KeyError(f"no trainer group for job {job.full_name!r}")
        return g

    def _allows_multi_domain(self, job_uid: str) -> bool:
        spec = self._job_specs.get(job_uid)
        if spec is None:
            return False
        trainer = getattr(spec.spec, "trainer", None)
        if trainer is None:
            # a replica group without a trainer section (ServingJob):
            # replicas are independent meshes — no inter-replica ICI
            # collective to protect, so the fleet may spread across
            # domains and is never pinned (matches PlannedJob.multi_domain)
            return True
        return trainer.allow_multi_domain

    def _find_node_for(self, pod: FakePod) -> Optional[str]:
        idle = {
            n.name: [n.cpu_milli, n.memory_mega, n.tpu_chips]
            for n in self._nodes.values()
        }
        for p in self._pods.values():
            if p.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED) or p.node is None:
                continue
            if p.node in idle:
                idle[p.node][0] -= p.cpu_request_milli
                idle[p.node][1] -= p.memory_request_mega
                idle[p.node][2] -= p.tpu_limit
        # TPU jobs must stay within one ICI domain: once the first chip pod
        # of a job lands, its siblings only place on nodes in the same
        # domain (a DP mesh spanning domains would all-reduce over DCN) —
        # unless the job opted into multi-slice (allow_multi_domain).
        required_domain = None
        if (pod.tpu_limit > 0 and pod.job_uid
                and not self._allows_multi_domain(pod.job_uid)):
            for p in self._pods.values():
                if (p.job_uid == pod.job_uid and p.tpu_limit > 0
                        and p.node is not None
                        and p.phase == PodPhase.RUNNING):
                    required_domain = self._nodes[p.node].ici_domain
                    break
        for name, (cpu, mem, tpu) in idle.items():
            if required_domain is not None and (
                    self._nodes[name].ici_domain != required_domain):
                continue
            if (pod.cpu_request_milli <= cpu and pod.memory_request_mega <= mem
                    and pod.tpu_limit <= tpu):
                return name
        return None

    def _emit(self, pod: FakePod, what: str) -> None:
        hook = self.pod_event_hook
        if hook is not None:
            hook(pod, what)
