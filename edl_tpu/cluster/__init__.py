"""Cluster inventory & actuation layer (role of reference pkg/cluster.go)."""

from edl_tpu.cluster.resource import ClusterResource, NodeResources
from edl_tpu.cluster.base import Cluster, PodPhase, PodCounts
from edl_tpu.cluster.fake import FakeCluster, FakeNode

__all__ = [
    "ClusterResource",
    "NodeResources",
    "Cluster",
    "PodPhase",
    "PodCounts",
    "FakeCluster",
    "FakeNode",
]
