"""Cluster interface — the façade the controller/scheduler talk through.

Role of the reference's ``Cluster`` struct (reference pkg/cluster.go:31-291):
inventory snapshots (`InquiryResource`, cluster.go:176-242), trainer-group
actuation (`GetTrainerJob`/`UpdateTrainerJob`, cluster.go:91-113), and pod
counting by job label (`JobPods`, cluster.go:117-136).

Implementations: :class:`edl_tpu.cluster.fake.FakeCluster` (in-memory, used
by all tests and the local elastic runtime) and
:class:`edl_tpu.cluster.k8s.K8sCluster` (real GKE/Kubernetes backend, gated
on the ``kubernetes`` package being importable).
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass

from edl_tpu.api.types import TrainingJob
from edl_tpu.cluster.resource import ClusterResource


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    TERMINATING = "Terminating"  # deletion_timestamp set (k8s_tools.py:29-36)
    UNKNOWN = "Unknown"  # kubelet unreachable; standard k8s phase


@dataclass(frozen=True)
class PodCounts:
    """Per-job trainer pod counts — reference cluster.go:117-136 plus the
    Succeeded/Failed counts the Gen-2 phase machine needs
    (reference pkg/updater/trainingJobUpdater.go:343-382)."""

    total: int = 0
    running: int = 0
    pending: int = 0
    succeeded: int = 0
    failed: int = 0


class Cluster(abc.ABC):
    """What the autoscaler and controller need from the substrate."""

    # -- inventory ---------------------------------------------------------

    @abc.abstractmethod
    def inquiry_resource(self) -> ClusterResource:
        """Snapshot totals + requests + per-node idleness
        (reference cluster.go:176-242)."""

    # -- trainer-group actuation ------------------------------------------

    @abc.abstractmethod
    def get_trainer_parallelism(self, job: TrainingJob) -> int:
        """Current desired trainer count (role of GetTrainerJob →
        Spec.Parallelism, reference cluster.go:91-97)."""

    @abc.abstractmethod
    def update_trainer_parallelism(self, job: TrainingJob, parallelism: int) -> None:
        """Actuate a resize (role of UpdateTrainerJob, cluster.go:100-113).
        May raise ConflictError; callers retry (autoscaler.go:339-376)."""

    @abc.abstractmethod
    def job_pods(self, job: TrainingJob) -> PodCounts:
        """Count the job's trainer pods by phase (cluster.go:117-136)."""

    @abc.abstractmethod
    def list_pods(self, job_uid: str | None = None, role: str | None = None):
        """Pod records (FakePod attribute surface: name/job_uid/role/phase/
        node/...), optionally scoped to one job and/or role — what the
        collector, pod discovery and per-role status reporting consume."""

    # -- resource lifecycle (role of CreateJob/DeleteJob/Create|DeleteReplicaSet,
    #    cluster.go:245-291) ----------------------------------------------

    @abc.abstractmethod
    def create_resources(self, job: TrainingJob) -> None:
        """Materialize the job's worker groups (trainer/master/pserver)."""

    @abc.abstractmethod
    def delete_resources(self, job: TrainingJob) -> None:
        """Tear the job's worker groups down (foreground-GC semantics)."""


class ConflictError(RuntimeError):
    """Optimistic-concurrency conflict on actuation (k8s resourceVersion)."""
