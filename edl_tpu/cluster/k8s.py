"""Kubernetes/GKE cluster backend.

Role of the reference's real k8s façade (reference pkg/cluster.go:31-291):
node/pod inventory via the apiserver, trainer-group actuation via a
Job-like resource's parallelism, TPU capacity read from the
``google.com/tpu`` allocatable (where the reference read
``alpha.kubernetes.io/nvidia-gpu``, cluster.go:224).

Gated on the ``kubernetes`` client package, which is not part of this
build's baked-in dependency set — constructing :class:`K8sCluster` without
it raises a clear error, and everything else in edl_tpu (controller,
scheduler, runtime, tests) runs against :class:`~edl_tpu.cluster.fake.FakeCluster`.
The class documents the full mapping so wiring it to a live cluster is
mechanical.
"""

from __future__ import annotations

from dataclasses import dataclass

from edl_tpu.api.types import (
    COORDINATOR_LABEL,
    MULTI_DOMAIN_LABEL,
    PSERVER_LABEL,
    RESOURCE_TPU,
    SERVING_LABEL,
    TRAINER_LABEL,
    TrainingJob,
)
from edl_tpu.cluster.base import Cluster, ConflictError, PodCounts, PodPhase
from edl_tpu.cluster.resource import ClusterResource, NodeResources


@dataclass(frozen=True)
class PodView:
    """Read-only pod record matching the FakePod attribute surface."""

    name: str
    job_uid: str
    role: str
    phase: PodPhase
    node: str | None = None
    deletion_timestamp: bool = False
    cpu_request_milli: int = 0
    memory_request_mega: int = 0
    tpu_limit: int = 0
    #: pod IP — the static path's rendezvous address (role of the
    #: reference's fetch_ips, docker/k8s_tools.py:95-110)
    ip: str = ""

try:
    import kubernetes  # type: ignore

    _HAVE_K8S = True
except ImportError:
    _HAVE_K8S = False

#: Node labels that identify the ICI fabric a TPU node belongs to, in
#: preference order.  On GKE every node of a multi-host slice carries the
#: slice's topology labels; nodes without any of these are their own domain
#: (single-host ICI).
ICI_DOMAIN_LABELS = (
    "edl-tpu/ici-domain",
    "cloud.google.com/gke-tpu-slice",  # nodepool slice identity
)


class K8sCluster(Cluster):
    """Live-cluster backend; requires the ``kubernetes`` package."""

    def __init__(self, kubeconfig: str | None = None, namespace: str = "default"):
        if not _HAVE_K8S:
            raise RuntimeError(
                "K8sCluster requires the 'kubernetes' package; this build "
                "image does not include it — use FakeCluster, or install "
                "kubernetes in a deployment image"
            )
        if kubeconfig:
            kubernetes.config.load_kube_config(kubeconfig)
        else:
            kubernetes.config.load_incluster_config()
        self._core = kubernetes.client.CoreV1Api()
        self._batch = kubernetes.client.BatchV1Api()
        self._custom = kubernetes.client.CustomObjectsApi()
        self.namespace = namespace

    # The method bodies below mirror reference pkg/cluster.go behavior and
    # only run with the kubernetes package present.

    def inquiry_resource(self) -> ClusterResource:
        r = ClusterResource()
        nodes = NodeResources()
        for node in self._core.list_node().items:
            alloc = node.status.allocatable or {}
            cpu = _milli(alloc.get("cpu", "0"))
            mem = _mega(alloc.get("memory", "0"))
            tpu = int(alloc.get(RESOURCE_TPU, "0"))
            r.node_count += 1
            r.cpu_total_milli += cpu
            r.memory_total_mega += mem
            r.tpu_total += tpu
            nodes.nodes_cpu_idle_milli[node.metadata.name] = cpu
            nodes.nodes_memory_free_mega[node.metadata.name] = mem
            nodes.nodes_tpu_free[node.metadata.name] = tpu
            labels = node.metadata.labels or {}
            for key in ICI_DOMAIN_LABELS:
                if labels.get(key):
                    nodes.nodes_ici_domain[node.metadata.name] = labels[key]
                    break
        # all non-terminal pods hold their requests (cluster.go:202-242)
        pods = self._core.list_pod_for_all_namespaces(
            field_selector="status.phase!=Succeeded,status.phase!=Failed"
        )
        for pod in pods.items:
            creq, cl, mreq, ml, tl = _pod_resources(pod)
            r.cpu_request_milli += creq
            r.cpu_limit_milli += cl
            r.memory_request_mega += mreq
            r.memory_limit_mega += ml
            r.tpu_request += tl
            r.tpu_limit += tl
            nn = pod.spec.node_name
            if nn in nodes.nodes_cpu_idle_milli:
                nodes.nodes_cpu_idle_milli[nn] -= creq
                nodes.nodes_memory_free_mega[nn] -= mreq
                nodes.nodes_tpu_free[nn] -= tl
            labels = pod.metadata.labels or {}
            # Pin only to LIVE nodes: a non-terminal pod lingering on a
            # deleted/drained node must not pin its job to a domain that no
            # longer exists (the planner would find no candidate nodes and
            # freeze the job's scale-up until the stale pod is reaped).
            # DCN-spanning jobs (MULTI_DOMAIN_LABEL) are never pinned —
            # a pin would re-cap them at one domain.
            if (tl > 0 and TRAINER_LABEL in labels
                    and MULTI_DOMAIN_LABEL not in labels
                    and nn in nodes.nodes_cpu_idle_milli):
                uid = f"{pod.metadata.namespace}/{labels[TRAINER_LABEL]}"
                r.jobs_ici_domain.setdefault(
                    uid, nodes.nodes_ici_domain.get(nn, nn))
        r.nodes = nodes
        return r

    def get_trainer_parallelism(self, job: TrainingJob) -> int:
        if getattr(job, "replica_role", "trainer") == "server":
            apps = kubernetes.client.AppsV1Api()
            rs = apps.read_namespaced_replica_set(
                f"{job.name}-server", job.namespace)
            return int(rs.spec.replicas or 0)
        tj = self._batch.read_namespaced_job(_trainer_name(job), job.namespace)
        return int(tj.spec.parallelism or 0)

    def update_trainer_parallelism(self, job: TrainingJob, parallelism: int
                                   ) -> None:
        """Fresh-read then replace; a 409 (stale resourceVersion — someone
        wrote between our read and replace) surfaces as ConflictError so the
        autoscaler's bounded retry re-reads and tries again (reference
        autoscaler.go:339-376 does the same 5-retry refresh-then-write).
        The replica-group dial is workload-agnostic: a TrainingJob's dial
        is the trainer Job's ``parallelism``, a ServingJob's the server
        ReplicaSet's ``replicas``."""
        if getattr(job, "replica_role", "trainer") == "server":
            apps = kubernetes.client.AppsV1Api()
            name = f"{job.name}-server"
            rs = apps.read_namespaced_replica_set(name, job.namespace)
            rs.spec.replicas = parallelism
            try:
                apps.replace_namespaced_replica_set(name, job.namespace, rs)
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status == 409:
                    raise ConflictError(
                        f"resourceVersion conflict updating {name}") from exc
                raise
            return
        name = _trainer_name(job)
        tj = self._batch.read_namespaced_job(name, job.namespace)
        tj.spec.parallelism = parallelism
        try:
            self._batch.replace_namespaced_job(name, job.namespace, tj)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 409:
                raise ConflictError(
                    f"resourceVersion conflict updating {name}") from exc
            raise

    def job_pods(self, job: TrainingJob) -> PodCounts:
        label = (SERVING_LABEL
                 if getattr(job, "replica_role", "trainer") == "server"
                 else TRAINER_LABEL)
        sel = f"{label}={job.name}"
        total = running = pending = succeeded = failed = 0
        for pod in self._core.list_namespaced_pod(
            job.namespace, label_selector=sel
        ).items:
            total += 1
            if pod.metadata.deletion_timestamp is not None:
                continue
            if pod.status.phase == "Running":
                running += 1
            elif pod.status.phase == "Pending":
                pending += 1
            elif pod.status.phase == "Succeeded":
                succeeded += 1
            elif pod.status.phase == "Failed":
                failed += 1
        return PodCounts(total, running, pending, succeeded, failed)

    def create_resources(self, job: TrainingJob) -> None:
        """Materialize the job's pod groups.  A 409 AlreadyExists is
        ADOPTION, not an error: after a controller restart the sync loop
        re-submits every listed CR, and the job's resources are usually
        still there — the updater then simply confirms the running cohort
        (the reference's create also tolerates existing resources by
        logging and continuing, pkg/controller.go:134-148).

        Adoption is sound because every RUNTIME-mutable spec field
        (trainer min/max bounds) lives in the controller registry and the
        autoscaler actuates it via parallelism writes; pod-template fields
        (image, entrypoint, per-pod resources) are create-time for the
        life of the job here exactly as in the reference, whose controller
        also never rewrites a running job's pod specs (its only actuation
        is TrainerJob.Spec.Parallelism, autoscaler.go:339-376).  Changing
        a template field means delete + resubmit."""
        from edl_tpu.controller.jobparser import (parse_serving_manifests,
                                                   parse_to_manifests)

        apps = kubernetes.client.AppsV1Api()
        manifests = (parse_serving_manifests(job)
                     if getattr(job, "replica_role", "trainer") == "server"
                     else parse_to_manifests(job))
        for manifest in manifests:
            try:
                if manifest["kind"] == "Job":
                    self._batch.create_namespaced_job(job.namespace, manifest)
                elif manifest["kind"] == "ReplicaSet":
                    apps.create_namespaced_replica_set(job.namespace, manifest)
                elif manifest["kind"] == "Service":
                    self._core.create_namespaced_service(job.namespace,
                                                         manifest)
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status != 409:
                    raise

    def list_training_jobs(self) -> list[str]:
        """Names of jobs with a trainer group in this namespace (role of
        the TrainingJob list the reference's del_jobs.sh iterates)."""
        names = []
        for j in self._batch.list_namespaced_job(self.namespace).items:
            labels = j.metadata.labels or {}
            if TRAINER_LABEL in labels:
                names.append(labels[TRAINER_LABEL])
        return sorted(set(names))

    def list_trainer_groups(self) -> list[tuple[str, str]]:
        """(namespace, job-name) of every trainer group CLUSTER-WIDE —
        the sweep surface matching the cluster-wide CR watch, so an
        orphaned group in any namespace is visible."""
        out = set()
        for j in self._batch.list_job_for_all_namespaces().items:
            labels = j.metadata.labels or {}
            if TRAINER_LABEL in labels:
                out.add((j.metadata.namespace, labels[TRAINER_LABEL]))
        return sorted(out)

    def delete_resources(self, job: TrainingJob) -> None:
        apps = kubernetes.client.AppsV1Api()
        if getattr(job, "replica_role", "trainer") == "server":
            # ServingJob: server ReplicaSet + its Service, nothing else
            try:
                apps.delete_namespaced_replica_set(
                    f"{job.name}-server", job.namespace,
                    propagation_policy="Foreground")
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status != 404:
                    raise
            try:
                self._core.delete_namespaced_service(
                    f"{job.name}-serve", job.namespace)
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status != 404:
                    raise
            return
        for rs in (f"{job.name}-coordinator", f"{job.name}-pserver"):
            try:
                apps.delete_namespaced_replica_set(
                    rs, job.namespace, propagation_policy="Foreground"
                )
            except kubernetes.client.exceptions.ApiException as exc:
                if exc.status != 404:
                    raise
        try:
            self._batch.delete_namespaced_job(
                _trainer_name(job), job.namespace,
                propagation_policy="Foreground",
            )
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status != 404:
                raise
        try:
            self._core.delete_namespaced_service(
                f"{job.name}-coordinator", job.namespace)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status != 404:
                raise

    # -- TrainingJob custom resources (the deployed control-plane surface;
    #    role of the reference's generated clientset CRUD+Watch,
    #    pkg/client/clientset/versioned/typed/paddlepaddle/v1/
    #    trainingjob.go:33-44) --------------------------------------------

    def list_training_job_crs(self) -> list[dict]:
        """TrainingJob custom objects across ALL namespaces (the poll-list
        the sync loop diffs; role of the informer's NamespaceAll ListWatch
        source, reference pkg/controller.go:80-87)."""
        return self.list_training_job_crs_with_rv()[0]

    def list_training_job_crs_with_rv(self) -> tuple[list[dict], str]:
        """(items, list resourceVersion) — the rv anchors a streaming
        watch exactly where this LIST observed the collection."""
        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        out = self._custom.list_cluster_custom_object(
            CRD_GROUP, CRD_VERSION, CRD_PLURAL)
        rv = str((out.get("metadata") or {}).get("resourceVersion") or "")
        return list(out.get("items") or []), rv

    def watch_training_job_crs(self, resource_version: str,
                               timeout_seconds: int = 30):
        """Streaming watch from ``resource_version``: yields kubernetes
        watch events ({"type": ADDED|MODIFIED|DELETED, "object": cr}) —
        the event-driven half of the reference informer's ListWatch
        (reference pkg/controller.go:87-107).  The stream ends at the
        server-side timeout (the caller loops); a stale rv raises the
        client's 410 Gone ApiException, which the sync loop answers with
        a fresh LIST."""
        from kubernetes import watch as k8s_watch

        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        w = k8s_watch.Watch()
        try:
            yield from w.stream(
                self._custom.list_cluster_custom_object,
                CRD_GROUP, CRD_VERSION, CRD_PLURAL,
                resource_version=resource_version,
                timeout_seconds=timeout_seconds)
        finally:
            w.stop()

    def get_training_job_cr(self, name: str, namespace: str | None = None
                            ) -> dict | None:
        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        try:
            return self._custom.get_namespaced_custom_object(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                CRD_PLURAL, name)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return None
            raise

    def create_training_job_cr(self, manifest: dict) -> None:
        """Submit = create the CR and let the controller materialize it
        (the reference's submission flow, doc/usage.md + controller
        onAdd, pkg/controller.go:110-148).  The CR lands in the
        manifest's own metadata.namespace (an apiserver rejects a
        namespace mismatch), falling back to this client's default."""
        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        ns = ((manifest.get("metadata") or {}).get("namespace")
              or self.namespace)
        self._custom.create_namespaced_custom_object(
            CRD_GROUP, CRD_VERSION, ns, CRD_PLURAL, manifest)

    def delete_training_job_cr(self, name: str, namespace: str | None = None
                               ) -> bool:
        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        try:
            self._custom.delete_namespaced_custom_object(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                CRD_PLURAL, name)
            return True
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return False
            raise

    def patch_training_job_status(self, name: str, status: dict,
                                  namespace: str | None = None) -> bool:
        """Write phase + replica statuses into the CR's status subresource
        so ``kubectl get tj`` shows them (role of updateCRDStatus,
        reference pkg/updater/trainingJobUpdater.go:295-307).  False if the
        CR vanished (deleted between list and patch) — not an error."""
        from edl_tpu.api.serde import CRD_GROUP, CRD_PLURAL, CRD_VERSION

        try:
            self._custom.patch_namespaced_custom_object_status(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                CRD_PLURAL, name, {"status": status})
            return True
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return False
            raise

    # -- ServingJob custom resources (kind dispatch mirror of the
    #    TrainingJob CR surface; plural servingjobs, k8s/crd.yaml) ---------

    def list_serving_job_crs(self) -> list[dict]:
        from edl_tpu.api.serde import CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL

        out = self._custom.list_cluster_custom_object(
            CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL)
        return list(out.get("items") or [])

    def get_serving_job_cr(self, name: str, namespace: str | None = None
                           ) -> dict | None:
        from edl_tpu.api.serde import CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL

        try:
            return self._custom.get_namespaced_custom_object(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                SERVING_CRD_PLURAL, name)
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return None
            raise

    def create_serving_job_cr(self, manifest: dict) -> None:
        from edl_tpu.api.serde import CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL

        ns = ((manifest.get("metadata") or {}).get("namespace")
              or self.namespace)
        self._custom.create_namespaced_custom_object(
            CRD_GROUP, CRD_VERSION, ns, SERVING_CRD_PLURAL, manifest)

    def delete_serving_job_cr(self, name: str, namespace: str | None = None
                              ) -> bool:
        from edl_tpu.api.serde import CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL

        try:
            self._custom.delete_namespaced_custom_object(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                SERVING_CRD_PLURAL, name)
            return True
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return False
            raise

    def patch_serving_job_status(self, name: str, status: dict,
                                 namespace: str | None = None) -> bool:
        from edl_tpu.api.serde import CRD_GROUP, CRD_VERSION, SERVING_CRD_PLURAL

        try:
            self._custom.patch_namespaced_custom_object_status(
                CRD_GROUP, CRD_VERSION, namespace or self.namespace,
                SERVING_CRD_PLURAL, name, {"status": status})
            return True
        except kubernetes.client.exceptions.ApiException as exc:
            if exc.status == 404:
                return False
            raise

    def list_pods(self, job_uid: str | None = None, role: str | None = None
                  ) -> list["PodView"]:
        """Pods as lightweight records with the FakePod attribute surface
        (what the Collector and PodDiscovery consume)."""
        out = []
        role_labels = {"trainer": TRAINER_LABEL,
                       "master": COORDINATOR_LABEL,
                       "pserver": PSERVER_LABEL,
                       "server": SERVING_LABEL}
        if job_uid is not None or role is not None:
            # Job-scoped callers (PodDiscovery polls every 5 s): a
            # namespaced LIST with a label selector, not a full-cluster
            # scan.  job_uid is "namespace/name".
            ns, _, jname = (job_uid or "").partition("/")
            ns = ns if jname else self.namespace
            if role in role_labels:
                sel = (f"{role_labels[role]}={jname}" if jname
                       else role_labels[role])
            else:
                sel = None  # any role of the job; filtered client-side
            items = self._core.list_namespaced_pod(
                ns, label_selector=sel).items
        else:
            # Full scan (the Collector): all namespaces, so the
            # utilization numerator covers the same pod set as the
            # inquiry_resource denominator (system pods included — the
            # reference counts every Running pod's requests,
            # example/collector.py:156-179).
            items = self._core.list_pod_for_all_namespaces().items
        for pod in items:
            labels = pod.metadata.labels or {}
            pod_role, pod_job = "system", ""
            for r, label in role_labels.items():
                if label in labels:
                    pod_role = r
                    pod_job = f"{pod.metadata.namespace}/{labels[label]}"
                    break
            if job_uid is not None and pod_job != job_uid:
                continue
            if role is not None and pod_role != role:
                continue
            creq, _, mreq, _, tl = _pod_resources(pod)
            out.append(PodView(
                name=pod.metadata.name,
                job_uid=pod_job,
                role=pod_role,
                phase=PodPhase(pod.status.phase or "Pending"),
                node=pod.spec.node_name,
                deletion_timestamp=pod.metadata.deletion_timestamp is not None,
                cpu_request_milli=creq,
                memory_request_mega=mreq,
                tpu_limit=tl,
                ip=getattr(pod.status, "pod_ip", None) or "",
            ))
        return out


def _trainer_name(job: TrainingJob) -> str:
    return f"{job.name}-trainer"


def _milli(q: str) -> int:
    from edl_tpu.api.quantity import Quantity

    return Quantity(q).milli_value()


def _mega(q: str) -> int:
    from edl_tpu.api.quantity import Quantity

    return Quantity(q).scaled_value(6)


def _pod_resources(pod):
    creq = cl = mreq = ml = tl = 0
    containers = list(pod.spec.containers or []) + list(
        pod.spec.init_containers or []
    )
    for c in containers:
        res = c.resources
        if res is None:
            continue
        req = res.requests or {}
        lim = res.limits or {}
        creq += _milli(req.get("cpu", "0"))
        cl += _milli(lim.get("cpu", "0"))
        mreq += _mega(req.get("memory", "0"))
        ml += _mega(lim.get("memory", "0"))
        tl += int(lim.get(RESOURCE_TPU, "0"))
    return creq, cl, mreq, ml, tl
