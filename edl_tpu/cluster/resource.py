"""Cluster resource snapshot — the value type the planner plans over.

Port of the reference's ``ClusterResource`` / ``Nodes`` structs
(reference pkg/cluster.go:32-61), with the accelerator dimension renamed
GPU → TPU chips and extended with per-node free-chip tracking so the planner
can keep slice allocations node-local (an ICI mesh cannot span hosts that are
not ICI-linked).

The snapshot is deliberately a plain mutable value type: the planner mutates
a *copy* during its dry run and the real cluster is never touched
(reference pkg/autoscaler.go:296 passes ClusterResource by value — the
property its whole unit-test suite relies on).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class NodeResources:
    """Per-node idle/free maps — reference pkg/cluster.go:56-61 (``Nodes``),
    plus TPU chip-freeness and ICI-domain membership per node."""

    nodes_cpu_idle_milli: dict[str, int] = field(default_factory=dict)
    nodes_memory_free_mega: dict[str, int] = field(default_factory=dict)
    nodes_tpu_free: dict[str, int] = field(default_factory=dict)
    #: node → ICI domain (hosts wired into one ICI fabric).  A node absent
    #: here is its own domain: a single-host mesh is always ICI-local.
    nodes_ici_domain: dict[str, str] = field(default_factory=dict)

    def copy(self) -> "NodeResources":
        return NodeResources(
            dict(self.nodes_cpu_idle_milli),
            dict(self.nodes_memory_free_mega),
            dict(self.nodes_tpu_free),
            dict(self.nodes_ici_domain),
        )

    def domain_of(self, node: str) -> str:
        return self.nodes_ici_domain.get(node) or node


@dataclass
class ClusterResource:
    """Whole-cluster totals + requested/limited sums — reference
    pkg/cluster.go:32-54."""

    node_count: int = 0

    # Accelerator chips (role of GPURequest/GPULimit/GPUTotal).
    tpu_request: int = 0
    tpu_limit: int = 0
    tpu_total: int = 0

    cpu_request_milli: int = 0
    cpu_limit_milli: int = 0
    cpu_total_milli: int = 0

    memory_request_mega: int = 0
    memory_limit_mega: int = 0
    memory_total_mega: int = 0

    nodes: NodeResources = field(default_factory=NodeResources)

    #: job uid → the ICI domain its running chip pods occupy.  Written by
    #: ``inquiry_resource`` (from live pods) and by the planner's dry run
    #: (pinning the domain it chose, so later fixpoint rounds keep growing
    #: the job in the same fabric instead of re-choosing per round).
    jobs_ici_domain: dict[str, str] = field(default_factory=dict)

    def copy(self) -> "ClusterResource":
        """Value-semantics copy handed to the dry-run planner
        (role of Go's pass-by-value at reference pkg/autoscaler.go:296)."""
        c = ClusterResource(**{
            k: v for k, v in self.__dict__.items()
            if k not in ("nodes", "jobs_ici_domain")})
        c.nodes = self.nodes.copy()
        c.jobs_ici_domain = dict(self.jobs_ici_domain)
        return c

    def utilization(self) -> float:
        """Chip utilization if the cluster has chips, else CPU utilization."""
        if self.tpu_total > 0:
            return self.tpu_limit / self.tpu_total
        if self.cpu_total_milli > 0:
            return self.cpu_request_milli / self.cpu_total_milli
        return 0.0
