"""Cluster resource snapshot — the value type the planner plans over.

Port of the reference's ``ClusterResource`` / ``Nodes`` structs
(reference pkg/cluster.go:32-61), with the accelerator dimension renamed
GPU → TPU chips and extended with per-node free-chip tracking so the planner
can keep slice allocations node-local (an ICI mesh cannot span hosts that are
not ICI-linked).

The snapshot is deliberately a plain mutable value type: the planner mutates
a *copy* during its dry run and the real cluster is never touched
(reference pkg/autoscaler.go:296 passes ClusterResource by value — the
property its whole unit-test suite relies on).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class NodeResources:
    """Per-node idle/free maps — reference pkg/cluster.go:56-61 (``Nodes``),
    plus TPU chip-freeness per node."""

    nodes_cpu_idle_milli: dict[str, int] = field(default_factory=dict)
    nodes_memory_free_mega: dict[str, int] = field(default_factory=dict)
    nodes_tpu_free: dict[str, int] = field(default_factory=dict)

    def copy(self) -> "NodeResources":
        return NodeResources(
            dict(self.nodes_cpu_idle_milli),
            dict(self.nodes_memory_free_mega),
            dict(self.nodes_tpu_free),
        )


@dataclass
class ClusterResource:
    """Whole-cluster totals + requested/limited sums — reference
    pkg/cluster.go:32-54."""

    node_count: int = 0

    # Accelerator chips (role of GPURequest/GPULimit/GPUTotal).
    tpu_request: int = 0
    tpu_limit: int = 0
    tpu_total: int = 0

    cpu_request_milli: int = 0
    cpu_limit_milli: int = 0
    cpu_total_milli: int = 0

    memory_request_mega: int = 0
    memory_limit_mega: int = 0
    memory_total_mega: int = 0

    nodes: NodeResources = field(default_factory=NodeResources)

    def copy(self) -> "ClusterResource":
        """Value-semantics copy handed to the dry-run planner
        (role of Go's pass-by-value at reference pkg/autoscaler.go:296)."""
        c = ClusterResource(**{k: v for k, v in self.__dict__.items() if k != "nodes"})
        c.nodes = self.nodes.copy()
        return c

    def utilization(self) -> float:
        """Chip utilization if the cluster has chips, else CPU utilization."""
        if self.tpu_total > 0:
            return self.tpu_limit / self.tpu_total
        if self.cpu_total_milli > 0:
            return self.cpu_request_milli / self.cpu_total_milli
        return 0.0
