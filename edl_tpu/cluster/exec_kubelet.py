"""Process-backed kubelet: FakeCluster pods actually exec their command.

The reference's whole stack meets in one running system because its
controller-created pods really execute the shipped entrypoint — the
trainer Job's template says ``paddle_k8s start_new_trainer``
(reference pkg/jobparser.go:124), the kubelet runs it
(reference docker/paddle_k8s:119-141), and the controller only created
the objects (reference pkg/controller.go:134-147).  This module closes
the same loop for the TPU-native build without a real cluster:

* :class:`ProcessKubelet` attaches to a :class:`FakeCluster` via
  ``pod_event_hook``.  When reconcile starts a pod, the kubelet compiles
  the pod's container command + env **from the same jobparser manifest
  the deployed path ships** (`controller/jobparser.py` — it does not
  invent its own command line) and spawns it as a real OS process group.
* When reconcile stops a pod, the process group gets SIGTERM, escalating
  to SIGKILL after a grace period — kubelet pod termination semantics.
* When a pod's process exits on its own, the kubelet reports the exit
  back (``FakeCluster.report_pod_exit``): rc 0 → Succeeded (work-queue
  Job complete), else Failed → the Job controller's next reconcile
  replaces the pod.  This is what turns a ``kill -9`` of a worker into
  the full failure story: membership epoch bump → world reform →
  replacement pod → rejoin.

Single-machine emulation notes (the kubelet owns the pod sandbox, so
these belong here, not in the manifests):

* **Service DNS**: a ``*.svc`` host in ``EDL_COORD_ENDPOINT`` resolves
  to 127.0.0.1 — every "pod" runs on this machine.
* **Volumes**: each declared volumeMount maps to a per-job host
  directory; env values under the mount path are rewritten to it.
  Keying by job (not pod) gives the coordinator's state volume PVC
  semantics — its state survives pod replacement, which is the
  durability story the coordinator manifest documents
  (`controller/jobparser.py` EDL_COORD_STATE_FILE).
* **Pod identity**: ``EDL_POD_NAME``/``HOSTNAME`` are injected per pod,
  exactly what the downward API / pod hostname provide for real.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

from edl_tpu.cluster.fake import FakeCluster, FakePod
from edl_tpu.observability.logging import get_logger

log = get_logger("exec-kubelet")

_ROLE_PARSERS = {
    "trainer": "parse_to_trainer",
    "coordinator": "parse_to_coordinator",
    "pserver": "parse_to_pserver",
}


class ProcessKubelet:
    """Runs FakeCluster pods as real local processes.

    ``env_overrides`` is the harness knob (test/demo sizing, forcing the
    CPU backend, free health ports); it is applied after the manifest env
    and therefore must not be used to change the contract under test.
    """

    def __init__(
        self,
        cluster: FakeCluster,
        workdir: str,
        env_overrides: Optional[dict[str, str]] = None,
        term_grace_s: float = 5.0,
        reap_interval_s: float = 0.2,
    ) -> None:
        self.cluster = cluster
        self.workdir = workdir
        self.env_overrides = dict(env_overrides or {})
        self.term_grace_s = term_grace_s
        os.makedirs(os.path.join(workdir, "logs"), exist_ok=True)
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        self._term_deadline: dict[str, float] = {}
        self._prev_hook = cluster.pod_event_hook
        self._prev_aux = cluster.materialize_aux_pods
        cluster.materialize_aux_pods = True
        cluster.pod_event_hook = self._on_pod_event
        self._stop = threading.Event()
        self._reaper = threading.Thread(
            target=self._reap_loop, args=(reap_interval_s,),
            daemon=True, name="process-kubelet-reaper")
        self._reaper.start()

    # -- public surface ----------------------------------------------------

    def log_path(self, pod_name: str) -> str:
        return os.path.join(self.workdir, "logs", f"{pod_name}.log")

    def pid_of(self, pod_name: str) -> Optional[int]:
        with self._lock:
            p = self._procs.get(pod_name)
            return p.pid if p is not None and p.poll() is None else None

    def signal_pod(self, pod_name: str, sig: int = signal.SIGKILL) -> bool:
        """Chaos hook: signal the pod's whole process group (the
        ``kill -9`` of the reference's failure demo, doc-level parity
        with docker/paddle_k8s:119-141's dead-trainer-is-a-non-event)."""
        pid = self.pid_of(pod_name)
        if pid is None:
            return False
        try:
            os.killpg(pid, sig)
            return True
        except ProcessLookupError:
            return False

    def live_pods(self) -> list[str]:
        with self._lock:
            return [n for n, p in self._procs.items() if p.poll() is None]

    def stop(self) -> None:
        """Tear the kubelet down: kill every pod process group."""
        self._stop.set()
        with self._lock:
            names = list(self._procs)
        for name in names:
            self._kill_registered(name)
        self._reaper.join(timeout=5)
        # An in-flight _start_pod may have passed its _stop check before
        # set() above and registered a fresh process AFTER the sweep; with
        # the reaper gone nothing else would ever reap it (ADVICE r5
        # item 2).  The reaper has exited here, so re-sweep whatever is
        # still registered.
        with self._lock:
            leaked = [n for n, p in self._procs.items() if p.poll() is None]
        for name in leaked:
            log.warn("reaping pod spawned during teardown", pod=name)
            self._kill_registered(name)
        self.cluster.pod_event_hook = self._prev_hook
        self.cluster.materialize_aux_pods = self._prev_aux

    # -- manifest → process ------------------------------------------------

    def _container_for(self, pod: FakePod) -> Optional[dict]:
        from edl_tpu.controller import jobparser

        job = self.cluster.job_spec(pod.job_uid)
        if job is None:
            return None
        parser = _ROLE_PARSERS.get(pod.role)
        if parser is None:
            return None  # system pods have no command to run
        manifest = getattr(jobparser, parser)(job)
        if manifest is None:
            return None
        tmpl = manifest["spec"]["template"]["spec"]
        container = tmpl["containers"][0]
        return {
            "command": list(container["command"]),
            # valueFrom (downward-API) entries carry no literal value —
            # this kubelet injects the pod identity itself in _pod_env
            "env": {e["name"]: e["value"]
                    for e in container.get("env", []) if "value" in e},
            "volumes": [v["name"] for v in tmpl.get("volumes", [])],
            "mounts": {m["name"]: m["mountPath"]
                       for m in container.get("volumeMounts", [])},
        }

    def _pod_env(self, pod: FakePod, container: dict) -> dict[str, str]:
        env = dict(os.environ)
        # "the job image has the framework installed": pod processes run
        # with the kubelet's workdir as cwd, so the package root must be
        # importable explicitly
        import edl_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(edl_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.update(container["env"])
        # volume emulation: env values under a mount path point into the
        # per-job volume dir (PVC semantics — survives pod replacement)
        job_dir = pod.job_uid.replace("/", "_")
        for vol, mount in container["mounts"].items():
            host_dir = os.path.join(self.workdir, "volumes", job_dir, vol)
            os.makedirs(host_dir, exist_ok=True)
            prefix = mount.rstrip("/") + "/"
            for k, v in list(env.items()):
                # exact path or a child of the mount — NOT a sibling that
                # merely shares the string prefix (/state vs /state-backup)
                if isinstance(v, str) and (v == mount
                                           or v.startswith(prefix)):
                    env[k] = host_dir + v[len(mount.rstrip("/")):]
        # Service DNS emulation: *.svc resolves to this machine
        ep = env.get("EDL_COORD_ENDPOINT", "")
        if ".svc" in ep:
            host, sep, port = ep.rpartition(":")
            env["EDL_COORD_ENDPOINT"] = (
                f"127.0.0.1:{port}" if sep and port.isdigit() else "127.0.0.1")
        # pod-API emulation for the static path: the launcher's
        # kubernetes-client discovery has no apiserver here, so hand it
        # the job's trainer pod set explicitly (launcher EDL_STATIC_PEERS
        # backend).  All pods run on this machine — name doubles as addr.
        if (pod.role == "trainer"
                and container["command"][-1] == "start_static_trainer"):
            from edl_tpu.cluster.base import PodPhase

            # LIVE pods only: a crashed trainer must not appear in its
            # replacement's frozen peer list (the env backend cannot
            # re-observe phases later — the non-FT updater's any-failure-
            # is-fatal rule is what ultimately enforces the zero budget)
            peers = sorted(p.name for p in self.cluster.list_pods(
                job_uid=pod.job_uid, role="trainer")
                if not p.deletion_timestamp
                and p.phase in (PodPhase.PENDING, PodPhase.RUNNING))
            env.setdefault("EDL_STATIC_PEERS", ",".join(peers))
        # pod identity (downward API / pod hostname)
        env["EDL_POD_NAME"] = pod.name
        env["HOSTNAME"] = pod.name
        env.update(self.env_overrides)
        return env

    def _on_pod_event(self, pod: FakePod, what: str) -> None:
        if self._prev_hook is not None:
            self._prev_hook(pod, what)
        if what == "start":
            self._start_pod(pod)
        elif what == "stop":
            self._request_stop(pod.name)

    def _start_pod(self, pod: FakePod) -> None:
        # start events race teardown and scale-down: reconcile() runs on
        # several threads and the hook fires outside the cluster lock, so
        # a pod may already be stopped/deleted (or the kubelet stopping)
        # by the time its start event lands — spawning then would leak a
        # live process no snapshot tracks
        if self._stop.is_set():
            return
        from edl_tpu.cluster.base import PodPhase

        current = {p.name for p in self.cluster.list_pods()
                   if p.phase == PodPhase.RUNNING
                   and not p.deletion_timestamp}
        if pod.name not in current:
            return
        container = self._container_for(pod)
        if container is None:
            return
        command = container["command"]
        if command and command[0] == "python":
            command = [sys.executable] + command[1:]
        env = self._pod_env(pod, container)
        logf = open(self.log_path(pod.name), "w")
        try:
            proc = subprocess.Popen(
                command, env=env, stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True, cwd=self.workdir)
        except OSError as exc:
            log.error("pod spawn failed", pod=pod.name, error=str(exc))
            logf.close()
            self.cluster.report_pod_exit(pod.name, 127)
            return
        finally:
            logf.close()  # the child holds its own fd now
        with self._lock:
            self._procs[pod.name] = proc
        # stop() may have run between the _stop check above and the
        # registration: its kill sweep missed this process and the reaper
        # is gone, so nothing would ever reap it — kill it ourselves
        # (stop()'s post-join re-sweep is the backstop for the symmetric
        # window where registration lands mid-sweep)
        if self._stop.is_set():
            self._kill_registered(pod.name)
            return
        log.info("pod started", pod=pod.name, pid=proc.pid,
                 command=" ".join(command[:4]))

    def _kill_registered(self, pod_name: str) -> None:
        """SIGKILL + reap a process already in ``_procs`` (teardown path)."""
        with self._lock:
            proc = self._procs.pop(pod_name, None)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            log.error("pod process unreapable", pod=pod_name, pid=proc.pid)

    def _request_stop(self, pod_name: str) -> None:
        with self._lock:
            proc = self._procs.get(pod_name)
            if proc is None or proc.poll() is not None:
                return
            self._term_deadline[pod_name] = time.monotonic() + self.term_grace_s
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass

    # -- the reaper (kubelet status loop) ----------------------------------

    def _reap_loop(self, interval_s: float) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            exited: list[tuple[str, int]] = []
            with self._lock:
                for name, proc in list(self._procs.items()):
                    rc = proc.poll()
                    if rc is not None:
                        exited.append((name, rc))
                        self._procs.pop(name, None)
                        self._term_deadline.pop(name, None)
                    elif self._term_deadline.get(name, float("inf")) < now:
                        # grace expired: kubelet escalates to SIGKILL
                        self._term_deadline.pop(name, None)
                        try:
                            os.killpg(proc.pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
            for name, rc in exited:
                if self._stop.is_set():
                    break  # teardown: a FAILED report would spawn a
                    # replacement process that outlives stop()
                log.info("pod exited", pod=name, rc=rc)
                # a stop-requested pod is already deleted cluster-side;
                # report_pod_exit no-ops for it (pod gone / terminal)
                self.cluster.report_pod_exit(name, rc)
            self._stop.wait(interval_s)
