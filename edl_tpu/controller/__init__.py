"""Controller layer: job compilation + per-job lifecycle actors + reconciler
(role of reference pkg/controller.go, pkg/jobparser.go, pkg/updater/)."""

from edl_tpu.controller.jobparser import parse_to_manifests
from edl_tpu.controller.updater import TrainingJobUpdater
from edl_tpu.controller.controller import Controller

__all__ = ["parse_to_manifests", "TrainingJobUpdater", "Controller"]
