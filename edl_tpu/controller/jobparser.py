"""Job compilation: TrainingJob → runnable worker-group specs.

Role of the reference's DefaultJobParser (reference pkg/jobparser.go:30-315,
pkg/updater/jobparser.go:35-335), which compiles a TrainingJob into a
trainer batch Job, a pserver ReplicaSet, and a master ReplicaSet with an
etcd sidecar.  The TPU-native compilation differs by design:

* the **master** role becomes one *coordinator* pod running the edl_tpu
  coordination service (task-lease queue + membership epochs, C++ core) —
  no etcd sidecar; the coord service holds the state the reference kept in
  etcd (reference pkg/jobparser.go:167-184).
* the **pserver** role is only materialized when the spec asks for it
  (migration compatibility); TPU jobs shard parameters across the trainer
  mesh via jax/pjit instead.
* the **env contract** (role of PADDLE_INIT_*, reference
  pkg/jobparser.go:263-311) becomes EDL_* + JAX distributed variables.
* port fan-out (reference podPorts, jobparser.go:232-247) collapses to one
  coordinator port: collectives ride ICI/DCN via XLA, not a TCP port range.
"""

from __future__ import annotations

from typing import Any

from edl_tpu.api.types import (
    COORDINATOR_LABEL,
    DEFAULT_PORT,
    DEFAULT_SERVING_PORT,
    MULTI_DOMAIN_LABEL,
    PSERVER_LABEL,
    SERVING_LABEL,
    TRAINER_LABEL,
    ServingJob,
    TrainingJob,
)

COORDINATOR_PORT = DEFAULT_PORT  # single source of truth (api/types.py)
HEALTH_PORT = 8080  # role of the master's 8080 (reference jobparser.go:249-261)

#: where FT trainer pods keep the persistent XLA compilation cache
#: (jax_compilation_cache_dir, consumed by the multihost world children
#: via EDL_COMPILE_CACHE).  Backed by an emptyDir: every world child the
#: pod's supervisor respawns across membership epochs hits the cache the
#: previous child populated, so the post-reform recompile is paid once
#: per pod instead of once per epoch.  Mount a shared PVC at the same
#: path (spec.trainer.volumes/volume_mounts override the default) to
#: amortize across pods and restarts too.
COMPILE_CACHE_PATH = "/var/edl/compile-cache"
COMPILE_CACHE_VOLUME = "edl-compile-cache"

#: downward-API pod identity (role of the reference's NAMESPACE/POD_IP
#: fieldRefs, pkg/jobparser.go:263-311).  HOSTNAME is NOT a substitute:
#: under spec.host_network it is the node's hostname, so the static
#: path's rank lookup would use the wrong identity.
_DOWNWARD_ENV = [
    {"name": "EDL_POD_NAME",
     "valueFrom": {"fieldRef": {"fieldPath": "metadata.name"}}},
    {"name": "EDL_POD_IP",
     "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}}},
]


def _trainer_labels(job: TrainingJob) -> dict[str, str]:
    labels = {TRAINER_LABEL: job.name}
    if job.spec.trainer.allow_multi_domain:
        # the pod IS the inventory's unit of truth: the label tells the
        # cluster backend not to pin this job to one ICI domain
        labels[MULTI_DOMAIN_LABEL] = "true"
    return labels


def pod_env(job: TrainingJob, role: str) -> dict[str, str]:
    """Environment contract consumed by the elastic runtime entrypoint
    (role of podEnv, reference pkg/jobparser.go:263-311; consumed by
    docker/paddle_k8s + trainers in the reference, by
    edl_tpu.runtime.launcher here)."""
    spec = job.spec
    env = {
        "EDL_JOB_NAME": job.name,
        "EDL_NAMESPACE": job.namespace,
        "EDL_ROLE": role,
        "EDL_FAULT_TOLERANT": "1" if spec.fault_tolerant else "0",
        "EDL_TRAINER_MIN": str(spec.trainer.min_instance),
        "EDL_TRAINER_MAX": str(spec.trainer.max_instance),
        "EDL_PASSES": str(spec.passes),
        "EDL_ENTRY": spec.trainer.entrypoint,
        "EDL_TRAINER_PACKAGE": spec.trainer.workspace,
        # role of ETCD_IP / MASTER_IP discovery (paddle_k8s:119-141): the
        # runtime resolves the coordinator endpoint itself, but a fixed
        # port is part of the contract.
        "EDL_COORD_PORT": str(spec.port or COORDINATOR_PORT),
        "EDL_TPU_CHIPS_PER_TRAINER": str(job.tpu_chips_per_trainer()),
    }
    if spec.fault_tolerant and role == "trainer":
        # Mid-world checkpoint cadence ON by default for deployed FT
        # trainers: the reference's pserver param residency meant a
        # trainer crash never lost global state; the TPU-native
        # equivalent (publish_mid_state) must be armed out of the box or
        # a crash loses everything back to the last membership change.
        # 200 steps ≈ tens of seconds of work at flagship step times;
        # spec.trainer.env (merged below) overrides per job.
        env["EDL_MH_CKPT_EVERY"] = "200"
        # Persistent XLA compilation cache for the elastic path's world
        # children (multihost._world_child reads this): first compile per
        # pod, cache hits on every reform after (see COMPILE_CACHE_PATH).
        env["EDL_COMPILE_CACHE"] = COMPILE_CACHE_PATH
    if spec.trainer.topology is not None:
        env["EDL_TPU_TOPOLOGY"] = str(spec.trainer.topology)
    if spec.master.etcd_endpoint:
        env["EDL_COORD_ENDPOINT"] = spec.master.etcd_endpoint
    elif spec.fault_tolerant:
        # Default endpoint = the coordinator Service's cluster DNS name
        # (role of the MASTER_IP discovery the reference did by polling
        # pods, paddle_k8s:128-129 — a Service is the k8s-idiomatic form).
        env["EDL_COORD_ENDPOINT"] = (
            f"{job.name}-coordinator.{job.namespace}.svc"
            f":{spec.port or COORDINATOR_PORT}")
    if role == "trainer":
        # user env merged LAST — after every generated key, including the
        # topology/endpoint defaults above — so the documented "user
        # values win" contract holds for all of them
        env.update({k: str(v) for k, v in spec.trainer.env.items()})
    return env


def _resources_dict(res) -> dict[str, dict[str, str]]:
    return {
        "requests": {k: str(v) for k, v in res.requests.items()},
        "limits": {k: str(v) for k, v in res.limits.items()},
    }


def parse_to_trainer(job: TrainingJob) -> dict[str, Any]:
    """Trainer group manifest (role of ParseToTrainer,
    reference pkg/jobparser.go:120-165): parallelism starts at min_instance,
    restart-policy Never — failures are survived by elasticity, not pod
    restarts."""
    spec = job.spec
    # user-declared pod-template passthroughs, verbatim (spec parity with
    # real k8s training workloads: datasets on PVCs, /dev/shm tmpfs,
    # private registries) — plus the FT path's compile-cache emptyDir,
    # which a user volume of the same name overrides
    volumes = [dict(v) for v in spec.trainer.volumes]
    mounts = [dict(m) for m in spec.trainer.volume_mounts]
    if spec.fault_tolerant:
        if not any(v.get("name") == COMPILE_CACHE_VOLUME for v in volumes):
            volumes.append({"name": COMPILE_CACHE_VOLUME, "emptyDir": {}})
        if not any(m.get("mountPath") == COMPILE_CACHE_PATH for m in mounts):
            mounts.append({"name": COMPILE_CACHE_VOLUME,
                           "mountPath": COMPILE_CACHE_PATH})
    container = {
        "name": "trainer",
        "image": spec.image,
        # FT jobs take the coordinator-backed elastic
        # path; non-FT jobs take the static barrier
        # path (rank from the sorted pod list) — the
        # reference's start_new_trainer vs start_trainer
        # v2 switch (pkg/jobparser.go:124)
        "command": ["python", "-m",
                    "edl_tpu.runtime.launcher",
                    "start_trainer"
                    if spec.fault_tolerant
                    else "start_static_trainer"],
        "env": [
            {"name": k, "value": v}
            for k, v in pod_env(job, "trainer").items()
        ] + list(_DOWNWARD_ENV),
        "resources": _resources_dict(spec.trainer.resources),
    }
    if mounts:
        container["volumeMounts"] = mounts
    pod_spec: dict[str, Any] = {
        "restartPolicy": "Never",
        "nodeSelector": dict(spec.node_selector),
        "hostNetwork": spec.host_network,
        "containers": [container],
    }
    if volumes:
        pod_spec["volumes"] = volumes
    if spec.trainer.image_pull_secrets:
        pod_spec["imagePullSecrets"] = [
            dict(s) for s in spec.trainer.image_pull_secrets]
    return {
        "kind": "Job",
        "apiVersion": "batch/v1",
        "metadata": {
            "name": f"{job.name}-trainer",
            "namespace": job.namespace,
            "labels": _trainer_labels(job),
        },
        "spec": {
            "parallelism": spec.trainer.min_instance,
            "template": {
                "metadata": {"labels": _trainer_labels(job)},
                "spec": pod_spec,
            },
        },
    }


def parse_to_coordinator(job: TrainingJob) -> dict[str, Any]:
    """Coordinator manifest (role of ParseToMaster,
    reference pkg/jobparser.go:167-227, minus the etcd sidecar — the coord
    service subsumes it)."""
    spec = job.spec
    return {
        "kind": "ReplicaSet",
        "apiVersion": "apps/v1",
        "metadata": {
            "name": f"{job.name}-coordinator",
            "namespace": job.namespace,
            "labels": {COORDINATOR_LABEL: job.name},
        },
        "spec": {
            "replicas": 1,
            "template": {
                "metadata": {
                    "labels": {COORDINATOR_LABEL: job.name},
                    # the health port also serves GET /metrics in
                    # Prometheus text (server.cc): one scrape config
                    # covers coordinators and the controller alike
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/path": "/metrics",
                        "prometheus.io/port": str(HEALTH_PORT),
                    },
                },
                "spec": {
                    "containers": [
                        {
                            "name": "coordinator",
                            "image": spec.image,
                            "command": ["python", "-m", "edl_tpu.coord.server"],
                            "ports": [
                                {"containerPort": spec.port or COORDINATOR_PORT,
                                 "name": "coord"},
                                {"containerPort": HEALTH_PORT, "name": "health"},
                            ],
                            "env": [
                                {"name": k, "value": v}
                                for k, v in pod_env(job, "coordinator").items()
                            ] + [
                                # durability across pod restarts (role of
                                # the reference's etcd sidecar persistence,
                                # pkg/jobparser.go:167-184): write-through
                                # state on the pod volume; swap the
                                # emptyDir for a PVC to also survive node
                                # loss
                                {"name": "EDL_COORD_STATE_FILE",
                                 "value": "/var/edl-coord/state"},
                                # serve GET /healthz on the advertised
                                # health port (role of the master's :8080,
                                # reference docker/paddle_k8s:27-31) — the
                                # probes below point at it
                                {"name": "EDL_HEALTH_PORT",
                                 "value": str(HEALTH_PORT)},
                            ],
                            "volumeMounts": [
                                {"name": "coord-state",
                                 "mountPath": "/var/edl-coord"},
                            ],
                            # a wedged coordinator (accepting but not
                            # answering, or not accepting at all) must be
                            # restarted by the kubelet, not noticed by a
                            # human: the health server runs in the coord
                            # process, so probe failure == process wedge
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz",
                                            "port": HEALTH_PORT},
                                "initialDelaySeconds": 5,
                                "periodSeconds": 10,
                                "timeoutSeconds": 2,
                                "failureThreshold": 3,
                            },
                            "readinessProbe": {
                                "httpGet": {"path": "/healthz",
                                            "port": HEALTH_PORT},
                                "periodSeconds": 5,
                                "timeoutSeconds": 2,
                            },
                            "resources": _resources_dict(spec.master.resources),
                        }
                    ],
                    "volumes": [
                        {"name": "coord-state", "emptyDir": {}},
                    ],
                },
            },
        },
    }


def parse_to_pserver(job: TrainingJob) -> dict[str, Any] | None:
    """Parameter-server manifest (role of ParseToPserver, reference
    pkg/jobparser.go:74-117) — only for migration-mode jobs that request it;
    returns None when the spec leaves the role empty (the TPU-native path)."""
    spec = job.spec
    if spec.pserver.min_instance <= 0:
        return None
    return {
        "kind": "ReplicaSet",
        "apiVersion": "apps/v1",
        "metadata": {
            "name": f"{job.name}-pserver",
            "namespace": job.namespace,
            "labels": {PSERVER_LABEL: job.name},
        },
        "spec": {
            "replicas": spec.pserver.min_instance,
            "template": {
                "metadata": {"labels": {PSERVER_LABEL: job.name}},
                "spec": {
                    "containers": [
                        {
                            "name": "pserver",
                            "image": spec.image,
                            "command": ["python", "-m",
                                        "edl_tpu.runtime.launcher",
                                        "start_pserver"],
                            "env": [
                                {"name": k, "value": v}
                                for k, v in pod_env(job, "pserver").items()
                            ] + list(_DOWNWARD_ENV),
                            "resources": _resources_dict(spec.pserver.resources),
                        }
                    ],
                },
            },
        },
    }


def parse_to_coordinator_service(job: TrainingJob) -> dict[str, Any]:
    """Stable DNS name for the coordinator (role of the master's
    discoverability — the reference resolved the master pod IP by polling,
    paddle_k8s:128-129; a Service is the k8s-idiomatic equivalent and what
    pod_env's default EDL_COORD_ENDPOINT points at)."""
    spec = job.spec
    return {
        "kind": "Service",
        "apiVersion": "v1",
        "metadata": {
            "name": f"{job.name}-coordinator",
            "namespace": job.namespace,
            "labels": {COORDINATOR_LABEL: job.name},
        },
        "spec": {
            "selector": {COORDINATOR_LABEL: job.name},
            "ports": [
                {"name": "coord", "port": spec.port or COORDINATOR_PORT},
                {"name": "health", "port": HEALTH_PORT},
            ],
        },
    }


def parse_to_manifests(job: TrainingJob) -> list[dict[str, Any]]:
    """All worker-group manifests for a job, coordinator first (the
    Gen-2 create order: master → pserver → trainer,
    reference pkg/updater/trainingJobUpdater.go:282-293)."""
    out: list[dict[str, Any]] = []
    if job.spec.fault_tolerant:
        out.append(parse_to_coordinator(job))
        out.append(parse_to_coordinator_service(job))
    ps = parse_to_pserver(job)
    if ps is not None:
        out.append(ps)
    out.append(parse_to_trainer(job))
    return out


# -- ServingJob compilation (doc/serving.md) ---------------------------------

def serving_pod_env(job: ServingJob) -> dict[str, str]:
    """EDL_SERVING_* env contract for server pods — consumed by the
    ``start_server`` launcher verb (runtime/serving.py serve_main), the
    serving twin of :func:`pod_env`.  User env merges LAST so the
    documented "user values win" contract holds."""
    s = job.spec
    env = {
        "EDL_JOB_NAME": job.name,
        "EDL_NAMESPACE": job.namespace,
        "EDL_ROLE": "server",
        "EDL_SERVING_PORT": str(job.port or DEFAULT_SERVING_PORT),
        "EDL_SERVING_MODEL_DIR": s.model_dir,
        "EDL_SERVING_MODEL": s.model,
        "EDL_SERVING_SLO_P99_MS": str(s.slo_p99_ms),
        "EDL_SERVING_MAX_BATCH": str(s.max_batch_size),
        "EDL_SERVING_MAX_QUEUE_MS": str(s.max_queue_ms),
        "EDL_SERVING_DRAIN_S": str(s.drain_timeout_s),
        "EDL_SERVING_RELOAD_POLL_S": str(s.reload_poll_s),
    }
    if s.topology is not None:
        env["EDL_TPU_TOPOLOGY"] = str(s.topology)
    env.update({k: str(v) for k, v in s.env.items()})
    return env


def parse_to_server_group(job: ServingJob) -> dict[str, Any]:
    """Model-server ReplicaSet: ``replicas`` is the elastic dial the SLO
    policy moves (the serving analogue of the trainer Job's
    ``parallelism``).  The READINESS probe is load-bearing — it is the
    ready gate: a replica still compiling its serving step answers
    /healthz 503, the Service holds traffic off it, and the compile
    never rides a request."""
    s = job.spec
    container = {
        "name": "server",
        "image": job.image,
        "command": ["python", "-m", "edl_tpu.runtime.launcher",
                    "start_server"],
        "env": [{"name": k, "value": v}
                for k, v in serving_pod_env(job).items()]
        + list(_DOWNWARD_ENV),
        "ports": [
            {"containerPort": job.port or DEFAULT_SERVING_PORT,
             "name": "serve"},
            {"containerPort": HEALTH_PORT, "name": "health"},
        ],
        "resources": _resources_dict(s.resources),
        "readinessProbe": {
            "httpGet": {"path": "/healthz", "port": HEALTH_PORT},
            "periodSeconds": 2,
            "timeoutSeconds": 2,
        },
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": HEALTH_PORT},
            "initialDelaySeconds": 30,
            "periodSeconds": 10,
            "timeoutSeconds": 2,
            "failureThreshold": 3,
        },
    }
    return {
        "kind": "ReplicaSet",
        "apiVersion": "apps/v1",
        "metadata": {
            "name": f"{job.name}-server",
            "namespace": job.namespace,
            "labels": {SERVING_LABEL: job.name},
        },
        "spec": {
            "replicas": s.min_replicas,
            "template": {
                "metadata": {
                    "labels": {SERVING_LABEL: job.name},
                    "annotations": {
                        "prometheus.io/scrape": "true",
                        "prometheus.io/path": "/metrics",
                        "prometheus.io/port": str(HEALTH_PORT),
                    },
                },
                "spec": {
                    "restartPolicy": "Always",
                    "nodeSelector": dict(job.node_selector),
                    "hostNetwork": job.host_network,
                    "containers": [container],
                },
            },
        },
    }


def parse_to_serving_service(job: ServingJob) -> dict[str, Any]:
    """The traffic front door: a Service over READY server pods — what
    makes the readiness gate an actual traffic gate (an unready replica
    is not an endpoint)."""
    return {
        "kind": "Service",
        "apiVersion": "v1",
        "metadata": {
            "name": f"{job.name}-serve",
            "namespace": job.namespace,
            "labels": {SERVING_LABEL: job.name},
        },
        "spec": {
            "selector": {SERVING_LABEL: job.name},
            "ports": [
                {"name": "serve", "port": job.port or DEFAULT_SERVING_PORT},
                {"name": "health", "port": HEALTH_PORT},
            ],
        },
    }


def parse_serving_manifests(job: ServingJob) -> list[dict[str, Any]]:
    """All manifests for a ServingJob: the replica set + its Service."""
    return [parse_to_server_group(job), parse_to_serving_service(job)]
