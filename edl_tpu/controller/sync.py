"""TrainingJob CRD sync loop — the deployed control plane's watch.

Role of the reference's informer loop (reference pkg/controller.go:79-108:
``cache.NewListWatchFromClient`` + ``cache.NewInformer`` dispatching
onAdd/onUpdate/onDelete) plus the status write-back its Gen-2 updater added
(``updateCRDStatus``, reference pkg/updater/trainingJobUpdater.go:295-307).
This is what makes ``edl-tpu controller`` on a real cluster actually manage
jobs: users ``kubectl apply`` TrainingJob custom objects; the loop diffs
the listed set against the controller's registry and forwards

  new CR          → Controller.submit   (validate → materialize → phases)
  spec changed    → Controller.modify
  CR gone         → Controller.delete   (full teardown)

and each tick writes every job's phase + per-role replica statuses into
the CR's status subresource (only on change), so ``kubectl get tj`` shows
the lifecycle the way the reference's CRD printer columns did.

Two watch modes share the same diff/dispatch core:

* **poll-list** (default off the deployed path's critical sections, and
  the fallback everywhere): a full LIST each tick; the diff is driven
  purely by listed spec content, not resourceVersion bookkeeping, so a
  missed tick never loses an event — the next tick sees the same truth.
* **streaming watch** (``watch=True``; the reference informer's
  event-driven ListWatch, pkg/controller.go:87-107): a LIST anchors a
  resourceVersion, then watch events drive add/update/delete with no
  O(cluster) LIST per tick.  The stream is re-anchored by a fresh LIST
  on any error — including 410 Gone after apiserver compaction — and a
  periodic full resync (every ``resync_every`` windows) keeps the orphan
  sweep and any missed-event drift bounded, which is exactly the
  re-list discipline a production informer follows.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional, Protocol

from edl_tpu.api.serde import manifest_from_dict, status_to_dict
from edl_tpu.api.types import JobPhase, ServingJob, TrainingJob
from edl_tpu.controller.controller import Controller
from edl_tpu.observability.logging import get_logger

log = get_logger("crd-sync")


class TrainingJobStore(Protocol):
    """The CR surface the loop needs (K8sCluster implements it; the test
    stub's CustomObjectsApi backs it)."""

    def list_training_job_crs(self) -> list[dict]: ...

    def patch_training_job_status(self, name: str, status: dict,
                                  namespace: str | None = None) -> bool: ...


class TrainingJobSyncLoop:
    """Diff-based CR → controller synchronizer with status write-back."""

    def __init__(
        self,
        store: TrainingJobStore,
        controller: Controller,
        poll_seconds: float = 5.0,
        gc_orphans: bool = True,
        orphan_grace_ticks: int = 3,
        watch: bool = False,
        resync_every: int = 6,
    ) -> None:
        self.store = store
        self.controller = controller
        self.poll_seconds = poll_seconds
        #: True → consume streaming watch events between full LISTs.
        #: A store with no watch surface gets poll-list cadence outright —
        #: staying in "watch mode" against such a store would silently
        #: stretch reconcile latency from poll_seconds to
        #: resync_every*poll_seconds with no events ever arriving.
        if watch and getattr(store, "watch_training_job_crs", None) is None:
            log.warn("store has no watch surface; using poll-list cadence")
            watch = False
        self.watch = watch
        #: full LIST resync after this many watch windows (window length
        #: = poll_seconds), bounding sweep latency and any event drift
        self.resync_every = max(1, resync_every)
        #: resourceVersion of the last LIST (anchors the watch stream)
        self._last_rv: Optional[str] = None
        #: False → the orphan sweep only logs, never deletes (operator
        #: opt-out for clusters where other tooling shares the job label)
        self.gc_orphans = gc_orphans
        #: a group must be CR-less for this many CONSECUTIVE ticks before
        #: teardown — never on the first tick after controller start, so a
        #: transient LIST miss or a CR created moments after its resources
        #: cannot destroy running training work irreversibly.  The clamp
        #: floor of 2 enforces that invariant even for --orphan-grace-ticks 1
        self.orphan_grace_ticks = max(2, orphan_grace_ticks)
        #: (ns, name) → consecutive ticks observed CR-less
        self._orphan_strikes: dict[tuple[str, str], int] = {}
        #: uid → the spec dict we last acted on (change detection; spec
        #: content, not resourceVersion, so replays are harmless)
        self._seen_specs: dict[str, Any] = {}
        #: uid → job object handed to the controller (delete needs it)
        self._jobs: dict[str, TrainingJob] = {}
        #: uid → last status dict written to the CR (write only on change,
        #: reference trainingJobUpdater.go:295-307)
        self._written_status: dict[str, dict] = {}
        #: uid → (monotonic deadline before which no retry, current delay):
        #: per-job exponential backoff with jitter on failed status patches,
        #: so one job whose PATCH 500s doesn't get hammered every window
        #: while healthy jobs proceed (the reference informer's rate-limited
        #: workqueue discipline, pkg/controller.go:87-107)
        self._patch_backoff: dict[str, tuple[float, float]] = {}
        self.patch_backoff_base_s = 1.0
        self.patch_backoff_cap_s = 60.0
        #: uid → spec dict rejected by validation (retry only when the
        #: user edits the spec, not every tick)
        self._rejected_specs: dict[str, Any] = {}
        #: uid → reason a spec EDIT was rejected while the job keeps
        #: running under its last valid spec (surfaced via status.reason)
        self._rejected_update_reason: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="trainingjob-sync")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def is_alive(self) -> bool:
        """Liveness of the background loop — the /healthz probe truth."""
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        windows = 0
        while not self._stop.is_set():
            if (not self.watch or self._last_rv is None
                    or windows % self.resync_every == 0):
                try:
                    self.run_once()
                except Exception as exc:  # LIST failure must not kill the loop
                    log.error("sync tick failed", error=str(exc))
            if self.watch and self._last_rv is not None:
                try:
                    self._watch_window(self.poll_seconds)
                except Exception as exc:
                    # 410 Gone (compaction), dropped connection, anything:
                    # the informer answer is a fresh LIST re-anchor
                    log.warn("watch stream failed; re-listing",
                             error=str(exc))
                    self._last_rv = None
                # phase transitions happen without CR events (pods coming
                # ready); flush recorded statuses every window
                self._write_back_statuses()
            else:
                self._stop.wait(self.poll_seconds)
            windows += 1

    def _watch_window(self, seconds: float) -> None:
        """Consume watch events for one window.

        The stream normally ends at its server-side timeout, but a proxy
        or apiserver may close it early (idle-close, EOF-after-open);
        sleeping out the remainder of the window keeps the loop paced —
        without it an early-closing connection turns the controller into
        a hot loop of watch requests (review r4)."""
        t0 = time.monotonic()
        try:
            stream = getattr(self.store, "watch_training_job_crs", None)
            if stream is None:  # store has no watch surface: stay poll-list
                return
            for ev in stream(self._last_rv,
                             timeout_seconds=max(1, int(seconds))):
                if self._stop.is_set():
                    return
                self._handle_event(ev)
        finally:
            remaining = seconds - (time.monotonic() - t0)
            if remaining > 0 and not self._stop.is_set():
                self._stop.wait(remaining)

    def _handle_event(self, ev: dict) -> None:
        typ = ev.get("type")
        cr = ev.get("object") or {}
        meta = cr.get("metadata") or {}
        name = meta.get("name", "")
        if not name:
            return
        uid = f"{meta.get('namespace', 'default')}/{name}"
        rv = meta.get("resourceVersion")
        if rv:
            self._last_rv = str(rv)
        try:
            if typ == "DELETED":
                if uid in self._seen_specs or uid in self._jobs:
                    self._on_delete(uid)
                self._rejected_specs.pop(uid, None)
                self._written_status.pop(uid, None)
            elif typ in ("ADDED", "MODIFIED"):
                spec = cr.get("spec") or {}
                if uid not in self._seen_specs:
                    self._on_add(uid, cr, spec)
                elif spec != self._seen_specs[uid]:
                    self._on_update(uid, cr, spec)
        except Exception as exc:  # one CR must never kill the stream
            log.error("watch event dispatch failed", job=uid,
                      error=str(exc))

    # -- one reconcile tick ------------------------------------------------

    def run_once(self) -> None:
        """One list → diff → dispatch → status write-back pass.  Both
        job kinds ride the same diff: ServingJob CRs (when the store
        exposes ``list_serving_job_crs``) are listed alongside
        TrainingJobs each tick — the watch stream covers training CRs
        only, so serving reconcile latency is bounded by the periodic
        LIST, which every tick here is."""
        lister = getattr(self.store, "list_training_job_crs_with_rv", None)
        if lister is not None:
            items, rv = lister()
            self._last_rv = rv or None
        else:
            items = self.store.list_training_job_crs()
        serving_lister = getattr(self.store, "list_serving_job_crs", None)
        serving_items: list[dict] = []
        if serving_lister is not None:
            # NO try/except: like the training LIST above, a failed
            # serving LIST must abort the whole tick (caught by _run's
            # tick guard).  Swallowing it would leave every registered
            # ServingJob out of `listed`, and the delete pass below
            # would tear down live fleets — and permanently sweep their
            # job-scoped coordinator KV — on a single apiserver blip.
            serving_items = [dict(cr, kind=cr.get("kind", "ServingJob"))
                             for cr in serving_lister()]
        listed: dict[str, dict] = {}
        for cr in list(items) + serving_items:
            meta = cr.get("metadata") or {}
            name = meta.get("name", "")
            if not name:
                continue
            ns = meta.get("namespace", "default")
            uid = f"{ns}/{name}"
            if uid in listed:
                # a TrainingJob and a ServingJob may legally share a
                # name across their two CRDs, but the controller keys
                # jobs by ns/name — adopting the second kind would
                # repoint the first kind's updater at the wrong object.
                # First listed (training) wins; say so loudly.
                log.error("CR kind collision: this uid is already "
                          "managed by another kind; the later CR is "
                          "IGNORED — rename one of them",
                          job=uid,
                          kept=listed[uid].get("kind", "TrainingJob"),
                          ignored=cr.get("kind", "ServingJob"))
                continue
            listed[uid] = cr

        for uid, cr in listed.items():
            spec = cr.get("spec") or {}
            try:
                if uid not in self._seen_specs:
                    self._on_add(uid, cr, spec)
                elif spec != self._seen_specs[uid]:
                    self._on_update(uid, cr, spec)
            except Exception as exc:
                # One CR must never block the tick for every other CR —
                # the delete pass, orphan sweep and status write-back
                # below run regardless (the _on_* handlers already treat
                # any parse/validate failure as a recorded rejection; this
                # guard catches what they could not foresee).
                log.error("CR dispatch failed", job=uid, error=str(exc))

        for uid in list(self._seen_specs):
            if uid not in listed:
                self._on_delete(uid)
        for uid in list(self._rejected_specs):
            if uid not in listed:  # a rejected CR deleted without ever
                self._rejected_specs.pop(uid, None)  # becoming a job
                self._written_status.pop(uid, None)

        self._sweep_orphans(listed)
        self._write_back_statuses(listed)

    def _sweep_orphans(self, listed: dict[str, dict]) -> None:
        """Tear down trainer groups whose CR no longer exists — a
        `kubectl delete tj` issued while the controller was down leaves
        resources no in-memory diff can see (the restart-blind spot of
        the reference's informer too; its del_jobs.sh was the manual
        fix).  On the CRD-driven control plane the CR is the source of
        truth, so a group without a CR is garbage.  Cluster-wide, to
        match the cluster-wide CR watch.

        Deletion is irreversible, so three guards apply: jobs the
        in-process controller registry manages (the pre-CR submit flow)
        are never candidates; a candidate must stay CR-less for
        ``orphan_grace_ticks`` consecutive ticks (log-only until then);
        and ``gc_orphans=False`` turns the sweep into pure logging."""
        lister = getattr(self.store, "list_trainer_groups", None)
        deleter = getattr(self.store, "delete_resources", None)
        if lister is None or deleter is None:
            return
        cr_pairs = {tuple(uid.split("/", 1)) for uid in listed}
        managed = {tuple(uid.split("/", 1)) for uid in self._jobs}
        # jobs submitted in-process (Controller.submit without a CR —
        # tests, demos, legacy tooling) are owned work, not garbage
        managed |= {(j.namespace, j.name) for j in self.controller.jobs()}
        try:
            groups = set(lister())
        except Exception as exc:
            log.error("orphan sweep list failed", error=str(exc))
            return
        candidates = groups - cr_pairs - managed
        # a group that regained its CR (or vanished) resets its strikes
        for pair in list(self._orphan_strikes):
            if pair not in candidates:
                del self._orphan_strikes[pair]
        for ns, name in sorted(candidates):
            strikes = self._orphan_strikes.get((ns, name), 0) + 1
            self._orphan_strikes[(ns, name)] = strikes
            if strikes < self.orphan_grace_ticks:
                log.warn("orphaned job resources (no CR); will tear down "
                         "if still orphaned",
                         job=f"{ns}/{name}",
                         strike=f"{strikes}/{self.orphan_grace_ticks}")
                continue
            if not self.gc_orphans:
                log.warn("orphaned job resources (no CR); gc disabled, "
                         "leaving in place", job=f"{ns}/{name}")
                continue
            log.warn("tearing down orphaned job resources (no CR)",
                     job=f"{ns}/{name}")
            try:
                deleter(TrainingJob(name=name, namespace=ns))
                del self._orphan_strikes[(ns, name)]
            except Exception as exc:
                log.error("orphan teardown failed", job=f"{ns}/{name}",
                          error=str(exc))

    def _on_add(self, uid: str, cr: dict, spec: Any) -> None:
        if self._rejected_specs.get(uid) == spec:
            return  # unchanged invalid spec: don't re-reject every tick
        try:
            job = manifest_from_dict(cr)
            self.controller.submit(job)
        except Exception as exc:
            # Any failure to turn an arbitrary user dict into a registered
            # job is a spec rejection (the CRD schema's
            # x-kubernetes-preserve-unknown-fields admits shapes the
            # parser cannot — a string where a map belongs raises
            # AttributeError, an explicit null TypeError; all of them must
            # land in the CR status, not in a crash-looping tick).
            # surface the rejection where the user submitted it
            log.warn("TrainingJob rejected", job=uid, error=str(exc))
            self._rejected_specs[uid] = spec
            meta = cr.get("metadata") or {}
            self._patch_status(uid, {
                "phase": JobPhase.FAILED.value,
                "reason": f"invalid spec: {exc}",
                "replica_statuses": [],
            }, name=meta.get("name", ""),
                namespace=meta.get("namespace", "default"),
                serving=cr.get("kind") == "ServingJob")
            return
        self._rejected_specs.pop(uid, None)
        self._seen_specs[uid] = spec
        self._jobs[uid] = job
        log.info("TrainingJob added", job=uid)

    def _on_update(self, uid: str, cr: dict, spec: Any) -> None:
        try:
            job = manifest_from_dict(cr)
            self.controller.modify(job)
        except Exception as exc:  # same rejection surface as _on_add
            # Keep managing the last valid spec, but (a) record the spec so
            # the rejection isn't re-logged every tick and (b) surface the
            # reason in the CR status — the user must see the edit was
            # rejected where they submitted it.
            log.warn("TrainingJob update rejected", job=uid, error=str(exc))
            self._seen_specs[uid] = spec
            self._rejected_update_reason[uid] = str(exc)
            return
        self._rejected_update_reason.pop(uid, None)
        self._seen_specs[uid] = spec
        self._jobs[uid] = job
        log.info("TrainingJob updated", job=uid)

    def _on_delete(self, uid: str) -> None:
        job = self._jobs.pop(uid, None)
        self._seen_specs.pop(uid, None)
        self._written_status.pop(uid, None)
        self._patch_backoff.pop(uid, None)
        self._rejected_specs.pop(uid, None)
        self._rejected_update_reason.pop(uid, None)
        if job is not None:
            try:
                self.controller.delete(job)
            except Exception as exc:
                log.error("teardown failed", job=uid, error=str(exc))
        log.info("TrainingJob deleted", job=uid)

    # -- status write-back -------------------------------------------------

    def _write_back_statuses(self,
                             listed: Optional[dict[str, dict]] = None
                             ) -> None:
        """Record every managed job's phase into its CR status.  ``listed``
        (the LIST path) restricts to CRs seen this tick; the watch path
        passes None and patches by the registry's name/namespace — a CR
        deleted under us patches as a 404 no-op until the DELETED event
        or the next resync cleans the registry."""
        for uid, job in self._jobs.items():
            if listed is not None and uid not in listed:
                continue
            updater = self.controller.get_updater(job)
            if updater is None:
                continue
            status = status_to_dict(updater.job.status)
            reason = self._rejected_update_reason.get(uid)
            if reason is not None:
                status["reason"] = (f"spec update rejected: {reason}; "
                                    "running with last valid spec")
            self._patch_status(uid, status, name=job.name,
                               namespace=job.namespace,
                               serving=isinstance(job, ServingJob))

    def _patch_status(self, uid: str, status: dict, *, name: str,
                      namespace: str, serving: bool = False) -> None:
        if self._written_status.get(uid) == status:
            return
        patch = self.store.patch_training_job_status
        if serving:
            patch = getattr(self.store, "patch_serving_job_status", None)
            if patch is None:  # store predates the serving kind
                return
        deadline, delay = self._patch_backoff.get(uid, (0.0, 0.0))
        now = time.monotonic()
        if now < deadline:
            return  # this job is backing off; others are unaffected
        try:
            if patch(name, status, namespace=namespace):
                self._written_status[uid] = status
            self._patch_backoff.pop(uid, None)
        except Exception as exc:
            # exponential backoff + jitter; the in-memory phase machine is
            # unaffected and the patch retries once the deadline passes
            import random

            delay = min(self.patch_backoff_cap_s,
                        max(self.patch_backoff_base_s, delay * 2))
            jittered = delay * (0.5 + random.random() * 0.5)
            self._patch_backoff[uid] = (now + jittered, delay)
            log.error("status write-back failed; backing off", job=uid,
                      error=str(exc), retry_in_s=round(jittered, 2))
