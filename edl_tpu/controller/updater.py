"""Per-job lifecycle actor.

Port of the reference's Gen-2 updater — the richer, corrected design the
reference wrote but never wired in (reference
pkg/updater/trainingJobUpdater.go:19-481; SURVEY §0 "Gen-2"):

* one actor (thread) per job, fed by a bounded event queue with a
  near-full warning (reference :19-25, 80-86);
* ``init_resource`` drives None → Creating → Running: validate, create
  worker groups, wait until the minimum trainer cohort is Running with a
  confirm ticker (reference :209-257, 417-449);
* a periodic ``convert`` tick recomputes the phase from live pod counts —
  a fault-tolerant job fails only when **all** trainers have failed, a
  non-FT job when **any** has; succeeded when a pod succeeded and none are
  active (reference :343-382, 385-414);
* terminal phases release the job's resources and stop the ticker
  (reference :400-412, 471-478).
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from edl_tpu.api.types import (
    JobPhase,
    ResourceState,
    TrainingJob,
    TrainingResourceStatus,
)
from edl_tpu.api.validation import ValidationError, validate_any
from edl_tpu.cluster.base import Cluster, PodPhase
from edl_tpu.observability.logging import get_logger

EVENT_QUEUE_SIZE = 1000  # reference trainingJobUpdater.go:19-25
CONVERT_SECONDS = 10.0  # reference trainingJobUpdater.go:22 (10 s Convert tick)
CONFIRM_SECONDS = 5.0  # reference trainingJobUpdater.go:24 (5 s ready confirm)
CREATE_TIMEOUT_SECONDS = 120.0

log = get_logger("updater")

#: cluster pod-role name → TrainingResourceType (reference
#: pkg/apis/paddlepaddle/v1/types.go:139-147).
ROLE_TYPES = (("master", "MASTER"), ("pserver", "PSERVER"),
              ("trainer", "TRAINER"), ("server", "SERVER"))

_POD_TO_RESOURCE_STATE = {
    PodPhase.PENDING: ResourceState.STARTING,
    PodPhase.RUNNING: ResourceState.RUNNING,
    PodPhase.SUCCEEDED: ResourceState.SUCCEEDED,
    PodPhase.FAILED: ResourceState.FAILED,
    PodPhase.TERMINATING: ResourceState.NONE,
    PodPhase.UNKNOWN: ResourceState.NONE,
}


def compute_replica_statuses(cluster: Cluster, job_uid: str
                             ) -> list[TrainingResourceStatus]:
    """Per-role, per-pod states from live pods (the detail the reference
    declares in TrainingResourceStatus, pkg/apis/paddlepaddle/v1/
    types.go:154-162, and fills from the updater).  Shared by the updater
    (which writes it into job.status each convert tick) and the CLI's
    ``status`` verb (which computes the same view statelessly).

    One LIST for the whole job, bucketed by role client-side — per-role
    LISTs would be 3 API calls per convert tick per job on a live
    apiserver."""
    by_role: dict[str, list] = {}
    for p in cluster.list_pods(job_uid=job_uid):
        by_role.setdefault(p.role, []).append(p)
    statuses: list[TrainingResourceStatus] = []
    for role, rtype in ROLE_TYPES:
        if role == "server" and role not in by_role:
            # the serving role only reports when it exists: a
            # TrainingJob's status keeps its historical three rows, a
            # ServingJob grows its SERVER row from live pods
            continue
        states = {
            p.name: _POD_TO_RESOURCE_STATE.get(p.phase, ResourceState.NONE)
            for p in by_role.get(role, ())
        }
        vals = list(states.values())
        if not vals:
            agg = ResourceState.NONE
        elif all(s == ResourceState.SUCCEEDED for s in vals):
            agg = ResourceState.SUCCEEDED
        elif any(s == ResourceState.RUNNING for s in vals):
            agg = ResourceState.RUNNING
        elif any(s == ResourceState.STARTING for s in vals):
            agg = ResourceState.STARTING
        elif any(s == ResourceState.FAILED for s in vals):
            agg = ResourceState.FAILED
        else:
            agg = ResourceState.NONE
        statuses.append(TrainingResourceStatus(
            resource_type=rtype, state=agg, resource_states=states))
    return statuses


class TrainingJobUpdater:
    """Actor owning one job's lifecycle, from creation to teardown."""

    def __init__(
        self,
        job: TrainingJob,
        cluster: Cluster,
        convert_seconds: float = CONVERT_SECONDS,
        confirm_seconds: float = CONFIRM_SECONDS,
        create_timeout: float = CREATE_TIMEOUT_SECONDS,
        auto_start: bool = True,
    ) -> None:
        self.job = job
        self.cluster = cluster
        self.convert_seconds = convert_seconds
        self.confirm_seconds = confirm_seconds
        self.create_timeout = create_timeout
        self._events: "queue.Queue[str]" = queue.Queue(maxsize=EVENT_QUEUE_SIZE)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._released = False
        if auto_start:
            self.start()

    # -- public API (role of Modify/Delete/notify, reference :78-97) -------

    @property
    def phase(self) -> JobPhase:
        return self.job.status.phase

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"updater-{self.job.full_name}"
        )
        self._thread.start()

    def notify_delete(self) -> None:
        self._notify("delete")

    def modify(self, job: TrainingJob) -> None:
        self.job.spec = job.spec
        self._notify("modify")

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        self._stop.set()
        self.join(timeout=5)

    # -- lifecycle ---------------------------------------------------------

    def init_resource(self) -> None:
        """None → Creating → Running|Failed (reference :417-449)."""
        try:
            validate_any(self.job)  # kind-dispatching: training OR serving
        except ValidationError as exc:
            self._set_phase(JobPhase.FAILED, f"invalid spec: {exc}")
            return

        self._set_phase(JobPhase.CREATING)
        try:
            self.cluster.create_resources(self.job)
        except Exception as exc:
            self._set_phase(JobPhase.FAILED, f"create failed: {exc}")
            return

        # Wait for the minimum cohort, confirming on a ticker
        # (role of createResource's ReadyReplicas==Replicas wait, :209-257).
        # The wait also services delete events so a teardown of a
        # still-CREATING job doesn't dangle until the create timeout.
        deadline = self._now() + self.create_timeout
        while not self._stop.is_set():
            try:
                counts = self.cluster.job_pods(self.job)
            except Exception as exc:  # transient inventory error: keep waiting
                log.error("ready-wait: job_pods failed",
                          job=self.job.full_name, error=str(exc))
                counts = None
            min_replicas = self.job.group_range()[0]
            if counts is not None:
                if counts.running >= min_replicas:
                    self._refresh_replica_statuses()
                    self._set_phase(JobPhase.RUNNING)
                    return
                if self._now() > deadline:
                    self._set_phase(
                        JobPhase.FAILED,
                        f"timed out waiting for {min_replicas}"
                        f" running replicas (have {counts.running})",
                    )
                    self._release()
                    return
            try:
                evt = self._events.get(timeout=self.confirm_seconds)
            except queue.Empty:
                continue
            if evt == "delete":
                self.delete()
                return

    def convert(self) -> None:
        """Recompute phase + per-role replica statuses from live pods
        (reference :343-414 and the Gen-2 TrainingResourceStatus detail
        nothing populated in round 1)."""
        if self.phase not in (JobPhase.RUNNING, JobPhase.SCALING):
            return
        try:
            counts = self.cluster.job_pods(self.job)
        except Exception as exc:
            log.error("convert: job_pods failed", job=self.job.full_name,
                      error=str(exc))
            return
        self._refresh_replica_statuses()

        active = counts.running + counts.pending
        if self.job.replaceable_on_failure():
            # FT trainers / serving replicas: failed only when ALL
            # replicas have failed (reference :359-368)
            if counts.failed > 0 and active == 0 and counts.succeeded == 0:
                self._set_phase(JobPhase.FAILED, "all replicas failed")
                self._release()
                return
        else:
            # non-FT: any failure is fatal (reference :370-380)
            if counts.failed > 0:
                self._set_phase(JobPhase.FAILED,
                                f"{counts.failed} trainer(s) failed")
                self._release()
                return
        if counts.succeeded > 0 and active == 0:
            self._set_phase(JobPhase.SUCCEEDED)
            self._release()
            return
        # Resize in flight (the TPU addition to the reference's phases):
        # the autoscaler rewrote the desired parallelism and the pod set
        # hasn't caught up — surface it so operators can tell "scaling"
        # from "steady" (kubectl-visible, like the reference's phases).
        # Only when the count gap is actually a resize: early successes
        # (wind-down) and FT failure recovery also diverge running from
        # desired and must keep their own phase/reason.
        if counts.succeeded > 0 or counts.failed > 0:
            return
        try:
            desired = self.cluster.get_trainer_parallelism(self.job)
        except Exception as exc:
            # keep the current phase, but a persistent fault (e.g. the
            # trainer group deleted out-of-band) must not be silent
            log.error("convert: get_trainer_parallelism failed",
                      job=self.job.full_name, error=str(exc))
            return
        if counts.running != desired:
            self._set_phase(
                JobPhase.SCALING,
                f"replicas {counts.running} -> {desired}")
        else:
            self._set_phase(JobPhase.RUNNING)

    def delete(self) -> None:
        """Full teardown (reference deleteTrainingJob, :99-207)."""
        self._release()
        self._stop.set()

    # -- actor loop --------------------------------------------------------

    def _run(self) -> None:
        try:
            self.init_resource()
        except Exception as exc:  # never let the actor die silently
            log.error("init_resource crashed", job=self.job.full_name,
                      error=str(exc))
            self._set_phase(JobPhase.FAILED, f"init error: {exc}")
            self._release()
            return
        while not self._stop.is_set() and not self.phase.terminal():
            try:
                evt = self._events.get(timeout=self.convert_seconds)
            except queue.Empty:
                self.convert()  # the 10 s Convert ticker (reference :460-480)
                continue
            if evt == "delete":
                self.delete()
                return
            if evt == "modify":
                self.convert()

    def _notify(self, evt: str) -> None:
        # near-full warning (reference :80-86)
        if self._events.qsize() > EVENT_QUEUE_SIZE * 0.9:
            log.warn("event queue near full", job=self.job.full_name,
                     qsize=self._events.qsize())
        try:
            self._events.put_nowait(evt)
        except queue.Full:
            log.error("event queue full, dropping event",
                      job=self.job.full_name, event=evt)

    def _refresh_replica_statuses(self) -> None:
        """Status DETAIL only — a failure here must never block the phase
        machine (the CRD's phase is load-bearing; replica_statuses is
        operator information)."""
        try:
            self.job.status.replica_statuses = compute_replica_statuses(
                self.cluster, self.job.full_name)
        except Exception as exc:
            log.warn("replica-status refresh failed",
                     job=self.job.full_name, error=str(exc))

    def _release(self) -> None:
        """Release the job's cluster resources once (role of
        releaseResource/deleteTrainingJob, reference :99-207, 400-412)."""
        if self._released:
            return
        self._released = True
        try:
            self.cluster.delete_resources(self.job)
        except Exception as exc:
            log.error("release failed", job=self.job.full_name, error=str(exc))

    def _set_phase(self, phase: JobPhase, reason: str = "") -> None:
        if self.job.status.phase != phase:  # write only on change (:295-307)
            log.info("job phase", job=self.job.full_name,
                     phase=phase.value, reason=reason)
        self.job.status.phase = phase
        self.job.status.reason = reason

    @staticmethod
    def _now() -> float:
        import time

        return time.monotonic()
