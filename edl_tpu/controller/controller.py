"""The controller: job registry → per-job actors + autoscaler feed.

Role of the reference's Gen-1 controller (reference pkg/controller.go:44-161)
with the Gen-2 per-job-actor design it was migrating toward (SURVEY §0):
where the reference watches a k8s informer, this controller exposes an
explicit ``submit``/``modify``/``delete`` API (the local/in-process
equivalent of TrainingJob CRUD) and fans events out to

* a :class:`TrainingJobUpdater` actor per job (lifecycle, phases,
  ready-confirmation, teardown — the Gen-2 semantics), and
* the :class:`~edl_tpu.scheduler.autoscaler.Autoscaler` (elastic planning —
  the Gen-1 semantics),

fixing the Gen-1 quirks: resources are created via the updater only after
validation, and master/pserver groups are only created when the spec calls
for them (contrast reference pkg/controller.go:134-141, which creates both
unconditionally and never validates).
"""

from __future__ import annotations

import threading
from typing import Optional

from edl_tpu.api.types import JobPhase, ServingJob, TrainingJob
from edl_tpu.api.validation import ValidationError, validate_any
from edl_tpu.cluster.base import Cluster
from edl_tpu.controller.updater import TrainingJobUpdater
from edl_tpu.observability.logging import get_logger
from edl_tpu.scheduler.autoscaler import Autoscaler, ServingScaler
from edl_tpu.scheduler.topology import SliceShapePolicy, UNIT_POLICY

log = get_logger("controller")


class Controller:
    """One per cluster; owns the autoscaler and all job actors."""

    def __init__(
        self,
        cluster: Cluster,
        max_load_desired: float = 0.97,  # reference default (cmd/edl/edl.go:19)
        shape_policy: SliceShapePolicy = UNIT_POLICY,
        autoscaler_loop_seconds: float = 5.0,
        updater_convert_seconds: float = 10.0,
        updater_confirm_seconds: float = 5.0,
        resize_cooldown_s: float = 0.0,
        min_resize_delta: int = 1,
        mesh_shape_for=None,
        goodput_curves=None,
        goodput_objective: bool = True,
        serving_stats_for=None,
        serving_loop_seconds: float = 2.0,
        coord_for=None,
        scraper=None,
        scrape_window_s: float = 10.0,
    ) -> None:
        self.cluster = cluster
        #: the packing objective (doc/scheduling.md): default ON, chips
        #: are granted by marginal goodput whenever ``goodput_curves``
        #: resolves a measured ScalingCurve — priorities, preemption and
        #: gang placement included; with no curve source (or flag off)
        #: the reference count-based packing rules unchanged
        self.goodput_objective = goodput_objective
        self.autoscaler = Autoscaler(
            cluster,
            max_load_desired=max_load_desired,
            shape_policy=shape_policy,
            loop_seconds=autoscaler_loop_seconds,
            resize_cooldown_s=resize_cooldown_s,
            min_resize_delta=min_resize_delta,
            mesh_shape_for=mesh_shape_for,
            goodput_curves=goodput_curves,
            goodput_objective=goodput_objective,
        )
        #: the scrape plane (observability/scrape.py): when a
        #: MetricsScraper is handed in (the ``edl-tpu controller
        #: --scrape-targets/--scrape-coord`` flags build one), the
        #: controller owns its lifecycle, rolls it up through a
        #: FleetView, and feeds the serving scaler FROM SCRAPED REPLICA
        #: /metrics — the deployed signal path (ROADMAP #4's
        #: observability half).  ``serving_stats_for`` remains the
        #: in-process test seam and wins when explicitly given.
        self.scraper = scraper
        self.fleet_view = None
        if scraper is not None:
            from edl_tpu.observability.scrape import FleetView

            self.fleet_view = FleetView(scraper, window_s=scrape_window_s)
            if serving_stats_for is None:
                serving_stats_for = self.fleet_view.stats_for
        #: SLO-driven replica scaling for ServingJob kinds — fed by
        #: ``serving_stats_for(uid)`` (windowed p50/p99/qps; scraped
        #: from replica /metrics in a deployment via the FleetView
        #: above, read off the in-process fleet in the harness),
        #: actuating the same cluster replica-group dial the trainer
        #: autoscaler uses
        #: optional ``coord_for(job) -> kv-client | None`` hook: on job
        #: deletion the controller sweeps the job's coordinator KV
        #: (goodput curve, vw map/cursors, serving generation —
        #: edl_tpu.coord.gc.JOB_KV_PREFIXES); without it those keys
        #: outlive the job on any shared coordinator.  The serving
        #: scaler also records each fleet's measured QPS-capacity curve
        #: through it (goodput-curve/<job>), feeding chip arbitration.
        self.coord_for = coord_for
        self.serving_scaler = ServingScaler(
            cluster=cluster,
            stats_for=serving_stats_for,
            loop_seconds=serving_loop_seconds,
            coord_for=coord_for,
        )
        self._updater_convert_seconds = updater_convert_seconds
        self._updater_confirm_seconds = updater_confirm_seconds
        self._updaters: dict[str, TrainingJobUpdater] = {}
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Run the scaling loops in the background
        (role of Controller.Run, reference pkg/controller.go:64-76)."""
        if self.scraper is not None:
            self.scraper.start()
        self.autoscaler.start()
        self.serving_scaler.start()

    def stop(self) -> None:
        self.autoscaler.stop()
        self.serving_scaler.stop()
        if self.scraper is not None:
            self.scraper.stop()
        with self._lock:
            updaters = list(self._updaters.values())
        for u in updaters:
            u.stop()

    # -- TrainingJob CRUD (role of onAdd/onUpdate/onDelete,
    #    reference pkg/controller.go:110-161) ------------------------------

    def submit(self, job: "TrainingJob | ServingJob") -> TrainingJobUpdater:
        """Validate, spawn the job's actor, register with the matching
        scaler (trainer autoscaler for TrainingJob, the SLO policy for
        ServingJob — the updater lifecycle actor is shared)."""
        validate_any(job)  # raises ValidationError on bad spec
        with self._lock:
            if job.full_name in self._updaters:
                raise ValidationError(f"job {job.full_name} already submitted")
            updater = TrainingJobUpdater(
                job,
                self.cluster,
                convert_seconds=self._updater_convert_seconds,
                confirm_seconds=self._updater_confirm_seconds,
            )
            self._updaters[job.full_name] = updater
        if isinstance(job, ServingJob):
            self.serving_scaler.on_add(job)
            if self._arbitrated(job):
                # train+serve chip arbitration (doc/scheduling.md): an
                # elastic chip-holding fleet's replica dial is owned by
                # the goodput planner — its measured QPS-capacity curve
                # (recorded by the serving scaler from FleetView data)
                # is priced in the same marginal loop as every trainer's
                # scaling curve, so a saturated fleet outbids a
                # flat-curve trainer for the next chip.  The SLO policy
                # keeps observing and prewarm-hinting, but stops dialing.
                self.autoscaler.on_add(job)
                self.serving_scaler.observe_only.add(job.full_name)
        else:
            self.autoscaler.on_add(job)
        log.info("job submitted", job=job.full_name,
                 kind=type(job).__name__)
        return updater

    def _arbitrated(self, job: ServingJob) -> bool:
        """True when this serving fleet's chips are arbitrated by the
        goodput planner rather than dialed by the SLO policy alone."""
        return (self.goodput_objective
                and self.autoscaler.goodput_curves is not None
                and job.need_tpu() and job.elastic())

    def modify(self, job: "TrainingJob | ServingJob") -> None:
        validate_any(job)  # same gate as submit
        with self._lock:
            updater = self._updaters.get(job.full_name)
        if updater is None:
            raise KeyError(f"job {job.full_name} not found")
        if isinstance(job, ServingJob):
            updater.modify(job)
            self.serving_scaler.on_update(job)
            # reconcile arbitration ownership: a spec change can flip
            # eligibility (e.g. min==max made elastic, or the reverse) —
            # exactly one loop may own the replica dial afterwards
            was = job.full_name in self.serving_scaler.observe_only
            now = self._arbitrated(job)
            if now and not was:
                self.autoscaler.on_add(job)
                self.serving_scaler.observe_only.add(job.full_name)
            elif was and not now:
                self.autoscaler.on_del(job)
                self.serving_scaler.observe_only.discard(job.full_name)
            elif now:
                self.autoscaler.on_update(job)
            return
        old = updater.job.spec
        if old.trainer.allow_multi_domain != job.spec.trainer.allow_multi_domain:
            # The flag is baked into the running pods' labels (the cluster
            # inventory's pin/no-pin decision reads pods, not the spec) and
            # into where the mesh already sits; flipping it in place would
            # let the planner grow a "single-domain" mesh across a DCN
            # boundary.  Like pod-template fields, it is create-time.
            raise ValidationError(
                "allow_multi_domain is immutable on a running job; "
                "delete and resubmit to change it")
        updater.modify(job)
        self.autoscaler.on_update(job)

    def delete(self, job: "TrainingJob | ServingJob") -> None:
        with self._lock:
            updater = self._updaters.pop(job.full_name, None)
        if updater is not None:
            updater.notify_delete()
            updater.join(timeout=10)
        if isinstance(job, ServingJob):
            # membership truth, not a spec recomputation: deletion must
            # unregister wherever submit/modify actually registered
            if job.full_name in self.serving_scaler.observe_only:
                self.autoscaler.on_del(job)
            self.serving_scaler.on_del(job)
        else:
            self.autoscaler.on_del(job)
        self._gc_job_kv(job)
        log.info("job deleted", job=job.full_name)

    def _gc_job_kv(self, job) -> None:
        """Sweep the deleted job's coordinator KV (goodput curve, vw
        map/cursors, serving generation): job-scoped keys deliberately
        survive every reform/failover, so deletion is the ONLY moment
        they can be collected — on a shared coordinator they would
        otherwise leak forever (and poison a resubmitted job under the
        same name with the dead job's curve and cursors).  Best-effort:
        teardown never fails on an unreachable coordinator."""
        if self.coord_for is None:
            return
        try:
            coord = self.coord_for(job)
            if coord is None:
                return
            from edl_tpu.coord.gc import gc_job_kv

            gc_job_kv(coord, job.full_name)
        except Exception as exc:
            log.warn("job KV sweep failed", job=job.full_name,
                     error=str(exc)[:200])

    # -- introspection -----------------------------------------------------

    def get_updater(self, job: TrainingJob) -> Optional[TrainingJobUpdater]:
        with self._lock:
            return self._updaters.get(job.full_name)

    def phase(self, job: TrainingJob) -> JobPhase:
        u = self.get_updater(job)
        return u.phase if u is not None else JobPhase.NONE

    def jobs(self) -> list[TrainingJob]:
        with self._lock:
            return [u.job for u in self._updaters.values()]
