"""The controller: job registry → per-job actors + autoscaler feed.

Role of the reference's Gen-1 controller (reference pkg/controller.go:44-161)
with the Gen-2 per-job-actor design it was migrating toward (SURVEY §0):
where the reference watches a k8s informer, this controller exposes an
explicit ``submit``/``modify``/``delete`` API (the local/in-process
equivalent of TrainingJob CRUD) and fans events out to

* a :class:`TrainingJobUpdater` actor per job (lifecycle, phases,
  ready-confirmation, teardown — the Gen-2 semantics), and
* the :class:`~edl_tpu.scheduler.autoscaler.Autoscaler` (elastic planning —
  the Gen-1 semantics),

fixing the Gen-1 quirks: resources are created via the updater only after
validation, and master/pserver groups are only created when the spec calls
for them (contrast reference pkg/controller.go:134-141, which creates both
unconditionally and never validates).
"""

from __future__ import annotations

import threading
from typing import Optional

from edl_tpu.api.types import JobPhase, TrainingJob
from edl_tpu.api.validation import ValidationError, set_defaults_and_validate
from edl_tpu.cluster.base import Cluster
from edl_tpu.controller.updater import TrainingJobUpdater
from edl_tpu.observability.logging import get_logger
from edl_tpu.scheduler.autoscaler import Autoscaler
from edl_tpu.scheduler.topology import SliceShapePolicy, UNIT_POLICY

log = get_logger("controller")


class Controller:
    """One per cluster; owns the autoscaler and all job actors."""

    def __init__(
        self,
        cluster: Cluster,
        max_load_desired: float = 0.97,  # reference default (cmd/edl/edl.go:19)
        shape_policy: SliceShapePolicy = UNIT_POLICY,
        autoscaler_loop_seconds: float = 5.0,
        updater_convert_seconds: float = 10.0,
        updater_confirm_seconds: float = 5.0,
        resize_cooldown_s: float = 0.0,
        min_resize_delta: int = 1,
        mesh_shape_for=None,
        goodput_curves=None,
    ) -> None:
        self.cluster = cluster
        self.autoscaler = Autoscaler(
            cluster,
            max_load_desired=max_load_desired,
            shape_policy=shape_policy,
            loop_seconds=autoscaler_loop_seconds,
            resize_cooldown_s=resize_cooldown_s,
            min_resize_delta=min_resize_delta,
            mesh_shape_for=mesh_shape_for,
            goodput_curves=goodput_curves,
        )
        self._updater_convert_seconds = updater_convert_seconds
        self._updater_confirm_seconds = updater_confirm_seconds
        self._updaters: dict[str, TrainingJobUpdater] = {}
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Run the scaling loop in the background
        (role of Controller.Run, reference pkg/controller.go:64-76)."""
        self.autoscaler.start()

    def stop(self) -> None:
        self.autoscaler.stop()
        with self._lock:
            updaters = list(self._updaters.values())
        for u in updaters:
            u.stop()

    # -- TrainingJob CRUD (role of onAdd/onUpdate/onDelete,
    #    reference pkg/controller.go:110-161) ------------------------------

    def submit(self, job: TrainingJob) -> TrainingJobUpdater:
        """Validate, spawn the job's actor, register with the autoscaler."""
        set_defaults_and_validate(job)  # raises ValidationError on bad spec
        with self._lock:
            if job.full_name in self._updaters:
                raise ValidationError(f"job {job.full_name} already submitted")
            updater = TrainingJobUpdater(
                job,
                self.cluster,
                convert_seconds=self._updater_convert_seconds,
                confirm_seconds=self._updater_confirm_seconds,
            )
            self._updaters[job.full_name] = updater
        self.autoscaler.on_add(job)
        log.info("job submitted", job=job.full_name)
        return updater

    def modify(self, job: TrainingJob) -> None:
        set_defaults_and_validate(job)  # same gate as submit
        with self._lock:
            updater = self._updaters.get(job.full_name)
        if updater is None:
            raise KeyError(f"job {job.full_name} not found")
        old = updater.job.spec
        if old.trainer.allow_multi_domain != job.spec.trainer.allow_multi_domain:
            # The flag is baked into the running pods' labels (the cluster
            # inventory's pin/no-pin decision reads pods, not the spec) and
            # into where the mesh already sits; flipping it in place would
            # let the planner grow a "single-domain" mesh across a DCN
            # boundary.  Like pod-template fields, it is create-time.
            raise ValidationError(
                "allow_multi_domain is immutable on a running job; "
                "delete and resubmit to change it")
        updater.modify(job)
        self.autoscaler.on_update(job)

    def delete(self, job: TrainingJob) -> None:
        with self._lock:
            updater = self._updaters.pop(job.full_name, None)
        if updater is not None:
            updater.notify_delete()
            updater.join(timeout=10)
        self.autoscaler.on_del(job)
        log.info("job deleted", job=job.full_name)

    # -- introspection -----------------------------------------------------

    def get_updater(self, job: TrainingJob) -> Optional[TrainingJobUpdater]:
        with self._lock:
            return self._updaters.get(job.full_name)

    def phase(self, job: TrainingJob) -> JobPhase:
        u = self.get_updater(job)
        return u.phase if u is not None else JobPhase.NONE

    def jobs(self) -> list[TrainingJob]:
        with self._lock:
            return [u.job for u in self._updaters.values()]
