"""Sharding-aware embedding lookup.

One helper for every model in the zoo, because the right lookup depends on
how the table is laid out, not on the model:

* **gather** (``one_hot=False``) — free on an unsharded table; the
  single-chip default.
* **one-hot matmul** (``one_hot=True``) — for tables sharded
  ``P(tp, fsdp)``: a gather's output inherits the table layout, and XLA's
  SPMD partitioner can only reach batch-sharded activations by
  "involuntary full rematerialization" (replicate, then repartition — on
  both the forward gather and the backward scatter-add).  The matmul form
  partitions cleanly — the contraction over the tp-sharded vocab dim
  lowers to one psum — and rides the MXU, at ~2·b·s·v·d extra FLOPs: the
  standard TPU trade for sharded embeddings.

The reference has no counterpart (its models were word2vec/MNIST MLPs on
parameter servers, SURVEY §5.7); this is TPU-mesh machinery.
"""

from __future__ import annotations

import jax


def embed_lookup(table: jax.Array, tokens: jax.Array, *, one_hot: bool,
                 dtype) -> jax.Array:
    """``table[vocab, d]``, ``tokens[...] int`` → ``[..., d]`` in ``dtype``."""
    if one_hot:
        hot = jax.nn.one_hot(tokens, table.shape[0], dtype=dtype)
        return hot @ table.astype(dtype)
    return table.astype(dtype)[tokens]
