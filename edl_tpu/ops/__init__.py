"""Pallas TPU kernels for the hot ops (+ reference jnp fallbacks)."""

from edl_tpu.ops.flash_attention import attention

__all__ = ["attention"]
