"""Fused GroupNorm pallas kernel for conv nets (NHWC).

ResNet-50 at batch 256 is activation-bandwidth-bound: after the
single-pass-statistics rewrite (models/resnet.py::_group_norm history),
the remaining GroupNorm cost is the *second* read of the activation —
statistics need a full pass before normalization can start, so XLA's
best schedule is read-for-stats, read-for-normalize, write.  One image's
feature map fits VMEM at every ResNet-50 stage (worst case
112x112x64 f32 = 3.2 MB against ~16 MB/core), so a pallas kernel can
hold the block resident and do stats + normalize in ONE HBM read + one
write.  The backward pass fuses the same way: x and dy are read once,
dx and the per-image dgamma/dbeta partials come out, instead of XLA's
four-plus passes for the three group reductions and dx.

Mosaic layout note: the obvious [HW, C] → [HW, G, CG] reshape SPLITS THE
LANE DIMENSION and fails to lower ("infer-vector-layout: unsupported
shape cast").  The kernels therefore never reshape: channel→group
aggregation is a [1, C] @ [C, G] matmul against a constant 0/1
membership matrix, and group→channel broadcast is the transpose matmul —
both MXU-trivial and layout-clean.

Dispatch mirrors ops/flash_attention.py: pallas on TPU (or interpret
mode for CPU tests), pure-jnp single-pass math elsewhere.  The jnp path
is also the numerical reference in tests/test_models_ops.py.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _reference(x2d, scale, bias, groups: int, eps: float):
    """Single-pass-stats jnp math (the non-TPU path and test oracle).
    x2d: [b, hw, c]."""
    b, hw, c = x2d.shape
    g32 = x2d.reshape(b, hw, groups, c // groups).astype(jnp.float32)
    mean = jnp.mean(g32, axis=(1, 3), keepdims=True)
    mean2 = jnp.mean(g32 * g32, axis=(1, 3), keepdims=True)
    inv = jax.lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + eps)
    y = ((g32 - mean) * inv).reshape(b, hw, c)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x2d.dtype)


def _membership(c: int, groups: int) -> np.ndarray:
    """[C, G] 0/1 matrix: column g selects group g's channels."""
    m = np.zeros((c, groups), np.float32)
    cg = c // groups
    for g in range(groups):
        m[g * cg:(g + 1) * cg, g] = 1.0
    return m


def _dot(a, b):
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


# -- forward kernel ----------------------------------------------------------

def _fwd_kernel(x_ref, s_ref, b_ref, m_ref, y_ref, mean_ref, inv_ref,
                *, n_per_group: float, eps: float):
    # VMEM discipline: reductions accumulate straight from the input
    # block (dtype=f32 accumulators, no materialized f32 copy), and the
    # normalize collapses to ONE input-dtype multiply-add y = x*p + q
    # with per-channel p/q — f32 [hw, c] temps overflowed the 16 MB
    # scoped-vmem budget once the grid was big enough to double-buffer.
    x = x_ref[0]                                             # [hw, c]
    m = m_ref[...]                                           # [c, g]
    sum_c = jnp.sum(x, axis=0, keepdims=True, dtype=jnp.float32)
    sum2_c = jnp.sum(x * x, axis=0, keepdims=True, dtype=jnp.float32)
    mean_g = _dot(sum_c, m) / n_per_group                    # [1, g]
    mean2_g = _dot(sum2_c, m) / n_per_group
    inv_g = jax.lax.rsqrt(
        jnp.maximum(mean2_g - mean_g * mean_g, 0.0) + eps)
    mean_c = _dot(mean_g, m.T)                               # [1, c]
    inv_c = _dot(inv_g, m.T)
    gamma = s_ref[0].astype(jnp.float32)
    p = (inv_c * gamma).astype(x.dtype)
    q = (b_ref[0].astype(jnp.float32)
         - mean_c * inv_c * gamma).astype(x.dtype)
    y_ref[0] = (x * p + q).astype(y_ref.dtype)
    mean_ref[0] = mean_g
    inv_ref[0] = inv_g


def _fwd(x2d, scale, bias, groups: int, eps: float, interpret: bool):
    b, hw, c = x2d.shape
    s2 = scale.reshape(1, c)
    b2 = bias.reshape(1, c)
    memb = jnp.asarray(_membership(c, groups))
    n_per_group = float(hw * (c // groups))
    y, mean, inv = pl.pallas_call(
        functools.partial(_fwd_kernel, n_per_group=n_per_group, eps=eps),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((c, groups), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            # small outputs are (b, 1, g) with (1, 1, g) blocks: each of
            # the last two block dims must be tile-divisible or equal to
            # the full array dim
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, c), x2d.dtype),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, s2, b2, memb)
    return y, mean, inv


# -- backward kernel ---------------------------------------------------------

def _bwd_kernel(x_ref, dy_ref, s_ref, m_ref, mean_ref, inv_ref,
                dx_ref, dg_ref, db_ref, *, n_per_group: float):
    # VMEM discipline: every group statistic the backward needs reduces
    # to TWO per-channel sums (a = Σdy, b = Σdy·x), so no [hw, c]
    # intermediate (xhat, dy·γ) is ever materialized — the first version
    # that built them overflowed the 16 MB scoped-vmem budget by 466 KB
    # at the 12544x64 stem shape.
    x = x_ref[0]                                             # [hw, c]
    dy = dy_ref[0]
    m = m_ref[...]                                           # [c, g]
    gamma = s_ref[0].astype(jnp.float32)                     # [1, c]
    mean_c = _dot(mean_ref[0], m.T)                          # [1, c]
    inv_c = _dot(inv_ref[0], m.T)
    a_c = jnp.sum(dy, axis=0, keepdims=True, dtype=jnp.float32)
    b_c = jnp.sum(dy * x, axis=0, keepdims=True, dtype=jnp.float32)
    # param grads (partials over this image; XLA sums over b):
    #   dγ_c = Σ dy·x̂ = inv_c·(b_c − mean_c·a_c);  dβ_c = a_c
    dg_ref[0] = inv_c * (b_c - mean_c * a_c)
    db_ref[0] = a_c
    # group means of dy·γ and (dy·γ)·x̂, from the same channel sums
    s1_g = _dot(gamma * a_c, m)                              # [1, g]
    s2_g = _dot(gamma * b_c, m)
    m1_g = s1_g / n_per_group
    m2_g = inv_ref[0] * (s2_g - mean_ref[0] * s1_g) / n_per_group
    m1_c = _dot(m1_g, m.T)
    m2_c = _dot(m2_g, m.T)
    # dx = (dy·γ − m1 − x̂·m2)·inv  ≡  dy·p − x·q + r with per-channel
    # coefficients — one input-dtype fused multiply-add, no f32 temps
    p = (gamma * inv_c).astype(x.dtype)
    q = (inv_c * inv_c * m2_c).astype(x.dtype)
    r = ((mean_c * inv_c * m2_c - m1_c) * inv_c).astype(x.dtype)
    dx_ref[0] = (dy * p - x * q + r).astype(dx_ref.dtype)


def _bwd_call(x2d, dy, scale, mean, inv, groups: int, interpret: bool):
    b, hw, c = x2d.shape
    s2 = scale.reshape(1, c)
    memb = jnp.asarray(_membership(c, groups))
    n_per_group = float(hw * (c // groups))
    dx, dg_b, db_b = pl.pallas_call(
        functools.partial(_bwd_kernel, n_per_group=n_per_group),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((c, groups), lambda i: (0, 0)),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, hw, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, c), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hw, c), x2d.dtype),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, c), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, dy, s2, memb, mean, inv)
    return dx, dg_b[:, 0], db_b[:, 0]


# -- custom-vjp wrapper ------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _gn2d(x2d, scale, bias, groups: int, eps: float, interpret: bool):
    y, _mean, _inv = _fwd(x2d, scale, bias, groups, eps, interpret)
    return y


def _gn2d_fwd(x2d, scale, bias, groups, eps, interpret):
    y, mean, inv = _fwd(x2d, scale, bias, groups, eps, interpret)
    return y, (x2d, scale, mean, inv)


def _gn2d_bwd(groups, eps, interpret, res, dy):
    x2d, scale, mean, inv = res
    dx, dg_b, db_b = _bwd_call(x2d, dy, scale, mean, inv, groups,
                               interpret)
    return dx, jnp.sum(dg_b, axis=0), jnp.sum(db_b, axis=0)


_gn2d.defvjp(_gn2d_fwd, _gn2d_bwd)


def group_norm(x, scale, bias, groups: int, eps: float = 1e-5,
               use_pallas: bool | None = None,
               interpret: bool = False):
    """GroupNorm over NHWC ``x`` with per-channel ``scale``/``bias``.

    ``use_pallas=None`` → the fused-math jnp path everywhere; set
    ``EDL_GN_PALLAS=1`` (TPU only) to opt into the pallas kernel.

    MEASURED NEGATIVE RESULT (v5e, ResNet-50 b256, r5): the pallas
    kernel is 170.8 ms/step vs 107.9 ms for the jnp single-pass math.
    The kernel does save the second stats read, but a custom call is a
    fusion BARRIER — XLA had been folding the relu, residual add, and
    conv-input casts into the norm's elementwise epilogue for free, and
    losing those fusions costs more than the pass it saves.  The kernel
    stays as a tested building block (and the measurement as a warning:
    don't hand-schedule what the compiler already fuses — pallas pays
    off where XLA CANNOT fuse, like flash attention's softmax-rescale
    loop, not where it already does).
    """
    b, h, w, c = x.shape
    if c % groups:
        raise ValueError(f"channels {c} not divisible by groups {groups}")
    x2d = x.reshape(b, h * w, c)
    if use_pallas is None:
        use_pallas = (jax.default_backend() == "tpu"
                      and os.environ.get("EDL_GN_PALLAS", "0") == "1")
    # VMEM guards: one image's block (plus pipeline double-buffering and
    # reduction temps) must sit inside the ~16 MB scoped budget, and a
    # sub-128 channel count pads the lane dimension — the 112x112x64 stem
    # block doubles to an effective 12544x128 and overflowed by 2.2 MB
    # (measured).  Such shapes take the jnp path; every other ResNet-50
    # site is 128-multiple.
    if use_pallas and ((h * w) * c * 4 > 6 * 1024 * 1024 or c % 128):
        use_pallas = False
    if use_pallas or interpret:
        y = _gn2d(x2d, scale, bias, groups, eps, interpret)
    else:
        y = _reference(x2d, scale, bias, groups, eps)
    return y.reshape(b, h, w, c)
