"""Flash attention for TPU: an online-softmax pallas kernel that never
materializes the [s, s] score matrix in HBM.

Why a kernel at all: XLA fuses elementwise chains into matmuls well, but
softmax(QKᵀ)V with causal masking still round-trips the score matrix
through HBM at long sequence lengths — the classic HBM-bandwidth wall.
The kernel streams K/V blocks through VMEM with online max/sum rescaling
(the standard flash recurrence), so HBM traffic is O(s·d) instead of
O(s²), and the two matmuls per block land on the MXU at 128-aligned tiles.

Gradients: the op carries a custom VJP whose backward recomputes attention
blockwise with the same online recurrence expressed in jnp — XLA fuses it
adequately; a hand-written pallas backward is a later optimization.

``attention()`` dispatches: pallas on TPU (or in interpret mode for tests),
reference jnp otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


# -- reference implementation (also the VJP recompute path) ------------------


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True) -> jax.Array:
    """q,k,v: [b, s, h, d] → [b, s, h, d]; fp32 softmax."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# -- pallas kernel -----------------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc, m_state, l_state, *,
                  block_q: int, block_k: int, causal: bool, scale: float):
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_state[:] = jnp.full_like(m_state, _NEG_INF)
        l_state[:] = jnp.zeros_like(l_state)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    # Causal: whole block strictly above the diagonal → nothing to do.
    should_run = True
    if causal:
        should_run = q_start + block_q - 1 >= k_start

    @pl.when(should_run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)  # [bk, d]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            scores = jnp.where(q_start + rows >= k_start + cols, scores,
                               _NEG_INF)

        m_prev = m_state[:]  # [bq, 1]
        l_prev = l_state[:]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_state[:] = m_new
        l_state[:] = l_new

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0] = (acc[:] / l_state[:]).astype(o_ref.dtype)


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   block_q: int, block_k: int,
                   interpret: bool) -> jax.Array:
    """q,k,v: [bh, s, d] (heads already folded into batch)."""
    bh, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    grid = (bh, s // block_q, s // block_k)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    # Recompute-based backward through the reference path ([bh, s, d] with a
    # single folded head axis → einsum over bh).
    q, k, v = res

    def ref(q, k, v):
        d = q.shape[-1]
        scores = jnp.einsum("bqd,bkd->bqk", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(d))
        if causal:
            s = q.shape[1]
            mask = jnp.tril(jnp.ones((s, s), dtype=bool))
            scores = jnp.where(mask[None], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("bqk,bkd->bqd", probs, v)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd, _bwd)


# -- public entry ------------------------------------------------------------


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
              use_pallas: bool = True, block_q: int = DEFAULT_BLOCK_Q,
              block_k: int = DEFAULT_BLOCK_K,
              interpret: bool = False) -> jax.Array:
    """Multi-head attention, q/k/v: [b, s, h, d] → [b, s, h, d].

    Dispatches to the pallas flash kernel on TPU when shapes allow
    (s divisible by the block sizes), else to the reference path.
    """
    b, s, h, d = q.shape
    eligible = (
        use_pallas
        and (interpret or _on_tpu())
        and s % block_q == 0
        and s % block_k == 0
    )
    if not eligible:
        return reference_attention(q, k, v, causal=causal)
    # fold heads into batch: [b, s, h, d] → [b*h, s, d]
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    unfold = lambda x: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    out = _flash_attention(fold(q), fold(k), fold(v), causal, block_q,
                           block_k, interpret)
    return unfold(out)
