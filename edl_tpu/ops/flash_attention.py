"""Flash attention for TPU: an online-softmax pallas kernel that never
materializes the [s, s] score matrix in HBM.

Why a kernel at all: XLA fuses elementwise chains into matmuls well, but
softmax(QKᵀ)V with causal masking still round-trips the score matrix
through HBM at long sequence lengths — the classic HBM-bandwidth wall.
The kernel streams K/V blocks through VMEM with online max/sum rescaling
(the standard flash recurrence), so HBM traffic is O(s·d) instead of
O(s²), and the two matmuls per block land on the MXU at 128-aligned tiles.

Gradients: the op carries a custom VJP with hand-written pallas backward
kernels (dQ pass and dK/dV pass) that reconstruct the probabilities
blockwise from the logsumexp saved by the forward — the [s, s] matrices
never exist outside a VMEM tile in either direction.  Measured on one
v5e chip, flagship-dims train step (fwd+bwd), vs XLA's fused attention:
1.08x at seq 1024, 1.9x at 4096, 24-30x at 8192 (XLA's score
materialization hits the HBM wall; the kernel doesn't), recorded in
BENCH_r03; 32k trains at ~39k tokens/s, 64k (with remat) at ~17.7k.

``attention()`` dispatches: pallas on TPU (or in interpret mode for tests),
reference jnp otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Measured on TPU v5e (d 128): larger blocks win — fewer grid steps
# amortize per-block DMA/setup.  256x512 ran the seq-4096 forward 1.7x
# faster than 128x128; 512x1024 adds ~5% end-to-end train throughput at
# seq 8192 over 256x512 (62.9k -> 65.9k tokens/s) and is neutral at seq
# 1024/32k.  attention() shrinks the blocks for shorter sequences.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
_NEG_INF = -1e30


def fit_blocks(s: int, block_q: int = DEFAULT_BLOCK_Q,
               block_k: int = DEFAULT_BLOCK_K) -> tuple[int, int]:
    """Shape-adapt the block sizes to a sequence (or ring-chunk) length:
    clamp to the length, then halve toward a divisor (floor 128) so every
    128-aligned length keeps the kernel — without this, lengths that are
    multiples of 512 but not 1024 (1536, 2560, 3584, ...) would silently
    regress to the score-materializing reference path the moment the
    defaults grew past them."""
    bq, bk = min(block_q, s), min(block_k, s)
    while bq > 128 and s % bq:
        bq //= 2
    while bk > 128 and s % bk:
        bk //= 2
    return bq, bk


# -- reference implementation (also the VJP recompute path) ------------------


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True) -> jax.Array:
    """q,k,v: [b, s, h, d] → [b, s, h, d]; fp32 softmax."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(d))
    if causal:
        s = q.shape[1]
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# -- pallas kernel -----------------------------------------------------------


def _block_scores(q, k, q_start, k_start, causal: bool, scale: float):
    """One VMEM tile of masked, scaled QKᵀ in fp32 — the shared opening of
    the forward and both backward kernels (one definition so fwd and bwd
    can never desynchronize on masking/scaling)."""
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [bq, bk] fp32
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        scores = jnp.where(q_start + rows >= k_start + cols, scores,
                           _NEG_INF)
    return scores


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_state, l_state,
                  *, block_q: int, block_k: int, causal: bool, scale: float):
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_state[:] = jnp.full_like(m_state, _NEG_INF)
        l_state[:] = jnp.zeros_like(l_state)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    # Causal: whole block strictly above the diagonal → nothing to do.
    should_run = True
    if causal:
        should_run = q_start + block_q - 1 >= k_start

    @pl.when(should_run)
    def _compute():
        # dots run on the NATIVE (bf16) operands with fp32 accumulation —
        # exactly what the MXU does natively; upcasting the operands first
        # halves MXU throughput for zero numeric gain
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        scores = _block_scores(q, k, q_start, k_start, causal, scale)

        m_prev = m_state[:]  # [bq, 1]
        l_prev = l_state[:]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(scores - m_new)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_state[:] = m_new
        l_state[:] = l_new

    @pl.when(ki == num_k - 1)
    def _finish():
        o_ref[0] = (acc[:] / l_state[:]).astype(o_ref.dtype)
        # per-row logsumexp: the single residual the backward needs to
        # reconstruct exact softmax probabilities blockwise ([bq, 1] —
        # kept 3D because mosaic requires the last two block dims tiled)
        lse_ref[0] = m_state[:] + jnp.log(l_state[:])


def _kv_head_map(h: int, hk: int):
    """Folded-q index [b·h] → folded-kv index [b·hk] — how the kernels read
    GQA directly: each kv head serves ``h // hk`` query heads through the
    BlockSpec index map, so the repeated K/V never exist in HBM
    (jnp.repeat would materialize them, 4x for a Llama-3-8B-class model)."""
    rep = h // hk
    return lambda bh: (bh // h) * hk + (bh % h) // rep


def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool,
                   block_q: int, block_k: int, h: int, hk: int,
                   interpret: bool) -> tuple[jax.Array, jax.Array]:
    """q: [b·h, s, d]; k,v: [b·hk, s, d] (heads folded into batch; GQA via
    the kv index map) → (out [b·h, s, d], lse [b·h, s, 1] fp32)."""
    bh, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    grid = (bh, s // block_q, s // block_k)
    kvm = _kv_head_map(h, hk)
    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (kvm(b), ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (kvm(b), ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


# -- pallas backward ---------------------------------------------------------
#
# Standard flash backward from the saved per-row logsumexp: probabilities
# are reconstructed blockwise as p = exp(s - lse), so the [s, s] matrices
# (p, dp, ds) only ever exist one VMEM tile at a time.  Two kernels because
# the two accumulation directions want opposite grid orders: dQ accumulates
# over k blocks (k innermost), dK/dV accumulate over q blocks (q innermost).
# With delta = rowsum(dO ∘ O):
#   dp = dO Vᵀ;  ds = p ∘ (dp − delta) · scale;  dQ = ds K;
#   dV = pᵀ dO;  dK = dsᵀ Q.


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, block_q: int, block_k: int,
                         causal: bool, scale: float):
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    should_run = True
    if causal:
        should_run = q_start + block_q - 1 >= k_start

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [bq, 1]
        delta = delta_ref[0]
        scores = _block_scores(q, k, q_start, k_start, causal, scale)
        p = jnp.exp(scores - lse)  # exact probs from the saved lse
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                          block_k: int, causal: bool, scale: float,
                          n_q_blocks: int):
    # inner = g * n_q_blocks + qi: one kv head accumulates over every
    # query block of every one of its GQA group's query heads
    inner = pl.program_id(2)
    num_inner = pl.num_programs(2)

    @pl.when(inner == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    ki = pl.program_id(1)
    qi = inner % n_q_blocks
    q_start = qi * block_q
    k_start = ki * block_k

    should_run = True
    if causal:
        should_run = q_start + block_q - 1 >= k_start

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]  # [bq, 1]
        delta = delta_ref[0]
        scores = _block_scores(q, k, q_start, k_start, causal, scale)
        p = jnp.exp(scores - lse).astype(do.dtype)  # [bq, bk]
        # dV += pᵀ dO — contract the q dim, no explicit transpose needed
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p.astype(jnp.float32) * (dp - delta) * scale).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(inner == num_inner - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k, h, hk,
                    interpret):
    bh, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    rep = h // hk
    nqb = s // block_q
    kvm = _kv_head_map(h, hk)
    # delta = rowsum(dO ∘ O): tiny elementwise pass, XLA fuses it
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, s, 1]

    qkv_spec = [
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (kvm(b), ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (kvm(b), ki, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),  # dO
        pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),  # lse
        pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),  # delta
    ]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale),
        grid=(bh, s // block_q, s // block_k),
        in_specs=qkv_spec,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    # dK/dV: one program per KV head; the inner grid dim walks every
    # (group member, query block) pair, so the accumulators sum over the
    # whole GQA group — the sum jnp.repeat's backward would have formed
    def qrow(bkh, inner):
        return (bkh // hk) * h + (bkh % hk) * rep + inner // nqb

    kv_spec = [
        pl.BlockSpec((1, block_q, d),
                     lambda b, ki, nn: (qrow(b, nn), nn % nqb, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, ki, nn: (b, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, ki, nn: (b, ki, 0)),
        pl.BlockSpec((1, block_q, d),
                     lambda b, ki, nn: (qrow(b, nn), nn % nqb, 0)),  # dO
        pl.BlockSpec((1, block_q, 1),
                     lambda b, ki, nn: (qrow(b, nn), nn % nqb, 0)),  # lse
        pl.BlockSpec((1, block_q, 1),
                     lambda b, ki, nn: (qrow(b, nn), nn % nqb, 0)),  # delta
    ]
    bkh = (bh // h) * hk
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          n_q_blocks=nqb),
        grid=(bkh, s // block_k, rep * nqb),
        in_specs=kv_spec,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, nn: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, nn: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bkh, s, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention(q, k, v, causal, block_q, block_k, h, hk, interpret):
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, h, hk,
                            interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k, h, hk, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, h, hk,
                              interpret)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, h, hk, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, block_q, block_k,
                           h, hk, interpret)


_flash_attention.defvjp(_fwd, _bwd)


# -- public entry ------------------------------------------------------------


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
              use_pallas: bool = True, block_q: int = DEFAULT_BLOCK_Q,
              block_k: int = DEFAULT_BLOCK_K,
              interpret: bool = False) -> jax.Array:
    """Multi-head attention, q: [b, s, h, d], k/v: [b, s, hk, d] with
    hk | h → [b, s, h, d].

    GQA is native: pass the UNREPEATED k/v heads and the kernel reads each
    kv head for its whole query group through the block index maps —
    the h/hk-repeated K/V (and their gradients) never exist in HBM.

    Dispatches to the pallas flash kernel on TPU when shapes allow
    (128-aligned s divisible by the — shape-adapted — block sizes), else
    to the reference path.
    """
    b, s, h, d = q.shape
    hk = k.shape[2]
    if h % hk != 0:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hk}")
    if v.shape[2] != hk:
        # the -1 fold below would silently accept it and the kernel would
        # read misaligned v rows — fail loudly instead
        raise ValueError(f"k has {hk} heads but v has {v.shape[2]}")
    # shape-adaptive blocks: shrink for short sequences and halve toward
    # a divisor for lengths the big defaults don't divide, instead of
    # falling back — a 128-token test sequence and a 1536-token train
    # sequence both go through the kernel path
    block_q, block_k = fit_blocks(s, block_q, block_k)
    eligible = (
        use_pallas
        and (interpret or _on_tpu())
        # lane alignment: unaligned lengths take the reference path (the
        # shrunken blocks would otherwise always divide s and hand Mosaic
        # an unaligned full-dim block, a regime never exercised on HW)
        and s % 128 == 0
        and s % block_q == 0
        and s % block_k == 0
    )
    if not eligible:
        if hk != h:
            k = jnp.repeat(k, h // hk, axis=2)
            v = jnp.repeat(v, h // hk, axis=2)
        return reference_attention(q, k, v, causal=causal)
    # fold heads into batch: [b, s, h, d] → [b*h, s, d]
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(-1, s, d)
    unfold = lambda x: x.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    out = _flash_attention(fold(q), fold(k), fold(v), causal, block_q,
                           block_k, h, hk, interpret)
    return unfold(out)
