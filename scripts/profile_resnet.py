"""Attribute ResNet-50's step time on the real chip (verdict r5 weak #1).

BENCH_r04: 16.7 % MFU at batch 256 — 188 ms/step where the pure-FLOPs
floor is ~31 ms.  This script measures WHERE the time goes by timing
targeted model variants (each isolates one suspected sink), then the
candidate fixes.  Run on the TPU:

    python scripts/profile_resnet.py [--steps 10]

Variants:
  baseline      the shipped model (GroupNorm f32 two-pass stats)
  fwd_only      no backward/optimizer — splits fwd vs bwd+update
  no_norm       GroupNorm removed (scale+bias only) — the norm's total tax
  gn_onepass    var = E[x^2] - E[x]^2 (one fused read instead of two)
  gn_bf16_out   one-pass stats + normalized output computed in bf16
  s2d_stem      4x4 space-to-depth input + 2x2-stride stem conv (the
                MLPerf conv0 trick: 3 input channels pad to 8 MXU lanes,
                wasting 5/8 of the systolic array on the biggest image)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(loss_fn, params, data, n_steps, fwd_only=False):
    import jax
    import optax

    if fwd_only:
        compiled = jax.jit(lambda p, d: loss_fn(p, d)).lower(
            params, data).compile()
        float(compiled(params, data))
        t0 = time.perf_counter()
        for _ in range(n_steps):
            loss = compiled(params, data)
        final = float(loss)
        return 1000 * (time.perf_counter() - t0) / n_steps, final

    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)

    def step(params, opt_state, data):
        loss, grads = jax.value_and_grad(loss_fn)(params, data)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    compiled = jax.jit(step).lower(params, opt_state, data).compile()
    params, opt_state, loss = compiled(params, opt_state, data)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = compiled(params, opt_state, data)
    final = float(loss)
    return 1000 * (time.perf_counter() - t0) / n_steps, final


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--only", default="",
                    help="comma-separated variant subset")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from edl_tpu.models import resnet

    try:
        os.makedirs("/tmp/edl-bench-cache", exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", "/tmp/edl-bench-cache")
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    print(f"# platform={dev.platform} kind={dev.device_kind}", flush=True)

    cfg = resnet.RESNET50
    b, hw = args.batch, 224
    images = jax.random.normal(jax.random.key(0), (b, hw, hw, 3)
                               ).astype(cfg.dtype)
    labels = jax.random.randint(jax.random.key(1), (b,), 0,
                                cfg.num_classes, dtype=jnp.int32)
    params = resnet.init(jax.random.key(2), cfg)
    data = (images, labels)

    orig_gn = resnet._group_norm

    def gn_onepass(x, p, groups, eps=1e-5):
        bb, h, w, c = x.shape
        g = x.reshape(bb, h, w, groups, c // groups)
        g32 = g.astype(jnp.float32)
        mean = jnp.mean(g32, axis=(1, 2, 4), keepdims=True)
        mean2 = jnp.mean(g32 * g32, axis=(1, 2, 4), keepdims=True)
        inv = jax.lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + eps)
        y = (g32 - mean) * inv
        return (y.reshape(bb, h, w, c) * p["scale"]
                + p["bias"]).astype(x.dtype)

    def gn_bf16_out(x, p, groups, eps=1e-5):
        bb, h, w, c = x.shape
        g = x.reshape(bb, h, w, groups, c // groups)
        g32 = g.astype(jnp.float32)
        mean = jnp.mean(g32, axis=(1, 2, 4), keepdims=True)
        mean2 = jnp.mean(g32 * g32, axis=(1, 2, 4), keepdims=True)
        inv = jax.lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + eps)
        # fold (mean, inv, scale, bias) into one bf16 multiply-add over x
        scale = (inv.astype(x.dtype)
                 * p["scale"].astype(x.dtype).reshape(1, 1, 1, groups, -1))
        shift = (p["bias"].astype(x.dtype).reshape(1, 1, 1, groups, -1)
                 - (mean * inv).astype(x.dtype)
                 * p["scale"].astype(x.dtype).reshape(1, 1, 1, groups, -1))
        return (g * scale + shift).reshape(bb, h, w, c)

    def gn_none(x, p, groups, eps=1e-5):
        return x * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)

    def s2d_loss_fn(cfg):
        # 4x4 space-to-depth: [b,224,224,3] -> [b,56,56,48]; the stem
        # becomes a 2x2 conv over 48 channels (dense on MXU lanes) with
        # the same receptive-field stride product (7x7 s2 + 3x3 maxpool
        # s2 ~ 56x56 output); here: s2d + 2x2 s1 conv -> 56x56x64
        import functools

        w_key = jax.random.key(9)
        stem48 = (jax.random.normal(w_key, (2, 2, 48, cfg.width),
                                    jnp.float32)
                  * (2.0 / (2 * 2 * 48)) ** 0.5)

        def apply_s2d(p, imgs):
            x = imgs.astype(cfg.dtype)
            bb, h, w, c = x.shape
            x = x.reshape(bb, h // 4, 4, w // 4, 4, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(bb, h // 4, w // 4,
                                                      48)
            x = jax.lax.conv_general_dilated(
                x, p["stem48"].astype(x.dtype), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(resnet._group_norm(x, p["stem_norm"],
                                               cfg.groups))
            for stage, blocks in enumerate(p["stages"]):
                for bi, blk in enumerate(blocks):
                    stride = 2 if (stage > 0 and bi == 0) else 1
                    x = resnet._bottleneck(x, blk, cfg.groups, stride)
            x = jnp.mean(x, axis=(1, 2))
            return (x @ p["head"].astype(x.dtype)
                    + p["head_bias"]).astype(jnp.float32)

        def loss(p, batch):
            imgs, lbls = batch
            logp = jax.nn.log_softmax(apply_s2d(p, imgs), axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, lbls[:, None],
                                                 axis=1))

        p2 = dict(params)
        p2["stem48"] = stem48
        return functools.partial(loss), p2

    variants = {}
    variants["baseline"] = (resnet.make_loss_fn(cfg), params, False, None)
    variants["fwd_only"] = (resnet.make_loss_fn(cfg), params, True, None)
    variants["no_norm"] = (resnet.make_loss_fn(cfg), params, False, gn_none)
    variants["gn_onepass"] = (resnet.make_loss_fn(cfg), params, False,
                              gn_onepass)
    variants["gn_bf16_out"] = (resnet.make_loss_fn(cfg), params, False,
                               gn_bf16_out)
    s2d_loss, s2d_params = s2d_loss_fn(cfg)
    variants["s2d_stem"] = (s2d_loss, s2d_params, False, None)

    only = set(filter(None, args.only.split(",")))
    results = {}
    for name, (loss_fn, ps, fwd, gn) in variants.items():
        if only and name not in only:
            continue
        resnet._group_norm = gn if gn is not None else orig_gn
        try:
            ms, final = timed(loss_fn, ps, data, args.steps, fwd_only=fwd)
            results[name] = {"step_ms": round(ms, 1),
                             "img_s": round(1000 * b / ms, 1),
                             "final_loss": round(final, 3)}
            print(f"{name:12s} {ms:8.1f} ms/step "
                  f"{1000 * b / ms:8.1f} img/s", flush=True)
        except Exception as exc:
            results[name] = {"error": str(exc)[:200]}
            print(f"{name:12s} ERROR {str(exc)[:160]}", flush=True)
        finally:
            resnet._group_norm = orig_gn
    print(json.dumps(results))


if __name__ == "__main__":
    main()
