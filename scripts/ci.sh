#!/usr/bin/env bash
# CI entry point (role of the reference's .travis.yml + pre-commit hooks:
# style checks then the full test run).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== compile check"
python -m compileall -q edl_tpu tests examples bench.py __graft_entry__.py

echo "== native core"
make -C edl_tpu/coord/native -s

echo "== tests (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

echo "== elastic demo"
python examples/elastic_demo.py > /dev/null

echo "== bench smoke (scheduler only, no accelerator dependence)"
python - <<'EOF'
import bench
r = bench.scheduler_utilization_bench()
assert r["pending_jobs"] == 0, r
assert r["chip_utilization_pct"] >= 88.4, r  # reference peak
EOF

echo "CI OK"
