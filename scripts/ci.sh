#!/usr/bin/env bash
# CI entry point (role of the reference's .travis.yml + pre-commit hooks:
# style checks then the full test run).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== compile check"
python -m compileall -q edl_tpu tests examples bench.py __graft_entry__.py

echo "== native core"
make -C edl_tpu/coord/native -s

echo "== tests (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

echo "== elastic demo"
python examples/elastic_demo.py > /dev/null

echo "== bench smoke (scheduler only, no accelerator dependence)"
python - <<'EOF'
import bench
r = bench.scheduler_utilization_bench()
assert r["pending_jobs"] == 0, r
assert r["chip_utilization_pct"] >= 88.4, r  # reference peak
EOF

echo "== perf smoke (async checkpoint cadence + prewarm + long-poll counters)"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
# Fast tripwire for PR 3's zero-stall machinery: an async-cadence run with
# prewarmed resizes must leave the new counters populated and the stall
# watchdog silent — a regression that reintroduces a step-loop stall or
# breaks speculation shows up here, not in a 7-minute bench.
import tempfile, threading, time
import jax, numpy as np, optax

from edl_tpu.coord import PyCoordService
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.checkpoint import ElasticCheckpointer
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.watchdog import StallWatchdog

params = mlp.init(jax.random.key(0), [16, 32, 4])
tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                    spec=MeshSpec(dp=-1), initial_world_size=2)
rng = np.random.default_rng(0)
batch = (rng.normal(size=(64, 16)).astype(np.float32),
         rng.integers(0, 4, 64).astype(np.int32))
ck = ElasticCheckpointer(tempfile.mkdtemp(prefix="edl-perf-smoke-"))
wd = StallWatchdog(floor_s=30.0, k=8.0, scope="perf-smoke")
wd.start(poll_s=0.5)
try:
    tr.step(batch)                      # teach the batch shape
    tr.prewarm([4], wait=True)          # speculation lands off-path
    assert tr.resize(4)
    for step in range(2, 42):
        wd.beat(step)
        tr.step(batch)
        if step % 10 == 0:
            ck.save_async(step, {"params": tr.state.params})
    ck.finalize()
finally:
    wd.stop()
assert ck.latest_verified_step() is not None   # async saves finalized
ck.close()

# coord long-poll counters move when a parked wait fires
svc = PyCoordService()
svc.join("a")
t = threading.Thread(target=svc.wait_epoch, args=(svc.epoch(), 5.0))
t.start(); time.sleep(0.1); svc.join("b"); t.join(timeout=5)
m = svc.server_metrics()
assert m["longpolls_parked"] >= 1 and m["longpolls_fired"] >= 1, m

c = get_counters()
evt = tr.resize_events[-1]
assert evt["prewarm_hit"] and evt["compile_ms"] < 100.0, evt
assert c.get("prewarm_hits") >= 1, c.snapshot()
assert c.get("checkpoint_async_saves") >= 4, c.snapshot()
assert c.get("stalls_detected", scope="perf-smoke") == 0, c.snapshot()
print("perf smoke OK:", {k: v for k, v in c.snapshot().items()
                         if "prewarm" in k or "async" in k})
EOF

echo "CI OK"
