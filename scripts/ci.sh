#!/usr/bin/env bash
# CI entry point (role of the reference's .travis.yml + pre-commit hooks:
# style checks then the full test run).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== compile check"
python -m compileall -q edl_tpu tests examples bench.py __graft_entry__.py

echo "== native core"
make -C edl_tpu/coord/native -s

echo "== tests (virtual 8-device CPU mesh)"
python -m pytest tests/ -q

echo "== elastic demo"
python examples/elastic_demo.py > /dev/null

echo "== bench smoke (scheduler only, no accelerator dependence)"
python - <<'EOF'
import bench
r = bench.scheduler_utilization_bench()
assert r["pending_jobs"] == 0, r
assert r["chip_utilization_pct"] >= 88.4, r  # reference peak
EOF

echo "== perf smoke (async checkpoint cadence + prewarm + long-poll counters)"
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
# Fast tripwire for PR 3's zero-stall machinery: an async-cadence run with
# prewarmed resizes must leave the new counters populated and the stall
# watchdog silent — a regression that reintroduces a step-loop stall or
# breaks speculation shows up here, not in a 7-minute bench.
import tempfile, threading, time
import jax, numpy as np, optax

from edl_tpu.coord import PyCoordService
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.checkpoint import ElasticCheckpointer
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.watchdog import StallWatchdog

params = mlp.init(jax.random.key(0), [16, 32, 4])
tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                    spec=MeshSpec(dp=-1), initial_world_size=2)
rng = np.random.default_rng(0)
batch = (rng.normal(size=(64, 16)).astype(np.float32),
         rng.integers(0, 4, 64).astype(np.int32))
ck = ElasticCheckpointer(tempfile.mkdtemp(prefix="edl-perf-smoke-"))
wd = StallWatchdog(floor_s=30.0, k=8.0, scope="perf-smoke")
wd.start(poll_s=0.5)
try:
    tr.step(batch)                      # teach the batch shape
    tr.prewarm([4], wait=True)          # speculation lands off-path
    assert tr.resize(4)
    for step in range(2, 42):
        wd.beat(step)
        tr.step(batch)
        if step % 10 == 0:
            ck.save_async(step, {"params": tr.state.params})
    ck.finalize()
finally:
    wd.stop()
assert ck.latest_verified_step() is not None   # async saves finalized
ck.close()

# coord long-poll counters move when a parked wait fires
svc = PyCoordService()
svc.join("a")
t = threading.Thread(target=svc.wait_epoch, args=(svc.epoch(), 5.0))
t.start(); time.sleep(0.1); svc.join("b"); t.join(timeout=5)
m = svc.server_metrics()
assert m["longpolls_parked"] >= 1 and m["longpolls_fired"] >= 1, m

c = get_counters()
evt = tr.resize_events[-1]
assert evt["prewarm_hit"] and evt["compile_ms"] < 100.0, evt
assert c.get("prewarm_hits") >= 1, c.snapshot()
assert c.get("checkpoint_async_saves") >= 4, c.snapshot()
assert c.get("stalls_detected", scope="perf-smoke") == 0, c.snapshot()
print("perf smoke OK:", {k: v for k, v in c.snapshot().items()
                         if "prewarm" in k or "async" in k})
EOF

echo "== telemetry smoke (/metrics both backends + merged reform span tree)"
# Part A: exposition conformance over a live native coordinator and a
# controller-shaped Python process, held to the same strict parser the
# tests use — the "one scrape config covers everything" claim, executed.
JAX_PLATFORMS=cpu python - <<'EOF'
import json, urllib.request
from tests.test_observability import parse_prometheus
from edl_tpu.coord import PyCoordService
from edl_tpu.coord.server import spawn_server
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.health import serve_health
from edl_tpu.observability.metrics import MetricsRegistry

def scrape(port, path="/metrics"):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode(), r.headers["Content-Type"]

# native backend
h = spawn_server(health_port=0)
try:
    c = h.client(); c.join("w0", "a"); c.add_task(b"x")
    body, ctype = scrape(h.health_port)
    assert "version=0.0.4" in ctype, ctype
    s = parse_prometheus(body)
    assert s["edl_coord_requests_total"] >= 2, s
    assert s['edl_coord_queue_tasks{state="todo"}'] == 1, s
    assert s["edl_coord_members"] == 1, s
    c.close()
finally:
    h.stop()

# python backend: controller-style serve_health + PyCoordService gauges;
# series names must match the native exposition name-for-name
svc = PyCoordService(); svc.join("a"); svc.add_task(b"x")
reg = MetricsRegistry(); svc.register_metrics(reg)
s = parse_prometheus(reg.render())
assert s['edl_coord_queue_tasks{state="todo"}'] == 1, s
for parity in ("edl_coord_requests_total", "edl_coord_longpolls_parked_total",
               "edl_coord_members", "edl_coord_membership_epoch"):
    assert parity in s, (parity, sorted(s))
get_counters().inc("ci_telemetry_probe")
srv = serve_health(0, {"ok": lambda: True}, host="127.0.0.1")
try:
    body, ctype = scrape(srv.server_address[1])
    assert "version=0.0.4" in ctype, ctype
    s = parse_prometheus(body)
    assert s["edl_ci_telemetry_probe_total"] >= 1, s
    health, _ = scrape(srv.server_address[1], "/healthz")
    assert json.loads(health)["ok"] is True
finally:
    srv.shutdown()
print("telemetry scrape OK (native + python backends)")
EOF

# Part B: a scripted stall→kill→reform under the supervisor must leave a
# merged job timeline whose root reform span decomposes into the child's
# named startup phases, plus a flight record.  Runs from a real file (not
# stdin) because the spawn-context world children re-import __main__.
TELE_TMP="$(mktemp -d)"
cat > "$TELE_TMP/reform_span_smoke.py" <<'EOF'
import functools, json, os, sys, tempfile
import numpy as np

sys.path.insert(0, os.getcwd())
from tests.test_telemetry import (_tele_init_state, _tele_load_state,
                                  _tele_train_world)

def main():
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.observability.tracing import Tracer
    from edl_tpu.runtime.multihost import run_elastic_worker, save_numpy_tree

    tmp = tempfile.mkdtemp(prefix="edl-ci-tele-")
    traces = os.path.join(tmp, "traces")
    os.environ["EDL_MH_TRACE"] = traces
    h = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
    client = CoordClient("127.0.0.1", h.port)
    try:
        outcome = run_elastic_worker(
            client, "w0",
            init_state=_tele_init_state,
            train_world=functools.partial(
                _tele_train_world, marker=os.path.join(tmp, "wedged"),
                done_at=14, wedge_at=5),
            save_state=save_numpy_tree, load_state=_tele_load_state,
            ckpt_dir=tmp, settle_s=0.1, warm_spawn=False,
            reform_grace_s=2.0, stall_floor_s=1.5, stall_k=6.0)
        assert outcome.step == 14, outcome
        files = sorted(os.path.join(traces, f) for f in os.listdir(traces))
        merged = Tracer.merge_files(files)
        slices = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        roots = [e for e in slices if e["name"] == "reform"]
        assert len(roots) >= 2, [e["name"] for e in slices]
        phases = {"world_start.spawn_imports",
                  "world_start.coordinator_handshake",
                  "world_start.device_acquire", "world_start.restore"}
        for root in roots:
            tid = root["args"]["trace_id"]
            names = {e["name"] for e in slices
                     if e["args"].get("trace_id") == tid}
            assert phases <= names, (tid, names)
        assert any(f.startswith("flightrec-") and "stall" in f
                   for f in os.listdir(tmp)), os.listdir(tmp)
        print("reform span tree OK:", len(roots), "roots,",
              len(slices), "spans")
    finally:
        client.close()
        h.stop()

if __name__ == "__main__":
    main()
EOF
JAX_PLATFORMS=cpu python "$TELE_TMP/reform_span_smoke.py"
rm -rf "$TELE_TMP"

echo "== coordinator HA smoke (primary SIGKILL mid-run: 1 failover, 0 reforms)"
# A supervised training run against a replicated coordinator pair loses
# its PRIMARY to SIGKILL mid-run: training must resume against the
# promoted standby with exactly one observed client failover and ZERO
# world reforms, and the promoted standby's /metrics must stay green
# under the strict exposition parser.  Runs from a real file (spawn-
# context world children re-import __main__).
HA_TMP="$(mktemp -d)"
cat > "$HA_TMP/ha_smoke.py" <<'EOF'
import functools, os, signal, sys, tempfile, threading, time
import urllib.request

import numpy as np

sys.path.insert(0, os.getcwd())


def _init_state():
    return {"step": np.zeros((), np.int32)}


def _load_state(path):
    from edl_tpu.runtime.multihost import load_numpy_tree

    return load_numpy_tree(path, _init_state())


def _train_world(world, state, should_stop, *, done_at=30, heartbeat=None):
    step = int(state["step"])
    while step < done_at:
        if should_stop():
            return {"step": np.asarray(step, np.int32)}, True
        step += 1
        if heartbeat is not None:
            heartbeat(step)
        time.sleep(0.1)
    return {"step": np.asarray(step, np.int32)}, False


def main():
    from tests.test_observability import parse_prometheus
    from edl_tpu.coord import CoordClient, spawn_ha_pair
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.runtime.multihost import run_elastic_worker, save_numpy_tree

    tmp = tempfile.mkdtemp(prefix="edl-ci-ha-")
    pr, sb = spawn_ha_pair(tmp, member_ttl_ms=6000, repl_lease_ms=1000,
                           health_port=0)
    client = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                         reconnect_window_s=15.0, promote_grace_s=0.3,
                         endpoints=[("127.0.0.1", sb.port)])
    # assassin: SIGKILL the primary once the world is PROVABLY
    # mid-training (the stall-watchdog heartbeat file shows step >= 5),
    # so the failover always lands inside the training window
    def assassinate():
        hb = os.path.join(tmp, "hb-w0")
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if int(open(hb).read().strip()) >= 5:
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        pr.process.send_signal(signal.SIGKILL)
    threading.Thread(target=assassinate, daemon=True).start()
    try:
        outcome = run_elastic_worker(
            client, "w0",
            init_state=_init_state,
            train_world=functools.partial(_train_world, done_at=60),
            save_state=save_numpy_tree, load_state=_load_state,
            ckpt_dir=tmp, settle_s=0.1, warm_spawn=False,
            reform_grace_s=2.0, stall_floor_s=30.0)
        assert outcome.step == 60, outcome
        c = get_counters()
        assert c.get("coord_failovers") == 1, c.snapshot()
        assert c.get("world_reforms") == 0, c.snapshot()
        assert c.get("coord_fencing_rejects") == 0, c.snapshot()
        # strict exposition parse on the PROMOTED standby
        with urllib.request.urlopen(
                f"http://127.0.0.1:{sb.health_port}/metrics",
                timeout=5) as r:
            s = parse_prometheus(r.read().decode())
        assert s["edl_coord_role"] == 0, s       # promoted: primary
        assert s["edl_coord_fence"] == 1, s      # exactly one promotion
        assert s["edl_coord_promotions_total"] == 1, s
        # epoch == 2: the worker's join (1) + its graceful leave (2) —
        # membership survived the failover with NO rejoin/expiry churn
        assert s["edl_coord_membership_epoch"] == 2, s
        print("HA smoke OK: failovers=1 reforms=0 fence=1 step=60")
    finally:
        client.close()
        pr.stop()
        sb.stop()


if __name__ == "__main__":
    main()
EOF
JAX_PLATFORMS=cpu python "$HA_TMP/ha_smoke.py"
rm -rf "$HA_TMP"

echo "== control-plane scale smoke (200 members, follower read, delta bytes)"
# The coordinator scale-out tentpole (doc/coordinator_scale.md), small:
# 200 simulated member slots form over ONE multiplexed connection per
# simulated host with coalesced KEEPALIVE heartbeats; a follower serves
# a version-gated read while the primary is SIGSTOPped; a crash reform
# (SIGKILL) completes under a fixed budget with every slot re-confirmed
# on the promoted standby; and replication bytes per KV put are asserted
# O(delta) — an order of magnitude under the full-snapshot size the
# pre-PR stream shipped per mutation — via the new METRICS counters.
JAX_PLATFORMS=cpu python - <<'EOF'
import signal, socket, tempfile, threading, time

from edl_tpu.coord import CoordClient, CoordMux
from edl_tpu.coord.server import spawn_server
from edl_tpu.runtime.discovery import BatchKeepalive

N, HOSTS = 200, 2

def metrics(port):
    with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
        s.settimeout(5)
        s.sendall(b"METRICS\n")
        r = s.makefile("rb").readline().decode().strip().split(" ")
    keys = ("requests", "parked", "fired", "repl_bytes", "repl_deltas",
            "repl_ckpts", "snapshot_bytes", "follower_reads")
    return {k: int(r[i + 1]) for i, k in enumerate(keys) if len(r) > i + 1}

tmp = tempfile.mkdtemp(prefix="edl-ci-coordscale-")
sb = spawn_server(standby=True, state_file=tmp + "/b.state")
pr = spawn_server(state_file=tmp + "/a.state",
                  replicate_to=f"127.0.0.1:{sb.port}", repl_lease_ms=1000)
muxes = [CoordMux("127.0.0.1", pr.port, timeout=5.0,
                  reconnect_window_s=20.0, promote_grace_s=0.3,
                  endpoints=[("127.0.0.1", sb.port)])
         for _ in range(HOSTS)]
kas = []
try:
    # formation: one mux per host, coalesced keepalives
    per = N // HOSTS
    for h, mux in enumerate(muxes):
        c = mux.client()
        ka = BatchKeepalive(c, interval_s=1.0)
        for i in range(h * per, (h + 1) * per):
            c.join(f"m{i}", f"a{i}")
            ka.add(f"m{i}", f"a{i}")
        kas.append(ka)
    c0 = muxes[0].client()
    assert c0.epoch() == N
    m0 = metrics(pr.port)
    for ka in kas:
        assert ka.beat_once() == per
    m1 = metrics(pr.port)
    hb_reqs = m1["requests"] - m0["requests"] - 1
    assert hb_reqs <= HOSTS + 1, hb_reqs  # N heartbeats in HOSTS lines

    # O(delta) replication bytes per KV put vs the snapshot the pre-PR
    # stream would have shipped for EACH of these mutations
    for i in range(20):
        c0.kv_set(f"ci/k{i}", b"x" * 32)
    m2 = metrics(pr.port)
    per_put = (m2["repl_bytes"] - m1["repl_bytes"]) / 20
    assert m2["repl_deltas"] >= 20, m2
    assert per_put * 10 < m2["snapshot_bytes"], (per_put, m2)

    # follower read while the primary is FROZEN: the version-gated READ
    # is served from the standby's applied stream position
    cf = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                     reconnect_window_s=10.0,
                     endpoints=[("127.0.0.1", sb.port)],
                     follower_reads=True)
    assert cf.kv_get("ci/k0") == b"x" * 32  # learn the follower path
    pr.process.send_signal(signal.SIGSTOP)
    time.sleep(0.1)
    t0 = time.monotonic()
    assert cf.kv_get("ci/k1") == b"x" * 32
    frozen_read_s = time.monotonic() - t0
    assert frozen_read_s < 1.0, frozen_read_s
    pr.process.send_signal(signal.SIGCONT)
    fr = metrics(sb.port)["follower_reads"]
    assert fr >= 2, fr
    cf.close()

    # crash reform under budget: kill the primary; every host's mux
    # fails over (promoting the standby) and re-confirms all its slots
    pr.process.send_signal(signal.SIGKILL)
    pr.process.wait(timeout=10)
    t0 = time.monotonic()
    def recover(h):
        muxes[h].client().kv_get("ci/k0")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if kas[h].beat_once() == per:
                return
            time.sleep(0.05)
        raise TimeoutError(f"host {h} never recovered")
    ts = [threading.Thread(target=recover, args=(h,))
          for h in range(HOSTS)]
    for t in ts: t.start()
    for t in ts: t.join()
    reform_s = time.monotonic() - t0
    assert reform_s < 10.0, reform_s
    assert muxes[0].client().epoch() == N  # zero rejoin churn
    print(f"control-plane scale smoke OK: members={N} "
          f"hb_requests_per_beat={hb_reqs} repl_bytes_per_put={per_put:.0f} "
          f"snapshot_bytes={m2['snapshot_bytes']} "
          f"follower_reads={fr} reform_s={reform_s:.2f}")
finally:
    for ka in kas:
        ka.stop()
    for mux in muxes:
        mux.close()
    pr.stop()
    sb.stop()
EOF

echo "== goodput smoke (chip-second ledger conservation + curve in coord KV)"
# Part A: an in-process trainer eats one injected resize with the process
# ledger installed — compile/reshard chip-seconds attributed, curve
# samples at both world sizes persisted in coordinator KV, the
# edl_goodput_* series green under the strict exposition parser, and the
# conservation invariant (attributed == wall x world within 1 %) held.
# Part B: a short SUPERVISED run with one stall->kill->reform — the
# supervisor's own ledger attributes queued/productive/stall/reform_dark
# and still conserves through the kill.  Real file: spawn-context world
# children re-import __main__.
GP_TMP="$(mktemp -d)"
cat > "$GP_TMP/goodput_smoke.py" <<'EOF'
import functools, os, sys, tempfile

sys.path.insert(0, os.getcwd())


def main():
    import jax, numpy as np, optax

    from tests.test_observability import parse_prometheus
    from tests.test_telemetry import (_tele_init_state, _tele_load_state,
                                      _tele_train_world)
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.models import mlp
    from edl_tpu.observability import goodput
    from edl_tpu.observability.goodput import CurveStore, GoodputLedger
    from edl_tpu.observability.metrics import get_registry
    from edl_tpu.parallel.mesh import MeshSpec
    from edl_tpu.runtime.elastic import ElasticTrainer
    from edl_tpu.runtime.multihost import run_elastic_worker, save_numpy_tree

    h = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
    client = CoordClient("127.0.0.1", h.port)
    try:
        # -- part A: injected resize + curve samples into coord KV ------
        led = goodput.set_process_ledger(GoodputLedger(
            job="ci/goodput", world_size=2, base_phase=goodput.QUEUED))
        goodput.register_metrics(led)
        params = mlp.init(jax.random.key(0), [16, 32, 4])
        tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                            spec=MeshSpec(dp=-1), initial_world_size=2)
        rng = np.random.default_rng(0)
        batch = (rng.normal(size=(64, 16)).astype(np.float32),
                 rng.integers(0, 4, 64).astype(np.int32))
        store = CurveStore(client, "ci/goodput")
        import time as _t
        tr.step(batch)
        led.reset(goodput.PRODUCTIVE)

        def window(n):
            t0 = _t.perf_counter()
            for _ in range(n):
                tr.step(batch)
            return 64 * n / (_t.perf_counter() - t0)

        store.record(2, window(30), shape=tr.shape.describe())
        assert tr.resize(4), "injected resize failed"
        store.record(4, window(30), shape=tr.shape.describe())
        snap = led.snapshot()
        assert led.conserves(0.01), snap
        assert 0.0 < snap["goodput_fraction"] <= 1.0, snap
        # strictly positive: resize(4) without a prewarm always pays an
        # inline compile, so a regressed compile-attribution path (the
        # note_span wiring going no-op) must fail here, not pass green
        assert snap["chip_seconds"]["compile"] > 0.0, snap
        assert snap["chip_seconds"]["reshard"] > 0.0, snap
        # curve samples present in coordinator KV, both world sizes
        raw = client.kv_get("goodput-curve/ci/goodput")
        assert raw is not None, "curve never persisted"
        curve = goodput.load_curve(client, "ci/goodput")
        assert curve.world_sizes() == [2, 4], curve.summary()
        # edl_goodput_* green under the strict parser
        series = parse_prometheus(get_registry().render())
        frac = series['edl_goodput_fraction{job="ci/goodput"}']
        assert 0.0 < frac <= 1.0, frac
        assert series[
            'edl_goodput_chip_seconds{job="ci/goodput",'
            'phase="reshard"}'] > 0
        assert series['edl_goodput_curve_tokens_per_second'
                      '{job="ci/goodput",world_size="4"}'] > 0
        goodput.set_process_ledger(None)

        # -- part B: supervised stall->kill->reform conserves -----------
        tmp = tempfile.mkdtemp(prefix="edl-ci-goodput-")
        outcome = run_elastic_worker(
            client, "gp0",
            init_state=_tele_init_state,
            train_world=functools.partial(
                _tele_train_world, marker=os.path.join(tmp, "wedged"),
                done_at=14, wedge_at=5),
            save_state=save_numpy_tree, load_state=_tele_load_state,
            ckpt_dir=tmp, settle_s=0.1, warm_spawn=False,
            reform_grace_s=2.0, stall_floor_s=1.5, stall_k=6.0)
        assert outcome.step == 14, outcome
        g = outcome.goodput
        assert g is not None, "supervisor ledger missing"
        assert g["conservation_error_pct"] < 1.0, g
        assert 0.0 < g["goodput_fraction"] <= 1.0, g
        assert g["chip_seconds"]["reform_dark"] > 0, g   # the kill's cost
        assert g["chip_seconds"]["stall"] > 0, g         # the wedge's cost
        print("goodput smoke OK: fraction_A=%.3f fraction_B=%.3f "
              "curve=%s" % (frac, g["goodput_fraction"], curve.summary()))
    finally:
        client.close()
        h.stop()


if __name__ == "__main__":
    main()
EOF
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
  python "$GP_TMP/goodput_smoke.py"
rm -rf "$GP_TMP"

echo "== reshard smoke (dynamic reparallelization + dryrun sharding checks)"
# A dp→fsdp reparallelizing resize on CPU devices through the
# transactional path: zero failures, state preserved, a nonzero replan
# phase observation on the shared registry, and the recorded bytes_moved
# under the plan's own gather-scatter bound.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python - <<'EOF'
import re

import jax, numpy as np, optax

from edl_tpu.models import mlp
from edl_tpu.observability.metrics import get_registry
from edl_tpu.parallel.mesh import MeshShape, MeshSpec
from edl_tpu.runtime.elastic import ElasticTrainer

params = mlp.init(jax.random.key(0), [16, 32, 4])
tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                    spec=MeshSpec(dp=-1), param_sharding="fsdp",
                    initial_world_size=4)
rng = np.random.default_rng(0)
batch = (rng.normal(size=(64, 16)).astype(np.float32),
         rng.integers(0, 4, 64).astype(np.int32))
tr.step(batch)
ev = tr.eval_loss(batch)
assert tr.resize(MeshShape(dp=2, fsdp=2))
assert abs(tr.eval_loss(batch) - ev) < 1e-5  # no checkpoint round-trip
evt = tr.resize_events[-1]
assert evt["shape"] == "dp2xfsdp2", evt
assert evt["bytes_moved"] < evt["bytes_naive"], evt
assert tr.resizes_failed == 0
tr.step(batch)
m = re.search(r'edl_resize_phase_seconds_count\{phase="replan"\} (\d+)',
              get_registry().render())
assert m and int(m.group(1)) >= 1, "no replan phase observation"
print("reshard smoke OK:", evt["shape"], "bytes_moved", evt["bytes_moved"],
      "vs naive", evt["bytes_naive"])
EOF

# dryrun sharding checks green across the swept sizes (one process per n:
# the virtual device count pins at backend init)
for n in 2 4 8; do
  JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; g.dryrun_multichip($n)" \
    | grep -q DRYRUN_COMM || { echo "dryrun n=$n produced no comm record"; exit 1; }
done
# negative control: an injected replicated-instead-of-fsdp layout must
# FAIL the dryrun (non-zero exit) — the machine check is live, not décor
if JAX_PLATFORMS=cpu EDL_DRYRUN_INJECT=replicate \
   python -c "import __graft_entry__ as g; g.dryrun_multichip(4)" 2>/dev/null; then
  echo "dryrun did not catch the injected layout regression"; exit 1
fi
echo "dryrun sharding checks OK (n=2,4,8 + injected-regression control)"

echo "== serving smoke (traffic through a prewarmed scale-up + rolling reload)"
# Elastic inference tripwire (doc/serving.md): Poisson traffic against a
# continuous-batching fleet through ONE hint→prewarm scale-up (the hit
# asserted — the serve-step compile stayed off the traffic path) and one
# rolling weight reload, with p99 under the smoke SLO, ZERO dropped
# requests, and the edl_serving_* series green under the strict parser.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python - <<'EOF'
import threading, time

import jax, numpy as np

from tests.test_observability import parse_prometheus
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.metrics import get_registry
from edl_tpu.runtime.serving import PoissonTraffic, ServingFleet

SLO_MS = 150.0  # smoke SLO: generous for loaded CI hosts
params = mlp.init(jax.random.key(0), [16, 32, 4])
fleet = ServingFleet(lambda p, b: mlp.apply(p, b[0]), params,
                     example_row=(np.zeros((16,), np.float32),),
                     job="ci/serving", max_batch_size=8, max_queue_ms=1.0,
                     slo_p99_ms=SLO_MS, drain_timeout_s=10.0)
try:
    fleet.scale_to(1)
    traffic = PoissonTraffic(
        fleet, lambda i: (np.full((16,), i % 9, np.float32),),
        qps=200, seed=3)
    # the autoscaler-plan moment: hint FIRST (build starts off the
    # traffic path), actuate second — the adoption is the prewarm hit
    fleet.hint(2)
    traffic.run(1.0)
    fleet.scale_to(2)
    assert fleet.prewarm_hits >= 1, "scale-up missed the prewarmed replica"
    # rolling reload to generation 2 while traffic keeps flowing
    p2 = jax.tree.map(lambda a: a * 1.01, params)
    rl = threading.Thread(target=lambda: fleet.rolling_reload(p2, 2))
    rl.start(); traffic.run(1.5); rl.join()
    tally = traffic.await_all(timeout_s=30.0)
    assert tally["dropped"] == 0, tally
    assert tally["errors"] == 0 and tally["timeouts"] == 0, tally
    assert tally["p99_ms"] <= SLO_MS, tally
    assert fleet.generation == 2
    # a post-reload answer comes from generation 2's weights
    got = np.asarray(fleet.submit((np.ones((16,), np.float32),)).wait(10))
    want = np.asarray(mlp.apply(p2, np.ones((1, 16), np.float32)))[0]
    assert np.allclose(got, want)
finally:
    fleet.stop()
c = get_counters()
assert c.get("serving_dropped_requests", job="ci/serving") == 0
s = parse_prometheus(get_registry().render())
assert s['edl_serving_requests_total{job="ci/serving"}'] >= tally["served"]
assert s['edl_serving_prewarm_hits_total{job="ci/serving"}'] >= 1
assert s['edl_serving_reloads_total{job="ci/serving"}'] >= 2
assert s['edl_serving_request_seconds_count{job="ci/serving"}'] > 0
print("serving smoke OK:", {k: tally[k] for k in
                            ("served", "p50_ms", "p99_ms", "dropped")},
      "prewarm_hits", fleet.prewarm_hits, "generation", fleet.generation)
EOF

echo "== decode smoke (speculative batching through a live 2->1 scale-down)"
# Autoregressive tripwire (doc/serving.md §autoregressive serving +
# §decode-v2): sessions decode SPECULATIVELY (self-drafted multi-token
# verify steps, strictly lossless) against a 2-replica DecodeFleet with
# a paged KV pool, the fleet scales 2→1 MID-DECODE (every live
# session's K/V evacuates to the survivor), zero dropped sessions,
# every continuation bitwise-equal to the full-context greedy
# reference, an identical re-admitted prompt adopts its sealed prefix
# blocks without re-prefill, and the edl_serving_ttft/tpot/kv_* +
# edl_decode_spec_*/edl_kv_prefix_* series green under the strict
# parser.
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np

from edl_tpu.models.transformer import TINY, apply, init
from edl_tpu.observability.metrics import get_registry, parse_exposition
from edl_tpu.runtime.serving import DecodeFleet

params = init(__import__("jax").random.PRNGKey(0), TINY)

def ref_decode(prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        logits = apply(params, np.asarray([toks], np.int32), TINY)
        t = int(np.asarray(logits[0, -1]).argmax())
        out.append(t); toks.append(t)
    return out

rng = np.random.default_rng(5)
ps = [rng.integers(1, 255, size=int(rng.integers(3, 10))).tolist()
      for _ in range(6)]
ps += [[11, 4, 11, 4, 11, 4, 11, 4]] * 2  # periodic: drafts accept
fleet = DecodeFleet(params, TINY, job="ci/decode", roles={"decode": 2},
                    slots=3, prefill_chunk=8, kv_blocks=48,
                    kv_block_size=8, max_blocks_per_session=8,
                    spec_tokens=4, spec_ngram=3)
try:
    ss = [fleet.submit(p, max_new_tokens=24) for p in ps]
    for s in ss[:3]:
        s.wait_first_token(60)     # demonstrably mid-decode...
    fleet.scale_to(1)              # ...when the fleet shrinks LIVE
    outs = [s.wait(120) for s in ss]
    # prefix sharing: the same 24-token prompt twice — the second
    # admission adopts the first's sealed blocks, no re-prefill
    pp = list(range(7, 31))
    pa = fleet.submit(pp, max_new_tokens=8).wait(60)
    pb = fleet.submit(pp, max_new_tokens=8).wait(60)
finally:
    fleet.stop(drain=False)
assert fleet.sessions_failed == 0, "scale-down dropped sessions"
assert fleet.sessions_completed == len(ps) + 2
assert fleet.migrations >= 1, "shrink never migrated a session"
for p, o in zip(ps, outs):
    assert o == ref_decode(p, 24), "migrated continuation diverged"
assert fleet.kv_blocks()[0] == 0, "finished sessions leaked KV blocks"
series = parse_exposition(get_registry().render())  # strict grammar or die
assert any(k.startswith("edl_serving_ttft_seconds_bucket")
           and 'job="ci/decode"' in k for k in series), "no TTFT series"
assert any(k.startswith("edl_serving_tpot_seconds_bucket")
           and 'job="ci/decode"' in k for k in series), "no TPOT series"
assert any(k.startswith("edl_serving_kv_blocks_total")
           and 'job="ci/decode"' in k for k in series), "no KV gauges"
assert series.get('edl_serving_kv_admission_rejects_total'
                  '{job="ci/decode"}', -1) == 0
assert pa == pb == ref_decode(pp, 8), "prefix-shared continuation diverged"
spec_ok = sum(v for k, v in series.items()
              if k.startswith("edl_decode_spec_accepted_total")
              and 'job="ci/decode"' in k)
assert spec_ok > 0, "speculative decode never accepted a draft"
hits = sum(v for k, v in series.items()
           if k.startswith("edl_kv_prefix_hits_total")
           and 'job="ci/decode"' in k)
assert hits >= 1, "re-admitted prompt never hit the prefix cache"
saved = sum(v for k, v in series.items()
            if k.startswith("edl_kv_prefix_tokens_saved_total")
            and 'job="ci/decode"' in k)
assert saved >= 8, "prefix hit saved no prefill tokens"
print("decode smoke OK:", {"sessions": fleet.sessions_completed,
                           "migrations": fleet.migrations,
                           "dropped": fleet.sessions_failed,
                           "spec_accepted": int(spec_ok),
                           "prefix_hits": int(hits)})
EOF

echo "== calib smoke (predicted-vs-measured ledger across the cost models)"
# Calibration plane end-to-end (doc/observability.md §calibration
# plane): with the process ledger armed against a coordinator, a
# dp→fsdp trainer resize, a speculative DecodeFleet scaled 2→1
# mid-decode (KV evacuation between distinct devices), a goodput-curve
# re-record and a settled serving scale plan must each land ≥1
# predicted-vs-measured sample on their predictor; every
# edl_calibration_* series passes the strict parser; the factor
# records read back from coordinator KV (calib/<job>/<predictor>) and
# through the CalibrationFactors hook; the drift alert stays QUIET
# (consecutive-window + min-sample gating — the negative control);
# and `edl-tpu calib` renders a non-empty dashboard off a live
# /metrics endpoint.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python - <<'EOF'
import contextlib, io

import jax, numpy as np, optax

from edl_tpu import cli
from edl_tpu.api.types import ServingJob, ServingSpec
from edl_tpu.coord import PyCoordService
from edl_tpu.models import mlp
from edl_tpu.models.transformer import TINY, init
from edl_tpu.observability import calib
from edl_tpu.observability.calib import (CalibrationFactors,
                                         CalibrationLedger, load_factors)
from edl_tpu.observability.goodput import CurveStore
from edl_tpu.observability.health import serve_health
from edl_tpu.observability.metrics import get_registry, parse_exposition
from edl_tpu.parallel.mesh import MeshShape, MeshSpec
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.serving import DecodeFleet, FleetStats
from edl_tpu.scheduler.autoscaler import ServingScaler

JOB = "ci/calib"
kv = PyCoordService()
led = calib.set_process_calib(CalibrationLedger(job=JOB, coord=kv))
try:
    # 1. trainer resize, dp2 -> dp2xfsdp2: the reshard_seconds predictor
    #    (nominal-bandwidth transfer price vs the measured reshard wall)
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                        spec=MeshSpec(dp=-1), initial_world_size=2)
    rng = np.random.default_rng(0)
    batch = (rng.normal(size=(64, 16)).astype(np.float32),
             rng.integers(0, 4, 64).astype(np.int32))
    tr.step(batch)
    assert tr.resize(MeshShape(dp=2, fsdp=2)), "dp->fsdp resize failed"
    tr.step(batch)
    assert led.sample_count("reshard_seconds") >= 1, led.snapshot()

    # 2. decode-evacuation drill: speculative sessions through a live
    #    2->1 shrink -- kv_move_seconds, spec_accept and the interleave
    #    budget predictors all fire on the way
    tparams = init(jax.random.PRNGKey(0), TINY)
    prng = np.random.default_rng(7)
    ps = [prng.integers(1, 255, size=int(prng.integers(4, 10))).tolist()
          for _ in range(4)]
    ps += [[11, 4, 11, 4, 11, 4, 11, 4]] * 2   # periodic: drafts accept
    fleet = DecodeFleet(tparams, TINY, job=JOB, roles={"decode": 2},
                        slots=3, prefill_chunk=8, kv_blocks=48,
                        kv_block_size=8, max_blocks_per_session=8,
                        spec_tokens=4, spec_ngram=3,
                        devices_per_replica=1)
    try:
        ss = [fleet.submit(p, max_new_tokens=16) for p in ps]
        for s in ss[:2]:
            s.wait_first_token(60)     # mid-decode...
        fleet.scale_to(1)              # ...KV evacuates to the survivor
        for s in ss:
            s.wait(120)
    finally:
        fleet.stop(drain=False)
    assert fleet.sessions_failed == 0, "scale-down dropped sessions"
    assert fleet.migrations >= 1, "shrink never migrated a session"
    for pred in ("kv_move_seconds", "spec_accept",
                 "interleave_decode_ms", "interleave_prefill_ms"):
        assert led.sample_count(pred) >= 1, (pred, led.snapshot())

    # 3. goodput curve: the second window at a measured size pairs the
    #    curve's prediction against the realized tok/s
    store = CurveStore(kv, JOB)
    store.record(2, 1000.0)
    store.record(2, 950.0)
    assert led.sample_count("goodput_curve") >= 1

    # 4. serving scale plan, settled at target: the stashed qps/p99
    #    predictions resolve against the realized window
    clock = [100.0]
    stats = {"default/svc": FleetStats(
        p50_ms=30.0, p99_ms=80.0, qps=10.0, queue_depth=0,
        replicas_ready=2, replicas_active=2, requests_windowed=20)}
    sc = ServingScaler(stats_for=lambda uid: stats[uid],
                       actuate=lambda uid, n: None,
                       clock=lambda: clock[0])
    sc.on_add(ServingJob(name="svc", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=50.0)))
    assert sc.tick() == {"default/svc": 3}  # breach -> plan to 3
    stats["default/svc"] = FleetStats(
        p50_ms=10.0, p99_ms=30.0, qps=12.0, queue_depth=0,
        replicas_ready=3, replicas_active=3, requests_windowed=25)
    clock[0] += sc.calib_settle_s + 1.0
    sc.tick()
    assert led.sample_count("serving_scale_qps") >= 1
    assert led.sample_count("serving_scale_p99") >= 1
finally:
    calib.set_process_calib(None)

# every instrumented predictor landed, and the whole exposition holds
# under the strict parser
series = parse_exposition(get_registry().render())
PREDICTORS = ("reshard_seconds", "kv_move_seconds", "spec_accept",
              "interleave_decode_ms", "interleave_prefill_ms",
              "serving_scale_qps", "serving_scale_p99", "goodput_curve")
for pred in PREDICTORS:
    assert any(k.startswith("edl_calibration_samples_total")
               and f'predictor="{pred}"' in k
               for k in series), f"no scraped series for {pred}"
    assert any(k.startswith("edl_calibration_factor")
               and f'predictor="{pred}"' in k
               for k in series), f"no factor gauge for {pred}"

# factor records persisted under calib/<job>/<predictor> and readable
# through the opt-in CalibrationFactors hook
docs = load_factors(kv, JOB)
for pred in ("reshard_seconds", "kv_move_seconds"):
    assert pred in docs and docs[pred]["factor"] > 0, sorted(docs)
facs = CalibrationFactors(kv, JOB, min_samples=1)
assert facs.factor("reshard_seconds") > 0

# `edl-tpu calib` off a live /metrics endpoint: non-empty dashboard,
# and --check exits 0 -- the drift rule's consecutive-window gating
# keeps one noisy window from paging (the negative control)
srv = serve_health(0, {}, host="127.0.0.1")
buf = io.StringIO()
try:
    port = srv.server_address[1]
    with contextlib.redirect_stdout(buf):
        rc = cli.main(["calib", "--scrape-targets", f"127.0.0.1:{port}",
                       "--sweeps", "1", "--check"])
finally:
    srv.shutdown()
out = buf.getvalue()
assert rc == 0, f"calib --check paged on a healthy fleet:\n{out}"
for pred in ("reshard_seconds", "kv_move_seconds", "goodput_curve"):
    assert pred in out, out
assert "DRIFT: none firing" in out, out
snap = led.snapshot()["predictors"]
print("calib smoke OK:", {p: (snap[p]["samples"],
                              round(snap[p]["factor"], 2))
                          for p in PREDICTORS})
EOF

echo "== scrape-plane smoke (HA pair + serving fleet under the MetricsScraper)"
# The fleet scrape plane end-to-end (doc/observability.md §scrape-plane):
# an HA coordinator pair and a live serving fleet are discovered/scraped
# by a MetricsScraper — the fleet via its TTL'd serving-metrics-addr KV
# key, the coordinators as static targets — then (1) FleetView's
# qps/p99 rollup is held against the fleet's own FleetStats within
# tolerance, (2) an injected SLO breach fires the fast-burn rule within
# 2 evaluation windows, and (3) the SCRAPE-FED ServingScaler reproduces
# the scale-up decision the hook-fed policy test pins
# (tests/test_serving.py::test_policy_grows_on_p99_breach: 2 → 3).
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python - <<'EOF'
import tempfile, threading, time

import jax, numpy as np

from edl_tpu.api.types import ServingJob, ServingSpec
from edl_tpu.coord.client import CoordClient
from edl_tpu.coord.server import spawn_ha_pair
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.metrics import get_registry
from edl_tpu.observability.scrape import (
    AlertEngine, BurnRateRule, FleetView, MetricsScraper, ScrapeTarget,
    kv_targets, render_fleet_dashboard)
from edl_tpu.runtime.serving import PoissonTraffic, ServingFleet
from edl_tpu.scheduler.autoscaler import ServingScaler

JOB = "ci/scrape"
SLO_MS = 150.0
primary, standby = spawn_ha_pair(
    tempfile.mkdtemp(prefix="edl-ci-scrape-"), health_port=0)
client = CoordClient("127.0.0.1", primary.port)
params = mlp.init(jax.random.key(0), [16, 32, 4])
fleet = ServingFleet(lambda p, b: mlp.apply(p, b[0]), params,
                     example_row=(np.zeros((16,), np.float32),),
                     job=JOB, max_batch_size=8, max_queue_ms=1.0,
                     slo_p99_ms=SLO_MS, kv=client)
try:
    fleet.scale_to(1)
    fleet.serve_metrics(0, host="127.0.0.1", publish=True, replica="r0")
    scraper = MetricsScraper(discover=[kv_targets(client)],
                             interval_s=0.2, timeout_s=2.0)
    scraper.add_target(ScrapeTarget(
        name="coord/primary", addr=f"127.0.0.1:{primary.health_port}",
        labels={"role": "coordinator"}))
    scraper.add_target(ScrapeTarget(
        name="coord/standby", addr=f"127.0.0.1:{standby.health_port}",
        labels={"role": "coordinator"}))
    view = FleetView(scraper, window_s=2.0)
    engine = AlertEngine(view, rules=[BurnRateRule(
        budget_fraction=0.001, fast_window_s=2.0, slow_window_s=10.0,
        fast_factor=14.4, min_requests=50)])
    # dynamic discovery: the fleet's TTL'd KV key became a target
    scraper.sweep()
    names = {t.name for t in scraper.targets()}
    assert f"serving/{JOB}/r0" in names, names
    # traffic while sweeping, then the same-instant parity check
    traffic = PoissonTraffic(
        fleet, lambda i: (np.full((16,), i % 9, np.float32),),
        qps=200, seed=4)
    halt = threading.Event()
    def sweeper():
        while not halt.wait(0.2):
            scraper.sweep()
    t = threading.Thread(target=sweeper); t.start()
    traffic.run(3.0)
    scraper.sweep()
    st = view.stats_for(JOB)
    own = fleet.stats(window_s=2.0)
    halt.set(); t.join()
    tally = traffic.await_all(timeout_s=30.0)
    assert tally["dropped"] == 0 and tally["errors"] == 0, tally
    assert st.requests_windowed > 0, st
    assert 0.6 * own.qps <= st.qps <= 1.4 * own.qps, (st, own)
    assert st.p99_ms <= max(own.p99_ms * 4, 5.0), (st, own)
    assert own.p99_ms <= max(st.p99_ms * 4, 5.0), (st, own)
    # both HA members' coordinator series landed on one sweep config
    assert scraper.latest("edl_coord_members", agg="max") is not None
    states = {s["name"]: s["state"] for s in scraper.target_states()}
    assert states["coord/primary"] == "up", states
    assert states["coord/standby"] == "up", states
    # injected SLO breach: large observations + violations land in the
    # replica-owned series; the scraped view must (a) push the policy to
    # the PINNED hook-fed decision and (b) fire the fast-burn rule
    # within 2 evaluation windows
    h = get_registry().histogram("serving_request_seconds")
    for _ in range(60):
        h.observe(SLO_MS / 1000.0 * 1.6, job=JOB)
    get_counters().inc("serving_requests", 60, job=JOB)
    get_counters().inc("serving_slo_violations", 60, job=JOB)
    evals = None
    for i in range(1, 4):
        scraper.sweep()
        if "slo_fast_burn" in {a.rule for a in engine.evaluate()}:
            evals = i
            break
        time.sleep(0.2)
    assert evals is not None and evals <= 2, evals
    breach = view.stats_for(JOB)
    assert breach.p99_ms > SLO_MS, breach
    sc = ServingScaler().feed_from(view)
    job = ServingJob(name="scrape", namespace="ci", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=SLO_MS))
    decision = sc.decide(job, sc.stats_for(JOB), 2)
    assert decision == 3, decision  # the pinned hook-fed decision
    dash = render_fleet_dashboard(view, engine)
    assert JOB in dash and "slo_fast_burn" in dash, dash
    print("scrape smoke OK:", {"scraped_qps": st.qps, "own_qps": own.qps,
                               "scraped_p99_ms": st.p99_ms,
                               "own_p99_ms": own.p99_ms,
                               "fast_burn_evals": evals,
                               "decision": decision})
finally:
    fleet.stop()
    client.close()
    primary.stop()
    standby.stop()
EOF

echo "== determinism smoke (scripted 2→1→2 resize vs unresized control)"
# Accuracy-consistent elasticity tripwire: the SAME seeded job run with
# a scripted 2→1→2 resize must match the unresized control's loss
# trajectory within the documented policy (bitwise here: replicated
# accumulation on CPU), with every row trained exactly once and the
# virtual-worker remaps actually counted — a regression that lets a
# resize touch data order, RNG lineage, or the effective batch fails
# here, not in a user's A/B run.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
import jax, numpy as np, optax

from edl_tpu.coord import local_service
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.data import ShardRegistry
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.virtual import (VirtualBatches, VirtualConfig,
                                     VirtualWorkerLoop, loss_divergence,
                                     trajectories_equivalent)

rng = np.random.default_rng(1)
y = rng.integers(0, 4, 1024).astype(np.int32)
x = rng.normal(size=(1024, 16)).astype(np.float32)
reg = ShardRegistry()
ids = reg.register_arrays((x, y), num_shards=8)
cfg = VirtualConfig(vw_count=4, global_batch=32, job_seed=5)

def run(schedule):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    tr = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                        spec=MeshSpec(dp=-1), initial_world_size=2,
                        accum_mode="replicated")
    loop = VirtualWorkerLoop(tr, cfg, VirtualBatches(cfg, ids, reg.get),
                             kv=local_service(), job="ci-det")
    return loop.run(max_steps=18, world_size_for=schedule)

c0 = get_counters().get("vw_remaps")
ctrl = run(lambda s: 2)
res = run(lambda s: 2 if s < 6 else (1 if s < 12 else 2))
div = loss_divergence(ctrl.losses, res.losses)
assert trajectories_equivalent(ctrl.losses, res.losses), div
assert div["bitwise"], div
assert res.resizes == 2, res.resizes
assert get_counters().get("vw_remaps") - c0 > 0, "remaps never counted"
assert res.rows_duplicated() == 0
assert res.rows_missing(expected=18 * cfg.global_batch) == 0
print("determinism smoke OK:", div, "vw_remaps",
      get_counters().get("vw_remaps") - c0)
EOF

echo "== front-door smoke (LB → 2 replicas: keep-alive, hedge rescue, strict metrics, stitched trace)"
# The serving data plane tripwire (doc/serving.md §data-plane): a short
# pipelined burst through the load-balancer tier into two async
# front-door replicas must (a) ride persistent connections — requests ≫
# connections, (b) stay under the smoke SLO at p99, (c) drop nothing,
# (d) rescue an injected straggler iteration via a hedge whose late
# primary response is consumed and DISCARDED, (e) leave the new
# edl_lb_* / edl_frontdoor_* series green under the strict exposition
# parser, fetched over real HTTP like a production scraper would, and
# (f) yield a stitched LB→door→batch span tree for the hedged request —
# rendered by `edl-tpu trace`, with the hedge-loser span marked
# discarded (doc/serving.md §request tracing).
JAX_PLATFORMS=cpu python - <<'EOF'
import threading, time, socket, re, urllib.request
import numpy as np, jax

from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.health import serve_health
from edl_tpu.observability.metrics import iter_samples, parse_exposition
from edl_tpu.runtime.serving import ElasticServer
from edl_tpu.runtime.frontdoor import (BatchApp, FrontDoor, FD_READY,
                                       FD_RELOADING,
                                       build_predict_request)
from edl_tpu.runtime.lb import ServingLB

SLO_MS = 150.0
JOB = "ci/frontdoor"
SIZES = [8, 16, 4]
params = mlp.init(jax.random.key(0), SIZES)

class KV:  # in-process stand-in for the coordinator KV verbs used here
    def __init__(self): self.d, self.l = {}, threading.Lock()
    def kv_set(self, k, v):
        with self.l: self.d[k] = bytes(v)
    def kv_get(self, k):
        with self.l: return self.d.get(k)
    def kv_del(self, k):
        with self.l: return self.d.pop(k, None) is not None
    def kv_keys(self, p=""):
        with self.l: return [k for k in self.d if k.startswith(p)]

kv = KV()
def build():
    return ElasticServer(lambda p, b: mlp.apply(p, b[0]), params)
apps, doors = {}, {}
for name in ("ra", "rb"):
    apps[name] = BatchApp(build, SIZES[0], job=JOB, replica=name, kv=kv,
                          max_batch=32, max_queue_ms=1.0, addr_ttl_s=10.0)
    doors[name] = FrontDoor(apps[name], host="127.0.0.1",
                            job=f"{JOB}/{name}").start()
for app in apps.values():
    assert app.wait_ready(120)
lb = ServingLB(job=JOB, host="127.0.0.1", kv=kv, pool=2, discovery_s=0.1,
               sweep_ms=3.0, hedge_floor_ms=20.0).start()
deadline = time.monotonic() + 30
while time.monotonic() < deadline and sum(
        1 for u in lb.app.upstreams.values() if u.routable()) < 2:
    time.sleep(0.05)
assert sum(1 for u in lb.app.upstreams.values() if u.routable()) == 2

row = np.ones((SIZES[0],), np.float32)
req = build_predict_request(row)

def read_n(s, n, timeout=30.0):
    s.settimeout(timeout); buf = b""; out = []
    while len(out) < n:
        i = buf.find(b"\r\n\r\n")
        if i < 0:
            buf += s.recv(1 << 20); continue
        head = buf[:i + 4]
        st = int(head.split(b" ", 2)[1])
        cl = int(re.search(rb"[Cc]ontent-[Ll]ength: (\d+)", head).group(1))
        while len(buf) < i + 4 + cl:
            buf += s.recv(1 << 20)
        out.append(st); buf = buf[i + 4 + cl:]
    return out

try:
    # (a)+(b)+(c): 1000 requests over TWO keep-alive connections, in
    # pipelined blocks of 50, per-block closed-loop latency recorded
    conns_before = doors["ra"].connections + doors["rb"].connections
    lats = []
    socks = []
    for _ in range(2):
        s = socket.create_connection(("127.0.0.1", lb.port), timeout=30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        socks.append(s)
    statuses = []
    for k in range(20):
        s = socks[k % 2]
        t0 = time.perf_counter()
        s.sendall(req * 50)
        statuses += read_n(s, 50)
        lats.append(time.perf_counter() - t0)
    assert statuses.count(200) == 1000, statuses[:20]
    p99_ms = sorted(lats)[int(0.99 * (len(lats) - 1))] * 1000.0
    assert p99_ms <= SLO_MS, p99_ms
    # keep-alive held: the replica doors saw ONLY the LB's pooled dials
    assert doors["ra"].connections + doors["rb"].connections \
        == conns_before, "new upstream connections appeared mid-burst"
    served = sum(a.requests_served for a in apps.values())
    assert served >= 1000

    # (d) the straggler drill: wedge ra off the LB path, steer the next
    # block onto it, regate rb so the hedge sweep has a target
    c = get_counters()
    apps["ra"]._stall_once_ms = 2000
    d = socket.create_connection(("127.0.0.1", doors["ra"].port))
    d.sendall(req); time.sleep(0.05)
    apps["rb"]._set_state(FD_RELOADING)
    while lb.app.upstreams["rb"].state != FD_RELOADING: time.sleep(0.02)
    s = socks[0]
    s.sendall(req * 4); time.sleep(0.05)
    apps["rb"]._set_state(FD_READY)
    while lb.app.upstreams["rb"].state != FD_READY: time.sleep(0.02)
    sts = read_n(s, 4)
    assert sts == [200] * 4, sts
    read_n(d, 1); d.close()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            c.get("lb_hedges", job=JOB, result="win") == 0
            or c.get("lb_hedges", job=JOB, result="lose") == 0):
        time.sleep(0.05)
    wins = c.get("lb_hedges", job=JOB, result="win")
    loses = c.get("lb_hedges", job=JOB, result="lose")
    assert wins > 0, "hedge never fired"
    assert loses > 0, "straggler's late response never discarded"
    assert c.get("lb_overload_sheds", job=JOB) == 0
    assert c.get("lb_timeouts", job=JOB) == 0

    # (f) the stitched cross-tier trace: repeat the straggler drill
    # with a CLIENT-traced request, then recover the whole tree by id
    # through the `edl-tpu trace` verb (the operator's path)
    import io, os, tempfile
    from contextlib import redirect_stdout
    from edl_tpu import cli as edl_cli
    from edl_tpu.observability.tracing import get_tracer, new_trace_id
    tid = new_trace_id()
    treq = build_predict_request(row, trace_id=tid)
    apps["ra"]._stall_once_ms = 2000
    d = socket.create_connection(("127.0.0.1", doors["ra"].port))
    d.sendall(req); time.sleep(0.05)
    apps["rb"]._set_state(FD_RELOADING)
    while lb.app.upstreams["rb"].state != FD_RELOADING: time.sleep(0.02)
    s = socks[0]
    s.sendall(treq); time.sleep(0.05)
    apps["rb"]._set_state(FD_READY)
    while lb.app.upstreams["rb"].state != FD_READY: time.sleep(0.02)
    assert read_n(s, 1) == [200]
    read_n(d, 1); d.close()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        outs = {e.args.get("outcome") for e in get_tracer().events()
                if e.trace_id == tid and e.name == "lb.upstream"}
        if {"win", "discarded"} <= outs:
            break
        time.sleep(0.05)
    tdir = tempfile.mkdtemp(prefix="edl-ci-traces-")
    get_tracer().dump(os.path.join(tdir, "trace-ci-smoke.json"),
                      "ci-smoke")
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = edl_cli.main(["trace", tid, "--trace-dir", tdir])
    tree = buf.getvalue()
    assert rc == 0, (rc, tree)
    for need in ("lb_request", "lb.upstream", "frontdoor_request",
                 "frontdoor.queue", "frontdoor.forward",
                 "kind=hedge", "outcome=win", "outcome=discarded"):
        assert need in tree, (need, tree)
    assert c.get("traces_sampled", job=JOB, origin="client") >= 1
    for s in socks:
        s.close()

    # (e) the new series, over real HTTP, through the strict parser
    msrv = serve_health(0, {"ok": lambda: True}, host="127.0.0.1")
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{msrv.server_address[1]}/metrics",
        timeout=10).read().decode()
    parse_exposition(text)  # strict grammar or die
    got = {n for n, _l, v in iter_samples(text) if v > 0}
    for need in ("edl_lb_requests_total", "edl_lb_responses_total",
                 "edl_lb_hedges_total", "edl_lb_hedges_fired_total",
                 "edl_frontdoor_requests_served_total",
                 "edl_frontdoor_connections_total",
                 "edl_traces_sampled_total"):
        assert need in got, (need, sorted(got))
    msrv.shutdown()
    print("front-door smoke OK:", {
        "requests": 1005, "lb_connections": 2,
        "p99_ms": round(p99_ms, 2), "hedge_wins": int(wins),
        "hedge_discards": int(loses),
        "stitched_trace": tid,
        "trace_spans": tree.count("\n") + 1})
finally:
    lb.stop()
    for door in doors.values():
        door.stop()
EOF

echo "== chaos smoke (gray replica: breaker eject → half-open re-admit, nonce integrity, zero wrong payloads)"
# The serving-plane gray-failure tripwire (doc/fault_drills.md §serving,
# doc/serving.md §gray-failure defenses): a replica turned gray in
# error mode must be EJECTED by the LB circuit breaker with the client
# seeing only correct 200s (rescue resends mask the blast), then
# re-admitted through a half-open probe once the drill lapses; a
# corrupt-mode gray must be caught by the per-block response nonce —
# never forwarded.  Every defense series must render for the strict
# exposition parser from scrape #1 (zero-sample pre-registration).
JAX_PLATFORMS=cpu python - <<'EOF'
import tempfile, threading, time, socket, re
import numpy as np, jax

from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.metrics import get_registry, parse_exposition
from edl_tpu.runtime.serving import ElasticServer
from edl_tpu.runtime.frontdoor import (BatchApp, FrontDoor,
                                       build_predict_request)
from edl_tpu.runtime.lb import BRK_CLOSED, BRK_OPEN, ServingLB

JOB = "ci/chaos"
SIZES = [8, 16, 4]
params = mlp.init(jax.random.key(0), SIZES)
row = np.ones((SIZES[0],), np.float32)
expect = np.asarray(mlp.apply(params, row[None]))[0]
req = build_predict_request(row)

class KV:  # in-process stand-in for the coordinator KV verbs used here
    def __init__(self): self.d, self.l = {}, threading.Lock()
    def kv_set(self, k, v):
        with self.l: self.d[k] = bytes(v)
    def kv_get(self, k):
        with self.l: return self.d.get(k)
    def kv_del(self, k):
        with self.l: return self.d.pop(k, None) is not None
    def kv_keys(self, p=""):
        with self.l: return [k for k in self.d if k.startswith(p)]

kv = KV()
def build():
    return ElasticServer(lambda p, b: mlp.apply(p, b[0]), params)
apps, doors = {}, {}
for name in ("ra", "rb"):
    apps[name] = BatchApp(build, SIZES[0], job=JOB, replica=name, kv=kv,
                          max_batch=32, max_queue_ms=1.0, addr_ttl_s=10.0)
    doors[name] = FrontDoor(apps[name], host="127.0.0.1",
                            job=f"{JOB}/{name}").start()
for app in apps.values():
    assert app.wait_ready(120)
# hedging parked far out of reach: every resend below is the breaker /
# rescue machinery acting, not the tail-latency hedger
lb = ServingLB(job=JOB, host="127.0.0.1", kv=kv, pool=2, discovery_s=0.1,
               sweep_ms=3.0, hedge_floor_ms=60000.0, hedge_cap_ms=60000.0,
               breaker_errors=3, breaker_min=1000, breaker_window_s=0.5,
               breaker_cooldown_s=0.3, breaker_probes=1,
               flight_dir=tempfile.mkdtemp(prefix="edl-ci-chaos-")).start()
deadline = time.monotonic() + 30
while time.monotonic() < deadline and sum(
        1 for u in lb.app.upstreams.values() if u.routable()) < 2:
    time.sleep(0.05)
assert sum(1 for u in lb.app.upstreams.values() if u.routable()) == 2

def read_bodies(s, n, timeout=30.0):
    s.settimeout(timeout); buf = b""; out = []
    while len(out) < n:
        i = buf.find(b"\r\n\r\n")
        if i < 0:
            buf += s.recv(1 << 20); continue
        head = buf[:i + 4]
        st = int(head.split(b" ", 2)[1])
        cl = int(re.search(rb"[Cc]ontent-[Ll]ength: (\d+)", head).group(1))
        while len(buf) < i + 4 + cl:
            buf += s.recv(1 << 20)
        out.append((st, buf[i + 4:i + 4 + cl])); buf = buf[i + 4 + cl:]
    return out

wrong = [0]
def burst(k=8, allow_500=False):
    # two CONCURRENT pipelined bursts so the least-outstanding picker
    # spreads load over both upstreams (and a half-open probe can route)
    def one(res, slot):
        s = socket.create_connection(("127.0.0.1", lb.port), timeout=30)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            s.sendall(req * k)
            res[slot] = read_bodies(s, k)
        finally:
            s.close()
    res = [None, None]
    ts = [threading.Thread(target=one, args=(res, j)) for j in (0, 1)]
    for t in ts: t.start()
    for t in ts: t.join()
    n500 = 0
    for got in res:
        assert got is not None, "burst reader died"
        for st, body in got:
            if st == 500 and allow_500:
                n500 += 1; continue  # honest 5xx: breaker food, not lies
            assert st == 200, st
            out = np.frombuffer(body, "<f4")
            if out.shape != expect.shape or not np.allclose(
                    out, expect, atol=1e-4):
                wrong[0] += 1
    return n500

c = get_counters()
try:
    burst()  # clean warmup: both breakers CLOSED, payloads verified

    # (a) error-mode gray on ra → consecutive honest 500s trip the
    # breaker; once OPEN the gray replica is off the routable set, so
    # the 5xx blast is BOUNDED by the trip threshold, never masked into
    # a wrong 200 — every 200 in the drill still carries the right bytes
    GRAY_S = 2.0
    apps["ra"].set_gray(1.0, "error", GRAY_S)
    t0 = time.monotonic()
    blast = 0
    while (lb.app.upstreams["ra"].breaker.state != BRK_OPEN
           and time.monotonic() - t0 < 10):
        blast += burst(allow_500=True)
    assert lb.app.upstreams["ra"].breaker.state == BRK_OPEN, \
        "breaker never ejected the gray replica"
    eject_ms = (time.monotonic() - t0) * 1000.0
    assert blast > 0, "error drill never surfaced a 5xx"
    # ejected: while the drill still burns, traffic routes around ra
    assert burst(allow_500=True) == 0, "5xx after ejection"

    # (b) drill lapses → half-open probe → re-admit (CLOSED again)
    time.sleep(max(0.0, GRAY_S - (time.monotonic() - t0)) + 0.35)
    t1 = time.monotonic()
    while (lb.app.upstreams["ra"].breaker.state != BRK_CLOSED
           and time.monotonic() - t1 < 15):
        burst(); time.sleep(0.05)
    assert lb.app.upstreams["ra"].breaker.state == BRK_CLOSED, \
        "half-open probe never re-admitted the recovered replica"
    for to in ("open", "half_open", "closed"):
        assert c.get("lb_breaker_transitions", job=JOB, to=to) > 0, to

    # (c) corrupt-mode gray on rb → the per-block nonce catches the
    # forged echo; the poisoned connection is abandoned and the block
    # rescued — the corruption NEVER reaches a client
    i0 = c.get("lb_integrity_failures", job=JOB)
    apps["rb"].set_gray(1.0, "corrupt", 0.8)
    t2 = time.monotonic()
    while (c.get("lb_integrity_failures", job=JOB) == i0
           and time.monotonic() - t2 < 10):
        burst()
    assert c.get("lb_integrity_failures", job=JOB) > i0, \
        "corrupt gray never tripped the nonce check"
    time.sleep(0.9)
    burst()  # post-drill: fleet serves clean again

    assert wrong[0] == 0, f"{wrong[0]} wrong payloads reached a client"

    # (d) every defense series renders under the strict parser from a
    # single scrape — breaker state per upstream with a BOUNDED label
    # set, transitions, integrity, retry budget, brownout
    series = parse_exposition(get_registry().render())
    ups = {m.group(1) for k in series
           for m in [re.match(
               r'edl_lb_breaker_state\{.*upstream="([^"]+)"', k)] if m}
    assert ups == {"ra", "rb"}, ups
    for need in ("edl_lb_breaker_transitions_total",
                 "edl_lb_integrity_failures_total",
                 "edl_lb_retry_budget_exhausted_total",
                 "edl_lb_discovery_freezes_total",
                 "edl_frontdoor_brownout_seconds_total",
                 "edl_frontdoor_gray_responses_total"):
        assert any(k == need or k.startswith(need + "{")
                   for k in series), (need, sorted(series)[:40])

    print("chaos smoke OK:", {
        "wrong_payloads": 0,
        "drill_500s": blast,
        "breaker_eject_ms": round(eject_ms, 1),
        "breaker_transitions": {
            to: int(c.get("lb_breaker_transitions", job=JOB, to=to))
            for to in ("open", "half_open", "closed")},
        "integrity_failures":
            int(c.get("lb_integrity_failures", job=JOB)),
        "rescues": int(c.get("lb_rescues", job=JOB))})
finally:
    lb.stop()
    for door in doors.values():
        door.stop()
EOF

echo "== sched smoke (goodput objective vs count packing through the real planner)"
python - <<'EOF'
# Fast tripwire for the goodput-driven multi-tenant scheduler
# (doc/scheduling.md): a 120-job fleet sim through the REAL planner
# under both objectives, then the edl_sched_* / edl_autoscaler_objective
# series through the strict exposition parser.
from edl_tpu.observability.metrics import get_registry, parse_exposition
from edl_tpu.scheduler.sim import SimConfig, FleetSim, compare_objectives

# a hot fleet must actually preempt (aged HIGH gangs admitted by
# planned shrinks of cheaper victims, floored at min) — and still
# strand nothing
hot = SimConfig(n_jobs=120, hosts=16, chips_per_host=8, domains=4,
                horizon_s=900.0, arrival_spread_s=500.0, seed=17)
hcmp = compare_objectives(hot, register=True)
hout = hcmp["goodput"]
assert hout["preemptions"] > 0, hout
assert hcmp["sched_gang_strandings"] == 0, hcmp
assert hcmp["sched_min_violations"] == 0, hcmp

# the moderate-contention reference fleet LAST (its numbers are what
# the headline gauges report): the marginal objective must beat count
# packing on goodput without regressing admission
cfg = SimConfig(n_jobs=120, hosts=16, chips_per_host=8, domains=4,
                horizon_s=900.0, arrival_spread_s=700.0, seed=17)
out = compare_objectives(cfg, register=True)
assert out["sched_goodput_uplift_pct"] > 0, out
assert out["sched_gang_strandings"] == 0, out
assert out["sched_min_violations"] == 0, out  # never below min_instance
assert (out["sched_admission_p99_s"]
        <= out["sched_admission_p99_s_count"] + 1e-9), out

# the autoscaler's objective gauge: goodput mode with a curve source,
# bit-for-bit count mode without one
from edl_tpu.observability.goodput import ScalingCurve
from edl_tpu.scheduler.autoscaler import Autoscaler
from tests.test_autoscaler import cluster_with, mk_job, submit

curve = ScalingCurve("default/example")
curve.observe(2, 1000.0); curve.observe(8, 3000.0)
c = cluster_with(cpu_milli=10_000)
a = Autoscaler(c, goodput_curves=lambda uid: curve)
submit(c, a, mk_job("example", lo=2, hi=10))
a.tick()

series = parse_exposition(get_registry().render())  # strict grammar or die
assert series["edl_sched_goodput_uplift_pct"] > 0, series
assert series["edl_sched_gang_strandings"] == 0
assert series['edl_sched_admission_p99_s{objective="goodput"}'] >= 0
assert series["edl_sched_preemptions_total"] >= hout["preemptions"]
assert series['edl_autoscaler_objective{mode="goodput"}'] == 1.0
assert series['edl_autoscaler_objective{mode="count"}'] == 0.0

print("sched smoke OK:", {
    "uplift_pct": out["sched_goodput_uplift_pct"],
    "admission_p99_s": out["sched_admission_p99_s"],
    "admission_p99_s_count": out["sched_admission_p99_s_count"],
    "preemptions_hot": hout["preemptions"],
    "gang_strandings": 0})
EOF

echo "== sdc smoke (2-worker CorruptGradient: detect → quarantine → rollback → bitwise)"
# The SDC defense-plane tripwire (doc/sdc_defense.md): a corrupted
# gradient on one of two lock-step dp workers must split the published
# update fingerprints, be CONFIRMED by the shadow recomputation (which
# also breaks the 2-way vote tie and names the corrupt worker), leave a
# quarantine marker in coordinator KV, roll the corrupt worker back to
# its last VERIFIED checkpoint, and replay to a final trajectory
# BITWISE-IDENTICAL to the uninjected control — with every edl_sdc_*
# series green under the strict exposition parser.
JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
python - <<'EOF'
import tempfile

import jax, numpy as np, optax

from edl_tpu.coord import local_service
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.observability.metrics import get_registry, parse_exposition
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.checkpoint import ElasticCheckpointer
from edl_tpu.runtime.data import ShardRegistry
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.faults import (CorruptGradient, FaultContext,
                                    FaultPlan, FaultPlanEngine)
from edl_tpu.runtime.sdc import (AnomalyDetector, SdcPlane, ShadowRecompute,
                                 UpdateFingerprinter, clear_quarantine,
                                 quarantined_names)
from edl_tpu.runtime.virtual import (VirtualBatches, VirtualConfig,
                                     VirtualWorkerLoop)

SEED, STEPS = 3, 14
CFG = VirtualConfig(vw_count=8, global_batch=64, job_seed=SEED)
rng = np.random.default_rng(1)
y = rng.integers(0, 4, 2048).astype(np.int32)
x = rng.normal(size=(2048, 16)).astype(np.float32)
reg = ShardRegistry()
ids = reg.register_arrays((x, y), num_shards=16)

def trainer():
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                          spec=MeshSpec(dp=-1), initial_world_size=1,
                          accum_mode="replicated")

def batches():
    return VirtualBatches(CFG, ids, reg.get, passes=2)

control = VirtualWorkerLoop(trainer(), CFG, batches()).run(max_steps=STEPS)

kv = local_service()
rigs = {}
for worker in ("wA", "wB"):
    ck = ElasticCheckpointer(tempfile.mkdtemp(prefix=f"edl-ci-sdc-{worker}-"))
    tr = trainer()
    plane = SdcPlane(
        fingerprinter=UpdateFingerprinter(kv=kv, job="ci-sdc",
                                          worker=worker),
        detector=AnomalyDetector(),
        shadow=ShadowRecompute(trainer, batches, CFG, checkpointer=ck),
        checkpointer=ck, kv=kv)
    loop = VirtualWorkerLoop(tr, CFG, batches(), checkpointer=ck,
                             ckpt_every=5, sdc=plane)
    rigs[worker] = (tr, loop, plane, ck)

# the corruption strikes wB through the seeded fault engine; lock-step
# interleave so each worker's published fingerprint is visible to the
# peer's next cross-check
plan = FaultPlan(actions=[CorruptGradient(at_step=7)], seed=SEED)
ctx = FaultContext()
ctx.trainer = rigs["wB"][0]
engine = FaultPlanEngine(plan, ctx)
for i in range(1, STEPS + 1):
    engine(i)
    rigs["wA"][1].run(max_steps=i)
    rigs["wB"][1].run(max_steps=i)

_, loopA, planeA, ckA = rigs["wA"]
_, loopB, planeB, ckB = rigs["wB"]
conf = [v for v in planeB.verdicts if v.outcome == "confirmed"]
assert conf and conf[0].trigger == "fp_mismatch", planeB.verdicts
assert conf[0].quarantined == "wB", conf[0].to_dict()
assert "wB" in quarantined_names(kv), "quarantine marker missing from KV"
assert loopB.report.rollbacks == 1, loopB.report
assert loopA.report.rollbacks == 0, "the honest peer rolled back"
assert loopB.report.losses == control.losses, "wB not bitwise vs control"
assert loopA.report.losses == control.losses, "wA not bitwise vs control"
assert engine.quiescent() and engine.recovered == ["corrupt_gradient"]

# every edl_sdc_* series green under the strict parser
series = parse_exposition(get_registry().render())
for need in ("edl_sdc_fingerprints_total",
             'edl_sdc_anomalies_total{trigger="fp_mismatch"}',
             'edl_sdc_verdicts_total{outcome="confirmed"}',
             "edl_sdc_rollbacks_total",
             "edl_sdc_quarantines_total"):
    assert any(k == need or k.startswith(need.rstrip("}") + ",")
               for k in series), (need, sorted(series)[:40])
assert series['edl_sdc_verdicts_total{outcome="confirmed"}'] >= 1
assert series["edl_sdc_rollbacks_total"] >= 1
assert series["edl_sdc_quarantines_total"] >= 1
assert any(k.startswith("edl_sdc_fingerprint_seconds") for k in series)

clear_quarantine(kv, "wB")
ckA.close()
ckB.close()
c = get_counters()
print("sdc smoke OK:", {
    "trigger": conf[0].trigger, "quarantined": conf[0].quarantined,
    "rollback_step": conf[0].rollback_step,
    "rollbacks_B": loopB.report.rollbacks,
    "bitwise": loopB.report.losses == control.losses,
    "fingerprints": int(c.get("sdc_fingerprints"))})
EOF

echo "CI OK"
