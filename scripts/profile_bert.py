"""Attribute BERT-base's MFU gap on the real chip (verdict r5 weak #4).

BENCH_r04: 48.9 % MFU at 32x512 vs 73 % for the decoder legs on the same
chip.  Times targeted variants to locate the gap:

  baseline      shipped model (flash attention, f32 logits at the head)
  fwd_only      forward pass only
  xla_attn      use_flash=False (at seq 512 the dense-attention matmuls
                may beat the kernel's launch/block overhead)
  no_head       loss = mean(hidden) — isolates the 30522-vocab MLM head
  bf16_logits   keep the [32,512,30522] logits in bf16 (halves the head's
                HBM traffic; measurement only — training would want f32)
  hd128         6 heads x head_dim 128 (same d_model): MXU lane
                utilization of the attention matmuls at hd 64 vs 128

Run on the TPU:  python scripts/profile_bert.py [--steps 10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import replace
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(loss_fn, params, data, n_steps, fwd_only=False):
    import jax
    import optax

    if fwd_only:
        compiled = jax.jit(loss_fn).lower(params, data).compile()
        float(compiled(params, data))
        t0 = time.perf_counter()
        for _ in range(n_steps):
            loss = compiled(params, data)
        final = float(loss)
        return 1000 * (time.perf_counter() - t0) / n_steps, final

    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)

    def step(params, opt_state, data):
        loss, grads = jax.value_and_grad(loss_fn)(params, data)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    compiled = jax.jit(step).lower(params, opt_state, data).compile()
    params, opt_state, loss = compiled(params, opt_state, data)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = compiled(params, opt_state, data)
    final = float(loss)
    return 1000 * (time.perf_counter() - t0) / n_steps, final


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--only", default="")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from edl_tpu.models import bert

    try:
        os.makedirs("/tmp/edl-bench-cache", exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", "/tmp/edl-bench-cache")
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    dev = jax.devices()[0]
    print(f"# platform={dev.platform} kind={dev.device_kind}", flush=True)

    cfg = bert.BERT_BASE
    b, s = 32, 512
    key = jax.random.key(0)
    masked = jax.random.randint(key, (b, s), 0, cfg.vocab_size,
                                dtype=jnp.int32)
    targets = jax.random.randint(jax.random.key(1), (b, s), 0,
                                 cfg.vocab_size, dtype=jnp.int32)
    mask = (jax.random.uniform(jax.random.key(2), (b, s)) < 0.15
            ).astype(jnp.float32)
    data = (masked, targets, mask)
    params = bert.init(jax.random.key(3), cfg)

    def no_head_loss(params, batch, cfg):
        hdn = bert.apply(params, batch[0], cfg)
        return jnp.mean(hdn.astype(jnp.float32))

    def bf16_logits_loss(params, batch, cfg):
        masked, targets, mask = batch
        hdn = bert.apply(params, masked, cfg)
        logits = hdn @ params["embed"].astype(hdn.dtype).T  # stays bf16
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        tgt = jnp.take_along_axis(
            logits, targets[..., None], axis=-1)[..., 0].astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.sum((lse - tgt) * mask) / denom

    cfg_xla = replace(cfg, use_flash=False)
    cfg128 = replace(cfg, n_heads=6)
    params128 = bert.init(jax.random.key(3), cfg128)

    variants = {
        "baseline": (bert.make_loss_fn(cfg), params, False),
        "fwd_only": (bert.make_loss_fn(cfg), params, True),
        "xla_attn": (bert.make_loss_fn(cfg_xla), params, False),
        "no_head": (partial(no_head_loss, cfg=cfg), params, False),
        "bf16_logits": (partial(bf16_logits_loss, cfg=cfg), params, False),
        "hd128": (bert.make_loss_fn(cfg128), params128, False),
    }
    only = set(filter(None, args.only.split(",")))
    results = {}
    for name, (loss_fn, ps, fwd) in variants.items():
        if only and name not in only:
            continue
        try:
            ms, final = timed(loss_fn, ps, data, args.steps, fwd_only=fwd)
            results[name] = {"step_ms": round(ms, 1),
                             "tok_s": round(1000 * b * s / ms, 1),
                             "final_loss": round(final, 3)}
            print(f"{name:12s} {ms:8.1f} ms/step "
                  f"{1000 * b * s / ms:9.1f} tok/s", flush=True)
        except Exception as exc:
            results[name] = {"error": str(exc)[:200]}
            print(f"{name:12s} ERROR {str(exc)[:160]}", flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
