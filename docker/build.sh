#!/usr/bin/env bash
# Build the controller + job images — role of the reference's
# docker/build.sh (which writes a Dockerfile on the fly over a Paddle base
# and ADDs the k8s glue; ours are checked in).
set -euo pipefail
cd "$(dirname "$0")/.."

TAG="${TAG:-latest}"
docker build -f docker/Dockerfile.controller -t "edl-tpu/controller:${TAG}" .
docker build -f docker/Dockerfile.job        -t "edl-tpu/job:${TAG}" .
echo "built edl-tpu/controller:${TAG} and edl-tpu/job:${TAG}"
