"""Fault-plan engine: unit drills + the seeded multi-fault soak.

The fast tests pin the engine's contract (seed determinism, the ChaosProxy
middlebox, fire/recovery accounting on the fake cluster, the kubelet
teardown race).  The slow soak is the acceptance drill: a randomized
multi-fault campaign — coordinator kill, network flakes, domain
preemption, trainer kills, checkpoint corruption, disk-full — against a
real coord server (durable state file) behind the chaos proxy, driving a
live elastic training loop on the fake cluster, asserting exactly-once
task accounting, loss continuity across every recovery, auditable
chaos counters/traces, and zero leaked processes.
"""

from __future__ import annotations

import os
import random
import subprocess
import threading
import time

import pytest

from edl_tpu.api.types import (
    JobPhase, RESOURCE_CPU, RESOURCE_MEMORY,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.base import PodPhase
from edl_tpu.cluster.fake import FakeCluster, FakePod
from edl_tpu.runtime.faults import (
    ACTION_TYPES,
    ChaosProxy,
    CorruptCheckpoint,
    DiskFull,
    FaultContext,
    FaultPlan,
    FaultPlanEngine,
    KillCoordinator,
    KillTrainer,
    NetworkFlake,
    PreemptDomain,
    StallStep,
    WedgeCollective,
)


def _ft_job(name="drill", lo=2, hi=4, fault_tolerant=True):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=fault_tolerant,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                ),
            ),
        ),
    )


# ---------------------------------------------------------------------------
# FaultPlan: seeded, reproducible campaigns
# ---------------------------------------------------------------------------


def test_fault_plan_same_seed_same_campaign():
    """The reproduction contract: the seed IS the campaign."""
    a = FaultPlan.random(1234)
    b = FaultPlan.random(1234)
    assert a.describe() == b.describe()
    assert a.seed == 1234


def test_fault_plan_covers_all_kinds_with_spacing():
    # kinds= spans training AND serving: the default is the training
    # eight (seeded training campaigns stay bit-identical as the kind
    # registry grows)
    plan = FaultPlan.random(7, n_faults=len(ACTION_TYPES), first_step=10,
                            last_step=100, min_gap=8,
                            kinds=tuple(ACTION_TYPES))
    kinds = [d["kind"] for d in plan.describe()]
    assert sorted(kinds) == sorted(ACTION_TYPES)
    steps = [d["at_step"] for d in plan.describe()]
    assert steps == sorted(steps)
    assert all(b - a >= 8 for a, b in zip(steps, steps[1:]))
    assert steps[0] >= 10


def test_fault_plan_describe_carries_params():
    plan = FaultPlan(actions=[
        NetworkFlake(at_step=3, mode="blackhole", duration_s=2.5),
        CorruptCheckpoint(at_step=9, mode="truncate"),
        DiskFull(at_step=12, saves=2),
    ])
    assert plan.describe() == [
        {"kind": "network_flake", "at_step": 3, "mode": "blackhole",
         "duration_s": 2.5},
        {"kind": "corrupt_checkpoint", "at_step": 9, "mode": "truncate"},
        {"kind": "disk_full", "at_step": 12, "saves": 2},
    ]


# ---------------------------------------------------------------------------
# Engine fire/recovery accounting on the fake cluster (no jax, no procs)
# ---------------------------------------------------------------------------


def test_engine_kill_and_preempt_with_recovery_counters():
    from edl_tpu.observability.collector import get_counters

    cluster = FakeCluster()
    cluster.add_node("a0", cpu_milli=8000, memory_mega=64000,
                     ici_domain="slice-a")
    cluster.add_node("b0", cpu_milli=8000, memory_mega=64000,
                     ici_domain="slice-b")
    job = _ft_job()
    cluster.create_resources(job)
    plan = FaultPlan(actions=[KillTrainer(at_step=1),
                              PreemptDomain(at_step=3)])
    ctx = FaultContext(cluster=cluster, job=job, rng=random.Random(0))
    engine = FaultPlanEngine(plan, ctx)

    before = {k: get_counters().get("faults_injected", type=k)
              for k in ("kill_trainer", "preempt_domain")}
    engine(1)  # kill fires; reconcile replaces synchronously
    assert [k for _, k in engine.fired] == ["kill_trainer"]
    engine(2)  # recovery observed (replacement Running)
    assert engine.recovered == ["kill_trainer"]
    engine(3)  # whole-domain preemption: every pod in one domain dies
    assert [k for _, k in engine.fired] == ["kill_trainer", "preempt_domain"]
    engine(4)
    assert engine.recovered == ["kill_trainer", "preempt_domain"]
    assert engine.quiescent()
    for k in ("kill_trainer", "preempt_domain"):
        assert (get_counters().get("faults_injected", type=k)
                == before[k] + 1)
        assert get_counters().get("recoveries_completed", type=k) >= 1


def test_engine_retries_action_without_victims():
    """A fault whose preconditions are absent stays armed (mid-recovery
    strikes retry) instead of being lost or crashing."""
    cluster = FakeCluster()  # no nodes: pods all Pending, none Running
    job = _ft_job()
    cluster.create_resources(job)
    plan = FaultPlan(actions=[KillTrainer(at_step=1)])
    engine = FaultPlanEngine(plan, FaultContext(cluster=cluster, job=job))
    engine(1)
    assert engine.fired == [] and not engine.quiescent()
    cluster.add_node("n0", cpu_milli=8000, memory_mega=64000)
    cluster.reconcile()
    engine(2)
    assert [k for _, k in engine.fired] == ["kill_trainer"]


def test_engine_unfireable_action_is_disarmed_not_fatal():
    plan = FaultPlan(actions=[KillCoordinator(at_step=1)])
    engine = FaultPlanEngine(plan, FaultContext())  # no kubelet, no restart
    engine(1)  # must not raise
    assert engine.fired == []
    assert engine.quiescent()  # disarmed with a trace, drill continues


# ---------------------------------------------------------------------------
# The quiet faults: StallStep / WedgeCollective (watchdog drills)
# ---------------------------------------------------------------------------


def test_stall_and_wedge_fire_and_await_watchdog_detection():
    """The quiet pair's recovery contract: fired when the harness hook
    ran, recovered only once ``stalls_detected`` moved — i.e. the drill
    passes iff the watchdog actually SAW the hang."""
    from edl_tpu.observability.collector import get_counters

    stalls, wedges = [], []
    ctx = FaultContext(stall=stalls.append,
                       wedge=lambda: bool(wedges.append(1)) or True)
    plan = FaultPlan(actions=[StallStep(at_step=1, duration_s=2.5),
                              WedgeCollective(at_step=2)])
    assert plan.describe()[0] == {"kind": "stall_step", "at_step": 1,
                                  "duration_s": 2.5}
    engine = FaultPlanEngine(plan, ctx)
    engine(1)
    engine(2)
    assert [k for _, k in engine.fired] == ["stall_step",
                                            "wedge_collective"]
    assert stalls == [2.5] and wedges == [1]
    assert not engine.quiescent()  # hangs injected, not yet detected
    # the watchdog notices (what StallWatchdog.check emits on breach)
    get_counters().inc("stalls_detected", scope="drill-unit")
    engine(3)
    assert engine.quiescent()
    assert sorted(engine.recovered) == ["stall_step", "wedge_collective"]


def test_wedge_retries_until_a_victim_exists():
    """wedge() returning False (no live collective yet) re-arms."""
    ready = []
    ctx = FaultContext(wedge=lambda: bool(ready))
    engine = FaultPlanEngine(
        FaultPlan(actions=[WedgeCollective(at_step=1)]), ctx)
    engine(1)
    assert engine.fired == [] and not engine.quiescent()
    ready.append(1)
    engine(2)
    assert [k for _, k in engine.fired] == ["wedge_collective"]


def test_stall_without_hook_is_disarmed_not_fatal():
    engine = FaultPlanEngine(
        FaultPlan(actions=[StallStep(at_step=1)]), FaultContext())
    engine(1)  # must not raise
    assert engine.fired == [] and engine.quiescent()


# ---------------------------------------------------------------------------
# ChaosProxy middlebox
# ---------------------------------------------------------------------------


@pytest.fixture
def echo_upstream():
    """A tiny newline echo server standing in for the coord server."""
    import socket as s

    srv = s.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            def pump(c):
                try:
                    f = c.makefile("rb")
                    while line := f.readline():
                        c.sendall(b"echo " + line)
                except OSError:
                    pass
                finally:
                    c.close()
            threading.Thread(target=pump, args=(conn,), daemon=True).start()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    yield srv.getsockname()
    stop.set()
    srv.close()


def test_proxy_forwards_and_resets(echo_upstream):
    import socket as s

    proxy = ChaosProxy(echo_upstream)
    try:
        c = s.create_connection((proxy.host, proxy.port), timeout=5)
        c.sendall(b"hello\n")
        f = c.makefile("rb")
        assert f.readline() == b"echo hello\n"
        assert proxy.reset_all() >= 1
        # the severed connection is dead: EOF or reset on next read
        c.settimeout(2)
        try:
            assert f.readline() == b""
        except OSError:
            pass
        c.close()
        # new connections work again immediately
        c2 = s.create_connection((proxy.host, proxy.port), timeout=5)
        c2.sendall(b"again\n")
        assert c2.makefile("rb").readline() == b"echo again\n"
        c2.close()
    finally:
        proxy.close()


def test_proxy_blackhole_window_then_recovers(echo_upstream):
    import socket as s

    proxy = ChaosProxy(echo_upstream)
    try:
        c = s.create_connection((proxy.host, proxy.port), timeout=5)
        f = c.makefile("rb")
        c.sendall(b"one\n")
        assert f.readline() == b"echo one\n"
        proxy.blackhole(1.0)
        assert proxy.faults_active()
        c.sendall(b"lost\n")  # eaten by the window
        c.settimeout(0.5)
        with pytest.raises(OSError):
            f.readline()
        time.sleep(1.1)
        assert not proxy.faults_active()
        # the old connection's request was dropped mid-protocol; a fresh
        # connection (what a reconnecting client does) works
        c.close()
        c2 = s.create_connection((proxy.host, proxy.port), timeout=5)
        c2.sendall(b"back\n")
        assert c2.makefile("rb").readline() == b"echo back\n"
        c2.close()
    finally:
        proxy.close()


def test_proxy_delay_window(echo_upstream):
    import socket as s

    proxy = ChaosProxy(echo_upstream)
    try:
        c = s.create_connection((proxy.host, proxy.port), timeout=5)
        f = c.makefile("rb")
        proxy.delay(1.0, per_chunk_s=0.3)
        t0 = time.monotonic()
        c.sendall(b"slow\n")
        assert f.readline() == b"echo slow\n"
        assert time.monotonic() - t0 >= 0.25
        c.close()
    finally:
        proxy.close()


def test_proxy_retargets_upstream(echo_upstream):
    """set_upstream is the stable-endpoint story for a coordinator that
    came back on a different port."""
    import socket as s

    proxy = ChaosProxy(("127.0.0.1", 1))  # nothing there yet
    try:
        c = s.create_connection((proxy.host, proxy.port), timeout=5)
        # upstream dead: the proxy closes us (client reconnect path)
        assert c.makefile("rb").readline() == b""
        c.close()
        proxy.set_upstream(*echo_upstream)
        c2 = s.create_connection((proxy.host, proxy.port), timeout=5)
        c2.sendall(b"routed\n")
        assert c2.makefile("rb").readline() == b"echo routed\n"
        c2.close()
    finally:
        proxy.close()


# ---------------------------------------------------------------------------
# Kubelet teardown race (ADVICE r5 item 2): a pod registered by an
# in-flight _start_pod after stop()'s sweep must still be reaped
# ---------------------------------------------------------------------------


def test_kubelet_reaps_pod_spawned_during_stop(tmp_path, monkeypatch):
    from edl_tpu.cluster import exec_kubelet as ek

    cluster = FakeCluster()
    kubelet = ek.ProcessKubelet(cluster, str(tmp_path))
    pod = FakePod(name="ghost", job_uid="default/j", role="trainer",
                  phase=PodPhase.RUNNING)
    cluster._pods["ghost"] = pod
    monkeypatch.setattr(
        kubelet, "_container_for",
        lambda p: {"command": ["sleep", "60"], "env": {}, "volumes": [],
                   "mounts": {}})
    monkeypatch.setattr(kubelet, "_pod_env",
                        lambda p, c: dict(os.environ))
    entered, release = threading.Event(), threading.Event()
    real_popen = subprocess.Popen
    spawned = []

    def gated_popen(*args, **kwargs):
        entered.set()
        release.wait(10)  # hold the spawn past stop()'s kill sweep
        proc = real_popen(*args, **kwargs)
        spawned.append(proc)
        return proc

    monkeypatch.setattr(ek.subprocess, "Popen", gated_popen)
    t = threading.Thread(target=kubelet._start_pod, args=(pod,))
    t.start()
    assert entered.wait(10)  # _start_pod passed its _stop check, pre-spawn
    stopper = threading.Thread(target=kubelet.stop)
    stopper.start()
    time.sleep(0.3)  # stop() sets _stop and sweeps (ghost not registered)
    release.set()  # the racing spawn lands NOW
    t.join(timeout=15)
    stopper.join(timeout=15)
    assert spawned, "the gated spawn never ran"
    proc = spawned[0]
    deadline = time.monotonic() + 5
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(0.05)
    assert proc.poll() is not None, "pod process leaked through stop()"
    assert "ghost" not in kubelet._procs


# ---------------------------------------------------------------------------
# THE SOAK: seeded randomized multi-fault campaign, end to end
# ---------------------------------------------------------------------------

SOAK_SEED = int(os.environ.get("EDL_FAULT_SEED", "11"))


def _children_named(needle: str) -> list[int]:
    """PIDs of live direct children of this process whose cmdline contains
    ``needle`` (the leaked-process audit)."""
    me = os.getpid()
    out = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().split()
            if int(parts[3]) != me:
                continue
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode(errors="replace")
        except (OSError, IndexError, ValueError):
            continue
        if needle in cmd:
            out.append(int(pid))
    return out


@pytest.mark.slow
@pytest.mark.timeout_s(600)  # above the drill's own internal wait budgets
def test_seeded_multi_fault_campaign_soak(tmp_path):
    """Acceptance drill: ≥4 distinct fault types (all eight here,
    including coordinator kill, network flake, checkpoint corruption and
    the quiet stall/wedge pair that only the watchdog can see) fired
    from one seed against a live elastic training loop.  Asserts
    exactly-once task accounting, loss continuity/progress across
    recoveries, stall detection within the EWMA deadline bound, chaos
    counters + trace events per fault type, plan reproducibility from
    the seed, and zero leaked processes."""
    import jax
    import numpy as np
    import optax

    from edl_tpu.controller.controller import Controller
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.models import mlp
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.observability.tracing import get_tracer
    from edl_tpu.runtime.checkpoint import ElasticCheckpointer
    from edl_tpu.runtime.data import ShardRegistry
    from edl_tpu.runtime.elastic import ElasticTrainer
    from edl_tpu.runtime.local import LocalElasticJob

    counters = get_counters()
    state_file = str(tmp_path / "coord.state")
    handles = [spawn_server(state_file=state_file, task_timeout_ms=6000,
                            member_ttl_ms=6000)]
    proxy = ChaosProxy(("127.0.0.1", handles[0].port))

    def restart_coordinator():
        old = handles[-1]
        old.process.kill()
        old.process.wait(timeout=15)
        handles.append(spawn_server(state_file=state_file,
                                    task_timeout_ms=6000,
                                    member_ttl_ms=6000))
        proxy.set_upstream("127.0.0.1", handles[-1].port)

    client = CoordClient("127.0.0.1", proxy.port, timeout=3.0,
                         reconnect_window_s=40.0)
    # two ICI domains so a domain preemption is a partial-cluster event
    cluster = FakeCluster()
    cluster.add_node("a0", cpu_milli=4000, memory_mega=64000,
                     ici_domain="slice-a")
    cluster.add_node("b0", cpu_milli=4000, memory_mega=64000,
                     ici_domain="slice-b")
    ctl = Controller(cluster, autoscaler_loop_seconds=0.02,
                     updater_convert_seconds=0.02,
                     updater_confirm_seconds=0.01)
    ctl.start()
    job = _ft_job()
    ctl.submit(job)
    deadline = time.monotonic() + 30
    while ctl.phase(job) != JobPhase.RUNNING:
        assert time.monotonic() < deadline, "job never started"
        time.sleep(0.02)

    # data: 32 shards × 256 rows ÷ batch 64 = 128 exactly-once steps
    rng = np.random.default_rng(SOAK_SEED)
    x = rng.normal(size=(8192, 16)).astype(np.float32)
    y = rng.integers(0, 4, size=8192).astype(np.int32)
    reg = ShardRegistry()
    reg.add_arrays(client, (x, y), num_shards=32)

    params = mlp.init(jax.random.key(SOAK_SEED), [16, 32, 4])
    trainer = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                             initial_world_size=2)
    runner = LocalElasticJob(job, cluster, trainer, client, reg.fetch,
                             batch_size=64)
    ckpt = ElasticCheckpointer(tmp_path / "ckpt", max_to_keep=3)

    n_faults = len(ACTION_TYPES)
    plan = FaultPlan.random(SOAK_SEED, n_faults=n_faults, first_step=10,
                            last_step=100, min_gap=10)
    # the seed IS the campaign: rebuilding the plan from the same seed
    # must reproduce the exact fault sequence (the reproduction story
    # doc/fault_drills.md documents)
    assert plan.describe() == FaultPlan.random(
        SOAK_SEED, n_faults=n_faults, first_step=10, last_step=100,
        min_gap=10).describe()
    kinds = {d["kind"] for d in plan.describe()}
    assert kinds == set(ACTION_TYPES)  # all eight, incl. the quiet pair

    # The quiet-fault harness: a stall request wedges the training loop
    # (below, in on_step) until the threaded StallWatchdog's deadline
    # breaches and its escalation releases it — detection IS the
    # recovery trigger, exactly the multihost supervisor's ladder with
    # "SIGKILL the child" swapped for "unwedge the loop".
    from edl_tpu.runtime.watchdog import StallWatchdog

    released = threading.Event()
    stall_requests: list[float] = []
    watchdog = StallWatchdog(floor_s=1.0, k=6.0, warmup=3, alpha=0.5,
                             on_stall=lambda s: released.set(),
                             scope="soak")
    watchdog.start(poll_s=0.05)

    ctx = FaultContext(cluster=cluster, job=job, coord=client, proxy=proxy,
                       checkpointer=ckpt,
                       restart_coordinator=restart_coordinator,
                       stall=lambda d: stall_requests.append(d or 30.0),
                       wedge=lambda: bool(stall_requests.append(30.0))
                       or True,
                       rng=random.Random(SOAK_SEED))
    engine = FaultPlanEngine(plan, ctx)
    base = {
        "corrupt": counters.get("recoveries_completed",
                                type="corrupt_checkpoint"),
        "disk": counters.get("recoveries_completed", type="disk_full"),
    }
    audited = []
    stall_latencies: list[tuple[float, float]] = []  # (silent, deadline)

    def on_step(step, loss, world):
        watchdog.beat(step)
        if step % 5 == 0:
            ckpt.save(step, {"params": trainer.state.params,
                             "opt": trainer.state.opt_state},
                      best_effort=True)
        engine(step, loss, world)
        # corruption audit: the moment the corrupt fault has struck,
        # exercise the restore path (before newer saves mask the damage)
        # — the fallback must hand back an older verified step
        if not audited and any(k == "corrupt_checkpoint"
                               for _, k in engine.fired):
            restored = ckpt.restore({"params": trainer.state.params,
                                     "opt": trainer.state.opt_state})
            audited.append(jax.tree.leaves(restored["params"])[0] is not None)
        if stall_requests:  # a quiet fault struck: wedge THIS loop
            duration = stall_requests.pop()
            released.clear()
            t0 = time.monotonic()
            while (time.monotonic() - t0 < duration
                   and not released.is_set()):
                time.sleep(0.02)  # no beats while wedged
            stall = watchdog.last_stall()
            assert stall is not None, "watchdog never saw the hang"
            stall_latencies.append((stall.silent_s, stall.deadline_s))

    report = runner.run(on_step=on_step)

    # every action fired; engine-watched recoveries all completed
    deadline = time.monotonic() + 30
    while not engine.quiescent() and time.monotonic() < deadline:
        engine.tick()
        time.sleep(0.1)
    watchdog.stop()
    assert engine.quiescent(), (engine.unfired(), engine.recovered)
    assert len(engine.fired) == n_faults, engine.fired
    assert audited == [True]
    # both quiet faults were detected, each within 2× the EWMA deadline
    # in force at the breach (the acceptance bound), and training resumed
    assert len(stall_latencies) == 2, stall_latencies
    for silent_s, deadline_s in stall_latencies:
        assert silent_s <= 2 * deadline_s, stall_latencies

    # exactly-once task accounting across every fault (the coordinator
    # was SIGKILL'd and restarted from its durable state mid-campaign)
    stats = client.stats()
    assert stats.done == 32, stats
    assert stats.todo == 0 and stats.leased == 0 and stats.dropped == 0, stats

    # training progress: monotone steps, every shard's batches trained at
    # least once (128 exactly-once steps; a lease lost to a coordinator
    # restart may legitimately retrain one shard)
    assert report.steps >= 128
    assert trainer.state.step == report.steps
    losses = np.asarray(report.losses)
    assert np.isfinite(losses).all()
    assert losses[-10:].mean() < losses[:10].mean()  # it learned through it

    # auditable evidence: counters + chaos trace events per fault type
    for kind in ACTION_TYPES:
        assert counters.get("faults_injected", type=kind) >= 1, kind
    for kind in ("kill_trainer", "kill_coordinator", "network_flake",
                 "preempt_domain", "stall_step", "wedge_collective"):
        assert counters.get("recoveries_completed", type=kind) >= 1, kind
    assert counters.get("recoveries_completed",
                        type="corrupt_checkpoint") > base["corrupt"]
    assert counters.get("recoveries_completed",
                        type="disk_full") > base["disk"]
    chaos_names = {e.name for e in get_tracer().events(category="chaos")}
    assert "fault_injected" in chaos_names
    assert "recovery_completed" in chaos_names

    # teardown + the leaked-process audit: every server we ever spawned is
    # reaped, and no edl-coord-server child of this process survives
    ctl.stop()
    client.close()
    proxy.close()
    ckpt.close()
    for h in handles:
        h.stop()
    for h in handles:
        assert h.process.poll() is not None
    assert _children_named("edl-coord-server") == []
