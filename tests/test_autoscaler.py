"""Autoscaler loop against the FakeCluster: the reference's whole-system
behavior (reference autoscaler.go:339-511 + the BOSS-tutorial elastic trace,
doc/boss_tutorial.md:246-301) reproduced in-process and deterministic."""

from edl_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.scheduler.autoscaler import Autoscaler


def mk_job(name, lo, hi, cpu="1", mem="100M"):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem},
                    limits={RESOURCE_CPU: cpu, RESOURCE_MEMORY: mem},
                ),
            ),
        ),
    )


def cluster_with(cpu_milli=10_000, mem=100_000):
    c = FakeCluster()
    c.add_node("n0", cpu_milli=cpu_milli, memory_mega=mem)
    return c


def submit(cluster, scaler, job):
    cluster.create_resources(job)
    scaler.on_add(job)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_resize_cooldown_suppresses_thrash():
    """Hysteresis: after one actuation, further plan deltas for the same
    job are held until the cooldown lapses — a flapping load signal (or a
    watchdog-triggered reform wobbling the pod count) must not turn into
    continuous mesh resizes."""
    c = cluster_with(cpu_milli=10_000)
    clock = FakeClock()
    a = Autoscaler(c, max_load_desired=1.0, resize_cooldown_s=30.0,
                   clock=clock)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    assert a.tick()  # first resize actuates immediately (no prior)
    grown = c.get_trainer_parallelism(job)
    assert grown > 2
    # load flaps: an online service lands, the planner wants to shrink
    for i in range(4):
        c.add_system_pod(f"nginx-{i}", "n0", cpu_request_milli=1000,
                         memory_request_mega=100)
    clock.t += 5.0  # still inside the cooldown
    assert a.tick() == {}  # suppressed, not actuated
    assert c.get_trainer_parallelism(job) == grown
    assert a.suppressed_history and \
        a.suppressed_history[-1] == {job.full_name: "cooldown"}
    clock.t += 40.0  # cooldown lapsed: the shrink goes through
    target = a.tick()
    assert target and target[job.full_name] < grown
    assert c.get_trainer_parallelism(job) < grown


def test_min_resize_delta_ignores_one_chip_wobble():
    """A plan delta below min_resize_delta is not worth a reshard."""
    c = cluster_with(cpu_milli=10_000)
    clock = FakeClock()
    a = Autoscaler(c, max_load_desired=1.0, min_resize_delta=4,
                   clock=clock)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    # from 2 pods the planner wants +8 → passes the delta gate
    assert a.tick()
    assert c.get_trainer_parallelism(job) == 10
    # take away ONE cpu worth of headroom: the planner wants -1, which
    # is wobble, not a resize
    c.add_system_pod("nginx", "n0", cpu_request_milli=1000,
                     memory_request_mega=100)
    assert a.tick() == {}
    assert c.get_trainer_parallelism(job) == 10
    assert a.suppressed_history[-1] == {job.full_name: "min_delta"}


def test_cooldown_stamp_cleared_on_job_deletion():
    """A deleted-then-resubmitted job (same uid) must not inherit the
    previous incarnation's cooldown stamp."""
    c = cluster_with(cpu_milli=10_000)
    clock = FakeClock()
    a = Autoscaler(c, max_load_desired=1.0, resize_cooldown_s=300.0,
                   clock=clock)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    assert a.tick()  # actuates; cooldown stamp recorded
    a.on_del(job)
    c.delete_resources(job)
    a.drain_events()
    assert a._last_resize == {}  # stamp dropped with the job
    clock.t += 1.0  # well inside what the old cooldown would have been
    submit(c, a, job)
    assert a.tick()  # the reborn job's first scale-up is NOT suppressed
    assert c.get_trainer_parallelism(job) == 10


def test_hysteresis_defaults_off_preserve_pure_planner():
    """cooldown 0 + min_delta 1 = the pre-hysteresis behavior, tick for
    tick (the planner tests above rely on it)."""
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, max_load_desired=1.0)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    for _ in range(3):
        a.tick()
    assert c.get_trainer_parallelism(job) == 10
    assert a.suppressed_history == []


def test_single_job_scales_to_max():
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, max_load_desired=1.0)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    for _ in range(12):
        a.tick()
    assert c.get_trainer_parallelism(job) == 10
    assert c.job_pods(job).running == 10


def test_max_load_desired_ceiling():
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, max_load_desired=0.8)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    for _ in range(12):
        a.tick()
    assert c.get_trainer_parallelism(job) == 8  # 80% of 10 CPUs


def test_second_job_forces_rebalance():
    # The BOSS-tutorial scenario: a maxed-out job shrinks to admit another.
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, max_load_desired=1.0)
    j1 = mk_job("example", lo=2, hi=10)
    submit(c, a, j1)
    for _ in range(10):
        a.tick()
    assert c.get_trainer_parallelism(j1) == 10

    j2 = mk_job("example1", lo=2, hi=8)
    submit(c, a, j2)
    for _ in range(20):
        a.tick()
    p1 = c.get_trainer_parallelism(j1)
    p2 = c.get_trainer_parallelism(j2)
    assert p1 + p2 <= 10
    assert p2 >= j2.spec.trainer.min_instance
    assert c.job_pods(j2).pending == 0


def test_job_deletion_returns_capacity():
    c = cluster_with(cpu_milli=4_000)
    a = Autoscaler(c)
    j1 = mk_job("one", lo=2, hi=4)
    j2 = mk_job("two", lo=2, hi=4)
    submit(c, a, j1)
    submit(c, a, j2)
    for _ in range(10):
        a.tick()
    assert c.get_trainer_parallelism(j1) + c.get_trainer_parallelism(j2) == 4

    c.delete_resources(j2)
    a.on_del(j2)
    for _ in range(10):
        a.tick()
    assert c.get_trainer_parallelism(j1) == 4


def test_actuation_retries_on_conflict():
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c)
    job = mk_job("example", lo=2, hi=4)
    submit(c, a, job)
    c.fail_next_updates = 2  # two conflicts, then success (5 retries allowed)
    for _ in range(6):
        a.tick()
    assert c.get_trainer_parallelism(job) == 4


def test_non_elastic_job_untouched():
    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c)
    job = mk_job("fixed", lo=3, hi=3)
    submit(c, a, job)
    for _ in range(5):
        a.tick()
    assert c.get_trainer_parallelism(job) == 3


def test_background_thread_smoke():
    # reference autoscaler_test.go:29-45 (Run blocks forever) made useful:
    # start/stop the loop thread and ensure it actuated.
    c = cluster_with(cpu_milli=5_000)
    a = Autoscaler(c, loop_seconds=0.01)
    job = mk_job("example", lo=1, hi=5)
    submit(c, a, job)
    a.start()
    import time

    deadline = time.time() + 5
    while time.time() < deadline and c.get_trainer_parallelism(job) < 5:
        time.sleep(0.02)
    a.stop()
    assert c.get_trainer_parallelism(job) == 5
