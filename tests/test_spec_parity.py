"""Spec parity: volumes / volumeMounts / imagePullSecrets (VERDICT r5).

Real k8s training workloads mount datasets from PVCs, need /dev/shm
tmpfs, and pull from private registries — a trainer spec without pod
volume passthroughs can't express any of it.  These tests pin the full
thread: manifest → serde (both spellings) → TrainerSpec → jobparser pod
manifests, round-tripping without loss, plus the FT path's compile-cache
volume wiring that rides the same mechanism.
"""

from __future__ import annotations

from edl_tpu.api import serde
from edl_tpu.api.types import (
    RESOURCE_CPU,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.controller.jobparser import (
    COMPILE_CACHE_PATH,
    COMPILE_CACHE_VOLUME,
    parse_to_trainer,
    pod_env,
)

VOLUMES = [
    {"name": "dataset", "persistentVolumeClaim": {"claimName": "imagenet"}},
    {"name": "shm", "emptyDir": {"medium": "Memory"}},
]
MOUNTS = [
    {"name": "dataset", "mountPath": "/data", "readOnly": True},
    {"name": "shm", "mountPath": "/dev/shm"},
]
PULL_SECRETS = [{"name": "registry-cred"}]


def make_job(fault_tolerant=True) -> TrainingJob:
    return TrainingJob(
        name="j", spec=TrainingJobSpec(
            fault_tolerant=fault_tolerant,
            trainer=TrainerSpec(
                min_instance=2, max_instance=4,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1"}),
                volumes=[dict(v) for v in VOLUMES],
                volume_mounts=[dict(m) for m in MOUNTS],
                image_pull_secrets=[dict(s) for s in PULL_SECRETS],
            )))


# ------------------------------------------------------------------- serde

def test_round_trip_preserves_pod_template_fields():
    job = make_job()
    doc = serde.job_to_dict(job)
    t = doc["spec"]["trainer"]
    assert t["volumes"] == VOLUMES
    assert t["volume_mounts"] == MOUNTS
    assert t["image_pull_secrets"] == PULL_SECRETS
    back = serde.job_from_dict(doc)
    assert back.spec.trainer.volumes == VOLUMES
    assert back.spec.trainer.volume_mounts == MOUNTS
    assert back.spec.trainer.image_pull_secrets == PULL_SECRETS
    # yaml round-trip too (the CLI path)
    assert serde.job_from_yaml(serde.job_to_yaml(job)) == back


def test_camel_case_spellings_accepted():
    """Anyone porting a Deployment writes volumeMounts/imagePullSecrets;
    both spellings parse to the same spec (snake wins when both appear —
    the established alias rule)."""
    doc = {
        "kind": "TrainingJob", "metadata": {"name": "j"},
        "spec": {"trainer": {
            "min_instance": 1, "max_instance": 1,
            "volumes": VOLUMES,
            "volumeMounts": MOUNTS,
            "imagePullSecrets": PULL_SECRETS,
        }},
    }
    t = serde.job_from_dict(doc).spec.trainer
    assert t.volume_mounts == MOUNTS
    assert t.image_pull_secrets == PULL_SECRETS


def test_snake_wins_over_camel_when_both_present():
    doc = {
        "kind": "TrainingJob", "metadata": {"name": "j"},
        "spec": {"trainer": {
            "volume_mounts": MOUNTS[:1],
            "volumeMounts": MOUNTS,
        }},
    }
    assert serde.job_from_dict(doc).spec.trainer.volume_mounts == MOUNTS[:1]


# --------------------------------------------------------------- jobparser

def trainer_pod(job):
    return parse_to_trainer(job)["spec"]["template"]["spec"]


def test_manifest_carries_volumes_mounts_and_secrets():
    pod = trainer_pod(make_job())
    names = [v["name"] for v in pod["volumes"]]
    assert names[:2] == ["dataset", "shm"]  # user volumes verbatim, first
    mounts = pod["containers"][0]["volumeMounts"]
    assert mounts[0] == MOUNTS[0] and mounts[1] == MOUNTS[1]
    assert pod["imagePullSecrets"] == PULL_SECRETS


def test_ft_trainer_gets_compile_cache_volume_and_env():
    """Tentpole wiring: respawned world children amortize the post-reform
    recompile through a per-pod compile-cache volume + EDL_COMPILE_CACHE."""
    job = make_job(fault_tolerant=True)
    pod = trainer_pod(job)
    assert any(v["name"] == COMPILE_CACHE_VOLUME and "emptyDir" in v
               for v in pod["volumes"])
    assert any(m["mountPath"] == COMPILE_CACHE_PATH
               for m in pod["containers"][0]["volumeMounts"])
    assert pod_env(job, "trainer")["EDL_COMPILE_CACHE"] == COMPILE_CACHE_PATH


def test_non_ft_trainer_gets_no_compile_cache():
    job = make_job(fault_tolerant=False)
    job.spec.trainer.volumes = []
    job.spec.trainer.volume_mounts = []
    job.spec.trainer.image_pull_secrets = []
    pod = trainer_pod(job)
    assert "volumes" not in pod
    assert "volumeMounts" not in pod["containers"][0]
    assert "imagePullSecrets" not in pod
    assert "EDL_COMPILE_CACHE" not in pod_env(job, "trainer")


def test_user_compile_cache_volume_wins():
    """A user volume named like the cache (e.g. a shared PVC mounted at
    the cache path) overrides the default emptyDir instead of colliding."""
    job = make_job(fault_tolerant=True)
    job.spec.trainer.volumes = [
        {"name": COMPILE_CACHE_VOLUME,
         "persistentVolumeClaim": {"claimName": "shared-cache"}}]
    job.spec.trainer.volume_mounts = [
        {"name": COMPILE_CACHE_VOLUME, "mountPath": COMPILE_CACHE_PATH}]
    pod = trainer_pod(job)
    cache_vols = [v for v in pod["volumes"]
                  if v["name"] == COMPILE_CACHE_VOLUME]
    assert cache_vols == [job.spec.trainer.volumes[0]]
    cache_mounts = [m for m in pod["containers"][0]["volumeMounts"]
                    if m["mountPath"] == COMPILE_CACHE_PATH]
    assert len(cache_mounts) == 1


def test_user_env_still_overrides_compile_cache_default():
    job = make_job(fault_tolerant=True)
    job.spec.trainer.env = {"EDL_COMPILE_CACHE": "/my/cache"}
    assert pod_env(job, "trainer")["EDL_COMPILE_CACHE"] == "/my/cache"
