"""Real data end to end: text file → tokenized shards on disk → task
queue leases → training — with exactly-once accounting.

Round-4 verdict missing #3: every example and bench leg trained on
synthetic tensors; the reference ships real imikolov RecordIO shards in
its job image and leases them through the master
(reference example/Dockerfile:1-8, example/train_ft.py:112).  Here the
shipped corpus (examples/data/tiny_corpus.txt, baked into
docker/Dockerfile.job via its ``COPY examples``) flows through
``runtime.corpus`` → ``FileShardStore`` files → queue leases →
``examples/train_ft.py``'s training loop, and the bytes demonstrably
come from disk."""

from __future__ import annotations

import json
import os
import re
import sys
from pathlib import Path

import numpy as np

from edl_tpu.runtime import corpus
from edl_tpu.runtime.data import FileShardStore

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"
CORPUS = EXAMPLES / "data" / "tiny_corpus.txt"

if str(EXAMPLES) not in sys.path:  # mirror `python examples/x.py`
    sys.path.insert(0, str(EXAMPLES))


def test_vocab_and_windows_roundtrip():
    text = CORPUS.read_text()
    vocab = corpus.build_vocab(text, 512)
    assert vocab["<unk>"] == corpus.UNK
    # frequency ranking: 'the' is the most common word in the corpus
    assert vocab["the"] == 4
    ids = corpus.tokenize(text, vocab)
    assert ids.dtype == np.int32
    # every line is BOS-framed; specials appear in the stream
    assert (ids == corpus.BOS).sum() == (ids == corpus.EOS).sum() > 0
    ctx, tgt = corpus.context_windows(ids, 4)
    assert ctx.shape == (len(ids) - 4, 4)
    # windows really are the token stream: the target IS the next token
    assert np.array_equal(ctx[1, :3], ctx[0, 1:])
    assert tgt[0] == ids[4]


def test_prepare_shards_writes_real_files(tmp_path):
    out = str(tmp_path / "shards")
    paths = corpus.prepare_shards(str(CORPUS), out, num_shards=8,
                                  vocab_size=512, context=4)
    assert len(paths) == 8 and all(os.path.exists(p) for p in paths)
    meta = corpus.load_vocab_meta(out)
    assert meta["vocab_size"] <= 512 and meta["context"] == 4
    # the shards hold REAL tokenized bytes from the text file on disk
    total = 0
    for p in paths:
        ctx, tgt = FileShardStore.fetch_path(p)
        assert ctx.shape[1] == 4 and ctx.dtype == np.int32
        assert int(ctx.max()) < meta["vocab_size"]
        total += len(tgt)
    assert total == meta["tokens"] - 4
    # idempotent re-bake (seeder takeover safety): same bytes
    before = open(paths[0], "rb").read()
    corpus.prepare_shards(str(CORPUS), out, num_shards=8,
                          vocab_size=512, context=4)
    assert open(paths[0], "rb").read() == before


def test_train_ft_trains_on_bytes_from_disk(tmp_path, capsys, monkeypatch):
    """The flagship example end to end on the real corpus: the seeder
    bakes file shards, the queue leases them, the loss falls, and the
    accounting is exactly-once."""
    data_dir = str(tmp_path / "data")
    monkeypatch.setenv("EDL_DATA_FILE", str(CORPUS))
    monkeypatch.setenv("EDL_DATA_DIR", data_dir)
    monkeypatch.setenv("EDL_PASSES", "1")

    import importlib

    import train_ft

    importlib.reload(train_ft)  # re-read EDL_PASSES
    train_ft.main()

    out = capsys.readouterr().out
    # trained on the real corpus (its vocab, not the synthetic 2048)
    m = re.search(r"corpus tiny_corpus\.txt: (\d+) tokens, vocab (\d+)", out)
    assert m, out
    assert int(m.group(2)) < 1024  # the tiny corpus' true vocab
    # exactly-once accounting over the file-shard queue
    m = re.search(r"queue done=(\d+) todo=(\d+) dropped=(\d+)", out)
    assert m, out
    assert (int(m.group(1)), int(m.group(2)),
            int(m.group(3))) == (train_ft.SHARDS, 0, 0)
    # the shards exist on disk and carry the corpus' token count
    meta = json.load(open(os.path.join(data_dir, "vocab.json")))
    shard_files = [f for f in os.listdir(data_dir)
                   if f.startswith("shard-") and f.endswith(".npz")]
    assert len(shard_files) == train_ft.SHARDS
    assert meta["source"] == "tiny_corpus.txt"
