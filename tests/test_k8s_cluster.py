"""K8sCluster exercised against the stub kubernetes module.

The reference generated a fake clientset for exactly this purpose
(reference pkg/client/clientset/versioned/fake/) but never used it in-repo;
here the real :class:`K8sCluster` method bodies run end-to-end against an
in-memory apiserver (tests/k8s_stub.py): inventory accounting, ICI-domain
labeling, pod phase counting, create/delete of the compiled manifests, and
the 409 → ConflictError mapping the autoscaler's bounded retry depends on
(reference pkg/autoscaler.go:339-376).
"""

from __future__ import annotations

import importlib
import sys

import pytest

from edl_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_TPU,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.base import ConflictError

from tests.k8s_stub import StubState, build_module, make_node, make_pod


@pytest.fixture
def kube(monkeypatch):
    """Install the stub as ``kubernetes`` and reload the backend module so
    its import guard sees it; yields (k8s_module, StubState)."""
    state = StubState()
    module = build_module(state)
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    import edl_tpu.cluster.k8s as k8s_mod

    importlib.reload(k8s_mod)
    assert k8s_mod._HAVE_K8S
    yield k8s_mod, state
    # restore the no-kubernetes reality for every other test
    monkeypatch.delitem(sys.modules, "kubernetes")
    importlib.reload(k8s_mod)


def make_job(name="j1", namespace="default", lo=2, hi=4, tpu="2"):
    return TrainingJob(
        name=name,
        namespace=namespace,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1Gi"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1Gi",
                            RESOURCE_TPU: tpu},
                ),
            ),
        ),
    )


def test_requires_kubernetes_package():
    import edl_tpu.cluster.k8s as k8s_mod

    if k8s_mod._HAVE_K8S:  # pragma: no cover - image has no kubernetes
        pytest.skip("kubernetes actually installed")
    with pytest.raises(RuntimeError, match="requires the 'kubernetes'"):
        k8s_mod.K8sCluster()


def test_inquiry_resource_accounting_and_domains(kube):
    k8s_mod, state = kube
    state.nodes = [
        make_node("a0", cpu="8", memory="16Gi", tpu=4,
                  labels={"cloud.google.com/gke-tpu-slice": "slice-a"}),
        make_node("a1", cpu="8", memory="16Gi", tpu=4,
                  labels={"edl-tpu/ici-domain": "A",
                          "cloud.google.com/gke-tpu-slice": "ignored"}),
        make_node("cpuonly", cpu="4", memory="8Gi"),
    ]
    state.pods = [
        make_pod("t-0", node="a0", labels={"edl-tpu-job": "j1"},
                 cpu="1", memory="1Gi", tpu=2),
        make_pod("sys-0", node="cpuonly", cpu="500m", memory="256Mi"),
        make_pod("gone", node="a1", phase="Succeeded", cpu="4", tpu=4),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    r = c.inquiry_resource()
    assert r.node_count == 3
    assert r.tpu_total == 8 and r.tpu_limit == 2  # Succeeded holds nothing
    assert r.cpu_total_milli == 20_000
    assert r.cpu_request_milli == 1_500
    assert r.nodes.nodes_tpu_free["a0"] == 2
    # explicit edl-tpu domain label wins over the GKE slice label
    assert r.nodes.nodes_ici_domain == {"a0": "slice-a", "a1": "A"}
    # the running chip pod pinned its job to a0's domain
    assert r.jobs_ici_domain == {"default/j1": "slice-a"}


def test_pod_on_dead_node_does_not_pin_domain(kube):
    # a chip pod lingering on a deleted node must not pin its job to a
    # domain the planner can no longer find (it would freeze scale-up)
    k8s_mod, state = kube
    state.nodes = [make_node("live0", tpu=4)]
    state.pods = [
        make_pod("t-0", node="gone-node", labels={"edl-tpu-job": "j1"},
                 cpu="1", memory="1Gi", tpu=2),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    assert c.inquiry_resource().jobs_ici_domain == {}


def test_job_pods_counts_phases_and_terminating(kube):
    k8s_mod, state = kube
    lbl = {"edl-tpu-job": "j1"}
    state.pods = [
        make_pod("t-0", labels=lbl, phase="Running"),
        make_pod("t-1", labels=lbl, phase="Pending"),
        make_pod("t-2", labels=lbl, phase="Running", terminating=True),
        make_pod("t-3", labels=lbl, phase="Failed"),
        make_pod("other", labels={"edl-tpu-job": "j2"}, phase="Running"),
        make_pod("elsewhere", namespace="prod", labels=lbl, phase="Running"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    counts = c.job_pods(make_job())
    # terminating pods count toward total only (reference cluster.go:117-136
    # + k8s_tools.py:29-36 Terminating handling)
    assert (counts.total, counts.running, counts.pending, counts.failed) == (
        4, 1, 1, 1)


def test_create_then_list_then_delete_resources(kube):
    k8s_mod, state = kube
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    c.create_resources(job)
    assert ("default", "j1-trainer") in state.jobs
    assert state.jobs[("default", "j1-trainer")].spec.parallelism == 2
    assert ("default", "j1-coordinator") in state.replicasets
    assert ("default", "j1-coordinator") in state.services
    assert c.list_training_jobs() == ["j1"]
    c.delete_resources(job)
    assert not state.jobs and not state.replicasets and not state.services
    # deleting again is a no-op (404s swallowed, reference cluster.go:245-291
    # foreground deletes of already-gone objects)
    c.delete_resources(job)


def test_parallelism_read_update_roundtrip(kube):
    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    assert c.get_trainer_parallelism(job) == 2
    c.update_trainer_parallelism(job, 4)
    assert c.get_trainer_parallelism(job) == 4
    # the stub enforces real resourceVersion semantics: the write bumped it
    assert state.jobs[("default", "j1-trainer")].metadata.resource_version == 2


def test_replace_conflict_maps_to_conflict_error(kube):
    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    state.conflicts_to_inject = 1
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    with pytest.raises(ConflictError):
        c.update_trainer_parallelism(job, 4)
    # the conflict did not write; a retry re-reads fresh and succeeds —
    # exactly the autoscaler's bounded-retry contract
    assert c.get_trainer_parallelism(job) == 2
    c.update_trainer_parallelism(job, 4)
    assert c.get_trainer_parallelism(job) == 4


def test_autoscaler_retry_recovers_from_conflicts(kube):
    """The real Autoscaler._scale_all against K8sCluster: two injected 409s
    are absorbed by the 5-retry refresh-then-write loop."""
    from edl_tpu.scheduler.autoscaler import Autoscaler

    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    scaler = Autoscaler(c)
    scaler.on_add(job)
    scaler.drain_events()
    state.conflicts_to_inject = 2
    scaler._scale_all_jobs({"default/j1": 4})
    assert c.get_trainer_parallelism(job) == 4


def test_list_pods_roles_and_scoping(kube):
    k8s_mod, state = kube
    state.pods = [
        make_pod("t-0", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=2),
        make_pod("m-0", labels={"edl-tpu-job-coordinator": "j1"}),
        make_pod("sys-0"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    trainers = c.list_pods(job_uid="default/j1", role="trainer")
    assert [p.name for p in trainers] == ["t-0"]
    assert trainers[0].tpu_limit == 2 and trainers[0].node == "a0"
    everything = c.list_pods()
    assert {p.role for p in everything} == {"trainer", "master", "system"}


def test_collector_on_k8s_backend(kube):
    """The deployed observability path: the Collector's four TSV columns
    computed from the REAL K8sCluster method bodies (all-namespaces pod
    scan + node inventory) against the stub apiserver — previously only
    FakeCluster exercised the Collector."""
    import io

    from edl_tpu.observability.collector import Collector

    k8s_mod, state = kube
    state.nodes = [make_node("a0", cpu="16", memory="64Gi", tpu=8)]
    state.pods = [
        make_pod("j1-t-0", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=1),
        make_pod("j1-t-1", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=1),
        make_pod("j2-t-0", phase="Pending",
                 labels={"edl-tpu-job": "j2"}, cpu="1", memory="1Gi",
                 tpu=1),
        make_pod("sys-0", node="a0", cpu="500m", memory="1Gi"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    out = io.StringIO()
    s = Collector(c, out=out).run_once()
    assert s.submitted_jobs == 2
    assert s.pending_jobs == 1  # j2: all trainers pending
    assert s.running_trainers["default/j1"] == 2
    assert abs(s.chip_utils_pct - 100.0 * 2 / 8) < 1e-9
    header, line = out.getvalue().strip().split("\n")
    assert header.startswith("TIMESTAMP\tSUBMITTED-JOBS")
    fields = line.split("\t")
    assert len(fields) == len(header.split("\t"))
    assert fields[1] == "2" and fields[2] == "1"  # submitted, pending
