"""K8sCluster exercised against the stub kubernetes module.

The reference generated a fake clientset for exactly this purpose
(reference pkg/client/clientset/versioned/fake/) but never used it in-repo;
here the real :class:`K8sCluster` method bodies run end-to-end against an
in-memory apiserver (tests/k8s_stub.py): inventory accounting, ICI-domain
labeling, pod phase counting, create/delete of the compiled manifests, and
the 409 → ConflictError mapping the autoscaler's bounded retry depends on
(reference pkg/autoscaler.go:339-376).
"""

from __future__ import annotations

import importlib
import sys
import time

import pytest

from edl_tpu.api.types import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_TPU,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.base import ConflictError

from tests.k8s_stub import StubState, build_module, make_node, make_pod


@pytest.fixture
def kube(monkeypatch):
    """Install the stub as ``kubernetes`` and reload the backend module so
    its import guard sees it; yields (k8s_module, StubState)."""
    state = StubState()
    module = build_module(state)
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    import edl_tpu.cluster.k8s as k8s_mod

    importlib.reload(k8s_mod)
    assert k8s_mod._HAVE_K8S
    yield k8s_mod, state
    # restore the no-kubernetes reality for every other test
    monkeypatch.delitem(sys.modules, "kubernetes")
    importlib.reload(k8s_mod)


def make_job(name="j1", namespace="default", lo=2, hi=4, tpu="2"):
    return TrainingJob(
        name=name,
        namespace=namespace,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1Gi"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1Gi",
                            RESOURCE_TPU: tpu},
                ),
            ),
        ),
    )


def test_requires_kubernetes_package():
    import edl_tpu.cluster.k8s as k8s_mod

    if k8s_mod._HAVE_K8S:  # pragma: no cover - image has no kubernetes
        pytest.skip("kubernetes actually installed")
    with pytest.raises(RuntimeError, match="requires the 'kubernetes'"):
        k8s_mod.K8sCluster()


def test_inquiry_resource_accounting_and_domains(kube):
    k8s_mod, state = kube
    state.nodes = [
        make_node("a0", cpu="8", memory="16Gi", tpu=4,
                  labels={"cloud.google.com/gke-tpu-slice": "slice-a"}),
        make_node("a1", cpu="8", memory="16Gi", tpu=4,
                  labels={"edl-tpu/ici-domain": "A",
                          "cloud.google.com/gke-tpu-slice": "ignored"}),
        make_node("cpuonly", cpu="4", memory="8Gi"),
    ]
    state.pods = [
        make_pod("t-0", node="a0", labels={"edl-tpu-job": "j1"},
                 cpu="1", memory="1Gi", tpu=2),
        make_pod("sys-0", node="cpuonly", cpu="500m", memory="256Mi"),
        make_pod("gone", node="a1", phase="Succeeded", cpu="4", tpu=4),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    r = c.inquiry_resource()
    assert r.node_count == 3
    assert r.tpu_total == 8 and r.tpu_limit == 2  # Succeeded holds nothing
    assert r.cpu_total_milli == 20_000
    assert r.cpu_request_milli == 1_500
    assert r.nodes.nodes_tpu_free["a0"] == 2
    # explicit edl-tpu domain label wins over the GKE slice label
    assert r.nodes.nodes_ici_domain == {"a0": "slice-a", "a1": "A"}
    # the running chip pod pinned its job to a0's domain
    assert r.jobs_ici_domain == {"default/j1": "slice-a"}


def test_pod_on_dead_node_does_not_pin_domain(kube):
    # a chip pod lingering on a deleted node must not pin its job to a
    # domain the planner can no longer find (it would freeze scale-up)
    k8s_mod, state = kube
    state.nodes = [make_node("live0", tpu=4)]
    state.pods = [
        make_pod("t-0", node="gone-node", labels={"edl-tpu-job": "j1"},
                 cpu="1", memory="1Gi", tpu=2),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    assert c.inquiry_resource().jobs_ici_domain == {}


def test_job_pods_counts_phases_and_terminating(kube):
    k8s_mod, state = kube
    lbl = {"edl-tpu-job": "j1"}
    state.pods = [
        make_pod("t-0", labels=lbl, phase="Running"),
        make_pod("t-1", labels=lbl, phase="Pending"),
        make_pod("t-2", labels=lbl, phase="Running", terminating=True),
        make_pod("t-3", labels=lbl, phase="Failed"),
        make_pod("other", labels={"edl-tpu-job": "j2"}, phase="Running"),
        make_pod("elsewhere", namespace="prod", labels=lbl, phase="Running"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    counts = c.job_pods(make_job())
    # terminating pods count toward total only (reference cluster.go:117-136
    # + k8s_tools.py:29-36 Terminating handling)
    assert (counts.total, counts.running, counts.pending, counts.failed) == (
        4, 1, 1, 1)


def test_create_then_list_then_delete_resources(kube):
    k8s_mod, state = kube
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    c.create_resources(job)
    assert ("default", "j1-trainer") in state.jobs
    assert state.jobs[("default", "j1-trainer")].spec.parallelism == 2
    assert ("default", "j1-coordinator") in state.replicasets
    assert ("default", "j1-coordinator") in state.services
    assert c.list_training_jobs() == ["j1"]
    c.delete_resources(job)
    assert not state.jobs and not state.replicasets and not state.services
    # deleting again is a no-op (404s swallowed, reference cluster.go:245-291
    # foreground deletes of already-gone objects)
    c.delete_resources(job)


def test_parallelism_read_update_roundtrip(kube):
    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    assert c.get_trainer_parallelism(job) == 2
    c.update_trainer_parallelism(job, 4)
    assert c.get_trainer_parallelism(job) == 4
    # the stub enforces real resourceVersion semantics: the write bumped it
    assert state.jobs[("default", "j1-trainer")].metadata.resource_version == 2


def test_replace_conflict_maps_to_conflict_error(kube):
    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    state.conflicts_to_inject = 1
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    with pytest.raises(ConflictError):
        c.update_trainer_parallelism(job, 4)
    # the conflict did not write; a retry re-reads fresh and succeeds —
    # exactly the autoscaler's bounded-retry contract
    assert c.get_trainer_parallelism(job) == 2
    c.update_trainer_parallelism(job, 4)
    assert c.get_trainer_parallelism(job) == 4


def test_autoscaler_retry_recovers_from_conflicts(kube):
    """The real Autoscaler._scale_all against K8sCluster: two injected 409s
    are absorbed by the 5-retry refresh-then-write loop."""
    from edl_tpu.scheduler.autoscaler import Autoscaler

    k8s_mod, state = kube
    state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    job = make_job()
    scaler = Autoscaler(c)
    scaler.on_add(job)
    scaler.drain_events()
    state.conflicts_to_inject = 2
    scaler._scale_all_jobs({"default/j1": 4})
    assert c.get_trainer_parallelism(job) == 4


def test_list_pods_roles_and_scoping(kube):
    k8s_mod, state = kube
    state.pods = [
        make_pod("t-0", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=2),
        make_pod("m-0", labels={"edl-tpu-job-coordinator": "j1"}),
        make_pod("sys-0"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    trainers = c.list_pods(job_uid="default/j1", role="trainer")
    assert [p.name for p in trainers] == ["t-0"]
    assert trainers[0].tpu_limit == 2 and trainers[0].node == "a0"
    everything = c.list_pods()
    assert {p.role for p in everything} == {"trainer", "master", "system"}


class TestHTTPMode:
    """The same K8sCluster bodies through REAL SOCKETS (VERDICT r5 #7):
    the schema-enforcing stub served by a threaded HTTP apiserver
    (tests/k8s_stub.py ``StubApiServer``), with a kubernetes-shaped
    client whose every call crosses the wire — watch streams as live
    line-delimited bytes, 410 Gone as an actual HTTP status, 409 as a
    conflict the autoscaler's retry observes end-to-end."""

    @pytest.fixture
    def kube_http(self, monkeypatch):
        from tests.k8s_stub import StubApiServer, build_http_module

        state = StubState()
        server = StubApiServer(state)
        module = build_http_module(server.url)
        monkeypatch.setitem(sys.modules, "kubernetes", module)
        import edl_tpu.cluster.k8s as k8s_mod

        importlib.reload(k8s_mod)
        assert k8s_mod._HAVE_K8S
        yield k8s_mod, state
        server.stop()
        monkeypatch.delitem(sys.modules, "kubernetes")
        importlib.reload(k8s_mod)

    def _cr(self, name: str) -> dict:
        return {"apiVersion": "edl.tpu/v1", "kind": "TrainingJob",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"image": "img", "fault_tolerant": True,
                         "trainer": {
                             "entrypoint": "python train.py",
                             "min_instance": 1, "max_instance": 2,
                             "resources": {
                                 "requests": {"cpu": "1",
                                              "memory": "1Gi"},
                                 "limits": {"cpu": "1", "memory": "1Gi",
                                            "google.com/tpu": "1"}}}}}

    def test_inventory_and_job_verbs_over_sockets(self, kube_http):
        """Typed objects survive the wire: node inventory, pod phase
        accounting, and the read→mutate→replace parallelism round trip
        (resourceVersion semantics enforced server-side)."""
        k8s_mod, state = kube_http
        state.nodes = [make_node("a0", cpu="8", memory="16Gi", tpu=4)]
        state.pods = [make_pod("t-0", node="a0",
                               labels={"edl-tpu-job": "j1"},
                               cpu="1", memory="1Gi", tpu=2)]
        state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
        c = k8s_mod.K8sCluster(kubeconfig="ignored")
        r = c.inquiry_resource()
        assert r.tpu_total == 4 and r.tpu_limit == 2
        job = make_job()
        assert c.get_trainer_parallelism(job) == 2
        c.update_trainer_parallelism(job, 4)
        assert c.get_trainer_parallelism(job) == 4
        assert state.jobs[("default", "j1-trainer")
                          ].metadata.resource_version == 2

    def test_watch_stream_over_real_sockets(self, kube_http):
        k8s_mod, state = kube_http
        c = k8s_mod.K8sCluster(kubeconfig="ignored")
        c.create_training_job_cr(self._cr("early"))
        _, rv = c.list_training_job_crs_with_rv()
        stream = c.watch_training_job_crs(rv, timeout_seconds=10)
        # mutate AFTER the stream is anchored: the events must arrive
        # over the open socket, not from a replayed list
        c.create_training_job_cr(self._cr("late"))
        evt = next(stream)
        assert evt["type"] == "ADDED"
        assert evt["object"]["metadata"]["name"] == "late"
        c.delete_training_job_cr("late")
        evt = next(stream)
        assert evt["type"] == "DELETED"
        assert evt["object"]["metadata"]["name"] == "late"
        stream.close()

    def test_watch_410_gone_then_reanchor(self, kube_http):
        """Compaction answers a stale rv with a REAL HTTP 410; the
        client maps it to ApiException and a fresh LIST re-anchors the
        stream exactly where the informer contract says it should."""
        from tests.k8s_stub import ApiException

        k8s_mod, state = kube_http
        c = k8s_mod.K8sCluster(kubeconfig="ignored")
        c.create_training_job_cr(self._cr("a"))
        _, stale_rv = c.list_training_job_crs_with_rv()
        # the collection moves on, then etcd compacts PAST the anchored
        # rv — resuming from it must answer 410, not silently rewind
        c.create_training_job_cr(self._cr("compacted-away"))
        c.delete_training_job_cr("compacted-away")
        state.compact_custom_events()
        with pytest.raises(ApiException) as exc:
            next(c.watch_training_job_crs(stale_rv, timeout_seconds=5))
        assert exc.value.status == 410
        # the re-anchor: fresh LIST, then the watch sees the next event
        items, rv = c.list_training_job_crs_with_rv()
        assert [i["metadata"]["name"] for i in items] == ["a"]
        stream = c.watch_training_job_crs(rv, timeout_seconds=10)
        c.create_training_job_cr(self._cr("b"))
        evt = next(stream)
        assert (evt["type"], evt["object"]["metadata"]["name"]) == (
            "ADDED", "b")
        stream.close()

    def test_sync_loop_reanchors_through_410(self, kube_http):
        """The deployed watch consumer end-to-end over the wire: a
        TrainingJobSyncLoop in watch mode absorbs a mid-run compaction
        (410 on its next stream) by re-LISTing, and still converges on a
        CR created after the compaction."""
        from edl_tpu.cluster.fake import FakeCluster
        from edl_tpu.controller.controller import Controller
        from edl_tpu.controller.sync import TrainingJobSyncLoop

        k8s_mod, state = kube_http
        store = k8s_mod.K8sCluster(kubeconfig="ignored")
        fake = FakeCluster()
        fake.add_node("n0", cpu_milli=16000, memory_mega=16000,
                      tpu_chips=8)
        controller = Controller(fake, updater_convert_seconds=0.05,
                                updater_confirm_seconds=0.05)
        sync = TrainingJobSyncLoop(store, controller, poll_seconds=0.2,
                                   watch=True, resync_every=1000)
        sync.start()

        def submitted() -> set:
            return {j.full_name for j in controller.jobs()}

        try:
            store.create_training_job_cr(self._cr("first"))
            deadline = time.monotonic() + 30
            while "default/first" not in submitted():
                assert time.monotonic() < deadline, submitted()
                time.sleep(0.05)
            # compaction lands mid-run: the loop's anchored rv is stale
            state.compact_custom_events()
            state.custom_rv += 7
            store.create_training_job_cr(self._cr("second"))
            deadline = time.monotonic() + 30
            while "default/second" not in submitted():
                assert time.monotonic() < deadline, submitted()
                time.sleep(0.05)
        finally:
            sync.stop()
            controller.stop()

    def test_409_conflict_and_autoscaler_retry_over_sockets(self,
                                                            kube_http):
        from edl_tpu.scheduler.autoscaler import Autoscaler

        k8s_mod, state = kube_http
        state.put_job("default", "j1-trainer", 2, {"edl-tpu-job": "j1"})
        c = k8s_mod.K8sCluster(kubeconfig="ignored")
        job = make_job()
        state.conflicts_to_inject = 1
        with pytest.raises(ConflictError):
            c.update_trainer_parallelism(job, 4)
        assert c.get_trainer_parallelism(job) == 2  # conflict wrote nothing
        # the bounded refresh-then-write retry absorbs two more 409s,
        # each delivered as a real HTTP status over the socket
        scaler = Autoscaler(c)
        scaler.on_add(job)
        scaler.drain_events()
        state.conflicts_to_inject = 2
        scaler._scale_all_jobs({"default/j1": 4})
        assert c.get_trainer_parallelism(job) == 4


def test_collector_on_k8s_backend(kube):
    """The deployed observability path: the Collector's four TSV columns
    computed from the REAL K8sCluster method bodies (all-namespaces pod
    scan + node inventory) against the stub apiserver — previously only
    FakeCluster exercised the Collector."""
    import io

    from edl_tpu.observability.collector import Collector

    k8s_mod, state = kube
    state.nodes = [make_node("a0", cpu="16", memory="64Gi", tpu=8)]
    state.pods = [
        make_pod("j1-t-0", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=1),
        make_pod("j1-t-1", labels={"edl-tpu-job": "j1"}, node="a0",
                 cpu="1", memory="1Gi", tpu=1),
        make_pod("j2-t-0", phase="Pending",
                 labels={"edl-tpu-job": "j2"}, cpu="1", memory="1Gi",
                 tpu=1),
        make_pod("sys-0", node="a0", cpu="500m", memory="1Gi"),
    ]
    c = k8s_mod.K8sCluster(kubeconfig="ignored")
    out = io.StringIO()
    s = Collector(c, out=out).run_once()
    assert s.submitted_jobs == 2
    assert s.pending_jobs == 1  # j2: all trainers pending
    assert s.running_trainers["default/j1"] == 2
    assert abs(s.chip_utils_pct - 100.0 * 2 / 8) < 1e-9
    header, line = out.getvalue().strip().split("\n")
    assert header.startswith("TIMESTAMP\tSUBMITTED-JOBS")
    fields = line.split("\t")
    assert len(fields) == len(header.split("\t"))
    assert fields[1] == "2" and fields[2] == "1"  # submitted, pending
