"""Collector + tracing tests (reference example/collector.py behavior)."""

import io

from edl_tpu.api.types import (
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_TPU,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.observability.collector import Collector
from edl_tpu.observability.tracing import Tracer


def _job(name, chips=1, lo=2, hi=4):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1G"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1G",
                            RESOURCE_TPU: str(chips)},
                ),
            ),
        ),
    )


def _cluster(chips_per_node=4, nodes=4):
    c = FakeCluster()
    for i in range(nodes):
        c.add_node(f"n{i}", cpu_milli=16000, memory_mega=64000,
                   tpu_chips=chips_per_node, ici_domain="pod0")
    return c


class TestCollector:
    def test_empty_cluster(self):
        out = io.StringIO()
        s = Collector(_cluster(), out=out).run_once()
        assert s.submitted_jobs == 0
        assert s.pending_jobs == 0
        assert s.chip_utils_pct == 0.0
        header, line = out.getvalue().strip().split("\n")
        assert header.startswith("TIMESTAMP\tSUBMITTED-JOBS")

    def test_running_job_counted(self):
        c = _cluster()
        job = _job("j1", chips=1)
        c.create_resources(job)
        c.reconcile()
        s = Collector(c, out=io.StringIO()).run_once()
        assert s.submitted_jobs == 1
        assert s.pending_jobs == 0
        assert s.running_trainers["default/j1"] == 2
        # 2 trainers x 1 chip / 16 chips
        assert abs(s.chip_utils_pct - 100.0 * 2 / 16) < 1e-9

    def test_pending_rule(self):
        # Job too big for the cluster -> all trainers pending -> job pending
        c = _cluster(chips_per_node=0)
        job = _job("big", chips=8, lo=2, hi=2)
        c.create_resources(job)
        c.reconcile()
        s = Collector(c, out=io.StringIO()).run_once()
        assert s.pending_jobs == 1
        assert s.running_trainers["default/big"] == 0

    def test_tsv_format(self):
        out = io.StringIO()
        c = _cluster()
        c.create_resources(_job("j1"))
        c.reconcile()
        Collector(c, out=out).run_once()
        line = out.getvalue().strip().split("\n")[1]
        cols = line.split("\t")
        assert len(cols) == 6
        assert cols[1] == "1"  # SUBMITTED-JOBS
        assert "default/j1:2" in cols[3]

    def test_run_bounded(self):
        out = io.StringIO()
        Collector(_cluster(), interval_s=0.0, out=out).run(max_samples=3)
        assert len(out.getvalue().strip().split("\n")) == 4  # header + 3


class TestTracer:
    def test_span_and_instant(self):
        t = Tracer()
        t.instant("epoch_change", category="membership", epoch=3)
        with t.span("train_step", step=1):
            pass
        evs = t.events()
        assert [e.name for e in evs] == ["epoch_change", "train_step"]
        assert evs[0].duration_s == 0.0
        assert evs[1].duration_s >= 0.0
        assert t.events(category="membership")[0].args == {"epoch": 3}

    def test_bounded(self):
        t = Tracer(capacity=10)
        for i in range(100):
            t.instant(f"e{i}")
        assert len(t.events()) == 10
        assert t.events()[0].name == "e90"

    def test_chrome_trace(self, tmp_path):
        import json

        t = Tracer()
        with t.span("s"):
            pass
        p = tmp_path / "trace.json"
        t.dump(str(p))
        doc = json.loads(p.read_text())
        assert doc["traceEvents"][0]["name"] == "s"
        assert doc["traceEvents"][0]["ph"] == "X"

    def test_profile_step_cpu(self):
        # jax TraceAnnotation is a no-op outside a profile; must not raise.
        from edl_tpu.observability.tracing import get_tracer, profile_step

        get_tracer().clear()
        with profile_step("unit_step"):
            pass
        assert any(e.name == "unit_step" for e in get_tracer().events())


class TestServeHealth:
    """The controller-side /healthz (edl_tpu/observability/health.py):
    200 while every named check passes, 503 the moment one fails — that
    transition is what makes k8s/controller.yaml's livenessProbe restart
    a controller whose autoscaler/sync thread died."""

    def test_ok_then_unhealthy_then_shutdown(self):
        import json
        import urllib.error
        import urllib.request

        from edl_tpu.observability.health import serve_health

        state = {"alive": True}
        srv = serve_health(0, {"autoscaler": lambda: state["alive"]},
                           host="127.0.0.1")
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                doc = json.loads(r.read())
            assert r.status == 200
            assert doc["status"] == "ok" and doc["autoscaler"] is True
            # per-check detail: latency + timeout verdict in the body
            assert doc["checks"]["autoscaler"]["ok"] is True
            assert doc["checks"]["autoscaler"]["timed_out"] is False
            assert doc["checks"]["autoscaler"]["latency_ms"] >= 0

            state["alive"] = False  # the thread died
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["autoscaler"] is False

            # a check that RAISES counts as dead, not as a 500
            srv2 = serve_health(0, {"boom": lambda: 1 / 0},
                                host="127.0.0.1")
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv2.server_address[1]}/healthz",
                    timeout=5)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
            finally:
                srv2.shutdown()
        finally:
            srv.shutdown()

    def test_wedged_check_times_out_instead_of_blocking_probe(self):
        """One hung check must not wedge the probe thread: the probe
        still answers (503) within the per-check timeout, the stuck
        check is reported as timed_out, and healthy checks alongside it
        still report truthfully."""
        import json
        import threading
        import time
        import urllib.error
        import urllib.request

        from edl_tpu.observability.health import serve_health

        release = threading.Event()

        def wedged() -> bool:
            release.wait(30)  # a check stuck in a lock/collective
            return True

        srv = serve_health(0, {"wedged": wedged, "fine": lambda: True},
                           host="127.0.0.1", check_timeout_s=0.3)
        try:
            port = srv.server_address[1]
            t0 = time.monotonic()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                doc = json.loads(e.read())
            assert time.monotonic() - t0 < 5  # probe was never blocked
            assert doc["wedged"] is False
            assert doc["checks"]["wedged"]["timed_out"] is True
            assert doc["checks"]["wedged"]["latency_ms"] >= 300
            assert doc["fine"] is True
            assert doc["checks"]["fine"]["timed_out"] is False
            # a SECOND probe while the check is still wedged must not
            # stack another thread: it reports the check stuck instantly
            before_threads = threading.active_count()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                doc2 = json.loads(e.read())
            assert doc2["checks"]["wedged"]["stuck"] is True
            assert doc2["checks"]["wedged"]["timed_out"] is True
            assert doc2["fine"] is True
            # one leaked daemon thread TOTAL for the wedged check, not
            # one per probe (the HTTP handler thread itself comes and
            # goes; allow slack for it)
            assert threading.active_count() <= before_threads + 2
        finally:
            release.set()
            srv.shutdown()

    def test_concurrent_probes_share_inflight_check_no_false_503(self):
        """ThreadingHTTPServer overlaps probes (liveness + readiness +
        dashboards): a probe arriving while a healthy-but-slowish check
        is mid-run must SHARE that evaluation and report healthy — not
        declare it stuck and 503 a healthy pod."""
        import concurrent.futures
        import json
        import time
        import urllib.request

        from edl_tpu.observability.health import serve_health

        def slowish() -> bool:
            time.sleep(0.15)  # well inside the timeout
            return True

        srv = serve_health(0, {"slowish": slowish}, host="127.0.0.1",
                           check_timeout_s=2.0)
        try:
            port = srv.server_address[1]

            def probe():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                    return r.status, json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(4) as ex:
                results = list(ex.map(lambda _: probe(), range(4)))
            for code, doc in results:
                assert code == 200, doc
                assert doc["slowish"] is True
                assert "stuck" not in doc["checks"]["slowish"]
        finally:
            srv.shutdown()


class TestCounters:
    """Labeled counters — the chaos/recovery audit surface the fault-plan
    engine records into (faults_injected / recoveries_completed)."""

    def test_inc_get_with_labels(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        assert c.get("faults_injected", type="kill_trainer") == 0
        c.inc("faults_injected", type="kill_trainer")
        c.inc("faults_injected", type="kill_trainer")
        c.inc("faults_injected", type="network_flake")
        assert c.get("faults_injected", type="kill_trainer") == 2
        assert c.get("faults_injected", type="network_flake") == 1
        assert c.total("faults_injected") == 3
        assert c.get("recoveries_completed", type="kill_trainer") == 0

    def test_snapshot_and_clear(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        c.inc("plain")
        c.inc("labeled", n=3, type="x")
        snap = c.snapshot()
        assert snap["plain"] == 1
        assert snap["labeled{type=x}"] == 3
        c.clear()
        assert c.snapshot() == {}

    def test_label_order_is_canonical(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        c.inc("m", a="1", b="2")
        assert c.get("m", b="2", a="1") == 1

    def test_process_wide_registry(self):
        from edl_tpu.observability import get_counters

        g = get_counters()
        before = g.get("test_obs_probe")
        g.inc("test_obs_probe")
        assert get_counters().get("test_obs_probe") == before + 1

    def test_thread_safety(self):
        import threading

        from edl_tpu.observability.collector import Counters

        c = Counters()

        def bump():
            for _ in range(1000):
                c.inc("hot", type="t")

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get("hot", type="t") == 8000
