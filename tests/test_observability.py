"""Collector + tracing + unified-telemetry-plane tests.

Collector behavior mirrors reference example/collector.py; the metrics
registry / exposition / span-correlation tests cover the shared
telemetry plane (observability/metrics.py + tracing span IDs).
"""

import io

from edl_tpu.api.types import (
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_TPU,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.observability.collector import Collector
from edl_tpu.observability.tracing import Tracer


# -- strict Prometheus text-format (0.0.4) parser ---------------------------
#
# The conformance oracle every process's /metrics is held to — promoted
# to library code (edl_tpu/observability/metrics.py::parse_exposition,
# the same parser the scrape plane trusts in production); the alias
# keeps every existing import site (tests, ci.sh heredocs) working, and
# TestExpositionParser below remains its strictness unit suite.

from edl_tpu.observability.metrics import (  # noqa: E402
    ExpositionError, iter_samples, parse_exposition,
)

parse_prometheus = parse_exposition


class TestExpositionParser:
    """The promoted parser's unit suite: every grammar/contract rule the
    in-test implementation enforced, pinned against the library one."""

    def test_values_labels_and_specials(self):
        s = parse_exposition(
            "# HELP edl_x_total help\n# TYPE edl_x_total counter\n"
            'edl_x_total{job="a b",k="v"} 3\n'
            "edl_x_total 2\n"
            "edl_g +Inf\nedl_h -Inf\nedl_n NaN\n")
        assert s['edl_x_total{job="a b",k="v"}'] == 3
        assert s["edl_x_total"] == 2
        assert s["edl_g"] == float("inf")
        assert s["edl_h"] == float("-inf")
        assert s["edl_n"] != s["edl_n"]  # NaN

    def test_iter_samples_unescapes_label_values(self):
        samples = iter_samples('m{v="a\\"b\\\\c\\nd"} 1\n')
        assert samples == [("m", {"v": 'a"b\\c\nd'}, 1.0)]

    def test_unescape_backslash_abutting_n_is_not_a_newline(self):
        # spec form of the raw value `dir\name` is v="dir\\name": the
        # unescape must scan left-to-right — sequential replace would
        # see the second backslash + n as \n and corrupt the value
        samples = iter_samples('m{v="dir\\\\name"} 1\n')
        assert samples == [("m", {"v": "dir\\name"}, 1.0)]
        # and the dict view round-trips it back to the escaped form
        assert parse_exposition('m{v="dir\\\\name"} 1\n') == {
            'm{v="dir\\\\name"}': 1.0}

    def test_rejects_malformed_sample_line(self):
        import pytest

        for bad in ("1metric 3", "m{unquoted=x} 1", "m{} x",
                    "m 1 2 3", "# WAT comment"):
            with pytest.raises(ExpositionError):
                parse_exposition(bad + "\n")

    def test_rejects_bad_help_type_and_duplicates(self):
        import pytest

        with pytest.raises(ExpositionError, match="TYPE"):
            parse_exposition("# TYPE only\n")
        with pytest.raises(ExpositionError, match="unknown type"):
            parse_exposition("# TYPE m exotic\n")
        with pytest.raises(ExpositionError, match="duplicate TYPE"):
            parse_exposition("# TYPE m gauge\n# TYPE m gauge\nm 1\n")
        with pytest.raises(ExpositionError, match="duplicate series"):
            parse_exposition('m{a="1"} 1\nm{a="1"} 2\n')

    def test_histogram_contracts_enforced(self):
        import pytest

        ok = ("# TYPE h histogram\n"
              'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 2\n'
              "h_sum 0.3\nh_count 2\n")
        assert parse_exposition(ok)['h_bucket{le="+Inf"}'] == 2
        with pytest.raises(ExpositionError, match="no \\+Inf"):
            parse_exposition("# TYPE h histogram\n"
                             'h_bucket{le="0.1"} 1\n')
        with pytest.raises(ExpositionError, match="non-monotone"):
            parse_exposition("# TYPE h histogram\n"
                             'h_bucket{le="0.1"} 3\n'
                             'h_bucket{le="+Inf"} 2\n')

    def test_exposition_error_is_assertion_shaped(self):
        # pre-promotion callers wrapped the parser in try/except
        # AssertionError; the promoted exception must still satisfy them
        assert issubclass(ExpositionError, AssertionError)


def _job(name, chips=1, lo=2, hi=4):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1G"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1G",
                            RESOURCE_TPU: str(chips)},
                ),
            ),
        ),
    )


def _cluster(chips_per_node=4, nodes=4):
    c = FakeCluster()
    for i in range(nodes):
        c.add_node(f"n{i}", cpu_milli=16000, memory_mega=64000,
                   tpu_chips=chips_per_node, ici_domain="pod0")
    return c


class TestCollector:
    def test_empty_cluster(self):
        out = io.StringIO()
        s = Collector(_cluster(), out=out).run_once()
        assert s.submitted_jobs == 0
        assert s.pending_jobs == 0
        assert s.chip_utils_pct == 0.0
        header, line = out.getvalue().strip().split("\n")
        assert header.startswith("TIMESTAMP\tSUBMITTED-JOBS")

    def test_running_job_counted(self):
        c = _cluster()
        job = _job("j1", chips=1)
        c.create_resources(job)
        c.reconcile()
        s = Collector(c, out=io.StringIO()).run_once()
        assert s.submitted_jobs == 1
        assert s.pending_jobs == 0
        assert s.running_trainers["default/j1"] == 2
        # 2 trainers x 1 chip / 16 chips
        assert abs(s.chip_utils_pct - 100.0 * 2 / 16) < 1e-9

    def test_pending_rule(self):
        # Job too big for the cluster -> all trainers pending -> job pending
        c = _cluster(chips_per_node=0)
        job = _job("big", chips=8, lo=2, hi=2)
        c.create_resources(job)
        c.reconcile()
        s = Collector(c, out=io.StringIO()).run_once()
        assert s.pending_jobs == 1
        assert s.running_trainers["default/big"] == 0

    def test_tsv_format(self):
        out = io.StringIO()
        c = _cluster()
        c.create_resources(_job("j1"))
        c.reconcile()
        Collector(c, out=out).run_once()
        line = out.getvalue().strip().split("\n")[1]
        cols = line.split("\t")
        assert len(cols) == 6
        assert cols[1] == "1"  # SUBMITTED-JOBS
        assert "default/j1:2" in cols[3]

    def test_run_bounded(self):
        out = io.StringIO()
        Collector(_cluster(), interval_s=0.0, out=out).run(max_samples=3)
        assert len(out.getvalue().strip().split("\n")) == 4  # header + 3

    def test_deleted_job_series_pruned_from_metrics(self):
        """A job that leaves the cluster must leave /metrics too — not
        freeze at its last trainer count forever."""
        from edl_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = _cluster()
        job = _job("ephemeral")
        c.create_resources(job)
        c.reconcile()
        col = Collector(c, out=io.StringIO(), registry=reg)
        col.run_once()
        g = reg.gauge("cluster_running_trainers")
        assert g.value(job="default/ephemeral") == 2
        c.delete_resources(job)
        c.reconcile()
        col.run_once()
        assert 'job="default/ephemeral"' not in reg.render()
        assert reg.gauge("cluster_submitted_jobs").value() == 0


class TestTracer:
    def test_span_and_instant(self):
        t = Tracer()
        t.instant("epoch_change", category="membership", epoch=3)
        with t.span("train_step", step=1):
            pass
        evs = t.events()
        assert [e.name for e in evs] == ["epoch_change", "train_step"]
        assert evs[0].duration_s == 0.0
        assert evs[1].duration_s >= 0.0
        assert t.events(category="membership")[0].args == {"epoch": 3}

    def test_bounded(self):
        t = Tracer(capacity=10)
        for i in range(100):
            t.instant(f"e{i}")
        assert len(t.events()) == 10
        assert t.events()[0].name == "e90"

    def test_chrome_trace(self, tmp_path):
        import json

        t = Tracer()
        with t.span("s"):
            pass
        p = tmp_path / "trace.json"
        t.dump(str(p))
        doc = json.loads(p.read_text())
        assert doc["traceEvents"][0]["name"] == "s"
        assert doc["traceEvents"][0]["ph"] == "X"

    def test_profile_step_cpu(self):
        # jax TraceAnnotation is a no-op outside a profile; must not raise.
        from edl_tpu.observability.tracing import get_tracer, profile_step

        get_tracer().clear()
        with profile_step("unit_step"):
            pass
        assert any(e.name == "unit_step" for e in get_tracer().events())


class TestServeHealth:
    """The controller-side /healthz (edl_tpu/observability/health.py):
    200 while every named check passes, 503 the moment one fails — that
    transition is what makes k8s/controller.yaml's livenessProbe restart
    a controller whose autoscaler/sync thread died."""

    def test_ok_then_unhealthy_then_shutdown(self):
        import json
        import urllib.error
        import urllib.request

        from edl_tpu.observability.health import serve_health

        state = {"alive": True}
        srv = serve_health(0, {"autoscaler": lambda: state["alive"]},
                           host="127.0.0.1")
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as r:
                doc = json.loads(r.read())
            assert r.status == 200
            assert doc["status"] == "ok" and doc["autoscaler"] is True
            # per-check detail: latency + timeout verdict in the body
            assert doc["checks"]["autoscaler"]["ok"] is True
            assert doc["checks"]["autoscaler"]["timed_out"] is False
            assert doc["checks"]["autoscaler"]["latency_ms"] >= 0

            state["alive"] = False  # the thread died
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["autoscaler"] is False

            # a check that RAISES counts as dead, not as a 500
            srv2 = serve_health(0, {"boom": lambda: 1 / 0},
                                host="127.0.0.1")
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv2.server_address[1]}/healthz",
                    timeout=5)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
            finally:
                srv2.shutdown()
        finally:
            srv.shutdown()

    def test_wedged_check_times_out_instead_of_blocking_probe(self):
        """One hung check must not wedge the probe thread: the probe
        still answers (503) within the per-check timeout, the stuck
        check is reported as timed_out, and healthy checks alongside it
        still report truthfully."""
        import json
        import threading
        import time
        import urllib.error
        import urllib.request

        from edl_tpu.observability.health import serve_health

        release = threading.Event()

        def wedged() -> bool:
            release.wait(30)  # a check stuck in a lock/collective
            return True

        srv = serve_health(0, {"wedged": wedged, "fine": lambda: True},
                           host="127.0.0.1", check_timeout_s=0.3)
        try:
            port = srv.server_address[1]
            t0 = time.monotonic()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                doc = json.loads(e.read())
            assert time.monotonic() - t0 < 5  # probe was never blocked
            assert doc["wedged"] is False
            assert doc["checks"]["wedged"]["timed_out"] is True
            assert doc["checks"]["wedged"]["latency_ms"] >= 300
            assert doc["fine"] is True
            assert doc["checks"]["fine"]["timed_out"] is False
            # a SECOND probe while the check is still wedged must not
            # stack another thread: it reports the check stuck instantly
            before_threads = threading.active_count()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                doc2 = json.loads(e.read())
            assert doc2["checks"]["wedged"]["stuck"] is True
            assert doc2["checks"]["wedged"]["timed_out"] is True
            assert doc2["fine"] is True
            # one leaked daemon thread TOTAL for the wedged check, not
            # one per probe (the HTTP handler thread itself comes and
            # goes; allow slack for it)
            assert threading.active_count() <= before_threads + 2
        finally:
            release.set()
            srv.shutdown()

    def test_concurrent_probes_share_inflight_check_no_false_503(self):
        """ThreadingHTTPServer overlaps probes (liveness + readiness +
        dashboards): a probe arriving while a healthy-but-slowish check
        is mid-run must SHARE that evaluation and report healthy — not
        declare it stuck and 503 a healthy pod."""
        import concurrent.futures
        import json
        import time
        import urllib.request

        from edl_tpu.observability.health import serve_health

        def slowish() -> bool:
            time.sleep(0.15)  # well inside the timeout
            return True

        srv = serve_health(0, {"slowish": slowish}, host="127.0.0.1",
                           check_timeout_s=2.0)
        try:
            port = srv.server_address[1]

            def probe():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                    return r.status, json.loads(r.read())

            with concurrent.futures.ThreadPoolExecutor(4) as ex:
                results = list(ex.map(lambda _: probe(), range(4)))
            for code, doc in results:
                assert code == 200, doc
                assert doc["slowish"] is True
                assert "stuck" not in doc["checks"]["slowish"]
        finally:
            srv.shutdown()


class TestCounters:
    """Labeled counters — the chaos/recovery audit surface the fault-plan
    engine records into (faults_injected / recoveries_completed)."""

    def test_inc_get_with_labels(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        assert c.get("faults_injected", type="kill_trainer") == 0
        c.inc("faults_injected", type="kill_trainer")
        c.inc("faults_injected", type="kill_trainer")
        c.inc("faults_injected", type="network_flake")
        assert c.get("faults_injected", type="kill_trainer") == 2
        assert c.get("faults_injected", type="network_flake") == 1
        assert c.total("faults_injected") == 3
        assert c.get("recoveries_completed", type="kill_trainer") == 0

    def test_snapshot_and_clear(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        c.inc("plain")
        c.inc("labeled", n=3, type="x")
        snap = c.snapshot()
        assert snap["plain"] == 1
        assert snap["labeled{type=x}"] == 3
        c.clear()
        assert c.snapshot() == {}

    def test_label_order_is_canonical(self):
        from edl_tpu.observability.collector import Counters

        c = Counters()
        c.inc("m", a="1", b="2")
        assert c.get("m", b="2", a="1") == 1

    def test_process_wide_registry(self):
        from edl_tpu.observability import get_counters

        g = get_counters()
        before = g.get("test_obs_probe")
        g.inc("test_obs_probe")
        assert get_counters().get("test_obs_probe") == before + 1

    def test_thread_safety(self):
        import threading

        from edl_tpu.observability.collector import Counters

        c = Counters()

        def bump():
            for _ in range(1000):
                c.inc("hot", type="t")

        threads = [threading.Thread(target=bump) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.get("hot", type="t") == 8000


class TestMetricsRegistry:
    """The unified plane: one registry, Prometheus exposition, and the
    Counters facade absorbed into it."""

    def test_per_histogram_buckets(self):
        """Serving latencies are ms-scale: a histogram may declare its
        own boundaries at first registration, while omitting buckets
        keeps DEFAULT_BUCKETS (existing series unchanged) and a
        conflicting re-registration raises instead of silently merging
        incomparable distributions under one name."""
        import pytest

        from edl_tpu.observability.metrics import (DEFAULT_BUCKETS,
                                                   SERVING_LATENCY_BUCKETS,
                                                   MetricsRegistry)

        r = MetricsRegistry()
        h = r.histogram("serving_request_seconds",
                        buckets=SERVING_LATENCY_BUCKETS)
        assert h.buckets == SERVING_LATENCY_BUCKETS
        h.observe(0.0007)   # would crush into DEFAULT's first bucket
        h.observe(0.003)
        d = r.histogram("resize_phase_seconds")  # default boundaries
        assert d.buckets == DEFAULT_BUCKETS
        d.observe(0.3)
        series = parse_prometheus(r.render())
        # the ms-scale resolution is real: 0.0007 and 0.003 land in
        # DIFFERENT custom buckets (DEFAULT's 0.001 lumps half of them)
        assert series['edl_serving_request_seconds_bucket{le="0.001"}'] == 1
        assert series['edl_serving_request_seconds_bucket{le="0.005"}'] == 2
        assert series['edl_serving_request_seconds_count'] == 2
        assert series['edl_resize_phase_seconds_bucket{le="0.5"}'] == 1
        # same-name re-registration: omitted/matching buckets fine,
        # conflicting boundaries refused
        assert r.histogram("serving_request_seconds") is h
        assert r.histogram("serving_request_seconds",
                           buckets=SERVING_LATENCY_BUCKETS) is h
        with pytest.raises(ValueError, match="buckets"):
            r.histogram("serving_request_seconds", buckets=(1.0, 2.0))

    def test_counter_gauge_histogram_render_conform(self):
        from edl_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        r.counter("faults_injected", help="chaos injections").inc(
            2, type="kill_trainer")
        r.counter("faults_injected").inc(type="network_flake")
        r.gauge("queue_depth").set(7, state="todo")
        h = r.histogram("world_start_phase_seconds")
        h.observe(0.004, phase="restore")
        h.observe(2.0, phase="restore")
        h.observe(200.0, phase="restore")  # beyond the last bucket
        series = parse_prometheus(r.render())
        assert series['edl_faults_injected_total{type="kill_trainer"}'] == 2
        assert series['edl_queue_depth{state="todo"}'] == 7
        assert series[
            'edl_world_start_phase_seconds_bucket'
            '{phase="restore",le="+Inf"}'] == 3
        assert series[
            'edl_world_start_phase_seconds_count{phase="restore"}'] == 3
        assert abs(series[
            'edl_world_start_phase_seconds_sum{phase="restore"}']
            - 202.004) < 1e-6

    def test_counters_facade_lands_in_registry(self):
        from edl_tpu.observability.collector import get_counters
        from edl_tpu.observability.metrics import get_registry

        get_counters().inc("telemetry_probe", kind="facade")
        series = parse_prometheus(get_registry().render())
        assert series['edl_telemetry_probe_total{kind="facade"}'] >= 1

    def test_gauge_fn_families_and_failures_skipped(self):
        from edl_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        r.gauge_fn("coord_queue_tasks", lambda: 3, state="todo")
        r.gauge_fn("coord_queue_tasks", lambda: 1, state="leased")
        r.gauge_fn("boom", lambda: 1 / 0)
        series = parse_prometheus(r.render())
        assert series['edl_coord_queue_tasks{state="todo"}'] == 3
        assert series['edl_coord_queue_tasks{state="leased"}'] == 1
        assert not any("boom" in k for k in series)

    def test_name_and_label_sanitization(self):
        from edl_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        r.counter("weird-name.with spaces").inc(**{"label": 'va"l\\ue'})
        parse_prometheus(r.render())  # the strict parser IS the assertion

    def test_type_collision_raises(self):
        import pytest

        from edl_tpu.observability.metrics import MetricsRegistry

        r = MetricsRegistry()
        r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_histogram_quantile_bucket(self):
        from edl_tpu.observability.metrics import MetricsRegistry

        h = MetricsRegistry().histogram("lat", buckets=(0.1, 1.0, 10.0))
        assert h.quantile_bucket(0.5) is None
        for v in (0.05, 0.05, 0.5, 5.0):
            h.observe(v)
        assert h.quantile_bucket(0.5) == 0.1
        assert h.quantile_bucket(0.99) == 10.0


class TestMetricsRoute:
    """Every process that serves /healthz now serves /metrics from the
    shared registry on the same port."""

    def test_metrics_route_serves_registry(self):
        import urllib.request

        from edl_tpu.observability.collector import get_counters
        from edl_tpu.observability.health import serve_health

        get_counters().inc("route_probe")
        srv = serve_health(0, {"ok": lambda: True}, host="127.0.0.1")
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                assert r.status == 200
                assert "version=0.0.4" in r.headers["Content-Type"]
                series = parse_prometheus(r.read().decode())
            assert series["edl_route_probe_total"] >= 1
        finally:
            srv.shutdown()

    def test_metrics_route_private_registry(self):
        import urllib.request

        from edl_tpu.observability.health import serve_health
        from edl_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge("only_here").set(42)
        srv = serve_health(0, {"ok": lambda: True}, host="127.0.0.1",
                           registry=reg)
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
                series = parse_prometheus(r.read().decode())
            assert series == {"edl_only_here": 42.0}
        finally:
            srv.shutdown()


class TestSpanCorrelation:
    """Span IDs, trace propagation, and the cross-process merge."""

    def test_span_ids_and_parenting(self):
        from edl_tpu.observability.tracing import Tracer

        t = Tracer()
        with t.root_span("reform", epoch=3) as root:
            assert root.trace_id and root.span_id
            with t.span("plan", category="reform",
                        parent_id=root.span_id) as child:
                pass
        evs = {e.name: e for e in t.events()}
        assert evs["plan"].parent_id == root.span_id
        assert evs["plan"].trace_id == root.trace_id
        assert evs["reform"].span_id == root.span_id
        assert evs["reform"].parent_id is None

    def test_root_span_env_propagation_and_restore(self):
        import os

        from edl_tpu.observability.tracing import Tracer, current_trace_id

        t = Tracer()
        prev = os.environ.get("EDL_TRACE_ID")
        with t.root_span("resize") as root:
            assert os.environ["EDL_TRACE_ID"] == root.trace_id
            assert current_trace_id() == root.trace_id
        assert os.environ.get("EDL_TRACE_ID") == prev

    def test_merge_files_aligns_and_separates_pids(self, tmp_path):
        import json
        import time as _time

        from edl_tpu.observability.tracing import Tracer

        a, b = Tracer(), Tracer()
        with a.root_span("reform") as root:
            tid = root.trace_id
        b.record_span("world_start.restore", "reform",
                      b.from_wall(_time.time() - 0.2),
                      b.from_wall(_time.time()),
                      trace_id=tid, parent_id=root.span_id)
        pa, pb = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        a.dump(pa, process_name="supervisor")
        b.dump(pb, process_name="child")
        merged = Tracer.merge_files([pa, pb],
                                    str(tmp_path / "merged.json"))
        doc = json.loads((tmp_path / "merged.json").read_text())
        assert doc == merged
        slices = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in slices} == {0, 1}
        assert {e["args"]["trace_id"] for e in slices} == {tid}
        child = next(e for e in slices
                     if e["name"] == "world_start.restore")
        assert child["args"]["parent_id"] == root.span_id
        # wall alignment: both events within a second of each other on
        # the merged axis (they were recorded ~at the same wall time)
        root_ev = next(e for e in slices if e["name"] == "reform")
        assert abs(child["ts"] - root_ev["ts"]) < 5e6

    def test_merge_files_anchorless_file_does_not_skew_base(self, tmp_path):
        """A file without the edl wall anchor (pre-plane dump, foreign
        chrome trace) merges at its raw timestamps; the anchored files
        still align among themselves — not shifted by ~wall-epoch."""
        import json

        from edl_tpu.observability.tracing import Tracer

        t = Tracer()
        t.instant("anchored_event")
        pa = str(tmp_path / "anchored.json")
        t.dump(pa, process_name="anchored")
        pb = str(tmp_path / "legacy.json")
        (tmp_path / "legacy.json").write_text(json.dumps({
            "traceEvents": [{"name": "legacy_event", "cat": "x",
                             "ph": "i", "ts": 123.0, "dur": 0.0,
                             "pid": 0, "tid": 0, "args": {}}]}))
        merged = Tracer.merge_files([pa, pb])
        by_name = {e["name"]: e for e in merged["traceEvents"]
                   if e.get("ph") != "M"}
        # legacy keeps raw ts; anchored file is base → shift ~0, so its
        # ts stays clock-relative (perf_counter µs), nowhere near the
        # wall epoch (~1.7e15 µs) the old min(0.0, anchor) bug produced
        assert by_name["legacy_event"]["ts"] == 123.0
        assert by_name["anchored_event"]["ts"] < 1e14
