"""The fleet-scale scheduler simulation (edl_tpu/scheduler/sim.py):
the goodput objective beats count packing on aggregate goodput through
the REAL planner, priorities buy admission latency, and the gang/min
invariants hold — plus the strict-parser contract of the edl_sched_*
series the CI smoke scrapes."""

import statistics

import pytest

from edl_tpu.scheduler.sim import (
    CURVE_TEMPLATES,
    FleetSim,
    SimConfig,
    compare_objectives,
)

#: the reference test fleet: moderate contention (elastic headroom is
#: where the objectives differ), 4 ICI domains, mixed curve classes,
#: ~15% serving fleets, seeded — both objectives see an identical world
CFG = SimConfig(n_jobs=120, hosts=16, chips_per_host=8, domains=4,
                horizon_s=900.0, arrival_spread_s=700.0, seed=17)


@pytest.fixture(scope="module")
def comparison():
    return compare_objectives(CFG, register=True)


def test_goodput_objective_beats_count_on_aggregate_goodput(comparison):
    assert comparison["sched_goodput_uplift_pct"] > 0, comparison


def test_admission_p99_not_regressed(comparison):
    assert (comparison["sched_admission_p99_s"]
            <= comparison["sched_admission_p99_s_count"] + 1e-9), comparison


def test_gang_and_min_invariants(comparison):
    """No partial or domain-split gang ever exists under EITHER
    objective, and no planned resize took a running world below its
    min_instance."""
    assert comparison["sched_gang_strandings"] == 0
    assert comparison["sched_min_violations"] == 0


def test_priorities_buy_admission_latency():
    """Under HEAVY contention (arrivals outpace capacity), HIGH-priority
    gangs preempt their way in under the goodput objective and are
    admitted faster on average than under count packing, which makes
    them wait in line like everyone else."""
    hot = SimConfig(n_jobs=120, hosts=16, chips_per_host=8, domains=4,
                    horizon_s=900.0, arrival_spread_s=500.0, seed=17)
    waits = {}
    preemptions = {}
    for objective in ("goodput", "count"):
        sim = FleetSim(hot)
        out = sim.run(objective)
        assert out["gang_strandings"] == 0
        assert out["min_violations"] == 0
        preemptions[objective] = out["preemptions"]
        waits[objective] = statistics.mean(
            (j.admitted_at if j.admitted_at is not None
             else hot.horizon_s) - j.arrival_s
            for j in sim.jobs if j.priority == 2
            and j.arrival_s < hot.horizon_s)
    assert preemptions["goodput"] > 0
    assert preemptions["count"] == 0   # count packing never preempts
    assert waits["goodput"] <= waits["count"], waits


def test_sim_drives_the_real_planner():
    """The sim's plans come from planner.plan_cluster — pinned by
    intercepting it (no shadow scheduler can drift from production)."""
    import edl_tpu.scheduler.planner as planner

    calls = []
    orig = planner.plan_cluster
    try:
        def spy(jobs, r, mld=1.0, **kw):
            plan = orig(jobs, r, mld, **kw)
            calls.append(plan.mode)
            return plan

        # sim.py binds the name at import; patch where it looks it up
        import edl_tpu.scheduler.sim as sim_mod

        sim_mod.plan_cluster = spy
        cfg = SimConfig(n_jobs=12, hosts=4, domains=2, horizon_s=120.0,
                        arrival_spread_s=60.0, seed=3)
        FleetSim(cfg).run("goodput")
        assert calls and set(calls) <= {"goodput", "degraded"}
        # the first plans run degraded (nothing measured yet); once
        # jobs have run, measured curves flip the allocator on
        assert "goodput" in calls
    finally:
        sim_mod.plan_cluster = orig


def test_curves_are_sampled_from_recorded_template_shapes():
    sim = FleetSim(CFG)
    templates = {j.template for j in sim.jobs}
    assert templates <= set(CURVE_TEMPLATES)
    # jobs only measure sizes they have run at
    sim.run("goodput")
    for j in sim.jobs:
        for ws in j.measured.world_sizes():
            assert j.lo <= ws or ws <= j.hi


def test_identical_fleet_across_objectives():
    """Both runs see a bit-identical workload (same seed ⇒ same
    arrivals, curves, priorities) — the comparison is apples-to-apples."""
    a, b = FleetSim(CFG), FleetSim(CFG)
    assert [(j.name, j.arrival_s, j.priority, j.chips, j.lo, j.hi,
             j.template, j.work, j.demand) for j in a.jobs] == \
           [(j.name, j.arrival_s, j.priority, j.chips, j.lo, j.hi,
             j.template, j.work, j.demand) for j in b.jobs]


def test_sched_metrics_strict_exposition(comparison):
    """The edl_sched_* series render strict-parser-green on the shared
    registry (what scripts/ci.sh's sched smoke asserts over HTTP)."""
    from edl_tpu.observability.metrics import get_registry, parse_exposition

    series = parse_exposition(get_registry().render())
    assert series["edl_sched_goodput_uplift_pct"] == pytest.approx(
        comparison["sched_goodput_uplift_pct"])
    assert series['edl_sched_admission_p99_s{objective="goodput"}'] == \
        pytest.approx(comparison["sched_admission_p99_s"])
    assert series["edl_sched_gang_strandings"] == 0.0
    if comparison["sched_preemptions"]:
        assert series["edl_sched_preemptions_total"] >= \
            comparison["sched_preemptions"]
