"""Paged KV-cache pool (doc/serving.md §autoregressive serving): block
allocation, bounded admission (typed 429, never OOM), fragmentation-free
reuse under churn, abandon/timeout frees, export/import migration, and
the occupancy gauges the scrape plane reads."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from edl_tpu.models import llama
from edl_tpu.models.transformer import TINY
from edl_tpu.observability.metrics import MetricsRegistry
from edl_tpu.runtime.kvcache import (
    KVBlockPool,
    KVPoolExhausted,
    SessionUnknown,
)


def make_pool(num_blocks=8, block_size=4, cap=4, **kw):
    kw.setdefault("registry", MetricsRegistry())
    return KVBlockPool(TINY, num_blocks, block_size, cap, job="t/kv", **kw)


class TestAllocation:
    def test_lazy_growth_by_block(self):
        pool = make_pool()
        assert pool.ensure_capacity(1, 3) == pool.session_blocks(1)
        assert len(pool.session_blocks(1)) == 1  # 3 tokens, bs=4
        pool.ensure_capacity(1, 5)
        assert len(pool.session_blocks(1)) == 2
        # idempotent: capacity already covered allocates nothing
        pool.ensure_capacity(1, 5)
        assert len(pool.session_blocks(1)) == 2
        assert pool.blocks_used() == 2

    def test_exhaustion_is_typed_never_oom(self):
        pool = make_pool(num_blocks=4, cap=8)
        pool.ensure_capacity(1, 16)  # all 4 blocks
        with pytest.raises(KVPoolExhausted):
            pool.ensure_capacity(2, 1)
        # bounded admission: the failed session holds nothing
        with pytest.raises(SessionUnknown):
            pool.session_blocks(2)
        assert pool.blocks_free() == 0

    def test_failed_growth_keeps_existing_blocks(self):
        pool = make_pool(num_blocks=3, cap=8)
        pool.ensure_capacity(1, 8)   # 2 blocks
        pool.ensure_capacity(2, 4)   # last block
        with pytest.raises(KVPoolExhausted):
            pool.ensure_capacity(1, 16)  # wants 2 more, none free
        # the session's prior allocation survives the failed growth
        assert len(pool.session_blocks(1)) == 2

    def test_per_session_cap(self):
        pool = make_pool(num_blocks=8, cap=2)
        with pytest.raises(KVPoolExhausted):
            pool.ensure_capacity(1, 100)
        assert pool.blocks_used() == 0

    def test_can_admit_probe(self):
        pool = make_pool(num_blocks=4, cap=4)
        assert pool.can_admit(16)
        assert not pool.can_admit(17)  # needs 5 blocks > pool
        pool.ensure_capacity(1, 12)
        assert pool.can_admit(4)
        assert not pool.can_admit(8)


class TestChurn:
    def test_fragmentation_free_reuse(self):
        """Blocks freed by interleaved session churn serve any later
        session — a block list need not be contiguous, so external
        fragmentation cannot exist."""
        pool = make_pool(num_blocks=8, cap=8)
        for sid in range(4):
            pool.ensure_capacity(sid, 8)  # 2 blocks each → full
        assert pool.blocks_free() == 0
        # free the even sessions: holes at non-adjacent positions
        pool.free_session(0)
        pool.free_session(2)
        got = pool.ensure_capacity(9, 16)  # 4 blocks spanning the holes
        assert len(got) == 4
        assert pool.blocks_free() == 0
        # churn loop: repeated alloc/free never degrades capacity
        for i in range(20):
            pool.free_session(9 if i == 0 else 100 + i - 1)
            pool.ensure_capacity(100 + i, 16)
        assert pool.blocks_used() == 8

    def test_abandon_frees_idempotently(self):
        pool = make_pool()
        pool.ensure_capacity(7, 10)
        n = pool.free_session(7)
        assert n == 3 and pool.blocks_used() == 0
        assert pool.free_session(7) == 0  # double-free is a no-op
        assert pool.free_session(999) == 0  # unknown sid is a no-op

    def test_block_table_sentinel_padding(self):
        pool = make_pool(num_blocks=8, block_size=4, cap=4)
        pool.ensure_capacity(3, 6)  # 2 blocks
        table = pool.block_table(3)
        assert table.shape == (4,)
        assert list(table[:2]) == pool.session_blocks(3)
        # padding rows carry the out-of-range drop sentinel
        assert all(t == 8 for t in table[2:])
        with pytest.raises(SessionUnknown):
            pool.block_table(4)


class TestMigration:
    def test_export_import_roundtrip_bitwise(self):
        src = make_pool(num_blocks=8, block_size=4, cap=4)
        dst = make_pool(num_blocks=8, block_size=4, cap=4)
        params = llama.init(jax.random.PRNGKey(0), TINY)
        toks = np.asarray([3, 5, 7, 11, 13, 17], np.int32)
        blocks = src.ensure_capacity(1, len(toks))
        logits, cache = llama.prefill(
            params, src.cache, jax.numpy.asarray(toks),
            jax.numpy.asarray(src.block_table(1)),
            jax.numpy.asarray(0, "int32"),
            jax.numpy.asarray(len(toks), "int32"), TINY)
        src.set_cache(cache)
        host = src.export_session(1, len(toks))
        assert host["k"].shape[1] == len(toks)
        # occupy dst block 0 first so the import lands non-contiguously
        dst.ensure_capacity(99, 2)
        dst.import_session(1, host)
        back = dst.export_session(1, len(toks))
        np.testing.assert_array_equal(host["k"], back["k"])
        np.testing.assert_array_equal(host["v"], back["v"])
        assert src.blocks_used() == len(blocks)  # source kept until freed
        src.free_session(1)

    def test_import_into_full_pool_is_retriable(self):
        src = make_pool(num_blocks=4, block_size=4, cap=4)
        dst = make_pool(num_blocks=3, block_size=4, cap=4)
        src.ensure_capacity(1, 12)
        host = src.export_session(1, 12)
        dst.ensure_capacity(50, 8)  # fill destination
        with pytest.raises(KVPoolExhausted):
            dst.import_session(1, host)
        # nothing leaked at the destination; host copy intact → retry
        assert 1 not in dst.sessions()
        dst.free_session(50)
        assert len(dst.import_session(1, host)) == 3

    def test_import_duplicate_refused(self):
        src = make_pool()
        src.ensure_capacity(1, 4)
        host = src.export_session(1, 4)
        dst = make_pool()
        dst.import_session(1, host)
        with pytest.raises(ValueError):
            dst.import_session(1, host)

    def test_import_duplicate_race_atomic(self):
        """REVIEW regression: the residency guard and the allocation
        run under ONE lock hold — racing imports of the same sid admit
        exactly one winner and leak no blocks (the old split check let
        every racer pass the guard and share an allocation)."""
        import threading

        src = make_pool(num_blocks=8, block_size=4, cap=4)
        src.ensure_capacity(1, 8)
        host = src.export_session(1, 8)
        dst = make_pool(num_blocks=8, block_size=4, cap=4)
        results: list = []
        barrier = threading.Barrier(4)

        def race():
            barrier.wait()
            try:
                dst.import_session(1, host)
                results.append("ok")
            except ValueError:
                results.append("dup")

        threads = [threading.Thread(target=race) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == ["dup", "dup", "dup", "ok"]
        assert dst.blocks_used() == 2  # exactly one session's blocks

    def test_evacuate_exports_everything(self):
        pool = make_pool(num_blocks=8, cap=4)
        pool.ensure_capacity(1, 4)
        pool.ensure_capacity(2, 8)
        out = pool.evacuate({1: 4, 2: 8})
        assert set(out) == {1, 2}
        assert out[2]["k"].shape[1] == 8
        # evacuation is non-destructive until the caller frees
        assert pool.blocks_used() == 3


class TestAccounting:
    def test_bytes_accounting_matches_cache(self):
        pool = make_pool(num_blocks=8, block_size=4)
        expect = llama.cache_bytes(TINY, 8, 4)
        assert pool.total_bytes() == expect
        assert pool.bytes_per_block * 8 == expect
        pool.ensure_capacity(1, 8)
        assert pool.used_bytes() == 2 * pool.bytes_per_block

    def test_gauges_registered_and_live(self):
        reg = MetricsRegistry()
        pool = KVBlockPool(TINY, 8, 4, 4, job="t/kv", replica="r0",
                           registry=reg)
        pool.ensure_capacity(1, 10)
        text = reg.render()
        assert 'edl_serving_kv_blocks_used{job="t/kv",replica="r0"} 3' \
            in text
        assert 'edl_serving_kv_blocks_total{job="t/kv",replica="r0"} 8' \
            in text

    def test_reserved_bytes_tighten_replan_filter(self):
        """The pool's residency must shrink what the resize planner
        thinks fits — a plan blessed while ignoring KV bytes OOMs on
        first decode."""
        from edl_tpu.parallel.replan import propose_shape

        # 100B state, 100B/device budget: pure dp fits with no
        # reservation; reserving pool bytes forces state into fsdp
        loose = propose_shape(8, state_bytes=100,
                              max_bytes_per_device=100)
        assert loose.fsdp == 1 and loose.dp == 8
        tight = propose_shape(8, state_bytes=100,
                              max_bytes_per_device=100,
                              reserved_bytes_per_device=60)
        assert tight.fsdp >= 3  # ceil(100/fsdp) + 60 <= 100 → fsdp >= 3
        exact = propose_shape(8, state_bytes=100,
                              max_bytes_per_device=100,
                              reserved_bytes_per_device=75)
        assert exact.fsdp == 4
