"""Fleet scrape plane tests (edl_tpu/observability/scrape.py).

Covers the ring store + windowed queries, sweep behavior (jittered
intervals are exercised via the loop; backoff/staleness via a fake
clock), dynamic target discovery (coordinator KV, address files,
jobparser manifest annotations), the end-to-end scrape against BOTH
coordinator backends plus a black-holed target, the FleetView rollup
feeding ServingScaler the same decisions the hook-fed policy tests pin,
the AlertEngine rules, and the shared flight-record dump lock / cooldown
dedupe regression.
"""

import json
import os
import socket
import threading
import time

import pytest

from edl_tpu.observability.metrics import MetricsRegistry
from edl_tpu.observability.scrape import (
    SERVING_METRICS_ADDR_PREFIX, AddrPublisher, Alert, AlertEngine,
    AlertRule, BurnRateRule, ConservationRule, FleetView,
    GoodputCollapseRule, MetricsScraper, ScrapeTarget, TargetDownRule,
    file_targets, format_addr_value, kv_targets, manifest_targets,
    parse_addr_value, publish_serving_metrics_addr,
    render_fleet_dashboard, static_targets,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_scraper(fetch_map, clock=None, **kw):
    """Scraper over injected fetchers: fetch_map maps target name →
    callable returning exposition text (or raising)."""
    clock = clock or FakeClock()
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("registry", MetricsRegistry())

    def fetch(target):
        return fetch_map[target.name]()

    s = MetricsScraper(fetch=fetch, clock=clock, **kw)
    for name in fetch_map:
        s.add_target(ScrapeTarget(name=name, addr=f"{name}:0"))
    return s, clock


# ----------------------------------------------------- ring store + queries


class TestQueries:
    def test_latest_delta_rate_and_counter_reset(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs")
        c.inc(10, job="a")
        s, clock = make_scraper({"t1": reg.render})
        s.sweep()
        clock.advance(1.0)
        c.inc(20, job="a")
        s.sweep()
        assert s.latest("edl_reqs_total", {"job": "a"}) == 30
        assert s.delta("edl_reqs_total", 10.0, {"job": "a"}) == 20
        assert abs(s.rate("edl_reqs_total", 10.0) - 20.0) < 1e-6
        # counter reset (process restart): the post-reset value counts
        # from zero instead of producing a negative increase
        clock.advance(1.0)
        c.clear()
        c.inc(5, job="a")
        s.sweep()
        assert s.delta("edl_reqs_total", 10.0) == 25  # 20 + 5
        # label filter that matches nothing
        assert s.latest("edl_reqs_total", {"job": "zzz"}) is None

    def test_sum_by_and_label_values(self):
        reg = MetricsRegistry()
        g = reg.gauge("replicas")
        g.set(2, job="a")
        g.set(3, job="b")
        s, clock = make_scraper({"t1": reg.render})
        s.sweep()
        assert s.sum_by("edl_replicas", "job") == {"a": 2.0, "b": 3.0}
        assert s.label_values("edl_replicas", "job") == ["a", "b"]

    def test_latest_aggregations_across_targets(self):
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.gauge("frac").set(0.9, job="a")
        r2.gauge("frac").set(0.5, job="a")
        s, _ = make_scraper({"t1": r1.render, "t2": r2.render})
        s.sweep()
        assert s.latest("edl_frac", agg="min") == 0.5
        assert s.latest("edl_frac", agg="max") == 0.9
        assert abs(s.latest("edl_frac", agg="avg") - 0.7) < 1e-9
        assert abs(s.latest("edl_frac") - 1.4) < 1e-9  # sum default

    def test_histogram_quantile_windowed_interpolation(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.05, 0.1))
        s, clock = make_scraper({"t1": reg.render})
        s.sweep()
        clock.advance(1.0)
        for _ in range(90):
            h.observe(0.005)   # le 0.01
        for _ in range(10):
            h.observe(0.08)    # le 0.1
        s.sweep()
        p50 = s.histogram_quantile("edl_lat_seconds", 0.50, 10.0)
        p99 = s.histogram_quantile("edl_lat_seconds", 0.99, 10.0)
        assert p50 is not None and p50 <= 0.01
        assert 0.05 < p99 <= 0.1  # interpolated inside the last bucket
        # a window with no observations: None, not zero
        clock.advance(100.0)
        s.sweep()
        assert s.histogram_quantile("edl_lat_seconds", 0.99, 1.0) is None

    def test_ring_bounded_retention(self):
        reg = MetricsRegistry()
        g = reg.gauge("v")
        s, clock = make_scraper({"t1": reg.render}, retention=8)
        for i in range(50):
            g.set(i)
            s.sweep()
            clock.advance(1.0)
        assert s.series_count() >= 1
        fam = s._series["edl_v"]
        ring = next(iter(fam.values()))
        assert len(ring.samples) == 8  # bounded, oldest evicted


# ------------------------------------------------ sweep / backoff / staleness


class TestSweepBehavior:
    def test_failure_backoff_grows_and_is_bounded(self):
        calls = {"n": 0}

        def boom():
            calls["n"] += 1
            raise OSError("connection refused")

        s, clock = make_scraper({"dead": boom}, interval_s=1.0,
                                backoff_base_s=1.0, backoff_max_s=4.0)
        s.sweep()
        assert calls["n"] == 1
        st = s._state[("dead:0", "/metrics")]
        assert st.consecutive_failures == 1
        # not due until the backoff lapses
        s.sweep()
        assert calls["n"] == 1
        clock.advance(1.1)
        s.sweep()
        assert calls["n"] == 2 and st.consecutive_failures == 2
        # exponential: 2s now; then clamped at backoff_max_s forever
        clock.advance(1.1)
        s.sweep()
        assert calls["n"] == 2
        for _ in range(5):
            clock.advance(4.1)
            s.sweep()
        assert st.consecutive_failures >= 5
        assert st.next_due_t - clock() <= 4.0 + 1e-9  # bounded

    def test_staleness_marked_and_healthy_targets_unaffected(self):
        reg = MetricsRegistry()
        reg.gauge("ok").set(1)
        flaky = {"fail": False}

        def maybe():
            if flaky["fail"]:
                raise OSError("down")
            return reg.render()

        s, clock = make_scraper({"good": reg.render, "flaky": maybe},
                                interval_s=1.0, stale_after_s=3.0)
        s.sweep()
        states = {t["name"]: t for t in s.target_states()}
        assert states["good"]["state"] == "up"
        assert states["flaky"]["state"] == "up"
        flaky["fail"] = True
        for _ in range(6):
            clock.advance(1.0)
            s.sweep()
        states = {t["name"]: t for t in s.target_states()}
        # the dead target is marked, the healthy one kept its cadence
        assert states["flaky"]["state"] == "down"
        assert states["flaky"]["consecutive_failures"] >= 1
        assert states["flaky"]["staleness_s"] > 3.0
        assert states["good"]["state"] == "up"
        assert states["good"]["staleness_s"] <= 1.0

    def test_removed_target_rings_pruned_and_stale_gauges_excluded(self):
        """A dead/removed target's final gauge samples must not be
        summed into latest() rollups forever: a drained pod's frozen
        queue-depth would otherwise block shrink decisions for good."""
        r1, r2 = MetricsRegistry(), MetricsRegistry()
        r1.gauge("serving_fleet_queue_depth").set(9, job="j")
        r2.gauge("serving_fleet_queue_depth").set(0, job="j")
        s, clock = make_scraper({"dead": r1.render, "live": r2.render},
                                stale_after_s=3.0)
        s.sweep()
        assert s.latest("edl_serving_fleet_queue_depth",
                        {"job": "j"}) == 9
        # the dead pod stops answering: its last sample ages past the
        # staleness horizon and drops out of latest() (the live target
        # keeps being re-scraped)
        del s._fetch  # not used below; guard against accidental scrape
        s._fetch = lambda t: (_ for _ in ()).throw(OSError("down")) \
            if t.name == "dead" else r2.render()
        for _ in range(5):
            clock.advance(1.0)
            s.sweep()
        assert s.latest("edl_serving_fleet_queue_depth",
                        {"job": "j"}) == 0
        # explicit last-known-value opt-out still sees it
        assert s.latest("edl_serving_fleet_queue_depth", {"job": "j"},
                        max_age_s=float("inf")) == 9
        # removing the target prunes its rings entirely (no unbounded
        # ring growth under target churn)
        before = s.series_count()
        s.remove_target(ScrapeTarget(name="dead", addr="dead:0"))
        assert s.series_count() < before
        assert s.latest("edl_serving_fleet_queue_depth", {"job": "j"},
                        max_age_s=float("inf")) == 0

    def test_raising_discovery_source_freezes_not_forgets_targets(self):
        """A transient coordinator outage (discovery source raising)
        must FREEZE the discovered target set, not age it out — the
        fleet going undiscoverable is exactly when its down-alerts must
        keep standing."""
        reg = MetricsRegistry()
        reg.gauge("x").set(1)
        broken = {"on": False}

        def discover():
            if broken["on"]:
                raise OSError("coordinator unreachable")
            return [ScrapeTarget(name="d1", addr="d1:0")]

        s = MetricsScraper(discover=[discover],
                           fetch=lambda t: reg.render(),
                           clock=FakeClock(), registry=MetricsRegistry(),
                           forget_after_sweeps=2)
        s.sweep()
        assert [t.name for t in s.targets()] == ["d1"]
        broken["on"] = True
        for _ in range(5):  # well past forget_after_sweeps
            s.sweep()
        assert [t.name for t in s.targets()] == ["d1"]  # frozen, kept
        broken["on"] = False
        s.sweep()
        assert [t.name for t in s.targets()] == ["d1"]

    def test_discovered_target_dropped_after_source_forgets_it(self):
        reg = MetricsRegistry()
        present = {"on": True}

        def discover():
            if present["on"]:
                return [ScrapeTarget(name="d1", addr="d1:0")]
            return []

        s = MetricsScraper(discover=[discover], fetch=lambda t: reg.render(),
                           clock=FakeClock(), registry=MetricsRegistry(),
                           forget_after_sweeps=2)
        s.sweep()
        assert [t.name for t in s.targets()] == ["d1"]
        present["on"] = False
        s.sweep()
        assert s.targets()  # one miss: kept
        s.sweep()
        assert s.targets() == []  # forgotten

    def test_self_metrics_rendered_strict(self):
        from edl_tpu.observability.metrics import parse_exposition

        reg = MetricsRegistry()
        src = MetricsRegistry()
        src.gauge("x").set(1)
        s = MetricsScraper(fetch=lambda t: src.render(), registry=reg,
                           clock=FakeClock())
        s.add_target(ScrapeTarget(name="t", addr="t:0"))
        s.sweep()
        series = parse_exposition(reg.render())
        assert series['edl_scrape_targets{state="up"}'] == 1
        assert series["edl_scrape_sweep_seconds_count"] >= 1
        assert series["edl_scrape_series"] >= 1

    def test_jittered_loop_runs_and_stops(self):
        reg = MetricsRegistry()
        reg.gauge("x").set(1)
        s = MetricsScraper(fetch=lambda t: reg.render(),
                           registry=MetricsRegistry(),
                           interval_s=0.02, jitter_frac=0.5)
        s.add_target(ScrapeTarget(name="t", addr="t:0"))
        s.start()
        deadline = time.monotonic() + 5.0
        while s.sweeps < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        s.stop()
        assert s.sweeps >= 3
        assert not s.is_alive()


# ------------------------------------------------------------- discovery


class TestDiscovery:
    def test_kv_targets_supervisor_and_serving_with_ttl(self):
        from edl_tpu.coord import PyCoordService

        kv = PyCoordService()
        kv.kv_set("metrics-addr-w0", b"127.0.0.1:9100")
        publish_serving_metrics_addr(kv, "ns/svc", "r0",
                                     "127.0.0.1:9200", ttl_s=60.0)
        # an EXPIRED serving key is skipped — the TTL semantics plain KV
        # lacks, honored scraper-side
        kv.kv_set(SERVING_METRICS_ADDR_PREFIX + "ns/svc/r1",
                  format_addr_value("127.0.0.1:9300", -5.0))
        found = {t.name: t for t in kv_targets(kv)()}
        assert found["supervisor/w0"].addr == "127.0.0.1:9100"
        assert found["supervisor/w0"].labels["role"] == "supervisor"
        svc = found["serving/ns/svc/r0"]
        assert svc.addr == "127.0.0.1:9200"
        assert svc.labels == {"role": "serving", "job": "ns/svc",
                              "replica": "r0"}
        assert "serving/ns/svc/r1" not in found

    def test_addr_value_roundtrip(self):
        addr, expired = parse_addr_value(
            format_addr_value("h:1", ttl_s=30.0))
        assert addr == "h:1" and not expired
        addr, expired = parse_addr_value(format_addr_value("h:1", None))
        assert addr == "h:1" and not expired
        assert parse_addr_value(b"garbage")[0] is None

    def test_addr_publisher_refreshes_and_deletes_on_stop(self):
        from edl_tpu.coord import PyCoordService

        kv = PyCoordService()
        pub = AddrPublisher(kv, "serving-metrics-addr/j/r", "127.0.0.1:1",
                            ttl_s=3.0)
        pub.start()
        deadline = time.monotonic() + 5.0
        first = None
        while time.monotonic() < deadline:
            v = kv.kv_get("serving-metrics-addr/j/r")
            if v is not None:
                first = v
                break
            time.sleep(0.01)
        assert first is not None
        # refresh: the expiry stamp moves forward
        _, exp0 = first.decode().split()
        while time.monotonic() < deadline:
            v = kv.kv_get("serving-metrics-addr/j/r")
            if v is not None and v.decode().split()[1] != exp0:
                break
            time.sleep(0.05)
        assert v.decode().split()[1] != exp0, "expiry never refreshed"
        pub.stop()
        assert kv.kv_get("serving-metrics-addr/j/r") is None

    def test_serving_metrics_addr_swept_on_job_deletion(self):
        """The satellite contract: serving-metrics-addr/ rides
        JOB_KV_PREFIXES, so a deleted job's published addresses leave
        KV with its curve/cursors/generation."""
        from edl_tpu.coord import PyCoordService
        from edl_tpu.coord.gc import JOB_KV_PREFIXES, gc_job_kv

        assert "serving-metrics-addr/" in JOB_KV_PREFIXES
        kv = PyCoordService()
        publish_serving_metrics_addr(kv, "ns/doomed", "r0", "h:1")
        publish_serving_metrics_addr(kv, "ns/doomed2", "r0", "h:2")
        removed = gc_job_kv(kv, "ns/doomed")
        assert removed == 1
        assert kv.kv_get("serving-metrics-addr/ns/doomed/r0") is None
        # the name-prefix sibling survives (exact-uid scoping)
        assert kv.kv_get("serving-metrics-addr/ns/doomed2/r0") is not None

    def test_file_targets(self, tmp_path):
        (tmp_path / "metrics-addr-w3").write_text("127.0.0.1:9999")
        (tmp_path / "unrelated").write_text("x")
        found = file_targets(str(tmp_path))()
        assert len(found) == 1
        assert found[0].name == "supervisor/w3"
        assert found[0].addr == "127.0.0.1:9999"

    def test_manifest_targets_from_jobparser_annotations(self):
        """The controller/collector/coordinator manifests the jobparser
        emits carry prometheus.io annotations — the scrape plane reads
        the SAME manifests for its target list."""
        from edl_tpu.api.types import (
            ResourceRequirements, TrainerSpec, TrainingJob,
            TrainingJobSpec,
        )
        from edl_tpu.controller.jobparser import parse_to_coordinator

        job = TrainingJob(
            name="j1", namespace="ns",
            spec=TrainingJobSpec(
                fault_tolerant=True,
                trainer=TrainerSpec(min_instance=1, max_instance=2,
                                    resources=ResourceRequirements())))
        m = parse_to_coordinator(job)
        # a callable like its sibling sources (usable as discover=[...])
        targets = manifest_targets([m, {"kind": "Service"}],
                                   host="10.0.0.7")()
        assert len(targets) == 1
        t = targets[0]
        assert t.name == "ns/j1-coordinator"
        assert t.addr.startswith("10.0.0.7:")
        assert t.path == "/metrics"

    def test_static_targets(self):
        ts = static_targets(["a:1", "b:2"], role="x")
        assert [(t.name, t.addr) for t in ts] == [("a:1", "a:1"),
                                                  ("b:2", "b:2")]
        assert ts[0].labels == {"role": "x"}


# ---------------------------------------- end-to-end: both backends + wedged


def _blackhole_server():
    """A socket that accepts connections and never answers — the
    wedged/black-holed target."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    stop = threading.Event()
    conns = []

    def run():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                c, _ = srv.accept()
                conns.append(c)  # hold open, say nothing
            except socket.timeout:
                continue
            except OSError:
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()

    def close():
        stop.set()
        t.join(timeout=2)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        srv.close()

    return srv.getsockname()[1], close


class TestEndToEndBackends:
    def test_scrape_both_coordinator_backends_and_blackholed_target(self):
        """Satellite: one sweep over a NATIVE coordinator's /metrics, a
        PyCoordService-backed /metrics route, and a black-holed target —
        coord series land for both backends, the wedge is marked
        failing/stale with bounded backoff, and the healthy targets'
        scrape cadence is unaffected in the same sweep."""
        from edl_tpu.coord import PyCoordService, native_available
        from edl_tpu.coord.server import spawn_server
        from edl_tpu.observability.health import serve_health

        if not native_available():
            pytest.skip("native coord core unavailable")
        h = spawn_server(health_port=0)
        py_reg = MetricsRegistry()
        svc = PyCoordService()
        svc.join("a")
        svc.register_metrics(py_reg)
        py_srv = serve_health(0, {"ok": lambda: True}, host="127.0.0.1",
                              registry=py_reg)
        bh_port, bh_close = _blackhole_server()
        scraper = MetricsScraper(
            interval_s=0.2, timeout_s=0.5, backoff_base_s=0.2,
            backoff_max_s=1.0, registry=MetricsRegistry())
        scraper.add_target(ScrapeTarget(
            name="coord/native", addr=f"127.0.0.1:{h.health_port}",
            labels={"role": "coordinator"}))
        scraper.add_target(ScrapeTarget(
            name="coord/python",
            addr=f"127.0.0.1:{py_srv.server_address[1]}",
            labels={"role": "coordinator"}))
        scraper.add_target(ScrapeTarget(
            name="wedged", addr=f"127.0.0.1:{bh_port}"))
        try:
            c = h.client()
            c.join("w0", "a")
            t0 = time.monotonic()
            report = scraper.sweep()
            sweep_s = time.monotonic() - t0
            # the black hole cost ONE timeout, not one per healthy target
            assert report["failed"] == 1, report
            assert report["scraped"] == 2, report
            assert sweep_s < 3.0, sweep_s
            # both backends' coord series landed, name-for-name
            assert scraper.latest("edl_coord_members",
                                  agg="max") is not None
            by_target = {t["name"]: t for t in scraper.target_states()}
            assert by_target["coord/native"]["state"] == "up"
            assert by_target["coord/python"]["state"] == "up"
            wedged = by_target["wedged"]
            assert wedged["consecutive_failures"] == 1
            assert wedged["state"] in ("stale", "down")
            # bounded backoff across repeated failures
            for _ in range(4):
                time.sleep(0.25)
                scraper.sweep()
            st = scraper._state[(f"127.0.0.1:{bh_port}", "/metrics")]
            assert st.next_due_t - time.monotonic() <= 1.0 + 0.5
            # healthy targets kept being scraped while the wedge backed off
            assert by_target["coord/native"]["scrapes"] >= 1
            fresh = {t["name"]: t for t in scraper.target_states()}
            assert fresh["coord/native"]["scrapes"] > 1
            c.close()
        finally:
            bh_close()
            py_srv.shutdown()
            h.stop()


# ---------------------------------------------- FleetView + scrape-fed scaler


def _serving_registry(job="ns/svc"):
    """A registry shaped like a serving replica's /metrics."""
    reg = MetricsRegistry()
    from edl_tpu.observability.metrics import SERVING_LATENCY_BUCKETS

    reqs = reg.counter("serving_requests")
    viol = reg.counter("serving_slo_violations")
    hist = reg.histogram("serving_request_seconds",
                         buckets=SERVING_LATENCY_BUCKETS)
    reg.gauge("serving_fleet_queue_depth").set(0, job=job)
    reg.gauge("serving_replicas_active").set(1, job=job)
    reg.gauge("serving_replicas_ready").set(1, job=job)
    return reg, reqs, viol, hist


class TestFleetView:
    JOB = "ns/svc"

    def _view(self, reg, clock):
        s = MetricsScraper(fetch=lambda t: reg.render(), clock=clock,
                           registry=MetricsRegistry())
        s.add_target(ScrapeTarget(name="r0", addr="r0:0",
                                  labels={"job": self.JOB}))
        return s, FleetView(s, window_s=10.0)

    def test_serving_stats_rollup(self):
        reg, reqs, viol, hist = _serving_registry(self.JOB)
        clock = FakeClock()
        s, view = self._view(reg, clock)
        s.sweep()
        clock.advance(2.0)
        for _ in range(100):
            reqs.inc(job=self.JOB)
            hist.observe(0.004, job=self.JOB)
        reg.gauge("serving_fleet_queue_depth").set(7, job=self.JOB)
        s.sweep()
        st = view.stats_for(self.JOB)
        assert st.requests_windowed == 100
        assert abs(st.qps - 50.0) < 1.0  # 100 over the 2 s span
        assert 2.5 <= st.p99_ms <= 5.0   # bucket-resolution estimate
        assert st.queue_depth == 7
        assert st.replicas_active == 1 and st.replicas_ready == 1
        assert view.jobs() == [self.JOB]

    def test_scrape_fed_scaler_matches_hook_fed_policy(self):
        """The acceptance parity: the SAME decisions the hook-fed policy
        tests pin (tests/test_serving.py::test_policy_*), produced from
        scraped metrics through FleetView.stats_for."""
        from edl_tpu.api.types import ServingJob, ServingSpec
        from edl_tpu.scheduler.autoscaler import ServingScaler

        # p99 breach at 2 active replicas → grow to 3 (the pinned case:
        # decide(_stats(p99=80, active=2), 2) == 3 with slo=50)
        reg, reqs, viol, hist = _serving_registry(self.JOB)
        reg.gauge("serving_replicas_active").set(2, job=self.JOB)
        reg.gauge("serving_replicas_ready").set(2, job=self.JOB)
        clock = FakeClock()
        s, view = self._view(reg, clock)
        s.sweep()
        clock.advance(1.0)
        for _ in range(50):
            reqs.inc(job=self.JOB)
            hist.observe(0.08, job=self.JOB)  # ~80 ms — over the SLO
        s.sweep()
        sc = ServingScaler().feed_from(view)
        job = ServingJob(name="svc", namespace="ns", spec=ServingSpec(
            min_replicas=1, max_replicas=8, slo_p99_ms=50.0))
        stats = sc.stats_for(self.JOB)
        assert stats.p99_ms > 50.0
        assert sc.decide(job, stats, 2) == 3

        # qps above the per-replica target → ceil(qps/target) (pinned:
        # decide(_stats(qps=100, active=2), 2) == 4 with target 30)
        reg2, reqs2, _, hist2 = _serving_registry(self.JOB)
        reg2.gauge("serving_replicas_active").set(2, job=self.JOB)
        clock2 = FakeClock()
        s2, view2 = self._view(reg2, clock2)
        s2.sweep()
        clock2.advance(2.0)
        for _ in range(200):  # 200 req over 2 s → 100 qps
            reqs2.inc(job=self.JOB)
            hist2.observe(0.001, job=self.JOB)
        s2.sweep()
        job_qps = ServingJob(name="svc", namespace="ns", spec=ServingSpec(
            min_replicas=1, max_replicas=8, slo_p99_ms=0.0,
            target_qps_per_replica=30.0))
        sc2 = ServingScaler().feed_from(view2)
        st2 = sc2.stats_for(self.JOB)
        assert abs(st2.qps - 100.0) < 5.0
        assert sc2.decide(job_qps, st2, 2) == 4

        # inside the SLO with a queue: hold (pinned: decide(None))
        assert sc.decide(job, type(stats)(
            p50_ms=10, p99_ms=30, qps=10, queue_depth=1,
            replicas_ready=2, replicas_active=2,
            requests_windowed=20), 2) is None

    def test_live_fleet_scrape_parity_with_fleetstats(self):
        """End-to-end over a REAL in-process fleet: serve /metrics, run
        traffic, scrape it, and hold FleetView's qps/p99 against the
        fleet's own FleetStats within tolerance (p99 is bucket-resolution
        — assert the same order, not equality)."""
        jax = pytest.importorskip("jax")
        import numpy as np

        from edl_tpu.models import mlp
        from edl_tpu.runtime.serving import PoissonTraffic, ServingFleet

        job = "t/scrape-parity"
        params = mlp.init(jax.random.key(0), [8, 16, 4])
        fleet = ServingFleet(
            lambda p, b: mlp.apply(p, b[0]), params,
            example_row=(np.zeros((8,), np.float32),), job=job,
            max_batch_size=4, max_queue_ms=1.0, slo_p99_ms=500.0)
        srv = None
        try:
            fleet.scale_to(1)
            srv = fleet.serve_metrics(0, host="127.0.0.1", publish=False)
            port = srv.server_address[1]
            scraper = MetricsScraper(interval_s=0.1, timeout_s=2.0,
                                     registry=MetricsRegistry())
            scraper.add_target(ScrapeTarget(
                name="replica", addr=f"127.0.0.1:{port}",
                labels={"job": job}))
            view = FleetView(scraper, window_s=2.5)
            traffic = PoissonTraffic(
                fleet, lambda i: (np.full((8,), i % 5, np.float32),),
                qps=120, seed=7)
            # sweep continuously WHILE traffic flows, then measure both
            # sides over the same window at the same instant — the
            # apples-to-apples moment
            halt = threading.Event()

            def sweeper():
                while not halt.wait(0.25):
                    scraper.sweep()

            sw = threading.Thread(target=sweeper, daemon=True)
            scraper.sweep()
            sw.start()
            traffic.run(3.0)
            scraper.sweep()
            st = view.stats_for(job)
            own = fleet.stats(window_s=2.5)
            halt.set()
            sw.join(timeout=5)
            tally = traffic.await_all(timeout_s=30.0)
            assert tally["dropped"] == 0 and tally["errors"] == 0
            assert st.requests_windowed > 0
            assert st.replicas_active == 1
            # qps parity: both sides within 40% of each other (open-loop
            # jitter + window edges), and both in the offered-load range
            assert own.qps > 0
            assert 0.6 * own.qps <= st.qps <= 1.4 * own.qps, (st, own)
            assert 60.0 <= st.qps <= 200.0, st
            # p99 parity within bucket resolution: same order of magnitude
            assert st.p99_ms > 0
            assert st.p99_ms <= max(own.p99_ms * 4.0, 5.0), (st, own)
            assert own.p99_ms <= max(st.p99_ms * 4.0, 5.0), (st, own)
        finally:
            fleet.stop()

    def test_dashboard_renders(self):
        reg, reqs, viol, hist = _serving_registry(self.JOB)
        reg.gauge("goodput_fraction").set(0.87, job="t/train")
        clock = FakeClock()
        s, view = self._view(reg, clock)
        s.sweep()
        clock.advance(1.0)
        reqs.inc(10, job=self.JOB)
        s.sweep()
        engine = AlertEngine(view, rules=[TargetDownRule()])
        engine.evaluate()
        out = render_fleet_dashboard(view, engine)
        assert "FLEET" in out and self.JOB in out
        assert "TARGETS" in out and "r0" in out
        assert "ALERTS" in out
        assert "t/train" in out  # non-serving goodput section


# ------------------------------------------------------------------ alerting


class TestAlertEngine:
    JOB = "ns/svc"

    def _armed(self, reg, clock, rules, **engine_kw):
        s = MetricsScraper(fetch=lambda t: reg.render(), clock=clock,
                           registry=MetricsRegistry())
        s.add_target(ScrapeTarget(name="r0", addr="r0:0"))
        view = FleetView(s, window_s=10.0)
        engine = AlertEngine(view, rules=rules,
                             registry=MetricsRegistry(), **engine_kw)
        return s, view, engine

    def test_fast_burn_fires_within_two_windows_and_resolves(self):
        reg, reqs, viol, hist = _serving_registry(self.JOB)
        clock = FakeClock()
        rule = BurnRateRule(budget_fraction=0.001, fast_window_s=5.0,
                            slow_window_s=60.0, fast_factor=10.0,
                            min_requests=10)
        s, view, engine = self._armed(reg, clock, [rule])
        s.sweep()
        assert engine.evaluate() == []  # no data, nothing fires
        # the breach: half the requests violate (burn = 500x budget)
        clock.advance(1.0)
        reqs.inc(100, job=self.JOB)
        viol.inc(50, job=self.JOB)
        s.sweep()
        firing = engine.evaluate()  # within 2 evaluation windows
        rules = {a.rule for a in firing}
        assert "slo_fast_burn" in rules, firing
        fast = next(a for a in firing if a.rule == "slo_fast_burn")
        assert fast.labels == {"job": self.JOB}
        assert fast.value > 10.0
        assert engine._gauge.value(rule="slo_fast_burn") == 1
        from edl_tpu.observability.collector import get_counters

        assert get_counters().get("alerts_fired",
                                  rule="slo_fast_burn") >= 1
        # recovery: violations stop, the window ages the breach out
        clock.advance(20.0)
        reqs.inc(100, job=self.JOB)
        s.sweep()
        assert "slo_fast_burn" not in {a.rule for a in engine.evaluate()}
        assert engine._gauge.value(rule="slo_fast_burn") == 0

    def test_goodput_collapse_and_conservation_rules(self):
        reg = MetricsRegistry()
        reg.gauge("goodput_fraction").set(0.2, job="t/j")
        reg.gauge("goodput_conservation_error_pct").set(4.2, job="t/j")
        clock = FakeClock()
        s, view, engine = self._armed(
            reg, clock, [GoodputCollapseRule(min_fraction=0.5),
                         ConservationRule(max_error_pct=1.0)])
        s.sweep()
        rules = {a.rule: a for a in engine.evaluate()}
        assert rules["goodput_collapse"].labels == {"job": "t/j"}
        assert rules["conservation_violation"].value == 4.2
        # recovery resolves both
        reg.gauge("goodput_fraction").set(0.9, job="t/j")
        reg.gauge("goodput_conservation_error_pct").set(0.1, job="t/j")
        clock.advance(1.0)
        s.sweep()
        assert engine.evaluate() == []

    def test_target_down_rule(self):
        reg = MetricsRegistry()

        def boom():
            raise OSError("refused")

        clock = FakeClock()
        s = MetricsScraper(fetch=lambda t: boom(), clock=clock,
                           registry=MetricsRegistry(),
                           backoff_base_s=0.1, backoff_max_s=0.1)
        s.add_target(ScrapeTarget(name="dead", addr="dead:0"))
        view = FleetView(s)
        engine = AlertEngine(view, rules=[TargetDownRule(
            down_after_failures=2)], registry=MetricsRegistry())
        s.sweep()
        assert engine.evaluate() == []  # one failure: not yet
        for _ in range(3):
            clock.advance(0.2)
            s.sweep()
        firing = engine.evaluate()
        assert [a.rule for a in firing] == ["scrape_target_down"]
        assert firing[0].labels == {"target": "dead"}

    def test_alert_fires_flight_record_through_shared_lock(self, tmp_path):
        reg = MetricsRegistry()
        reg.gauge("goodput_fraction").set(0.1, job="t/j")
        clock = FakeClock()
        s, view, engine = self._armed(
            reg, clock, [GoodputCollapseRule(min_fraction=0.5)],
            flight_dir=str(tmp_path), dump_cooldown_s=60.0)
        s.sweep()
        engine.evaluate()
        recs = [f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-")]
        assert len(recs) == 1
        doc = json.loads((tmp_path / recs[0]).read_text())
        assert doc["reason"] == "alert-goodput_collapse"
        assert doc["extra"]["labels"] == {"job": "t/j"}

    def test_rule_exception_does_not_stop_other_rules(self):
        class Broken(AlertRule):
            def evaluate(self, view):
                raise RuntimeError("boom")

        class Always(AlertRule):
            def evaluate(self, view):
                return [Alert(rule="always", labels={}, firing=True)]

        reg = MetricsRegistry()
        clock = FakeClock()
        s, view, engine = self._armed(reg, clock, [Broken(), Always()])
        assert [a.rule for a in engine.evaluate()] == ["always"]


# ----------------------------------- flight-record dump lock + cooldown dedupe


class TestFlightDumpSerialization:
    def test_same_reason_deduped_within_cooldown(self, tmp_path):
        from edl_tpu.observability.collector import get_counters
        from edl_tpu.observability.metrics import dump_flight_record

        p1 = dump_flight_record(str(tmp_path), "stall-x", cooldown_s=60.0)
        before = get_counters().get("flight_dumps_deduped",
                                    reason="stall-x")
        p2 = dump_flight_record(str(tmp_path), "stall-x", cooldown_s=60.0)
        assert p2 == p1  # the deduped call returns the existing record
        assert get_counters().get("flight_dumps_deduped",
                                  reason="stall-x") == before + 1
        recs = [f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-")]
        assert len(recs) == 1
        # a DIFFERENT reason inside the window still dumps: a stall and
        # an alert for the same incident are both evidence
        p3 = dump_flight_record(str(tmp_path), "alert-y", cooldown_s=60.0)
        assert p3 != p1
        assert len([f for f in os.listdir(tmp_path)
                    if f.startswith("flightrec-")]) == 2

    def test_cooldown_zero_keeps_legacy_always_dump(self, tmp_path):
        from edl_tpu.observability.metrics import dump_flight_record

        a = dump_flight_record(str(tmp_path), "r")
        b = dump_flight_record(str(tmp_path), "r")
        assert a != b

    def test_concurrent_watchdog_and_alert_dumps_serialized(self, tmp_path):
        """The regression the satellite names: a watchdog breach and an
        alert firing dump concurrently in one process — every record
        must be complete valid JSON (no interleaved prune/rename
        damage), and same-reason storms inside the cooldown collapse."""
        from edl_tpu.observability.metrics import dump_flight_record

        errors = []
        barrier = threading.Barrier(8)

        def dump(reason):
            try:
                barrier.wait(timeout=10)
                for _ in range(5):
                    dump_flight_record(str(tmp_path), reason,
                                       cooldown_s=60.0)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = ([threading.Thread(target=dump, args=("stall-wd",))
                    for _ in range(4)]
                   + [threading.Thread(target=dump, args=("alert-burn",))
                      for _ in range(4)])
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        recs = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith("flightrec-"))
        # 40 calls, 2 distinct reasons, one cooldown window → exactly 2
        assert len(recs) == 2, recs
        for f in recs:
            doc = json.loads((tmp_path / f).read_text())  # not torn
            assert doc["reason"] in ("stall-wd", "alert-burn")
        assert not [f for f in os.listdir(tmp_path)
                    if f.startswith(".flightrec-")]  # no leaked temps


# ------------------------------------------------- request spans (serving)


class TestRequestSpans:
    def test_traced_request_emits_span_tree_and_span_histograms(self):
        jax = pytest.importorskip("jax")
        import numpy as np

        from edl_tpu.models import mlp
        from edl_tpu.observability.metrics import get_registry
        from edl_tpu.observability.tracing import get_tracer
        from edl_tpu.runtime.serving import ServingFleet

        params = mlp.init(jax.random.key(0), [8, 16, 4])
        fleet = ServingFleet(
            lambda p, b: mlp.apply(p, b[0]), params,
            example_row=(np.zeros((8,), np.float32),), job="t/spans",
            max_batch_size=4, max_queue_ms=0.5, slo_p99_ms=1000.0)
        try:
            fleet.scale_to(1)
            get_tracer().clear()
            req = fleet.submit((np.ones((8,), np.float32),),
                               trace_id="feedbeef00000001")
            req.wait(10.0)
        finally:
            fleet.stop()
        evs = [e for e in get_tracer().events()
               if e.trace_id == "feedbeef00000001"]
        by_name = {e.name: e for e in evs}
        root = by_name["serving_request"]
        assert root.args["latency_ms"] > 0
        phases = {"admit", "queue", "batch", "forward", "respond"}
        for ph in phases:
            child = by_name[f"serving_request.{ph}"]
            assert child.parent_id == root.span_id
            assert child.trace_id == root.trace_id
        # phase ordering is physical: queue ends where batch begins
        q = by_name["serving_request.queue"]
        f = by_name["serving_request.forward"]
        assert q.start_s <= f.start_s
        # span histograms carry every phase
        from edl_tpu.observability.metrics import parse_exposition

        series = parse_exposition(get_registry().render())
        for ph in phases:
            key = f'edl_serving_span_seconds_count{{phase="{ph}"}}'
            assert series[key] >= 1, key
        # exemplar ring recorded the traced request with its phase split
        ex = [e for e in fleet.exemplars
              if e["trace_id"] == "feedbeef00000001"]
        assert ex and ex[0]["forward_ms"] >= 0

    def test_untraced_fast_request_emits_no_spans(self):
        jax = pytest.importorskip("jax")
        import numpy as np

        from edl_tpu.models import mlp
        from edl_tpu.observability.tracing import get_tracer
        from edl_tpu.runtime.serving import ServingFleet

        params = mlp.init(jax.random.key(0), [8, 16, 4])
        fleet = ServingFleet(
            lambda p, b: mlp.apply(p, b[0]), params,
            example_row=(np.zeros((8,), np.float32),), job="t/quiet",
            max_batch_size=4, max_queue_ms=0.5, slo_p99_ms=60000.0)
        try:
            fleet.scale_to(1)
            get_tracer().clear()
            fleet.submit((np.ones((8,), np.float32),)).wait(10.0)
        finally:
            fleet.stop()
        assert not [e for e in get_tracer().events()
                    if e.name.startswith("serving_request")]


# ------------------------------------------- histogram exemplar ingestion


class TestExemplars:
    """ISSUE-14 satellite: the scrape plane ingests trace-id exemplars
    off histogram bucket lines — the join from a fleet-level latency
    breach to an `edl-tpu trace`-able id."""

    def _exposed_registry(self, tid="feedbeef0001", v=0.05):
        reg = MetricsRegistry()
        h = reg.histogram("serving_request_seconds",
                          buckets=(0.001, 0.01, 0.1, 1.0))
        h.observe(v, job="j1")
        h.put_exemplar(v, tid, job="j1")
        reg.counter("serving_requests").inc(3, job="j1")
        return reg

    def test_parse_exposition_roundtrips_exemplars(self):
        from edl_tpu.observability.metrics import (
            iter_samples, parse_exposition,
        )

        reg = self._exposed_registry()
        text = reg.render()
        assert ' # {trace_id="feedbeef0001"} 0.05' in text
        # the strict parser accepts the annotated exposition whole…
        series = parse_exposition(text)
        assert series['edl_serving_request_seconds_count{job="j1"}'] == 1
        # …and hands the exemplars back on request
        ex = []
        iter_samples(text, exemplars=ex)
        assert len(ex) == 1
        name, labels, ex_labels, ex_value, ts = ex[0]
        assert name == "edl_serving_request_seconds_bucket"
        assert labels["le"] == "0.1" and labels["job"] == "j1"
        assert ex_labels == {"trace_id": "feedbeef0001"}
        assert ex_value == 0.05 and ts is not None

    def test_malformed_exemplar_is_a_grammar_violation(self):
        from edl_tpu.observability.metrics import (
            ExpositionError, iter_samples,
        )

        bad = ('# HELP edl_x_seconds x\n# TYPE edl_x_seconds histogram\n'
               'edl_x_seconds_bucket{le="+Inf"} 1 # {trace_id=oops} 1\n'
               'edl_x_seconds_sum 1\nedl_x_seconds_count 1\n')
        with pytest.raises(ExpositionError):
            iter_samples(bad)

    def test_scraper_ingests_and_fleetview_surfaces_slowest(self):
        reg = self._exposed_registry(tid="slowtrace001", v=0.25)
        # a second, faster exemplar on another job: slowest wins
        h = reg.histogram("serving_request_seconds")
        h.observe(0.002, job="j2")
        h.put_exemplar(0.002, "fasttrace002", job="j2")
        s, clock = make_scraper({"t1": reg.render})
        s.sweep()
        ex = s.exemplars("edl_serving_request_seconds")
        assert [e["trace_id"] for e in ex[:2]] == ["slowtrace001",
                                                   "fasttrace002"]
        view = FleetView(s)
        slow = view.slowest_exemplars(k=1)
        assert slow[0]["trace_id"] == "slowtrace001"
        assert slow[0]["family"] == "edl_serving_request_seconds"
        snap = view.snapshot()
        assert snap["jobs"]["j1"]["slowest_trace"]["trace_id"] == \
            "slowtrace001"
        assert snap["jobs"]["j1"]["slowest_trace"]["latency_ms"] == 250.0
        # the dashboard renders the handle an operator feeds to
        # `edl-tpu trace`
        assert "slowtrace001" in render_fleet_dashboard(view)

    def test_exemplar_stays_fresh_while_exposed(self):
        """Re-scraping the same still-exposed exemplar refreshes its
        age — it must not fade from rollups while the target is alive
        and still advertising it."""
        reg = self._exposed_registry()
        s, clock = make_scraper({"t1": reg.render})
        s.sweep()
        for _ in range(6):
            clock.advance(1.5)
            s.sweep()
        ex = s.exemplars("edl_serving_request_seconds", {"job": "j1"})
        assert len(ex) == 1 and ex[0]["age_s"] < s.stale_after_s

    def test_dead_target_exemplars_age_out_with_its_series(self):
        """A discovered target that vanishes (dead pod) takes its
        exemplars with its series — no immortal trace ids in the
        slowest-rollup."""
        reg = self._exposed_registry()
        alive = [True]

        def discover():
            return ([ScrapeTarget(name="d1", addr="d1:9", source="x")]
                    if alive[0] else [])

        clock = FakeClock()
        s = MetricsScraper(
            fetch=lambda t: reg.render(), clock=clock,
            discover=[discover], interval_s=1.0,
            forget_after_sweeps=3, registry=MetricsRegistry())
        s.sweep()
        assert s.exemplars("edl_serving_request_seconds")
        alive[0] = False
        for _ in range(4):
            clock.advance(1.5)
            s.sweep()
        assert s.targets() == []
        assert s.exemplars("edl_serving_request_seconds",
                           max_age_s=float("inf")) == []

    def test_hash_inside_label_value_is_not_an_exemplar(self):
        """A label value containing " # " (valid, and rendered verbatim
        by the module's own renderer) must not be mistaken for an
        exemplar separator — the whole target scrape would error."""
        from edl_tpu.observability.metrics import (
            iter_samples, parse_exposition,
        )

        reg = MetricsRegistry()
        reg.counter("jobs").inc(1, job="a # b")
        text = reg.render()
        series = parse_exposition(text)
        assert series['edl_jobs_total{job="a # b"}'] == 1
        ex = []
        iter_samples(text, exemplars=ex)
        assert ex == []
        # and both at once: hashy label + a real exemplar on one line
        h = reg.histogram("lat_seconds", buckets=(1.0,))
        h.observe(0.5, job="a # b")
        h.put_exemplar(0.5, "tid # x", job="a # b")
        ex = []
        iter_samples(reg.render(), exemplars=ex)
        assert len(ex) == 1
        assert ex[0][1]["job"] == "a # b"
        assert ex[0][2] == {"trace_id": "tid # x"}

    def test_expired_exemplar_stops_rendering(self):
        """A once-ever outlier exemplar must not be re-exposed (and so
        re-freshened by every scraper) past the histogram's TTL — by
        then its trace dumps have rotated and the handle is dead."""
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(1.0,))
        h.observe(0.5, job="j")
        h.put_exemplar(0.5, "oldtrace", job="j")
        assert "oldtrace" in reg.render()
        # age the stored exemplar past the TTL
        for ex in h._exemplars.values():
            for i, (tid, v, ts) in list(ex.items()):
                ex[i] = (tid, v, ts - h.exemplar_ttl_s - 1)
        assert "oldtrace" not in reg.render()
        # …and it stays gone (the expiry prunes, not just filters)
        assert all(not ex for ex in h._exemplars.values())
