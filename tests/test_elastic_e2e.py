"""The end-to-end elastic slice (SURVEY §7 stage 6 milestone):

submit job → controller creates pods → autoscaler scales 2→8 → the live
training loop resizes its mesh mid-training → loss keeps decreasing through
the resizes → scale-down under competing load also holds.

Everything runs in-process: FakeCluster pods, fast control loops, the real
coordination service, real jax training on the virtual 8-device CPU mesh.
"""

import time

import jax
import numpy as np
import optax

from edl_tpu.api.types import (
    JobPhase,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller
from edl_tpu.coord import local_service
from edl_tpu.models import mlp
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.data import ShardRegistry
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.local import LocalElasticJob


def mk_elastic_job(name="train", lo=2, hi=8):
    return TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                ),
            ),
        ),
    )


def wait_until(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_elastic_training_through_scale_up_and_down():
    # --- data: synthetic classification, registered as lease tasks
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(4, 16)) * 3
    y = rng.integers(0, 4, size=4096).astype(np.int32)
    x = (centers[y] + rng.normal(size=(4096, 16))).astype(np.float32)
    coord = local_service(passes=2)
    reg = ShardRegistry()
    reg.add_arrays(coord, (x, y), num_shards=16)

    # --- control plane: 10-CPU cluster, job elastic 2→8
    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=10_000, memory_mega=100_000)
    # POW2 slice-shape policy: mesh sizes stay {2,4,8}, which also keeps
    # them divisors of the global batch — the TPU-native constraint the
    # reference never had (its trainers were independent processes).
    from edl_tpu.scheduler.topology import POW2_POLICY

    ctl = Controller(cluster, max_load_desired=1.0,
                     shape_policy=POW2_POLICY,
                     autoscaler_loop_seconds=0.02,
                     updater_convert_seconds=0.02,
                     updater_confirm_seconds=0.01)
    ctl.start()
    job = mk_elastic_job()
    ctl.submit(job)
    assert wait_until(lambda: ctl.phase(job) == JobPhase.RUNNING)

    # --- training loop wired to the dial
    params = mlp.init(jax.random.key(0), [16, 64, 4])
    trainer = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                             spec=MeshSpec(dp=-1),
                             initial_world_size=2)
    runner = LocalElasticJob(job, cluster, trainer, coord, reg.fetch,
                             batch_size=64)

    # competing load appears mid-run → autoscaler must shrink the job
    competitor_added = []

    def on_step(step, loss, world):
        if step == 120 and not competitor_added:
            for i in range(4):
                cluster.add_system_pod(f"nginx-{i}", "n0",
                                       cpu_request_milli=1000,
                                       memory_request_mega=100)
            competitor_added.append(True)
        time.sleep(0.002)  # let control loops breathe

    report = runner.run(on_step=on_step)
    ctl.stop()

    # --- the elastic story holds end to end
    assert report.steps == 2 * (4096 // 64)  # both passes, exactly once
    assert max(report.world_sizes) == 8  # scaled up to max
    assert min(report.world_sizes[report.world_sizes.index(8):]) <= 6  # shrank under load
    assert report.resizes >= 2  # at least one grow + one shrink
    # learning survived every resize
    first_k = np.mean(report.losses[:10])
    last_k = np.mean(report.losses[-10:])
    assert last_k < first_k * 0.5
    # monotonic-ish: the loss right after the last resize is not blown up
    assert report.losses[-1] < report.first_loss


def test_trainer_pod_kill_does_not_stop_training():
    # Chaos: kill a trainer pod mid-run (reference demo killed pods by hand,
    # doc/boss_tutorial.md:271-301); the job controller replaces it and the
    # FT job keeps training.
    rng = np.random.default_rng(1)
    y = rng.integers(0, 4, size=1024).astype(np.int32)
    x = rng.normal(size=(1024, 16)).astype(np.float32)
    coord = local_service()
    reg = ShardRegistry()
    reg.add_arrays(coord, (x, y), num_shards=8)

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=8_000, memory_mega=100_000)
    ctl = Controller(cluster, autoscaler_loop_seconds=0.02,
                     updater_convert_seconds=0.02,
                     updater_confirm_seconds=0.01)
    ctl.start()
    job = mk_elastic_job(lo=2, hi=4)
    ctl.submit(job)
    assert wait_until(lambda: ctl.phase(job) == JobPhase.RUNNING)

    params = mlp.init(jax.random.key(1), [16, 32, 4])
    trainer = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                             initial_world_size=2)
    runner = LocalElasticJob(job, cluster, trainer, coord, reg.fetch,
                             batch_size=64)
    killed = []

    def on_step(step, loss, world):
        if step == 5 and not killed:
            pods = cluster.list_pods(job_uid=job.full_name, role="trainer")
            cluster.kill_pod(pods[0].name)
            killed.append(True)
        time.sleep(0.002)

    report = runner.run(on_step=on_step)
    ctl.stop()
    assert killed
    assert report.steps == 1024 // 64  # nothing lost
    assert ctl.phase(job) == JobPhase.RUNNING  # FT job survived
