"""Accuracy-consistent elasticity: the equivalence harness.

The acceptance property of the virtual-worker layer
(edl_tpu.runtime.virtual): a run whose world is resized mid-training
produces a loss trajectory IDENTICAL to the never-resized control —
bitwise on this CPU backend in replicated accumulation mode, within the
documented tolerance in the dp-packed mode — with every data row
trained exactly once, including under an injected kill-mid-accumulation,
a detected stall, and a coordinator-primary kill with failover.

Also home to the satellite regressions: the `_row_splits` determinism
contract, the versioned checkpoint meta (cursors + RNG lineage) with
its torn-cursor fallback, and exactly-once re-dispatch of a dead
worker's unconsumed offsets across a resize.
"""

from __future__ import annotations

import json
import signal
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import optax  # noqa: E402

from edl_tpu.coord import local_service  # noqa: E402
from edl_tpu.models import mlp  # noqa: E402
from edl_tpu.observability.collector import get_counters  # noqa: E402
from edl_tpu.parallel.mesh import MeshSpec  # noqa: E402
from edl_tpu.runtime.checkpoint import ElasticCheckpointer  # noqa: E402
from edl_tpu.runtime.data import ShardRegistry, _row_splits, shard_sizes  # noqa: E402
from edl_tpu.runtime.elastic import (  # noqa: E402
    AccumulationAborted,
    ElasticTrainer,
)
from edl_tpu.runtime.virtual import (  # noqa: E402
    CursorStore,
    OwnershipMap,
    VirtualBatches,
    VirtualConfig,
    VirtualWorkerLoop,
    assign_ownership,
    loss_divergence,
    trajectories_equivalent,
    vw_key,
    vw_keys,
)

SEED = 3
N_ROWS = 2048
N_SHARDS = 16
CFG = VirtualConfig(vw_count=8, global_batch=64, job_seed=SEED)


def _dataset(n=N_ROWS):
    rng = np.random.default_rng(1)
    y = rng.integers(0, 4, n).astype(np.int32)
    x = rng.normal(size=(n, 16)).astype(np.float32)
    return x, y


def _registry(n=N_ROWS, shards=N_SHARDS):
    reg = ShardRegistry()
    ids = reg.register_arrays(_dataset(n), num_shards=shards)
    return reg, ids


def _trainer(world=4, accum_mode="replicated", loss=mlp.loss_fn, **kw):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(loss, params, optax.adam(1e-2),
                          spec=MeshSpec(dp=-1), initial_world_size=world,
                          accum_mode=accum_mode, **kw)


def _loop(schedule, max_steps=20, cfg=CFG, kv=None, job="job",
          ckpt=None, ckpt_every=0, augment=None, on_step=None, **trainer_kw):
    reg, ids = _registry()
    tr = _trainer(world=schedule(0) if schedule else 4, **trainer_kw)
    vb = VirtualBatches(cfg, ids, reg.get, passes=2)
    loop = VirtualWorkerLoop(tr, cfg, vb, kv=kv, job=job,
                             checkpointer=ckpt, ckpt_every=ckpt_every,
                             augment=augment)
    report = loop.run(max_steps=max_steps, world_size_for=schedule,
                      on_step=on_step)
    return loop, report


RESIZE_4_2_8 = lambda s: 4 if s < 7 else (2 if s < 14 else 8)  # noqa: E731
CONTROL_4 = lambda s: 4  # noqa: E731


# ---------------------------------------------------------------------------
# satellite: the _row_splits determinism contract
# ---------------------------------------------------------------------------

class TestRowSplitsContract:
    def test_sizes_match_pure_arithmetic(self):
        for n, k in [(10, 3), (2048, 16), (7, 7), (100, 1), (5, 8)]:
            arrays = (np.arange(n, dtype=np.float32),)
            splits = _row_splits(arrays, k)
            assert [len(s) for s in splits] == shard_sizes(n, k)

    def test_order_preserving_contiguous_cover(self):
        splits = _row_splits((np.arange(101, dtype=np.float32),), 7)
        flat = np.concatenate(splits)
        assert np.array_equal(flat, np.arange(101))

    def test_registry_shard_map_invariant_to_world_size(self):
        """Two registries built from the same arrays — by processes that
        will run at DIFFERENT world sizes — must hold the identical
        shard id → row map: world size appears nowhere in the split."""
        data = _dataset(300)
        maps = []
        for _world_size in (2, 8):  # the split must not see this
            reg = ShardRegistry()
            ids = reg.register_arrays(data, num_shards=11)
            maps.append({sid: tuple(reg.get(sid)[1].tolist())
                         for sid in ids})
        assert maps[0] == maps[1]


# ---------------------------------------------------------------------------
# RNG lineage
# ---------------------------------------------------------------------------

class TestRngLineage:
    def test_key_is_pure_function_of_job_identifiers(self):
        a = vw_key(SEED, 3, 17)
        b = vw_key(SEED, 3, 17)
        assert jax.random.key_data(a).tolist() == \
            jax.random.key_data(b).tolist()

    def test_keys_distinct_across_vw_and_step(self):
        seen = set()
        for v in range(4):
            for s in range(4):
                seen.add(tuple(jax.random.key_data(
                    vw_key(SEED, v, s)).tolist()))
        assert len(seen) == 16

    def test_lineage_independent_of_physical_mapping(self):
        """The whole point: remapping VWs onto a different world derives
        the SAME keys — there is no per-host RNG state to migrate."""
        keys_a = vw_keys(SEED, 8, 5)
        # "resize": different ownership, same lineage
        assign_ownership(8, ["pw0", "pw1"])
        keys_b = vw_keys(SEED, 8, 5)
        for ka, kb in zip(keys_a, keys_b):
            assert jax.random.key_data(ka).tolist() == \
                jax.random.key_data(kb).tolist()


# ---------------------------------------------------------------------------
# ownership map
# ---------------------------------------------------------------------------

class TestOwnership:
    def test_assignment_deterministic_and_balanced(self):
        m = assign_ownership(8, ["w1", "w0"])  # order must not matter
        assert m == assign_ownership(8, ["w0", "w1"])
        per = {}
        for v, w in m.items():
            per.setdefault(w, []).append(v)
        assert sorted(len(vs) for vs in per.values()) == [4, 4]

    def test_remap_counts_moved_vws(self):
        c0 = get_counters().get("vw_remaps")
        m = OwnershipMap(8, [f"w{i}" for i in range(4)])
        moved = m.remap(["w0", "w1"])  # shrink 4 → 2
        # VWs on w2/w3 must move (4 of 8); w0/w1's keep their owner
        assert moved == 4
        assert get_counters().get("vw_remaps") == c0 + 4
        assert m.remap(["w0", "w1"]) == 0  # no change → no count

    def test_kv_roundtrip_and_publish_for_delta(self):
        kv = local_service()
        m = OwnershipMap(8, ["w0", "w1", "w2", "w3"])
        m.publish(kv, job="j")
        loaded = OwnershipMap.load(kv, job="j")
        assert loaded.mapping == m.mapping
        c0 = get_counters().get("vw_remaps")
        m2 = OwnershipMap.publish_for(kv, 8, ["w0", "w1"], job="j")
        assert get_counters().get("vw_remaps") == c0 + 4
        assert OwnershipMap.load(kv, job="j").mapping == m2.mapping

    def test_torn_map_returns_none(self):
        kv = local_service()
        kv.kv_set("vw-map/j", b"{torn")
        assert OwnershipMap.load(kv, job="j") is None


# ---------------------------------------------------------------------------
# the deterministic batch stream + cursors
# ---------------------------------------------------------------------------

class TestVirtualBatches:
    def test_stream_is_world_size_free_and_reproducible(self):
        reg, ids = _registry()
        a = VirtualBatches(CFG, ids, reg.get)
        b = VirtualBatches(CFG, ids, reg.get)
        for _ in range(5):
            ma, mb = a.next_step(), b.next_step()
            for ta, tb in zip(ma, mb):
                for la, lb in zip(ta, tb):
                    assert np.array_equal(la, lb)

    def test_cursor_restore_mid_shard_resumes_exactly_once(self):
        """Crash after k steps with cursors pointing MID-shard; a fresh
        instance restored from the snapshot continues the stream with no
        row duplicated and none dropped."""
        reg, ids = _registry(n=320, shards=5)  # shard=64, V streams mix
        cfg = VirtualConfig(vw_count=4, global_batch=16, job_seed=0)
        full = VirtualBatches(cfg, ids, reg.get)
        seen_control = []
        while (mb := full.next_step()) is not None:
            seen_control.append(np.concatenate(full.last_step_rows))
        crashed = VirtualBatches(cfg, ids, reg.get)
        seen: list[np.ndarray] = []
        for _ in range(7):  # cursor 28 rows into a 64-row shard
            crashed.next_step()
            seen.append(np.concatenate(crashed.last_step_rows))
        snap = crashed.state()
        resumed = VirtualBatches(cfg, ids, reg.get)
        resumed.restore(json.loads(json.dumps(snap)))  # via-serialization
        while (mb := resumed.next_step()) is not None:
            seen.append(np.concatenate(resumed.last_step_rows))
        got = np.sort(np.concatenate(seen))
        want = np.sort(np.concatenate(seen_control))
        assert np.array_equal(got, want)
        assert len(np.unique(got)) == len(got)  # exactly-once

    def test_cursors_for_step_matches_actual(self):
        reg, ids = _registry()
        vb = VirtualBatches(CFG, ids, reg.get)
        for _ in range(9):
            vb.next_step()
        derived = vb.cursors_for_step(9)
        assert derived["cursors"] == vb.state()["cursors"]
        assert derived["pass"] == vb.state()["pass"]

    def test_remainder_rows_accounted_deterministically(self):
        reg, ids = _registry(n=300, shards=6)  # streams don't divide m
        cfg = VirtualConfig(vw_count=2, global_batch=16, job_seed=0)
        vb = VirtualBatches(cfg, ids, reg.get)
        n_steps = 0
        while vb.next_step() is not None:
            n_steps += 1
        assert n_steps == vb.total_steps
        assert n_steps * 16 + vb.rows_dropped_remainder == 300

    def test_starved_vw_stream_rejected_loudly(self):
        """Fewer shards than virtual workers would leave some VW with an
        EMPTY stream — the loop would silently train on nothing; the
        constructor must refuse instead."""
        reg, ids = _registry(n=300, shards=6)
        with pytest.raises(ValueError, match="fewer than one micro-batch"):
            VirtualBatches(VirtualConfig(vw_count=8, global_batch=64,
                                         job_seed=0), ids, reg.get)

    def test_cursor_store_torn_blob_counts_and_falls_back(self):
        kv = local_service()
        store = CursorStore(kv, job="j")
        store.save({"step": 4, "pass": 0, "cursors": {"0": 8}})
        assert store.load()["step"] == 4
        c0 = get_counters().get("vw_cursor_torn")
        kv.kv_set("vw-cursor/j", b"\xff{torn")
        assert store.load() is None
        assert get_counters().get("vw_cursor_torn") == c0 + 1


# ---------------------------------------------------------------------------
# constant effective batch (gradient accumulation)
# ---------------------------------------------------------------------------

class TestAccumulation:
    def _micro(self, B=64, V=8):
        x, y = _dataset(B)
        m = B // V
        return [(x[v * m:(v + 1) * m], y[v * m:(v + 1) * m])
                for v in range(V)], (x, y)

    def test_replicated_mode_bitwise_across_world_sizes(self):
        micro, _ = self._micro()
        trajs = {}
        for w in (1, 2, 4, 8):
            tr = _trainer(world=w, accum_mode="replicated")
            trajs[w] = [tr.step_accumulate(micro) for _ in range(4)]
        for w in (2, 4, 8):
            assert trajs[w] == trajs[1]  # BITWISE

    def test_dp_mode_matches_full_batch_step_within_tolerance(self):
        micro, full = self._micro()
        tr_a = _trainer(world=4, accum_mode="dp")
        tr_b = _trainer(world=4)
        for _ in range(4):
            la = tr_a.step_accumulate(micro)
            lb = tr_b.step(full)
            assert abs(la - lb) < 1e-5

    def test_dp_mode_bounded_across_world_sizes(self):
        micro, _ = self._micro()
        t2 = _trainer(world=2, accum_mode="dp")
        t8 = _trainer(world=8, accum_mode="dp")
        for _ in range(4):
            assert abs(t2.step_accumulate(micro)
                       - t8.step_accumulate(micro)) < 1e-5

    def test_abort_mid_accumulation_leaves_state_untouched(self):
        micro, _ = self._micro()
        tr = _trainer(world=2, accum_mode="replicated")
        before = jax.tree.map(np.asarray, tr.state.params)
        step0 = tr.state.step
        with pytest.raises(AccumulationAborted):
            tr.step_accumulate(micro, abort_after=3)
        after = jax.tree.map(np.asarray, tr.state.params)
        assert tr.state.step == step0
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            assert np.array_equal(a, b)
        # the replayed step applies normally
        tr.step_accumulate(micro)
        assert tr.state.step == step0 + 1

    def test_rng_in_loss_requires_keys_and_is_layout_invariant(self):
        def noisy_loss(params, batch, key):
            x, y = batch
            return mlp.loss_fn(params, (x + 0.05 * jax.random.normal(
                key, x.shape), y))

        micro, _ = self._micro()
        tr = _trainer(world=2, accum_mode="replicated", loss=noisy_loss,
                      rng_in_loss=True)
        with pytest.raises(ValueError):
            tr.step_accumulate(micro)
        with pytest.raises(ValueError):
            tr.step(micro[0])
        trajs = {}
        for w in (2, 8):
            t = _trainer(world=w, accum_mode="replicated", loss=noisy_loss,
                         rng_in_loss=True)
            trajs[w] = [t.step_accumulate(micro,
                                          rng_keys=vw_keys(SEED, 8, s))
                        for s in range(3)]
        assert trajs[2] == trajs[8]  # dropout draws ride the VW lineage


# ---------------------------------------------------------------------------
# satellite: versioned checkpoint meta (cursors + RNG) + torn fallback
# ---------------------------------------------------------------------------

class TestCheckpointMeta:
    META = {"cursor": {"version": 1, "step": 6, "pass": 0,
                       "cursors": {"0": 48, "1": 48}},
            "rng": {"job_seed": SEED, "vw_count": 8}}

    def test_sync_save_meta_roundtrip_versioned(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path)
        ck.save(6, {"w": np.ones((4,), np.float32)}, meta=self.META)
        assert ck.load_meta(6) == self.META
        manifest = json.loads(
            (tmp_path / ".integrity" / "6.json").read_text())
        assert manifest["version"] == 3
        assert manifest["meta"] is not None
        assert ck.verify(6)
        ck.close()

    def test_async_save_meta_lands_at_finalize(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path)
        ck.save_async(3, {"w": np.ones((4,), np.float32)}, meta=self.META)
        ck.finalize()
        assert ck.load_meta(3) == self.META
        ck.close()

    def test_torn_meta_counts_and_returns_none_but_step_restores(
            self, tmp_path):
        """The torn-cursor fallback: a half-written sidecar must not
        poison the checkpoint — params restore, load_meta says None, the
        caller derives cursors from the step."""
        ck = ElasticCheckpointer(tmp_path)
        tree = {"w": np.arange(4, dtype=np.float32)}
        ck.save(6, tree, meta=self.META)
        mpath = tmp_path / ".integrity" / "6.meta.json"
        mpath.write_bytes(mpath.read_bytes()[:11])  # tear it
        c0 = get_counters().get("checkpoint_meta_torn")
        assert ck.load_meta(6) is None
        assert get_counters().get("checkpoint_meta_torn") == c0 + 1
        restored = ck.restore({"w": np.zeros((4,), np.float32)})
        assert np.array_equal(restored["w"], tree["w"])
        ck.close()

    def test_meta_fingerprint_mismatch_detected(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path)
        ck.save(2, {"w": np.ones((2,), np.float32)}, meta=self.META)
        mpath = tmp_path / ".integrity" / "2.meta.json"
        # VALID json, wrong bytes: only the manifest fingerprint can
        # tell a silent rewrite from the one save() persisted
        mpath.write_text(json.dumps(
            {"step": 2, "meta": {"cursor": "forged"}}))
        assert ck.load_meta(2) is None
        ck.close()

    def test_v1_manifest_still_verifies_and_restores(self, tmp_path):
        """Old stores (pre-version manifests: {step, files} only) keep
        restoring — the schema change is backward compatible."""
        ck = ElasticCheckpointer(tmp_path)
        tree = {"w": np.ones((3,), np.float32)}
        ck.save(1, tree)
        mp = tmp_path / ".integrity" / "1.json"
        doc = json.loads(mp.read_text())
        mp.write_text(json.dumps({"step": 1, "files": doc["files"]}))
        assert ck.verify(1)
        assert ck.load_meta(1) is None  # no sidecar, no error
        restored = ck.restore({"w": np.zeros((3,), np.float32)})
        assert np.array_equal(restored["w"], tree["w"])
        ck.close()

    def test_metaless_resave_drops_stale_sidecar(self, tmp_path):
        """Re-saving the same step WITHOUT meta (a rollback replay
        through a meta-less path) must not leave the earlier save's
        sidecar behind for the new manifest to bless as valid — stale
        cursors presented as verified would replay/skip rows."""
        ck = ElasticCheckpointer(tmp_path)
        ck.save(4, {"w": np.ones((2,), np.float32)}, meta=self.META)
        assert ck.load_meta(4) == self.META
        ck.save(4, {"w": np.full((2,), 2.0, np.float32)})  # no meta
        assert not (tmp_path / ".integrity" / "4.meta.json").exists()
        assert ck.load_meta(4) is None
        ck.close()

    def test_meta_pruned_with_its_step(self, tmp_path):
        ck = ElasticCheckpointer(tmp_path, max_to_keep=1)
        for s in (1, 2):
            ck.save(s, {"w": np.full((2,), float(s), np.float32)},
                    meta=self.META)
        names = {p.name for p in (tmp_path / ".integrity").glob("*.json")}
        assert "2.json" in names and "2.meta.json" in names
        assert "1.json" not in names and "1.meta.json" not in names
        ck.close()


# ---------------------------------------------------------------------------
# the equivalence harness
# ---------------------------------------------------------------------------

@pytest.mark.timeout_s(420)
class TestEquivalence:
    def test_resize_4_2_8_matches_unresized_control(self):
        """THE acceptance run: same job, one world resized 4→2→8
        mid-training, one never resized — identical loss curves
        (bitwise on this backend in replicated accumulation mode),
        every row trained exactly once, remaps counted."""
        kv = local_service()
        c0 = get_counters().get("vw_remaps")
        _, ctrl = _loop(CONTROL_4, max_steps=20)
        loop, res = _loop(RESIZE_4_2_8, max_steps=20, kv=kv, job="acc")
        div = loss_divergence(ctrl.losses, res.losses)
        assert div["steps_compared"] == 20
        assert div["bitwise"], div
        assert trajectories_equivalent(ctrl.losses, res.losses)
        assert res.resizes == 2
        assert res.world_sizes[0] == 4 and 2 in res.world_sizes \
            and res.world_sizes[-1] == 8
        assert res.rows_duplicated() == 0
        assert res.rows_missing(expected=20 * CFG.global_batch) == 0
        assert get_counters().get("vw_remaps") > c0
        # ownership + cursors live in (HA-replicable) coordinator KV
        assert OwnershipMap.load(kv, job="acc") is not None
        assert CursorStore(kv, job="acc").load()["step"] == 20

    def test_dp_packed_mode_within_documented_tolerance(self):
        """The perf accumulation mode reorders float reductions with the
        world size; the equivalence guarantee is the documented bound,
        not bitwise — assert it holds through the same 4→2→8 walk."""
        _, ctrl = _loop(CONTROL_4, max_steps=16, accum_mode="dp")
        _, res = _loop(RESIZE_4_2_8, max_steps=16, accum_mode="dp")
        assert trajectories_equivalent(ctrl.losses, res.losses)
        div = loss_divergence(ctrl.losses, res.losses)
        assert div["max_loss_divergence"] < 1e-3, div

    def test_rng_augmentation_rides_the_lineage(self):
        """Host-side data augmentation drawn from per-VW keys is
        identical at any world size — and actually does something."""
        def augment(mb, key):
            x, y = mb
            return (x + 0.05 * np.asarray(jax.random.normal(key, x.shape)),
                    y)

        _, ctrl = _loop(CONTROL_4, max_steps=12, augment=augment)
        _, res = _loop(RESIZE_4_2_8, max_steps=12, augment=augment)
        assert ctrl.losses == res.losses  # bitwise
        _, bare = _loop(CONTROL_4, max_steps=12)
        assert ctrl.losses != bare.losses  # the augmentation is live

    def test_kill_mid_accumulation_restores_exactly_once(self, tmp_path):
        """The injected-fault leg: a worker dies INSIDE a step's
        accumulation (after 3 of 8 micro-grads).  Nothing partial was
        applied, so restore-from-checkpoint + cursor meta replays the
        step and the full trajectory still equals the control's —
        with no row trained twice and none dropped."""
        _, ctrl = _loop(CONTROL_4, max_steps=20)

        reg, ids = _registry()
        cfg = CFG
        ck = ElasticCheckpointer(tmp_path / "ck")
        tr = _trainer(world=4)
        vb = VirtualBatches(cfg, ids, reg.get, passes=2)
        kv = local_service()
        loop = VirtualWorkerLoop(tr, cfg, vb, kv=kv, job="kill",
                                 checkpointer=ck, ckpt_every=5)
        rep1 = loop.run(max_steps=10, world_size_for=RESIZE_4_2_8)
        # the kill: step 11's accumulation dies between micro-grads —
        # its rows were FETCHED (cursors advanced in memory) but the
        # update never applied, and the in-memory cursors die with the
        # process
        micro = vb.next_step()
        assert micro is not None
        with pytest.raises(AccumulationAborted):
            tr.step_accumulate(micro, abort_after=3)
        # recovery on a FRESH trainer (world 2 — the shrunken survivor
        # set), restored from the last checkpoint (step 10) + cursors
        tr2 = _trainer(world=2)
        vb2 = VirtualBatches(cfg, ids, reg.get, passes=2)
        loop2 = VirtualWorkerLoop(tr2, cfg, vb2, kv=kv, job="kill",
                                  checkpointer=ck, ckpt_every=5)
        restored_step = loop2.restore_latest()
        assert restored_step == 10
        rep2 = loop2.run(max_steps=10, world_size_for=RESIZE_4_2_8)
        stitched = rep1.losses + rep2.losses
        assert stitched == ctrl.losses  # bitwise, kill and all
        # exactly-once across the APPLIED updates of the whole run: the
        # aborted step's rows reappear exactly once, in rep2's replay
        rows: dict[int, int] = {}
        for rep in (rep1, rep2):
            for gid, c in rep.rows_trained.items():
                rows[gid] = rows.get(gid, 0) + c
        assert sum(rows.values()) == 20 * cfg.global_batch
        assert all(c == 1 for c in rows.values())
        ck.close()

    def test_restore_rejects_drifted_virtual_config(self, tmp_path):
        """A restart under a different VirtualConfig must refuse the
        checkpoint's cursors loudly: a changed V changes the ownership
        schedule, so resuming old offsets would duplicate/skip rows and
        fork the RNG lineage silently."""
        reg, ids = _registry()
        ck = ElasticCheckpointer(tmp_path / "ck")
        tr = _trainer(world=4)
        loop = VirtualWorkerLoop(tr, CFG,
                                 VirtualBatches(CFG, ids, reg.get),
                                 checkpointer=ck, ckpt_every=5)
        loop.run(max_steps=5, world_size_for=CONTROL_4)
        drifted = VirtualConfig(vw_count=4, global_batch=64, job_seed=SEED)
        loop2 = VirtualWorkerLoop(_trainer(world=4), drifted,
                                  VirtualBatches(drifted, ids, reg.get),
                                  checkpointer=ck, ckpt_every=5)
        with pytest.raises(ValueError, match="different virtual-worker"):
            loop2.restore_latest()
        # the ORIGINAL config still restores fine
        loop3 = VirtualWorkerLoop(_trainer(world=4), CFG,
                                  VirtualBatches(CFG, ids, reg.get),
                                  checkpointer=ck, ckpt_every=5)
        assert loop3.restore_latest() == 5
        ck.close()

    def test_stall_mid_run_detected_and_invisible_to_loss(self):
        """A wedged step (the watchdog's quiet-failure class) must be
        DETECTED yet leave the trajectory untouched — wall-clock noise
        is not training semantics."""
        from edl_tpu.runtime.watchdog import StallWatchdog

        _, ctrl = _loop(CONTROL_4, max_steps=12)
        wd = StallWatchdog(floor_s=0.4, k=8.0, scope="acc-elastic-test")
        wd.start(poll_s=0.05)
        stalled = []

        def on_step(step, loss, world):
            wd.beat(step)
            if step == 6 and not stalled:
                stalled.append(True)
                time.sleep(1.2)  # the wedge

        try:
            _, res = _loop(RESIZE_4_2_8, max_steps=12, on_step=on_step)
        finally:
            wd.stop()
        assert get_counters().get("stalls_detected",
                                  scope="acc-elastic-test") >= 1
        assert ctrl.losses == res.losses

    def test_coordinator_failover_preserves_cursors_and_equivalence(
            self, tmp_path):
        """Coordinator-primary SIGKILL mid-run: the ownership map and
        cursors ride HA replication, the client fails over, the run
        completes, and the trajectory still equals the control —
        the control-plane fault leaves no semantic fingerprint."""
        from edl_tpu.coord import CoordClient, native_available, \
            spawn_ha_pair

        if not native_available():
            pytest.skip("no native coordinator core")
        _, ctrl = _loop(CONTROL_4, max_steps=16)
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
        client = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                             reconnect_window_s=12.0, promote_grace_s=0.2,
                             endpoints=[("127.0.0.1", sb.port)])
        killed = []

        def on_step(step, loss, world):
            if step == 8 and not killed:
                killed.append(True)
                pr.process.send_signal(signal.SIGKILL)
                pr.process.wait(timeout=10)

        try:
            _, res = _loop(RESIZE_4_2_8, max_steps=16, kv=client,
                           job="ha", on_step=on_step)
            assert ctrl.losses == res.losses
            # the promoted standby serves the final cursors + map
            assert (client.host, client.port) == ("127.0.0.1", sb.port)
            assert CursorStore(client, job="ha").load()["step"] == 16
            assert OwnershipMap.load(client, job="ha") is not None
            assert res.rows_duplicated() == 0
        finally:
            client.close()
            pr.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# satellite: exactly-once re-dispatch across a resize (dead worker's shards)
# ---------------------------------------------------------------------------

@pytest.mark.timeout_s(300)
def test_dead_workers_offsets_reowned_exactly_once(tmp_path):
    """A worker dies MID-SHARD and the world shrinks 4→2: the dead
    worker's virtual workers — including their partially-consumed
    offsets — are re-owned by the remapped survivors, and counting every
    row across the whole run shows none duplicated, none dropped."""
    reg, ids = _registry(n=640, shards=5)  # 128-row shards: always mid-shard
    cfg = VirtualConfig(vw_count=4, global_batch=32, job_seed=0)
    kv = local_service()
    ck = ElasticCheckpointer(tmp_path / "ck")
    tr = _trainer(world=4)
    vb = VirtualBatches(cfg, ids, reg.get, passes=1)
    loop = VirtualWorkerLoop(tr, cfg, vb, kv=kv, job="redispatch",
                             checkpointer=ck, ckpt_every=1)
    rep1 = loop.run(max_steps=7, world_size_for=lambda s: 4)
    before = OwnershipMap.load(kv, job="redispatch").mapping
    assert len(set(before.values())) == 4
    # pw2/pw3 die; cursors at step 7 sit mid-shard (7*8=56 of 128 rows)
    tr2 = _trainer(world=2)
    vb2 = VirtualBatches(cfg, ids, reg.get, passes=1)
    loop2 = VirtualWorkerLoop(tr2, cfg, vb2, kv=kv, job="redispatch",
                              checkpointer=ck, ckpt_every=0)
    assert loop2.restore_latest() == 7
    rep2 = loop2.run(world_size_for=lambda s: 2)  # drain the pass
    after = OwnershipMap.load(kv, job="redispatch").mapping
    assert set(after.values()) == {"pw0", "pw1"}
    # every VW the dead workers owned is re-owned by a survivor
    orphaned = [v for v, w in before.items() if w in ("pw2", "pw3")]
    assert orphaned and all(after[v] in ("pw0", "pw1") for v in orphaned)
    # exactly-once across the WHOLE run
    rows: dict[int, int] = {}
    for rep in (rep1, rep2):
        for gid, c in rep.rows_trained.items():
            rows[gid] = rows.get(gid, 0) + c
    total = len(rep1.losses + rep2.losses) * cfg.global_batch
    assert sum(rows.values()) == total
    assert all(c == 1 for c in rows.values()), \
        f"duplicated rows: {[g for g, c in rows.items() if c > 1][:5]}"
    assert len(rows) + vb2.rows_dropped_remainder == 640
    ck.close()
