"""ServingJob spec surface: serde round-trips (kebab/camel), CRD
declaration lockstep, validation defaulting, and the compiled manifests
(replica ReplicaSet + Service) — the test_spec_parity.py discipline
applied to the serving kind (doc/serving.md)."""

from __future__ import annotations

import pathlib

import pytest
import yaml

from edl_tpu.api import serde
from edl_tpu.api.types import (
    DEFAULT_IMAGE,
    DEFAULT_SERVING_PORT,
    SERVING_LABEL,
    ResourceRequirements,
    ServingJob,
    ServingSpec,
)
from edl_tpu.api.validation import (
    ValidationError,
    set_defaults_and_validate_serving,
    validate_any,
)
from edl_tpu.controller.jobparser import (
    HEALTH_PORT,
    parse_serving_manifests,
    parse_to_server_group,
    parse_to_serving_service,
    serving_pod_env,
)

CRD_PATH = pathlib.Path(__file__).resolve().parent.parent / "k8s" / "crd.yaml"


def make_job(**server) -> ServingJob:
    defaults = dict(model_dir="/models/m", min_replicas=2, max_replicas=8,
                    slo_p99_ms=50.0, max_batch_size=16)
    defaults.update(server)
    return ServingJob(name="svc", namespace="prod",
                      image="edl-tpu/serve:latest", port=8500,
                      spec=ServingSpec(**defaults))


# ------------------------------------------------------------------- serde

def test_round_trip_preserves_everything():
    job = make_job(env={"A": "1"}, target_qps_per_replica=40.0,
                   max_queue_ms=1.5, drain_timeout_s=7.0, reload_poll_s=2.0,
                   resources=ResourceRequirements(
                       limits={"google.com/tpu": "4"}))
    doc = serde.serving_job_to_dict(job)
    assert doc["kind"] == "ServingJob"
    back = serde.serving_job_from_dict(doc)
    assert back == job
    assert serde.serving_job_from_yaml(serde.serving_job_to_yaml(job)) == job


def test_kebab_and_camel_spellings_accepted():
    doc = {
        "kind": "ServingJob", "metadata": {"name": "svc"},
        "spec": {"hostNetwork": True, "server": {
            "modelDir": "/m",
            "minReplicas": 2,
            "max-replicas": 8,
            "sloP99Ms": 50,
            "max-batch-size": 32,
            "maxQueueMs": 3,
            "drain-timeout-s": 9,
            "target-qps-per-replica": 25,
            "reloadPollS": 1,
        }},
    }
    job = serde.serving_job_from_dict(doc)
    s = job.spec
    assert job.host_network is True
    assert s.model_dir == "/m"
    assert (s.min_replicas, s.max_replicas) == (2, 8)
    assert s.slo_p99_ms == 50.0
    assert s.max_batch_size == 32
    assert s.max_queue_ms == 3.0
    assert s.drain_timeout_s == 9.0
    assert s.target_qps_per_replica == 25.0
    assert s.reload_poll_s == 1.0


def test_snake_wins_when_both_spellings_present():
    doc = {"kind": "ServingJob", "metadata": {"name": "svc"},
           "spec": {"server": {"min_replicas": 3, "minReplicas": 5}}}
    assert serde.serving_job_from_dict(doc).spec.min_replicas == 3


def test_kind_dispatch():
    sj = serde.manifest_from_dict(serde.serving_job_to_dict(make_job()))
    assert isinstance(sj, ServingJob)
    tj = serde.manifest_from_dict({"kind": "TrainingJob",
                                   "metadata": {"name": "t"}, "spec": {}})
    assert not isinstance(tj, ServingJob)
    with pytest.raises(ValueError):
        serde.serving_job_from_dict({"kind": "TrainingJob",
                                     "metadata": {"name": "t"}})


# ---------------------------------------------------------- CRD lockstep

def _serving_crd_schema() -> dict:
    for doc in yaml.safe_load_all(CRD_PATH.read_text()):
        if (doc and doc.get("kind") == "CustomResourceDefinition"
                and doc["spec"]["names"]["plural"] == "servingjobs"):
            return doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    raise AssertionError("servingjobs CRD missing from k8s/crd.yaml")


def test_every_alias_is_declared_in_the_crd():
    """The serde alias set is DERIVED from the spec dataclass; the CRD
    must declare every spelling (canonical + aliases) or a conformant
    apiserver prunes what the CLI accepts — the exact drift class
    test_crd_pruning.py exists for, now covering the serving kind."""
    schema = _serving_crd_schema()
    spec_props = schema["properties"]["spec"]["properties"]
    server_props = spec_props["server"]["properties"]
    serving_fields = serde._serving_fields()
    for alias, snake in serde.SERVING_ALIASES.items():
        where = server_props if snake in serving_fields else spec_props
        assert snake in where, f"canonical {snake} undeclared"
        assert alias in where, f"alias {alias} (-> {snake}) undeclared"


def test_crd_schema_survives_stub_pruning():
    """A manifest written in mixed spellings keeps every field through a
    conformant apiserver's structural-schema pruning (the shipped stub's
    admission path)."""
    from tests.k8s_stub import load_crd_schemas, prune_per_schema

    schema = load_crd_schemas()[("edl.tpu", "servingjobs")]
    spec = {"image": "i", "server": {"minReplicas": 2, "max-replicas": 4,
                                     "slo_p99_ms": 9.5}}
    pruned = prune_per_schema(spec, schema["properties"]["spec"])
    assert pruned == spec


# ------------------------------------------------------------- validation

def test_defaults_applied():
    job = ServingJob(name="svc", spec=ServingSpec(min_replicas=1,
                                                  max_replicas=1))
    set_defaults_and_validate_serving(job)
    assert job.image == DEFAULT_IMAGE
    assert job.port == DEFAULT_SERVING_PORT


@pytest.mark.parametrize("server,err", [
    (dict(min_replicas=0), "min_replicas"),
    (dict(min_replicas=4, max_replicas=2), "max_replicas"),
    (dict(slo_p99_ms=-1), "slo_p99_ms"),
    (dict(max_batch_size=0), "max_batch_size"),
    (dict(max_queue_ms=-0.5), "max_queue_ms"),
    (dict(target_qps_per_replica=-2), "target_qps_per_replica"),
])
def test_rejections(server, err):
    job = ServingJob(name="svc", spec=ServingSpec(**server))
    with pytest.raises(ValidationError, match=err):
        set_defaults_and_validate_serving(job)


def test_elastic_needs_a_scaling_signal():
    job = ServingJob(name="svc", spec=ServingSpec(
        min_replicas=1, max_replicas=4, slo_p99_ms=0.0,
        target_qps_per_replica=0.0))
    with pytest.raises(ValidationError, match="scaling signal"):
        set_defaults_and_validate_serving(job)
    job.spec.slo_p99_ms = 25.0
    set_defaults_and_validate_serving(job)  # now fine


def test_validate_any_dispatches():
    job = make_job()
    validate_any(job)
    with pytest.raises(ValidationError):
        validate_any(ServingJob(name="", spec=ServingSpec()))


def test_topology_chip_limit_agreement():
    from edl_tpu.api.types import TpuTopology

    job = make_job(topology=TpuTopology.parse("2x2"),
                   resources=ResourceRequirements(
                       limits={"google.com/tpu": "8"}))
    with pytest.raises(ValidationError, match="disagrees"):
        set_defaults_and_validate_serving(job)
    job.spec.resources = ResourceRequirements(
        limits={"google.com/tpu": "4"})
    set_defaults_and_validate_serving(job)
    assert job.tpu_chips_per_replica() == 4


# -------------------------------------------------------------- jobparser

def test_manifests_are_replicaset_plus_service():
    job = make_job()
    mans = parse_serving_manifests(job)
    assert [m["kind"] for m in mans] == ["ReplicaSet", "Service"]
    rs = parse_to_server_group(job)
    assert rs["metadata"]["name"] == "svc-server"
    assert rs["metadata"]["namespace"] == "prod"
    assert rs["spec"]["replicas"] == job.spec.min_replicas
    assert rs["metadata"]["labels"] == {SERVING_LABEL: "svc"}
    pod = rs["spec"]["template"]["spec"]
    assert pod["restartPolicy"] == "Always"  # ReplicaSet semantics
    c = pod["containers"][0]
    assert c["command"][-1] == "start_server"
    # the ready gate: readiness probes /healthz, which is 503 until the
    # serving step is compiled — traffic shifts only after
    assert c["readinessProbe"]["httpGet"]["port"] == HEALTH_PORT
    assert c["livenessProbe"]["httpGet"]["path"] == "/healthz"

    svc = parse_to_serving_service(job)
    assert svc["spec"]["selector"] == {SERVING_LABEL: "svc"}
    assert {p["port"] for p in svc["spec"]["ports"]} == {8500, HEALTH_PORT}


def test_pod_env_contract_and_user_override():
    job = make_job(env={"EDL_SERVING_MAX_BATCH": "64", "EXTRA": "x"})
    env = serving_pod_env(job)
    assert env["EDL_SERVING_MODEL_DIR"] == "/models/m"
    assert env["EDL_SERVING_SLO_P99_MS"] == "50.0"
    assert env["EDL_SERVING_MAX_BATCH"] == "64"  # user wins
    assert env["EXTRA"] == "x"
    assert env["EDL_ROLE"] == "server"
