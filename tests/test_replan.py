"""Dynamic reparallelization: MeshShape resolution, transfer-plan
accounting, minimal-transfer shape choice, the live dp×fsdp resize
through the transactional path, and the shape-hint control-plane flow.

Runs on the virtual 8-device CPU mesh (tests/conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import mlp
from edl_tpu.parallel.mesh import (
    MeshShape,
    MeshSpec,
    make_mesh,
    tree_shardings,
)
from edl_tpu.parallel.replan import (
    candidate_shapes,
    choose_shape,
    collective_stats,
    plan_reshard,
    propose_shape,
    total_collective_counts,
)
from edl_tpu.runtime.elastic import ElasticTrainer


def synthetic_classification(n=512, dim=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3
    y = rng.integers(0, classes, size=n)
    x = centers[y] + rng.normal(size=(n, dim))
    return x.astype(np.float32), y.astype(np.int32)


def make_trainer(n0=4, kind="fsdp", spec=None, **kw):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(
        mlp.loss_fn, params, optax.adam(1e-2),
        spec=spec or MeshSpec(dp=-1),
        param_sharding=kind, initial_world_size=n0, **kw,
    )


# -- MeshShape ---------------------------------------------------------------


def test_mesh_shape_resolution_paths():
    assert MeshShape.resolve(4, spec=MeshSpec(dp=-1)) == MeshShape(dp=4)
    assert MeshShape.resolve(8, spec=MeshSpec(dp=2, fsdp=-1)) == \
        MeshShape(dp=2, fsdp=4)
    s = MeshShape(dp=2, fsdp=2)
    assert MeshShape.resolve(s) is s
    assert s.size == 4 and s.describe() == "dp2xfsdp2"
    assert MeshShape().describe() == "1"
    with pytest.raises(ValueError):
        MeshShape(dp=-1)  # shapes are concrete; wildcards live in specs
    with pytest.raises(ValueError):
        MeshShape.resolve(6, spec=MeshSpec(dp=4))  # 6 not resolvable


def test_candidate_shapes_enumerate_dp_fsdp_splits():
    cands = {c.key() for c in candidate_shapes(4)}
    assert cands == {MeshShape(dp=4).key(), MeshShape(dp=2, fsdp=2).key(),
                     MeshShape(fsdp=4).key()}
    # tp/sp inherited when they divide, reset otherwise
    base = MeshShape(tp=2)
    assert all(c.tp == 2 for c in candidate_shapes(8, base=base))
    assert all(c.tp == 1 for c in candidate_shapes(3, base=base))


# -- transfer-plan accounting ------------------------------------------------


def _mesh_shardings(shape, tree, devices, kind="fsdp"):
    mesh = make_mesh(shape.size, shape.to_spec(), devices=devices)
    return mesh, tree_shardings(mesh, tree, kind)


def test_shape_preserving_plan_moves_nothing_and_beats_naive():
    devs = jax.devices()[:4]
    tree = {"w": jnp.zeros((16, 32)), "b": jnp.zeros((4,))}
    shape = MeshShape(dp=2, fsdp=2)
    _, sh = _mesh_shardings(shape, tree, devs)
    plan = plan_reshard(tree, sh, sh, shape, shape)
    assert plan.bytes_moved == 0
    assert plan.bytes_naive > 0
    assert plan.bytes_moved < plan.bytes_naive  # strict, the headline claim


def test_grow_plan_classifies_ici_vs_dcn():
    devs = jax.devices()
    tree = {"w": jnp.zeros((16, 32))}
    _, sh2 = _mesh_shardings(MeshShape(fsdp=2), tree, devs[:2])
    _, sh4 = _mesh_shardings(MeshShape(fsdp=4), tree, devs[:4])
    grow = plan_reshard(tree, sh2, sh4, MeshShape(fsdp=2), MeshShape(fsdp=4))
    # every byte the joiners need exists on a surviving device → pure ici
    assert grow.bytes_ici > 0 and grow.bytes_dcn == 0
    assert grow.bytes_stay + grow.bytes_ici == grow.bytes_total
    # shrink: shards held ONLY by departing devices must cross the
    # boundary (the host/DCN residue the fallback path exists for)
    shrink = plan_reshard(tree, sh4, sh2,
                          MeshShape(fsdp=4), MeshShape(fsdp=2))
    assert shrink.bytes_dcn > 0
    assert shrink.bytes_moved < shrink.bytes_naive


def test_plan_handles_uneven_divisibility():
    """A leaf whose dims don't divide the new axis size is replicated by
    fsdp_sharding — the plan must account it as such, not crash or
    invent fractional shards."""
    devs = jax.devices()[:3]
    tree = {"odd": jnp.zeros((7, 5)), "even": jnp.zeros((6, 4))}
    m1, sh1 = _mesh_shardings(MeshShape(dp=3), tree, devs)
    m3, sh3 = _mesh_shardings(MeshShape(fsdp=3), tree, devs)
    # 7 and 5 both indivisible by 3 → replicated; 6 divides → sharded
    assert sh3["odd"].spec == jax.sharding.PartitionSpec()
    assert sh3["even"].spec != jax.sharding.PartitionSpec()
    plan = plan_reshard(tree, sh1, sh3, MeshShape(dp=3), MeshShape(fsdp=3))
    odd = next(l for l in plan.leaves if "odd" in l.path)
    even = next(l for l in plan.leaves if "even" in l.path)
    # replicated → every device already holds it, nothing moves
    assert odd.bytes_moved == 0 and odd.bytes_stay == 3 * odd.nbytes
    # sharded-from-replicated → devices drop bytes, fetch none
    assert even.bytes_moved == 0
    assert plan.max_device_bytes == odd.nbytes + even.nbytes // 3


def test_choose_shape_minimizes_transfer_and_respects_memory():
    devs = jax.devices()[:4]
    tree = {"w": jnp.zeros((16, 32)), "b": jnp.zeros((4,))}
    shape0 = MeshShape(dp=4)
    _, sh0 = _mesh_shardings(shape0, tree, devs)
    # unconstrained from pure-dp: staying pure-dp moves zero bytes and
    # wins the dp-dominant tie-break
    best, plan = choose_shape(tree, sh0, 4, devs, "fsdp")
    assert best == shape0 and plan.bytes_moved == 0
    # a per-chip budget below the replicated footprint forces the fsdp
    # pivot — the dp→fsdp escape hatch
    total = sum(l.nbytes for l in jax.tree.leaves(tree))
    best2, plan2 = choose_shape(tree, sh0, 4, devs, "fsdp",
                                max_bytes_per_device=total // 2)
    assert best2.fsdp > 1
    assert plan2.max_device_bytes <= total // 2
    # impossible budget: hardest sharding wins rather than an exception
    best3, _ = choose_shape(tree, sh0, 4, devs, "fsdp",
                            max_bytes_per_device=1)
    assert best3.fsdp == 4


def test_propose_shape_pivots_dp_to_fsdp_on_memory_pressure():
    # fits replicated → pure dp
    assert propose_shape(8, state_bytes=100, max_bytes_per_device=100) == \
        MeshShape(dp=8)
    # half fits → fsdp 2
    assert propose_shape(8, 100, 50) == MeshShape(dp=4, fsdp=2)
    # nothing fits → shard as hard as the world allows
    assert propose_shape(8, 100, 1) == MeshShape(fsdp=8)
    # no budget → legacy behavior
    assert propose_shape(6, 100) == MeshShape(dp=6)
    # fixed tp rides along
    assert propose_shape(8, 100, 50, base=MeshShape(tp=2)) == \
        MeshShape(dp=2, fsdp=2, tp=2)


def test_collective_stats_attributes_axes():
    from edl_tpu.parallel.compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(4, MeshSpec(dp=2, fsdp=2))

    def body(x):
        return jax.lax.psum(x, "dp")

    f = shard_map(body, mesh=mesh, in_specs=P("dp", "fsdp"),
                  out_specs=P(None, "fsdp"), check_vma=False)
    x = jax.device_put(jnp.ones((4, 4)),
                       NamedSharding(mesh, P("dp", "fsdp")))
    stats = collective_stats(jax.jit(f).lower(x).compile(), mesh)
    assert "dp" in stats and stats["dp"]["ops"].get("all-reduce", 0) >= 1
    assert stats["dp"]["bytes"] > 0
    assert total_collective_counts(stats)["all-reduce"] >= 1


# -- the live dp×fsdp shape change (acceptance) ------------------------------


def test_live_shape_change_4x1_to_2x2_preserves_state():
    """The headline: a (4,1)→(2,2) re-split on 4 CPU devices goes through
    the transactional resize — no checkpoint round-trip, loss continuity
    exact, params bit-identical, recorded bytes_moved strictly under the
    plan's own gather-scatter bound."""
    x, y = synthetic_classification()
    t = make_trainer(n0=4, kind="fsdp")
    for i in range(8):
        t.step((x[i * 64:(i + 1) * 64], y[i * 64:(i + 1) * 64]))
    ev_before = t.eval_loss((x, y))
    before = jax.tree.map(np.asarray, t.state.params)
    assert t.shape == MeshShape(dp=4)

    assert t.resize(MeshShape(dp=2, fsdp=2)) is True
    assert t.shape == MeshShape(dp=2, fsdp=2)
    assert t.world_size == 4  # same chips, different split

    after = jax.tree.map(np.asarray, t.state.params)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool(np.array_equal(a, b)), before, after))
    assert abs(t.eval_loss((x, y)) - ev_before) < 1e-5

    evt = t.resize_events[-1]
    assert evt["shape"] == "dp2xfsdp2"
    assert evt["replan_ms"] >= 0.0 and evt["transfer"] == "device"
    assert evt["bytes_moved"] < evt["bytes_naive"]  # strict (== 0 here)

    # params really are fsdp-sharded now (not silently replicated)
    w = t.state.params["w1"]
    assert max(s.data.nbytes for s in w.addressable_shards) == w.nbytes // 2

    # and it keeps learning on the new layout
    for i in range(10):
        t.step((x[i * 32:(i + 1) * 32], y[i * 32:(i + 1) * 32]))
    assert np.isfinite(t.eval_loss((x, y)))


def test_shape_preserving_resize_degenerates_to_pure_dp_bit_identically():
    """resize(n) through the int path and resize(MeshShape(dp=n)) are the
    SAME layout: identical cache key, identical mesh, bit-identical
    state — the legacy path is a degenerate case of the shape path, not
    a parallel implementation."""
    x, y = synthetic_classification(n=128)
    a = make_trainer(n0=2, kind="replicated")
    b = make_trainer(n0=2, kind="replicated")
    a.step((x[:64], y[:64]))
    b.step((x[:64], y[:64]))
    assert a.resize(4) is True
    assert b.resize(MeshShape(dp=4)) is True
    assert a._cache_key(4) == b._cache_key(MeshShape(dp=4))
    assert a.shape == b.shape == MeshShape(dp=4)
    pa = jax.tree.map(np.asarray, a.state.params)
    pb = jax.tree.map(np.asarray, b.state.params)
    assert jax.tree.all(jax.tree.map(
        lambda u, v: bool(np.array_equal(u, v)), pa, pb))
    # the int resize is a no-op against the equal shape (and vice versa)
    assert a.matches(MeshShape(dp=4)) and b.matches(4)
    assert a.resize(MeshShape(dp=4)) is True and a.resizes == 1


def test_same_size_different_shapes_are_distinct_cache_entries():
    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=4, kind="fsdp")
    t.step((x[:64], y[:64]))
    assert t.resize(MeshShape(dp=2, fsdp=2))
    t.step((x[:64], y[:64]))
    assert t.resize(4)  # back to pure dp — a cache hit, not a recompile
    keys = set(t._step_cache)
    assert len(keys) == 2 and {k[0] for k in keys} == {4}
    # oscillating back reuses the exact staged mesh (stale-mesh guard)
    mesh_22 = t._step_cache[t._cache_key(MeshShape(dp=2, fsdp=2))].mesh
    assert t.resize(MeshShape(dp=2, fsdp=2))
    assert t.mesh is mesh_22


def test_shape_resize_rollback_restores_old_layout(monkeypatch):
    """A mid-reshard failure during a SHAPE change rolls back to the old
    layout (mesh identity, shape, live training) and the retry lands."""
    from edl_tpu.runtime import elastic as elastic_mod

    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=4, kind="fsdp")
    t.step((x[:64], y[:64]))
    old_mesh, old_shape = t.mesh, t.shape
    ev0 = t.eval_loss((x[:64], y[:64]))

    calls = []
    real = elastic_mod._reshard

    def failing(tree, shardings):
        calls.append(1)
        if len(calls) == 2:  # params staged, opt-state put explodes
            raise RuntimeError("injected: transfer failed mid-reshard")
        return real(tree, shardings)

    monkeypatch.setattr(elastic_mod, "_reshard", failing)
    assert t.resize(MeshShape(dp=2, fsdp=2)) is False
    assert t.mesh is old_mesh and t.shape == old_shape
    assert t.resizes_failed == 1 and t.resizes == 0
    assert t.eval_loss((x[:64], y[:64])) == pytest.approx(ev0, rel=1e-6)
    assert np.isfinite(t.step((x[:64], y[:64])))
    monkeypatch.setattr(elastic_mod, "_reshard", real)
    assert t.resize(MeshShape(dp=2, fsdp=2)) is True
    assert t.shape == MeshShape(dp=2, fsdp=2)


def test_host_fallback_retries_then_rolls_back(monkeypatch):
    """With the opt-in enabled, a failed device-to-device reshard retries
    through host memory (counted); when the host path fails too, the
    transactional rollback still holds."""
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.runtime import elastic as elastic_mod

    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=4, kind="fsdp", reshard_host_fallback=True)
    t.step((x[:64], y[:64]))
    before = get_counters().get("reshard_host_fallbacks")

    monkeypatch.setattr(
        elastic_mod, "_reshard",
        lambda tree, sh: (_ for _ in ()).throw(
            RuntimeError("injected: no direct transfer path")))
    assert t.resize(MeshShape(dp=2, fsdp=2)) is True  # host path saved it
    assert t.shape == MeshShape(dp=2, fsdp=2)
    assert t.resize_events[-1]["transfer"] == "host"
    assert get_counters().get("reshard_host_fallbacks") == before + 1

    # both paths down → rollback, not a half-moved world
    monkeypatch.setattr(
        elastic_mod, "_reshard_host",
        lambda tree, sh: (_ for _ in ()).throw(
            RuntimeError("injected: host path down too")))
    assert t.resize(4) is False
    assert t.shape == MeshShape(dp=2, fsdp=2)
    assert np.isfinite(t.step((x[:64], y[:64])))


def test_shape_prewarm_hits_skip_compile():
    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=4, kind="fsdp")
    t.step((x[:64], y[:64]))
    t.prewarm([MeshShape(dp=2, fsdp=2)], wait=True)
    assert t.resize(MeshShape(dp=2, fsdp=2))
    evt = t.resize_events[-1]
    assert evt["prewarm_hit"] is True
    assert evt["compile_ms"] < 100.0


def test_resize_phase_histogram_gains_replan_phase():
    from edl_tpu.observability.metrics import get_registry

    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=2, kind="replicated")
    t.step((x[:64], y[:64]))
    assert t.resize(4)
    rendered = get_registry().render()
    assert 'edl_resize_phase_seconds_count{phase="replan"}' in rendered
    assert 'edl_resize_phase_seconds_count{phase="reshard"}' in rendered


# -- control plane: shape hints ---------------------------------------------


def test_autoscaler_shape_policy_hints_full_shape():
    """With mesh_shape_for set, hint_sink fires (uid, MeshShape) at plan
    time; without it, the bare count (back-compat)."""
    from edl_tpu.api.types import (
        RESOURCE_CPU, RESOURCE_MEMORY, ResourceRequirements, TrainerSpec,
        TrainingJob, TrainingJobSpec,
    )
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.scheduler.autoscaler import Autoscaler

    def mk_job(name):
        return TrainingJob(name=name, spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=2, max_instance=8,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"}))))

    c = FakeCluster()
    c.add_node("n0", cpu_milli=8000, memory_mega=100_000)
    hints = []
    a = Autoscaler(
        c, max_load_desired=1.0,
        mesh_shape_for=lambda uid, n: propose_shape(
            n, state_bytes=100, max_bytes_per_device=50))
    a.hint_sink = lambda uid, target: hints.append((uid, target))
    job = mk_job("shaped")
    c.create_resources(job)
    a.on_add(job)
    a.tick()
    assert hints, "plan should have hinted"
    uid, target = hints[-1]
    assert uid == job.full_name
    assert isinstance(target, MeshShape) and target.fsdp == 2

    # a broken shape policy degrades to the bare count, never kills the tick
    hints.clear()
    a.mesh_shape_for = lambda uid, n: (_ for _ in ()).throw(ValueError("x"))
    for i in range(4):
        c.add_system_pod(f"sys-{i}", "n0", cpu_request_milli=1000,
                         memory_request_mega=100)
    a.tick()
    if hints:  # a shrink plan fired: the hint is the raw int
        assert isinstance(hints[-1][1], int)


def test_local_job_shape_policy_reparallelizes_live():
    """End-to-end: a LocalElasticJob with a shape_for policy commits the
    policy's layout when the pod count moves — the full dp→fsdp pivot
    through the real run loop, hint-prewarmed."""
    from edl_tpu.api.types import (
        RESOURCE_CPU, RESOURCE_MEMORY, ResourceRequirements, TrainerSpec,
        TrainingJob, TrainingJobSpec,
    )
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.coord import local_service
    from edl_tpu.runtime.data import ShardRegistry
    from edl_tpu.runtime.local import LocalElasticJob

    x, y = synthetic_classification(n=1024)
    coord = local_service(passes=2)
    reg = ShardRegistry()
    reg.add_arrays(coord, (x, y), num_shards=8)

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=10_000, memory_mega=100_000)
    job = TrainingJob(name="reparallel", spec=TrainingJobSpec(
        fault_tolerant=True,
        trainer=TrainerSpec(
            min_instance=2, max_instance=4,
            resources=ResourceRequirements(
                requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"}))))
    cluster.create_resources(job)
    cluster.update_trainer_parallelism(job, 2)
    cluster.reconcile()

    t = make_trainer(n0=2, kind="fsdp")
    state_bytes = sum(l.nbytes for l in jax.tree.leaves(t.state.params))
    # budget forces fsdp=2 at every world size >= 2
    policy = lambda n: propose_shape(  # noqa: E731
        n, state_bytes=state_bytes,
        max_bytes_per_device=state_bytes // 2 + 1)
    runner = LocalElasticJob(job, cluster, t, coord, reg.fetch,
                             batch_size=64, shape_for=policy,
                             resize_defer_s=0)
    grown = []

    def on_step(step, loss, world):
        if step == 3 and not grown:
            cluster.update_trainer_parallelism(job, 4)
            cluster.reconcile()
            grown.append(True)

    report = runner.run(max_steps=20, on_step=on_step)
    assert report.resizes >= 1
    assert t.shape == MeshShape(dp=2, fsdp=2)  # policy's 4-chip layout
    assert report.resize_bytes_moved and report.resize_replan_ms
    losses = np.asarray(report.losses)
    assert np.isfinite(losses).all()
    # loss continuity across the reparallelizing resize
    b = report.resize_steps[-1]
    pre = losses[max(b - 3, 0):b].mean() if b else losses[0]
    post = losses[b:b + 3].mean()
    assert post < max(pre, 0.05) * 2.0


def test_unresolvable_resize_target_soft_fails():
    """A pod count the spec's fixed axes don't divide is a FAILED resize
    (counted, rolled back), never an exception out of the step loop —
    the autoscaler can land any count it likes (review finding #1)."""
    t = make_trainer(n0=4, kind="replicated", spec=MeshSpec(dp=-1, tp=2))
    x, y = synthetic_classification(n=128)
    t.step((x[:64], y[:64]))
    assert t.matches(3) is False          # no crash
    assert t.is_building(3) is False      # no crash
    failed_before = t.resizes_failed
    assert t.resize(3) is False           # soft-fail, old world live
    assert t.resizes_failed == failed_before + 1
    assert t.world_size == 4
    assert np.isfinite(t.step((x[:64], y[:64])))
    assert t.resize(8) is True            # a divisible count still lands


def test_propose_shape_uses_ceil_division_at_the_budget_boundary():
    """Per-chip footprint is ceil(bytes/fsdp); floor blessed over-budget
    layouts exactly at the boundary (review finding #2)."""
    # 101 B over fsdp=2 is 51 B/chip > 50 — must shard harder, not stop
    s = propose_shape(8, state_bytes=101, max_bytes_per_device=50)
    assert s.fsdp == 4 and -(-101 // s.fsdp) <= 50
    # exact fits still accepted
    assert propose_shape(8, 100, 50) == MeshShape(dp=4, fsdp=2)


def test_collective_stats_async_start_counts_payload_once():
    """`-start` async collectives return (operand alias, output, ...):
    the census must count the payload once, not sum the tuple (review
    finding: sync vs async lowering of one program must agree)."""
    mesh = make_mesh(2, MeshSpec(dp=2))
    sync = ('%ag = f32[8,4]{1,0} all-gather(f32[4,4]{1,0} %p), '
            'replica_groups={{0,1}}, dimensions={0}')
    async_ = ('%ags = (f32[4,4]{1,0}, f32[8,4]{1,0}) '
              'all-gather-start(f32[4,4]{1,0} %p), '
              'replica_groups={{0,1}}, dimensions={0}')
    s_sync = collective_stats(sync, mesh)
    s_async = collective_stats(async_, mesh)
    assert s_sync["dp"]["bytes"] == 8 * 4 * 4
    assert s_async["dp"]["bytes"] == s_sync["dp"]["bytes"]
    assert s_async["dp"]["ops"] == {"all-gather": 1}


def test_local_job_shape_policy_exception_degrades_to_count():
    """A raising shape_for policy must not kill the step loop: the
    target degrades to the bare count (review finding)."""
    from edl_tpu.api.types import (
        RESOURCE_CPU, RESOURCE_MEMORY, ResourceRequirements, TrainerSpec,
        TrainingJob, TrainingJobSpec,
    )
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.runtime.local import LocalElasticJob

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=8000, memory_mega=100_000)
    job = TrainingJob(name="j", spec=TrainingJobSpec(
        fault_tolerant=True,
        trainer=TrainerSpec(
            min_instance=2, max_instance=4,
            resources=ResourceRequirements(
                requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"}))))
    t = make_trainer(n0=2, kind="replicated")

    def bad_policy(n):
        raise ValueError("no factorization for you")

    runner = LocalElasticJob(job, cluster, t, None, None, batch_size=64,
                             shape_for=bad_policy)
    assert runner._target_for(4) == 4  # degraded to the bare count
