"""ChaosMonkey fixture + example-script smoke tests."""

import runpy
import sys
import time
from pathlib import Path

import jax
import numpy as np
import optax
import pytest

from edl_tpu.api.types import (
    JobPhase, RESOURCE_CPU, RESOURCE_MEMORY,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller
from edl_tpu.coord import local_service
from edl_tpu.models import mlp
from edl_tpu.runtime.chaos import ChaosMonkey
from edl_tpu.runtime.data import ShardRegistry
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.local import LocalElasticJob

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"

# ``python examples/x.py`` puts examples/ on sys.path (for _bootstrap);
# runpy.run_path does not — mirror the script environment here.
if str(EXAMPLES) not in sys.path:
    sys.path.insert(0, str(EXAMPLES))


def _wait_until(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def test_chaos_monkey_repeated_kills_job_survives():
    """Kill a trainer every 8 steps; training still drains both passes and
    the FT job stays Running (SURVEY §5.3 build note)."""
    rng = np.random.default_rng(2)
    y = rng.integers(0, 4, size=2048).astype(np.int32)
    x = rng.normal(size=(2048, 16)).astype(np.float32)
    coord = local_service(passes=2)
    reg = ShardRegistry()
    reg.add_arrays(coord, (x, y), num_shards=8)

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=8_000, memory_mega=100_000)
    ctl = Controller(cluster, autoscaler_loop_seconds=0.02,
                     updater_convert_seconds=0.02,
                     updater_confirm_seconds=0.01)
    ctl.start()
    job = TrainingJob(
        name="chaos",
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=2, max_instance=4,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                    limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "100M"},
                ),
            ),
        ),
    )
    ctl.submit(job)
    assert _wait_until(lambda: ctl.phase(job) == JobPhase.RUNNING)

    params = mlp.init(jax.random.key(2), [16, 32, 4])
    trainer = ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                             initial_world_size=2)
    runner = LocalElasticJob(job, cluster, trainer, coord, reg.fetch,
                             batch_size=64)
    monkey = ChaosMonkey(cluster, job, every_n_steps=8, max_kills=4)

    def on_step(step, loss, world):
        monkey(step, loss, world)
        time.sleep(0.002)

    report = runner.run(on_step=on_step)
    ctl.stop()
    assert len(monkey.kills) >= 3  # the monkey actually struck repeatedly
    assert report.steps == 2 * (2048 // 64)  # nothing lost, both passes
    assert ctl.phase(job) == JobPhase.RUNNING


def test_chaos_monkey_respects_max_kills():
    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=8_000, memory_mega=100_000)
    job = TrainingJob(
        name="j",
        spec=TrainingJobSpec(fault_tolerant=True, trainer=TrainerSpec(
            min_instance=2, max_instance=2,
            resources=ResourceRequirements(
                requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "10M"},
                limits={RESOURCE_CPU: "1", RESOURCE_MEMORY: "10M"}))),
    )
    cluster.create_resources(job)
    cluster.reconcile()
    monkey = ChaosMonkey(cluster, job, every_n_steps=1, max_kills=2)
    for step in range(1, 10):
        monkey(step)
    assert len(monkey.kills) == 2


class TestExampleScripts:
    """Smoke-run the cheap examples in-process (the jax-heavy ones are
    exercised via their building blocks in the e2e/runtime tests)."""

    def test_elastic_demo(self, capsys):
        runpy.run_path(str(EXAMPLES / "elastic_demo.py"), run_name="__main__")
        out = capsys.readouterr().out
        assert "pending jobs: 0" in out
        assert "chip utilization" in out

    def test_fit_a_line(self, capsys):
        runpy.run_path(str(EXAMPLES / "fit_a_line.py"), run_name="__main__")
        assert "mse" in capsys.readouterr().out

    def test_examplejob_manifest_valid(self):
        from edl_tpu.api.serde import load_job_file
        from edl_tpu.api.validation import set_defaults_and_validate

        job = load_job_file(str(EXAMPLES / "examplejob.yaml"))
        set_defaults_and_validate(job)
        assert job.elastic() and job.spec.fault_tolerant
        assert job.tpu_chips_per_trainer() == 4
