"""ROADMAP #2 (bounded slice): the LocalElasticJob harness driven by
VirtualBatches instead of first-come task leases — the reference loop
and the production-path harness stop diverging.

The pin: the SAME seeded job run through LocalElasticJob with a
mid-run autoscaler-style resize matches (a) the never-resized
VirtualWorkerLoop control BITWISE (replicated accumulation on CPU) and
(b) trains every row exactly once.  The legacy lease path stays behind
the ``use_virtual_batches=False`` opt-out."""

from __future__ import annotations

import jax
import numpy as np
import optax
import pytest

from edl_tpu.api.types import (
    ResourceRequirements,
    TrainerSpec,
    TrainingJob,
    TrainingJobSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.coord import local_service
from edl_tpu.models import mlp
from edl_tpu.parallel.mesh import MeshSpec
from edl_tpu.runtime.data import ShardRegistry, TaskLeaseBatches
from edl_tpu.runtime.elastic import ElasticTrainer
from edl_tpu.runtime.local import LocalElasticJob
from edl_tpu.runtime.virtual import (
    VirtualBatches,
    VirtualConfig,
    VirtualWorkerLoop,
    loss_divergence,
)

CFG = VirtualConfig(vw_count=4, global_batch=32, job_seed=11)


def _data():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(1024, 16)).astype(np.float32)
    y = rng.integers(0, 4, 1024).astype(np.int32)
    reg = ShardRegistry()
    ids = reg.register_arrays((x, y), num_shards=8)
    return reg, ids


def _trainer(world: int = 2) -> ElasticTrainer:
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(mlp.loss_fn, params, optax.adam(1e-2),
                          spec=MeshSpec(dp=-1), initial_world_size=world,
                          accum_mode="replicated")


def _job(lo=1, hi=8) -> TrainingJob:
    return TrainingJob(name="vj", spec=TrainingJobSpec(
        fault_tolerant=True,
        trainer=TrainerSpec(min_instance=lo, max_instance=hi,
                            resources=ResourceRequirements(
                                requests={"cpu": "1"}))))


def test_harness_virtual_drive_matches_control_bitwise():
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device virtual CPU mesh")
    reg, ids = _data()

    # control: the reference loop, never resized, world 2
    loop = VirtualWorkerLoop(_trainer(2), CFG,
                             VirtualBatches(CFG, ids, reg.get),
                             kv=local_service(), job="ctl")
    control = loop.run(max_steps=16, world_size_for=lambda s: 2)

    # the harness: LocalElasticJob on a live FakeCluster, pods 2→4 mid-run
    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(f"n{i}", cpu_milli=16000, memory_mega=64000)
    job = _job()
    cluster.create_resources(job)
    cluster.update_trainer_parallelism(job, 2)
    coord = local_service()
    runner = LocalElasticJob(
        job, cluster, _trainer(2), coord, fetch=None, batch_size=32,
        virtual=CFG, shard_ids=ids, fetch_shard=reg.get,
        prewarm_neighbors=False)
    grown = []

    def on_step(step, loss, world):
        if step >= 8 and not grown:
            cluster.update_trainer_parallelism(job, 4)  # the autoscaler dial
            grown.append(True)

    report = runner.run(max_steps=16, on_step=on_step)

    assert report.steps == 16
    assert report.resizes == 1
    assert set(report.world_sizes) == {2, 4}
    div = loss_divergence(control.losses, report.losses)
    assert div["bitwise"], div  # the resize is invisible to the loss curve
    # exactly-once: the virtual evidence rides on the report
    assert report.virtual is not None
    assert report.virtual.rows_duplicated() == 0
    assert report.virtual.rows_missing(expected=16 * CFG.global_batch) == 0
    # and the harness published cursors/ownership to the job's coordinator
    assert coord.kv_get(f"vw-map/{job.full_name}") is not None
    assert coord.kv_get(f"vw-cursor/{job.full_name}") is not None


def test_opt_out_keeps_the_lease_path():
    """use_virtual_batches=False (or no virtual config at all) is the
    legacy task-lease drive, unchanged."""
    reg, ids = _data()
    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=16000, memory_mega=64000)
    job = _job()
    cluster.create_resources(job)
    coord = local_service()
    reg.enqueue(coord, ids[:2])
    runner = LocalElasticJob(
        job, cluster, _trainer(1), coord, fetch=reg.fetch, batch_size=32,
        virtual=CFG, shard_ids=ids, fetch_shard=reg.get,
        use_virtual_batches=False, prewarm_neighbors=False)
    report = runner.run(max_steps=4)
    assert report.steps == 4
    assert report.virtual is None  # lease path: no virtual evidence
    assert isinstance(TaskLeaseBatches(coord, "w", reg.fetch, 32),
                      TaskLeaseBatches)
