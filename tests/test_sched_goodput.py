"""The marginal-goodput scheduling objective (doc/scheduling.md):
pricing, priorities, preemption, gang discipline, degraded-mode parity,
and the control-plane wiring (priority field api→serde→CRD, the bounded
advisory log, the serving capacity-curve recorder)."""

import math
from collections import deque
from types import SimpleNamespace

import pytest

from edl_tpu.api.types import (
    RESOURCE_TPU,
    ResourceRequirements,
    SchedPriority,
    ServingJob,
    ServingSpec,
)
from edl_tpu.cluster.resource import ClusterResource, NodeResources
from edl_tpu.observability.goodput import ScalingCurve, load_curve
from edl_tpu.scheduler.planner import (
    OPTIMISTIC_PRIOR,
    PlannedJob,
    _step_marginal,
    plan_cluster,
    scale_all_jobs_dry_run,
    scale_all_jobs_goodput,
)
from edl_tpu.scheduler.topology import POW2_POLICY
from tests.test_planner import (
    big_cluster,
    make_job,
    make_multi_domain_job,
    two_domain_cluster,
)


def curve(points, job=""):
    c = ScalingCurve(job=job)
    for ws, tok in sorted(points.items()):
        c.observe(ws, tok)
    return c


def curves_for(mapping):
    """uid → ScalingCurve source, as the autoscaler wires it."""
    return lambda uid: mapping.get(uid)


def priced_job(name, chips, lo, hi, p, priority=SchedPriority.NORMAL,
               policy=None):
    j = make_job(name, "1", "1", "1Mi", "1Mi", str(chips), lo, hi, p,
                 **({"policy": policy} if policy else {}))
    j.config.spec.trainer.priority = int(priority)
    return j


def one_domain_cluster(nodes=2, chips_per_node=4):
    n = NodeResources(
        nodes_cpu_idle_milli={f"n{i}": 8000 for i in range(nodes)},
        nodes_memory_free_mega={f"n{i}": 16000 for i in range(nodes)},
        nodes_tpu_free={f"n{i}": chips_per_node for i in range(nodes)},
        nodes_ici_domain={f"n{i}": "D" for i in range(nodes)},
    )
    return ClusterResource(cpu_total_milli=8000 * nodes,
                           memory_total_mega=16000 * nodes,
                           tpu_total=chips_per_node * nodes, nodes=n)


# ---------------------------------------------------------------------------
# degraded mode: bit-for-bit count-packing parity
# ---------------------------------------------------------------------------


def test_degraded_mode_matches_count_packing_bit_for_bit():
    """No curve resolves → the plan IS the count packer's plan, same
    dict, on representative fixtures (the acceptance parity pin)."""
    fixtures = []
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 8, 1,
                 policy=POW2_POLICY)
    fixtures.append(([j], big_cluster()))
    a = make_job("a", "1", "1", "1Mi", "1Mi", "2", 0, 4, 0)
    b = make_job("b", "1", "1", "1Mi", "1Mi", "2", 0, 2, 0)
    fixtures.append(([a, b], two_domain_cluster()))
    for cv in (None, lambda uid: None, curves_for({})):
        for jobs, r in fixtures:
            expect = scale_all_jobs_dry_run(jobs, r.copy(), 1.0)
            plan = plan_cluster(jobs, r.copy(), 1.0, curves=cv)
            assert plan.mode == "degraded"
            assert plan.diff == expect
            assert not plan.preemptions and not plan.rollbacks


def test_raising_curve_source_degrades_not_raises():
    def broken(uid):
        raise RuntimeError("curve store unreachable")

    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 4, 1)
    r = big_cluster()
    plan = plan_cluster([j], r, 1.0, curves=broken)
    assert plan.mode == "degraded"
    assert plan.diff == scale_all_jobs_dry_run([j], r, 1.0)


def test_count_objective_is_the_reference_packer_wrapped():
    j = make_job("j", "1", "1", "1Mi", "1Mi", "0", 1, 4, 1)
    r = big_cluster()
    plan = plan_cluster([j], r, 1.0, curves=curves_for(
        {"default/j": curve({1: 100.0})}), objective="count")
    assert plan.mode == "count"
    assert plan.diff == scale_all_jobs_dry_run([j], r, 1.0)


# ---------------------------------------------------------------------------
# the marginal objective
# ---------------------------------------------------------------------------


def test_marginal_packing_prefers_steep_curve():
    """Two identical jobs, one steep curve, one flat: the contended
    chips all flow to the steep one — the uniform-fulfillment leveling
    the count packer would do is exactly what this objective replaces."""
    r = one_domain_cluster(nodes=1, chips_per_node=4)
    steep = priced_job("steep", 1, 0, 4, 0)
    flat = priced_job("flat", 1, 0, 4, 0)
    cv = curves_for({
        "default/steep": curve({1: 100.0, 2: 200.0, 4: 400.0}),
        "default/flat": curve({1: 50.0, 2: 52.0, 4: 53.0}),
    })
    plan = plan_cluster([steep, flat], r, 1.0, curves=cv)
    assert plan.mode == "goodput"
    assert plan.diff["default/steep"] == 4
    assert plan.diff["default/flat"] == 0
    # the evidence trail carries the price the last granted step paid
    assert plan.marginals["default/steep"] == pytest.approx(100.0)
    # the count packer would have leveled them 2/2
    leveled = scale_all_jobs_dry_run([steep, flat], r, 1.0)
    assert leveled["default/steep"] == leveled["default/flat"] == 2


def test_optimistic_prior_explores_unmeasured_jobs():
    """An unmeasured job outbids a measured one (prior = +inf): it gets
    capacity, runs, and becomes measured — exploration never starves."""
    r = one_domain_cluster(nodes=1, chips_per_node=4)
    measured = priced_job("measured", 1, 0, 4, 0)
    fresh = priced_job("fresh", 1, 0, 4, 0)
    cv = curves_for({"default/measured": curve({1: 100.0, 2: 190.0})})
    plan = plan_cluster([measured, fresh], r, 1.0, curves=cv)
    assert plan.diff["default/fresh"] == 4
    assert plan.diff["default/measured"] == 0


def test_zero_marginal_jobs_still_pack_leftover_capacity():
    """A measured-flat job is deprioritized, not starved: idle chips
    are pure waste, so leftovers still pack after every better bidder
    is satisfied."""
    r = one_domain_cluster(nodes=2, chips_per_node=4)  # 8 chips
    steep = priced_job("steep", 1, 0, 4, 0)
    flat = priced_job("flat", 1, 0, 4, 0)
    cv = curves_for({
        "default/steep": curve({1: 100.0, 2: 200.0}),
        "default/flat": curve({1: 100.0, 2: 100.0, 4: 100.0}),
    })
    plan = plan_cluster([steep, flat], r, 1.0, curves=cv)
    assert plan.diff["default/steep"] == 4
    assert plan.diff["default/flat"] == 4  # leftovers, not starvation


def test_fresh_pending_gang_does_not_preempt_yet():
    """The age gate: a gang pending for ZERO plans reserves free
    capacity but shrinks no one — the kubelet may well place it before
    the next tick, and an arrival burst at light load must not churn
    running jobs."""
    r = one_domain_cluster(nodes=2, chips_per_node=4)
    victim = priced_job("victim", 2, 1, 3, 2)
    r.nodes.nodes_tpu_free["n0"] = 0
    r.nodes.nodes_tpu_free["n1"] = 2
    r.tpu_limit = 4 + 4
    r.jobs_ici_domain = {"default/victim": "D"}
    gang = priced_job("gang", 2, 2, 2, 2, priority=SchedPriority.HIGH)
    gang.pending = 2                       # fresh: pending_age == 0
    cv = curves_for({"default/victim": curve({1: 100.0, 2: 101.0})})
    plan = plan_cluster([victim, gang], r, 1.0, curves=cv)
    assert not plan.preemptions
    assert plan.diff["default/victim"] == 0


def test_pending_high_gang_preempts_cheapest_victim_to_min():
    """An AGED HIGH pending gang shrinks strictly-lower-priority elastic
    victims — cheapest marginal FIRST, never below min_instance — until
    its whole gang fits the domain."""
    r = one_domain_cluster(nodes=2, chips_per_node=4)  # 8 chips in D
    # V_flat runs 2x2 chips (cheap marginal), V_steep runs 1x2 (pricey)
    v_flat = priced_job("vflat", 2, 1, 3, 2)
    v_steep = priced_job("vsteep", 2, 1, 2, 1)
    r.nodes.nodes_tpu_free["n0"] = 0       # v_flat's 4 chips
    r.nodes.nodes_tpu_free["n1"] = 2       # v_steep's 2 chips, 2 free
    r.tpu_limit = 6 + 4                    # placed + the gang's pending
    r.cpu_request_milli = 3 * 1_000_000 + 2 * 1_000_000
    r.jobs_ici_domain = {"default/vflat": "D", "default/vsteep": "D"}
    gang = priced_job("gang", 2, 2, 2, 2, priority=SchedPriority.HIGH)
    gang.pending = 2                       # whole min gang unplaced
    gang.pending_age = 1                   # aged past the kubelet grace
    cv = curves_for({
        "default/vflat": curve({1: 100.0, 2: 101.0}),
        "default/vsteep": curve({1: 400.0}),
    })
    plan = plan_cluster([v_flat, v_steep, gang], r, 1.0, curves=cv)
    assert plan.mode == "goodput"
    assert plan.preemptions, "no preemption planned"
    assert {p["victim"] for p in plan.preemptions} == {"default/vflat"}
    assert plan.diff["default/vflat"] == -1          # one step, to free 2
    assert plan.diff["default/vsteep"] == 0          # pricier: untouched
    assert v_flat.parallelism + plan.diff["default/vflat"] >= 1  # >= min
    assert not plan.rollbacks


def test_gang_rolled_back_whole_when_no_domain_feasible():
    """A gang no single domain can hold — even with every eligible
    victim at floor — is rolled back whole: nothing is shrunk for it."""
    r = two_domain_cluster()  # 2 domains x 4 chips
    # each domain: 2 chips held by a low-prio victim at min (nothing
    # shrinkable), 2 free — a 6-chip single-domain gang can never land
    va = priced_job("va", 2, 1, 1, 1, priority=SchedPriority.LOW)
    vb = priced_job("vb", 2, 1, 1, 1, priority=SchedPriority.LOW)
    r.nodes.nodes_tpu_free["a0"] = 0
    r.nodes.nodes_tpu_free["b0"] = 0
    r.tpu_limit = 4 + 6
    r.jobs_ici_domain = {"default/va": "A", "default/vb": "B"}
    gang = priced_job("gang", 2, 3, 3, 3, priority=SchedPriority.HIGH)
    gang.pending = 3
    gang.pending_age = 1
    cv = curves_for({"default/va": curve({1: 100.0})})
    plan = plan_cluster([va, vb, gang], r, 1.0, curves=cv)
    assert plan.rollbacks and plan.rollbacks[0]["job"] == "default/gang"
    assert not plan.preemptions
    assert all(d >= 0 for d in plan.diff.values()), plan.diff


def test_equal_priority_pending_rides_overcommit_drain():
    """A NORMAL gang among NORMAL incumbents cannot preempt — but its
    pending claim over-commits the cluster and the drain shrinks the
    cheapest-marginal victim (the count packer's admission-by-shrinking
    re-ranked by marginal value)."""
    r = one_domain_cluster(nodes=2, chips_per_node=4)
    v_flat = priced_job("vflat", 2, 1, 3, 2)   # 4 chips, flat curve
    v_steep = priced_job("vsteep", 2, 1, 2, 2)  # 4 chips, steep curve
    r.nodes.nodes_tpu_free["n0"] = 0
    r.nodes.nodes_tpu_free["n1"] = 0
    r.tpu_limit = 8 + 2                        # full + a 2-chip pending gang
    r.jobs_ici_domain = {"default/vflat": "D", "default/vsteep": "D"}
    gang = priced_job("gang", 2, 1, 1, 1)
    gang.pending = 1
    cv = curves_for({
        "default/vflat": curve({1: 100.0, 2: 102.0}),
        "default/vsteep": curve({1: 100.0, 2: 300.0}),
    })
    plan = plan_cluster([v_flat, v_steep, gang], r, 1.0, curves=cv)
    assert not plan.preemptions                # no priority edge
    assert any(rec["reason"] == "overcommit" for rec in plan.reclaims)
    assert plan.diff["default/vflat"] == -1    # cheapest marginal drained
    assert plan.diff["default/vsteep"] == 0


def test_rebalance_saturated_serving_outbids_flat_trainer():
    """Train+serve arbitration: a saturated serving fleet (steep
    measured QPS curve) reclaims a chip from a flat-curve trainer in
    the same marginal loop — the shrink and the paired grant land in
    ONE plan, actuated as planned resizes."""
    r = one_domain_cluster(nodes=1, chips_per_node=4)
    res = ResourceRequirements(requests={"cpu": "1", "memory": "1Mi"},
                               limits={RESOURCE_TPU: "1"})
    fleet = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=1, max_replicas=4, resources=res,
        priority=SchedPriority.NORMAL))
    serving = PlannedJob(config=fleet, parallelism=1)
    trainer = priced_job("batch", 1, 1, 4, 3)
    r.nodes.nodes_tpu_free["n0"] = 0           # 1 + 3 chips: cluster full
    r.tpu_limit = 4
    r.jobs_ici_domain = {"default/batch": "D"}
    cv = curves_for({
        "default/fleet": curve({1: 500.0, 2: 1000.0}),  # saturated: linear
        "default/batch": curve({1: 100.0, 3: 110.0}),   # flat
    })
    plan = plan_cluster([serving, trainer], r, 1.0, curves=cv)
    assert plan.diff["default/batch"] == -1
    assert plan.diff["default/fleet"] == 1
    assert any(rec["reason"] == "rebalance" and
               rec["victim"] == "default/batch" for rec in plan.reclaims)


def test_unmeasured_holdings_are_never_reclaimed():
    """Rebalance needs a measured victim: optimistically-priced
    (unmeasured) holdings are protected — exploration is not preempted
    by exploitation."""
    r = one_domain_cluster(nodes=1, chips_per_node=4)
    grower = priced_job("grower", 1, 1, 4, 1)
    fresh = priced_job("fresh", 1, 1, 4, 3)
    r.nodes.nodes_tpu_free["n0"] = 0
    r.tpu_limit = 4
    r.jobs_ici_domain = {"default/grower": "D", "default/fresh": "D"}
    cv = curves_for({"default/grower": curve({1: 500.0, 2: 1000.0})})
    plan = plan_cluster([grower, fresh], r, 1.0, curves=cv)
    assert plan.diff["default/fresh"] == 0
    assert not plan.reclaims and not plan.preemptions


def test_priority_tiers_rule_before_marginals():
    """A HIGH flat-curve job still outbids a NORMAL steep-curve job for
    the next chip: priority is the outer sort key, marginal the inner."""
    r = one_domain_cluster(nodes=1, chips_per_node=2)
    high_flat = priced_job("hflat", 1, 0, 2, 0, priority=SchedPriority.HIGH)
    norm_steep = priced_job("nsteep", 1, 0, 2, 0)
    cv = curves_for({
        "default/hflat": curve({1: 10.0, 2: 11.0}),
        "default/nsteep": curve({1: 100.0, 2: 200.0}),
    })
    plan = plan_cluster([high_flat, norm_steep], r, 1.0, curves=cv)
    assert plan.diff["default/hflat"] == 2
    assert plan.diff["default/nsteep"] == 0


# ---------------------------------------------------------------------------
# multi-domain contention stress under the new objective (VERDICT r5 #8)
# ---------------------------------------------------------------------------


def test_spanning_and_pinned_contention_under_goodput_objective():
    """The VERDICT r5 #8 contention case re-run under the marginal
    objective with measured curves on both jobs: the pinned job never
    leaves its fabric, the spanning job takes the remainder, every chip
    packs — the same world the count packer reaches."""
    nodes = NodeResources(
        nodes_cpu_idle_milli={n: 8000 for n in ("a0", "a1", "b0", "b1")},
        nodes_memory_free_mega={n: 16000 for n in ("a0", "a1", "b0", "b1")},
        nodes_tpu_free={"a0": 0, "a1": 2, "b0": 0, "b1": 2},
        nodes_ici_domain={"a0": "A", "a1": "A", "b0": "B", "b1": "B"},
    )
    r = ClusterResource(cpu_total_milli=32_000, memory_total_mega=64_000,
                        tpu_total=8, tpu_limit=4, nodes=nodes)
    r.jobs_ici_domain["default/p"] = "A"
    pinned = make_job("p", "1", "1", "1Mi", "1Mi", "2", 1, 2, 1)
    spanning = make_multi_domain_job("s", 1, 3, 1, chips="2")
    cv = curves_for({
        "default/p": curve({1: 100.0, 2: 220.0}),   # 60 tok/s per chip
        "default/s": curve({1: 100.0, 2: 190.0}),   # 45 tok/s per chip
    })
    plan = plan_cluster([pinned, spanning], r.copy(), 1.0, curves=cv)
    assert plan.mode == "goodput"
    # the pinned job's step lands in ITS fabric (A) and the spanning
    # job takes the remainder: every chip packed, nothing strandable
    assert pinned.parallelism + plan.diff["default/p"] == 2
    assert spanning.parallelism + plan.diff["default/s"] == 2
    # with these curves the marginal objective reaches the same world
    # the count packer reaches on the same snapshot
    count = scale_all_jobs_dry_run([pinned, spanning], r.copy(), 1.0)
    assert plan.diff == count


def test_unequal_domains_spanning_world_under_goodput_objective():
    """The 3+1 unequal-fabric case: a measured spanning job still packs
    both fabrics whole under the marginal objective, and actuating the
    plan on the fake kubelet strands nothing."""
    from edl_tpu.cluster.fake import FakeCluster

    cluster = FakeCluster()
    for name, dom, chips in (("a0", "A", 2), ("a1", "A", 1), ("b0", "B", 1)):
        cluster.add_node(name, cpu_milli=8000, memory_mega=16000,
                         tpu_chips=chips, ici_domain=dom)
    j = make_multi_domain_job("j", 1, 4, 1, chips="1")
    cluster.create_resources(j.config)
    cluster.reconcile()
    r = cluster.inquiry_resource()
    j.parallelism = cluster.get_trainer_parallelism(j.config)
    cv = curves_for({"default/j": curve({1: 100.0, 2: 198.0})})
    plan = plan_cluster([j], r, 1.0, curves=cv)
    target = j.parallelism + plan.diff["default/j"]
    assert target == 4
    cluster.update_trainer_parallelism(j.config, target)
    cluster.reconcile()
    counts = cluster.job_pods(j.config)
    assert counts.pending == 0 and counts.running == 4


# ---------------------------------------------------------------------------
# ScalingCurve pricing edge cases (the allocator leans on these)
# ---------------------------------------------------------------------------


def test_empty_curve_prices_at_prior():
    c = ScalingCurve()
    assert c.world_sizes() == []
    assert c.tokens_per_second(4) is None
    assert c.nearest_world_size(4) is None
    assert c.marginal_tokens_per_second_per_chip(4) is None
    assert _step_marginal(c, 4, 1, OPTIMISTIC_PRIOR) == OPTIMISTIC_PRIOR
    assert _step_marginal(None, 4, 1, 123.0) == 123.0


def test_single_measured_size_marginal_is_average_per_chip():
    c = curve({4: 400.0})
    assert c.marginal_tokens_per_second_per_chip(4) == pytest.approx(100.0)
    # a step ending anywhere reads the lone point's average
    assert _step_marginal(c, 8, 1, 0.0) == pytest.approx(100.0)
    assert _step_marginal(c, 2, 1, 0.0) == pytest.approx(100.0)
    # chips-per-instance normalizes the per-world-size slope
    assert _step_marginal(c, 8, 4, 0.0) == pytest.approx(25.0)


def test_queries_beyond_measured_range_use_the_curve_edge():
    c = curve({2: 100.0, 4: 180.0})
    # above the range: largest measured point answers, so the marginal
    # is the LAST measured slope (linear extrapolation)
    assert c.nearest_world_size(100) == 4
    assert _step_marginal(c, 100, 1, 0.0) == pytest.approx(40.0)
    # below the range: the smallest measured point answers
    assert c.nearest_world_size(1) == 2
    assert _step_marginal(c, 1, 1, 0.0) == pytest.approx(50.0)


def test_nearest_world_size_tie_breaking():
    c = curve({2: 100.0, 4: 180.0, 8: 260.0})
    assert c.nearest_world_size(2) == 2     # exact hit
    assert c.nearest_world_size(3) == 2     # largest measured <= query
    assert c.nearest_world_size(7) == 4
    assert c.nearest_world_size(8) == 8
    assert c.nearest_world_size(1) == 2     # nothing below: smallest rules


def test_degraded_parity_pin_when_no_curves_resolve():
    """The explicit acceptance pin: same jobs, same snapshot, curves
    present-but-empty → the goodput entry point returns the count
    packer's exact diff."""
    jobs = [priced_job("a", 1, 1, 6, 2), priced_job("b", 1, 1, 6, 2)]
    r = big_cluster()
    empty = curves_for({"default/a": ScalingCurve(),
                        "default/b": ScalingCurve()})
    plan = scale_all_jobs_goodput(jobs, r.copy(), 1.0, curves=empty)
    assert plan.mode == "degraded"
    assert plan.diff == scale_all_jobs_dry_run(jobs, r.copy(), 1.0)


# ---------------------------------------------------------------------------
# satellites: bounded advisory log, serving capacity recorder, priority
# threading, objective gauge
# ---------------------------------------------------------------------------


def test_empty_node_snapshot_never_crashes():
    """A drained cluster (every node gone NotReady) with an aged
    starved gang must plan to a rollback, not an IndexError — the
    autoscaler loop thread rides on it."""
    r = ClusterResource()  # no nodes at all
    gang = priced_job("gang", 2, 2, 2, 2, priority=SchedPriority.HIGH)
    gang.pending = 2
    gang.pending_age = 10  # well past the starvation threshold
    other = priced_job("other", 1, 1, 2, 1)
    cv = curves_for({"default/other": curve({1: 100.0})})
    plan = plan_cluster([gang, other], r, 1.0, curves=cv)
    assert plan.rollbacks and not plan.preemptions


def test_autoscaler_loop_survives_a_raising_planner():
    """Belt and braces: ANY goodput-planner exception degrades the tick
    to count packing instead of killing the loop thread."""
    from tests.test_autoscaler import cluster_with, mk_job, submit
    import edl_tpu.scheduler.autoscaler as auto_mod
    from edl_tpu.scheduler.autoscaler import Autoscaler

    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, goodput_curves=lambda uid: curve({1: 100.0}))
    submit(c, a, mk_job("example", lo=2, hi=10))
    orig = auto_mod.plan_cluster

    def boom(*args, **kw):
        raise RuntimeError("planner bug")

    auto_mod.plan_cluster = boom
    try:
        target = a.tick()   # must not raise; count packing rules
    finally:
        auto_mod.plan_cluster = orig
    assert target and c.get_trainer_parallelism(
        a.jobs["default/example"].config) == 10


def test_curve_source_fetched_once_per_tick():
    """One KV round-trip per job per tick: the planner's resolve pass
    and the advisory share the tick-scoped memo (the CLI wires a
    blocking coordinator fetch per call)."""
    from tests.test_autoscaler import cluster_with, mk_job, submit
    from edl_tpu.scheduler.autoscaler import Autoscaler

    calls = []
    cv = curve({2: 1000.0, 8: 3000.0})

    def source(uid):
        calls.append(uid)
        return cv

    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, goodput_curves=source)
    submit(c, a, mk_job("example", lo=2, hi=10))
    target = a.tick()
    assert target  # plan actuated AND advisory logged...
    assert a.advisory_history
    assert calls == ["default/example"]  # ...off ONE fetch


def test_advisory_history_is_bounded():
    """scheduler/autoscaler.py kept an unbounded list appended on every
    actuated plan — now a deque(maxlen=256)."""
    from tests.test_autoscaler import cluster_with
    from edl_tpu.scheduler.autoscaler import Autoscaler

    cv = curve({2: 1000.0, 8: 3000.0}, job="default/x")
    a = Autoscaler(cluster_with(), goodput_curves=lambda uid: cv)
    assert isinstance(a.advisory_history, deque)
    assert a.advisory_history.maxlen == 256
    for _ in range(300):
        a._advise_goodput({"default/x": 4})
    assert len(a.advisory_history) == 256


def test_serving_scaler_records_capacity_curve():
    """Each observed decide() folds (replica_count → fleet qps) into
    the job's CurveStore under goodput-curve/<job>, so the goodput
    planner prices serving fleets from MEASURED capacity."""
    from edl_tpu.scheduler.autoscaler import ServingScaler

    class KV:
        def __init__(self):
            self.d = {}

        def kv_set(self, k, v):
            self.d[k] = v

        def kv_get(self, k):
            return self.d.get(k)

    kv = KV()
    job = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=100.0))
    stats_by_tick = []

    def stats_for(uid):
        return stats_by_tick[-1]

    actuations = []
    s = ServingScaler(stats_for=stats_for,
                      actuate=lambda uid, n: actuations.append((uid, n)),
                      coord_for=lambda j: kv, clock=lambda: 1000.0)
    s.on_add(job)
    stats_by_tick.append(SimpleNamespace(
        requests_windowed=500, qps=120.0, p99_ms=40.0, queue_depth=0,
        replicas_active=2))
    s.tick()
    stats_by_tick.append(SimpleNamespace(
        requests_windowed=900, qps=260.0, p99_ms=150.0, queue_depth=12,
        replicas_active=4))
    s._clock = lambda: 2000.0
    s.tick()
    c = load_curve(kv, "default/fleet")
    assert c is not None
    assert c.world_sizes() == [2, 4]
    assert c.tokens_per_second(2) == pytest.approx(120.0)
    assert c.tokens_per_second(4) == pytest.approx(260.0)
    assert c.marginal_tokens_per_second_per_chip(4) == pytest.approx(70.0)

    # a RESTARTED controller (fresh scaler, same coordinator) must seed
    # from the persisted curve — its first record folds IN, it does not
    # clobber the accumulated multi-point curve with one new cell
    s2 = ServingScaler(stats_for=stats_for, actuate=lambda uid, n: None,
                       coord_for=lambda j: kv, clock=lambda: 3000.0)
    s2.on_add(job)
    stats_by_tick.append(SimpleNamespace(
        requests_windowed=400, qps=330.0, p99_ms=60.0, queue_depth=0,
        replicas_active=6))
    s2.tick()
    c = load_curve(kv, "default/fleet")
    assert c.world_sizes() == [2, 4, 6]


def test_capacity_curve_tracks_a_traffic_step():
    """The recorder's recency bound: after a traffic step, the cell's
    mean converges to the NEW qps within ~max_samples folds — a
    lifetime average would freeze and the planner could never re-price
    the fleet's growth."""
    c = ScalingCurve("default/fleet")
    for _ in range(500):
        c.observe(4, 100.0, shape="serving", max_samples=30)
    for _ in range(120):                       # the step: 100 → 400 qps
        c.observe(4, 400.0, shape="serving", max_samples=30)
    got = c.tokens_per_second(4)
    assert got > 350.0, got                    # tracked, not frozen
    # an unbounded fold over the same stream stays pinned near the
    # lifetime mean — the failure mode the bound exists to prevent
    frozen = ScalingCurve()
    for _ in range(500):
        frozen.observe(4, 100.0)
    for _ in range(120):
        frozen.observe(4, 400.0)
    assert frozen.tokens_per_second(4) < 180.0


def test_arbitrated_serving_fleet_is_not_shape_quantized():
    """A serving fleet's replicas are independent — the trainer slice
    policy (--pow2-shapes) must not quantize its dial to 1/2/4."""
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.scheduler.autoscaler import Autoscaler

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=64_000, memory_mega=64_000,
                     tpu_chips=8)
    res = ResourceRequirements(requests={"cpu": "1", "memory": "1Mi"},
                               limits={RESOURCE_TPU: "1"})
    fleet = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=1, max_replicas=6, slo_p99_ms=50.0, resources=res))
    a = Autoscaler(cluster, shape_policy=POW2_POLICY,
                   goodput_curves=lambda uid: curve({1: 100.0, 2: 200.0}))
    cluster.create_resources(fleet)
    a.on_add(fleet)
    for _ in range(8):
        a.tick()
    # pow2 would cap at 4; the fleet must reach its real max of 6
    assert cluster.get_trainer_parallelism(fleet) == 6


def test_paired_rebalance_legs_suppress_atomically():
    """Hysteresis must drop a rebalance's shrink+grant TOGETHER: a
    cooldown on the victim must not let the winner's grant actuate
    into capacity that was never freed."""
    from edl_tpu.scheduler.autoscaler import Autoscaler
    from edl_tpu.scheduler.planner import GoodputPlan

    class OneJobCluster:
        """Minimal Cluster seam: two running 1-chip jobs, full node."""

        def __init__(self):
            from edl_tpu.cluster.fake import FakeCluster

            self.fake = FakeCluster()
            self.fake.add_node("n0", cpu_milli=64_000,
                               memory_mega=64_000, tpu_chips=4)

        def __getattr__(self, name):
            return getattr(self.fake, name)

    c = OneJobCluster()
    clock_t = [1000.0]
    a = Autoscaler(c, goodput_curves=lambda uid: curve({1: 100.0}),
                   resize_cooldown_s=30.0, clock=lambda: clock_t[0])
    winner = priced_job("winner", 1, 1, 4, 1).config
    victim = priced_job("victim", 1, 1, 4, 3).config
    c.create_resources(winner)
    c.create_resources(victim)
    a.on_add(winner)
    a.on_add(victim)
    a.drain_events()
    # the victim resized moments ago: inside its cooldown
    a._last_resize["default/victim"] = clock_t[0] - 1.0

    import edl_tpu.scheduler.autoscaler as auto_mod

    orig = auto_mod.plan_cluster

    def fake_plan(jobs, r, mld=1.0, **kw):
        return GoodputPlan(
            diff={"default/victim": -1, "default/winner": 1},
            mode="goodput",
            reclaims=[{"victim": "default/victim",
                       "for_job": "default/winner",
                       "from": 3, "to": 2, "reason": "rebalance"}])

    auto_mod.plan_cluster = fake_plan
    try:
        actuated = a.tick()
    finally:
        auto_mod.plan_cluster = orig
    # neither leg actuated: the victim was cooling down, so the
    # winner's paired grant was dropped with it
    assert actuated == {}, actuated
    assert a.suppressed_history[-1] == {
        "default/victim": "cooldown", "default/winner": "paired_reclaim"}


def test_preemption_overrides_victim_cooldown():
    """A higher-priority gang's admission must not wait out its
    victim's resize cooldown."""
    from edl_tpu.scheduler.autoscaler import Autoscaler
    from edl_tpu.scheduler.planner import GoodputPlan
    from tests.test_autoscaler import cluster_with

    c = cluster_with()
    clock_t = [1000.0]
    a = Autoscaler(c, goodput_curves=lambda uid: curve({1: 100.0}),
                   resize_cooldown_s=30.0, clock=lambda: clock_t[0])
    victim = priced_job("victim", 0, 1, 8, 4).config
    c.create_resources(victim)
    c.update_trainer_parallelism(victim, 4)   # running at 4
    a.on_add(victim)
    a.drain_events()
    a._last_resize["default/victim"] = clock_t[0] - 1.0  # cooling down

    import edl_tpu.scheduler.autoscaler as auto_mod

    orig = auto_mod.plan_cluster
    auto_mod.plan_cluster = lambda jobs, r, mld=1.0, **kw: GoodputPlan(
        diff={"default/victim": -2}, mode="goodput",
        preemptions=[{"victim": "default/victim",
                      "for_job": "default/gang", "from": 4, "to": 2,
                      "domain": None, "reason": "preempt"}])
    try:
        actuated = a.tick()
    finally:
        auto_mod.plan_cluster = orig
    assert actuated == {"default/victim": 2}, actuated


def test_observe_only_serving_job_hints_but_never_actuates():
    """Under chip arbitration the SLO policy keeps observing, recording
    and prewarm-hinting — but the goodput planner owns the dial."""
    from edl_tpu.scheduler.autoscaler import ServingScaler

    job = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=100.0))
    breach = SimpleNamespace(requests_windowed=900, qps=260.0,
                             p99_ms=400.0, queue_depth=40,
                             replicas_active=2)
    actuations, hints = [], []
    s = ServingScaler(stats_for=lambda uid: breach,
                      actuate=lambda uid, n: actuations.append((uid, n)),
                      clock=lambda: 1000.0)
    s.hint_sink = lambda uid, n: hints.append((uid, n))
    s.on_add(job)
    s.observe_only.add(job.full_name)
    s.tick()
    assert actuations == []
    assert hints and hints[0][1] > 2  # the breach still prewarms ahead


def test_priority_threads_api_serde_crd():
    """SchedPriority round-trips through the manifest layer for both
    kinds, accepts tier names, survives apiserver structural pruning,
    and rejects negatives at validation."""
    import edl_tpu.api.serde as serde
    from edl_tpu.api.validation import ValidationError, validate_any
    from tests.k8s_stub import load_crd_schemas, prune_per_schema

    doc = serde.job_to_dict(
        priced_job("p", 1, 1, 2, 1, priority=SchedPriority.HIGH).config)
    assert doc["spec"]["trainer"]["priority"] == 2
    back = serde.job_from_dict(doc)
    assert back.sched_priority() == 2
    # tier names parse (case-insensitive)
    doc["spec"]["trainer"]["priority"] = "high"
    assert serde.job_from_dict(doc).sched_priority() == 2
    with pytest.raises(ValueError):
        serde.job_from_dict(
            {**doc, "spec": {**doc["spec"],
                             "trainer": {**doc["spec"]["trainer"],
                                         "priority": "urgent"}}})
    # CRD lockstep: a conformant apiserver must not prune the field
    schema = load_crd_schemas()[("edl.tpu", "trainingjobs")]
    pruned = prune_per_schema(doc, schema)
    assert pruned["spec"]["trainer"]["priority"] == "high"
    sj = ServingJob(name="f", spec=ServingSpec(
        min_replicas=1, max_replicas=2, priority=SchedPriority.HIGH))
    sdoc = serde.serving_job_to_dict(sj)
    assert sdoc["spec"]["server"]["priority"] == 2
    assert serde.serving_job_from_dict(sdoc).sched_priority() == 2
    sschema = load_crd_schemas()[("edl.tpu", "servingjobs")]
    assert prune_per_schema(sdoc, sschema)["spec"]["server"]["priority"] == 2
    # validation bounds
    bad = priced_job("bad", 1, 1, 1, 1).config
    bad.spec.trainer.priority = -1
    with pytest.raises(ValidationError):
        validate_any(bad)


def test_autoscaler_objective_gauge_reports_active_mode():
    from tests.test_autoscaler import cluster_with, mk_job, submit
    from edl_tpu.observability.metrics import get_registry, parse_exposition
    from edl_tpu.scheduler.autoscaler import Autoscaler

    cv = curve({2: 1000.0, 8: 3000.0})
    c = cluster_with()
    a = Autoscaler(c, goodput_curves=lambda uid: cv)
    submit(c, a, mk_job("example", lo=2, hi=10))
    a.tick()
    series = parse_exposition(get_registry().render())
    assert series['edl_autoscaler_objective{mode="goodput"}'] == 1.0
    assert series['edl_autoscaler_objective{mode="count"}'] == 0.0
    # flag off → count mode, bit-for-bit reference behavior
    c2 = cluster_with()
    b = Autoscaler(c2, goodput_curves=lambda uid: cv,
                   goodput_objective=False)
    submit(c2, b, mk_job("example", lo=2, hi=10))
    b.tick()
    series = parse_exposition(get_registry().render())
    assert series['edl_autoscaler_objective{mode="count"}'] == 1.0


def test_controller_arbitrates_elastic_chip_serving_fleets():
    """An elastic chip-holding ServingJob submitted under the goodput
    objective registers with BOTH loops: the SLO policy observes and
    records, the goodput planner owns the dial."""
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.controller.controller import Controller

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=64_000, memory_mega=64_000,
                     tpu_chips=8)
    ctl = Controller(cluster, goodput_curves=lambda uid: None)
    res = ResourceRequirements(requests={"cpu": "1", "memory": "1Mi"},
                               limits={RESOURCE_TPU: "1"})
    job = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=1, max_replicas=4, slo_p99_ms=50.0, resources=res))
    try:
        ctl.submit(job)
        ctl.autoscaler.drain_events()
        assert job.full_name in ctl.autoscaler.jobs
        assert job.full_name in ctl.serving_scaler.observe_only
        ctl.delete(job)
        ctl.autoscaler.drain_events()
        assert job.full_name not in ctl.autoscaler.jobs
        assert job.full_name not in ctl.serving_scaler.observe_only
    finally:
        ctl.stop()


def test_controller_modify_reconciles_arbitration_both_ways():
    """A spec change can flip arbitration eligibility: exactly ONE loop
    owns the replica dial afterwards, in either direction."""
    from edl_tpu.cluster.fake import FakeCluster
    from edl_tpu.controller.controller import Controller

    cluster = FakeCluster()
    cluster.add_node("n0", cpu_milli=64_000, memory_mega=64_000,
                     tpu_chips=8)
    ctl = Controller(cluster, goodput_curves=lambda uid: None)
    res = ResourceRequirements(requests={"cpu": "1", "memory": "1Mi"},
                               limits={RESOURCE_TPU: "1"})
    # submitted NON-elastic: no arbitration — the SLO policy owns it
    job = ServingJob(name="fleet", spec=ServingSpec(
        min_replicas=2, max_replicas=2, slo_p99_ms=50.0, resources=res))
    try:
        ctl.submit(job)
        ctl.autoscaler.drain_events()
        assert job.full_name not in ctl.serving_scaler.observe_only
        assert job.full_name not in ctl.autoscaler.jobs
        # modified elastic → the goodput planner takes the dial
        job.spec.max_replicas = 4
        ctl.modify(job)
        ctl.autoscaler.drain_events()
        assert job.full_name in ctl.serving_scaler.observe_only
        assert job.full_name in ctl.autoscaler.jobs
        # modified back to fixed-size → ownership returns whole
        job.spec.max_replicas = 2
        ctl.modify(job)
        ctl.autoscaler.drain_events()
        assert job.full_name not in ctl.serving_scaler.observe_only
        assert job.full_name not in ctl.autoscaler.jobs
    finally:
        ctl.delete(job)
        ctl.stop()
