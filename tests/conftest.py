"""Test harness config.

Forces jax onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so every sharding/pjit test exercises real multi-device meshes
without TPU hardware (see SURVEY §4 implication 3: the reference had no way
to test multi-node behavior in CI; we do).
"""

import os

# Force, don't setdefault: the environment presets JAX_PLATFORMS=axon (the
# real TPU tunnel), but tests always run on the virtual CPU mesh.  The
# jaxtyping pytest plugin imports jax before this conftest runs, so setting
# the env var alone is not enough — update jax's config directly (the
# backend itself initializes lazily, at the first jax.devices() call).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def fake_cluster():
    from edl_tpu.cluster.fake import FakeCluster

    return FakeCluster()
