"""Test harness config.

Forces jax onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so every sharding/pjit test exercises real multi-device meshes
without TPU hardware (see SURVEY §4 implication 3: the reference had no way
to test multi-node behavior in CI; we do).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def fake_cluster():
    from edl_tpu.cluster.fake import FakeCluster

    return FakeCluster()
