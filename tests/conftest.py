"""Test harness config.

Forces jax onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so every sharding/pjit test exercises real multi-device meshes
without TPU hardware (see SURVEY §4 implication 3: the reference had no way
to test multi-node behavior in CI; we do).
"""

import os

# Force, don't setdefault: the environment presets JAX_PLATFORMS=axon (the
# real TPU tunnel), but tests always run on the virtual CPU mesh.  The
# jaxtyping pytest plugin imports jax before this conftest runs, so setting
# the env var alone is not enough — update jax's config directly (the
# backend itself initializes lazily, at the first jax.devices() call).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import signal  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402

# -- per-test timeout enforcement --------------------------------------------
#
# The suite's tier-1 budget is one 870 s umbrella; without a per-test
# ceiling, a single regressed hang (the stall failure mode this repo now
# detects at runtime) eats the WHOLE budget and the report says "timeout"
# instead of naming the guilty test.  A SIGALRM ring per test phase makes
# the hang fail fast, in place, with a stack-accurate traceback.  Override
# per test with @pytest.mark.timeout_s(N); disable with 0.

DEFAULT_TEST_TIMEOUT_S = float(os.environ.get("EDL_TEST_TIMEOUT_S", "300"))


class TestTimeout(Exception):
    pass


def _test_timeout_s(item) -> float:
    marker = item.get_closest_marker("timeout_s")
    if marker is not None and marker.args:
        return float(marker.args[0])
    return DEFAULT_TEST_TIMEOUT_S


def _alarm_guard(item, phase: str):
    """Context manager arming SIGALRM around one test phase.  Main-thread
    only (pytest runs tests there); a no-op where SIGALRM is unavailable."""
    import contextlib

    @contextlib.contextmanager
    def guard():
        timeout = _test_timeout_s(item)
        if (timeout <= 0 or not hasattr(signal, "SIGALRM")
                or threading.current_thread()
                is not threading.main_thread()):
            yield
            return

        def on_alarm(signum, frame):
            raise TestTimeout(
                f"{item.nodeid} {phase} exceeded {timeout:.0f}s "
                f"(EDL_TEST_TIMEOUT_S / @pytest.mark.timeout_s override)")

        old_handler = signal.signal(signal.SIGALRM, on_alarm)
        signal.setitimer(signal.ITIMER_REAL, timeout)
        try:
            yield
        finally:
            signal.setitimer(signal.ITIMER_REAL, 0)
            signal.signal(signal.SIGALRM, old_handler)

    return guard()


@pytest.hookimpl(wrapper=True)
def pytest_runtest_setup(item):
    with _alarm_guard(item, "setup"):
        return (yield)


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    with _alarm_guard(item, "call"):
        return (yield)


# -- multiprocess-collectives capability gate ---------------------------------
#
# Some environments' jax CPU backend cannot form multi-process worlds at
# all ("Multiprocess computations aren't implemented on the CPU
# backend" at backend init) — PR 7 watched `test_workers_survive_
# coordinator_restart` flip from green to that error on PRISTINE HEAD
# when the container changed.  Tests that REQUIRE a ≥2-process
# jax.distributed world carry @pytest.mark.needs_multiprocess_collectives
# and are skipped with an explicit reason when a direct 2-process probe
# fails, instead of failing on an environment property no code change
# caused.  The probe runs at most once per session, lazily (only when
# the first marked test is about to run).

_MP_PROBE = """
import sys
import jax
import jax.numpy as jnp
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]),
                           initialization_timeout=60)
print("devices:", len(jax.devices()))
# initialize + jax.devices() can succeed on backends that still abort at
# the first cross-process COMPUTATION ("Multiprocess computations aren't
# implemented on the CPU backend") — the probe must run one to count
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(
    jnp.ones((1,)) * jax.process_index())
assert float(out.sum()) == 1.0, out
print("collective ok")
"""

_mp_collectives_verdict: list = []  # memo: [(ok, reason)]


def multiprocess_collectives_supported() -> tuple[bool, str]:
    """Spawn a bare 2-process jax.distributed CPU world; (ok, reason)."""
    if _mp_collectives_verdict:
        return _mp_collectives_verdict[0]
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               PALLAS_AXON_POOL_IPS="")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE, coord, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    ok, tail = True, ""
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = (out or "") + "\n[probe timeout]"
        if p.returncode != 0:
            ok = False
            lines = [ln for ln in (out or "").strip().splitlines() if ln]
            tail = tail or (lines[-1][:200] if lines else "no output")
    verdict = (ok, "" if ok else
               "this jax backend cannot form multi-process CPU worlds "
               f"(2-process jax.distributed probe failed: {tail})")
    _mp_collectives_verdict.append(verdict)
    return verdict


@pytest.fixture(autouse=True)
def _multiprocess_collectives_gate(request):
    """Skip @needs_multiprocess_collectives tests (with the probe's
    reason) where the backend can't form multi-process worlds."""
    if request.node.get_closest_marker(
            "needs_multiprocess_collectives") is not None:
        ok, reason = multiprocess_collectives_supported()
        if not ok:
            pytest.skip(reason)


@pytest.fixture
def fake_cluster():
    from edl_tpu.cluster.fake import FakeCluster

    return FakeCluster()


@pytest.fixture
def kube(monkeypatch):
    """The stub apiserver (tests/k8s_stub.py) installed as the `kubernetes`
    package, with one 8-chip TPU node; yields (k8s module, StubState)."""
    import importlib
    import sys

    from tests.k8s_stub import StubState, build_module, make_node

    state = StubState()
    state.nodes = [make_node("a0", cpu="64", memory="128Gi", tpu=8,
                             labels={"edl-tpu/ici-domain": "slice-a"})]
    module = build_module(state)
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    import edl_tpu.cluster.k8s as k8s_mod

    importlib.reload(k8s_mod)
    yield k8s_mod, state
    monkeypatch.delitem(sys.modules, "kubernetes")
    importlib.reload(k8s_mod)


@pytest.fixture
def control_plane(kube):
    """A full deployed-style control plane over the stub apiserver:
    (K8sCluster, Controller, TrainingJobSyncLoop, StubState)."""
    from edl_tpu.controller.controller import Controller
    from edl_tpu.controller.sync import TrainingJobSyncLoop

    k8s_mod, state = kube
    cluster = k8s_mod.K8sCluster(kubeconfig="ignored")
    controller = Controller(cluster, updater_convert_seconds=0.05,
                            updater_confirm_seconds=0.05)
    sync = TrainingJobSyncLoop(cluster, controller, poll_seconds=0.05)
    yield cluster, controller, sync, state
    controller.stop()
