"""Test harness config.

Forces jax onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so every sharding/pjit test exercises real multi-device meshes
without TPU hardware (see SURVEY §4 implication 3: the reference had no way
to test multi-node behavior in CI; we do).
"""

import os

# Force, don't setdefault: the environment presets JAX_PLATFORMS=axon (the
# real TPU tunnel), but tests always run on the virtual CPU mesh.  The
# jaxtyping pytest plugin imports jax before this conftest runs, so setting
# the env var alone is not enough — update jax's config directly (the
# backend itself initializes lazily, at the first jax.devices() call).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def fake_cluster():
    from edl_tpu.cluster.fake import FakeCluster

    return FakeCluster()


@pytest.fixture
def kube(monkeypatch):
    """The stub apiserver (tests/k8s_stub.py) installed as the `kubernetes`
    package, with one 8-chip TPU node; yields (k8s module, StubState)."""
    import importlib
    import sys

    from tests.k8s_stub import StubState, build_module, make_node

    state = StubState()
    state.nodes = [make_node("a0", cpu="64", memory="128Gi", tpu=8,
                             labels={"edl-tpu/ici-domain": "slice-a"})]
    module = build_module(state)
    monkeypatch.setitem(sys.modules, "kubernetes", module)
    import edl_tpu.cluster.k8s as k8s_mod

    importlib.reload(k8s_mod)
    yield k8s_mod, state
    monkeypatch.delitem(sys.modules, "kubernetes")
    importlib.reload(k8s_mod)


@pytest.fixture
def control_plane(kube):
    """A full deployed-style control plane over the stub apiserver:
    (K8sCluster, Controller, TrainingJobSyncLoop, StubState)."""
    from edl_tpu.controller.controller import Controller
    from edl_tpu.controller.sync import TrainingJobSyncLoop

    k8s_mod, state = kube
    cluster = k8s_mod.K8sCluster(kubeconfig="ignored")
    controller = Controller(cluster, updater_convert_seconds=0.05,
                            updater_confirm_seconds=0.05)
    sync = TrainingJobSyncLoop(cluster, controller, poll_seconds=0.05)
    yield cluster, controller, sync, state
    controller.stop()
