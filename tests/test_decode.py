"""Token-level continuous batching (doc/serving.md §autoregressive
serving): decode parity against the full-context reference, per-
iteration join/leave, WFQ priorities, live resize with zero dropped
sessions (bitwise-stable continuations), cache-preserving rolling
reloads, the SIGKILL rescue drill, and the /generate front-door path."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from edl_tpu.models.transformer import TINY, apply, init
from edl_tpu.runtime.kvcache import KVPoolExhausted
from edl_tpu.runtime.serving import (
    PRI_HIGH,
    PRI_LOW,
    PRI_NORMAL,
    S_DECODING,
    S_PREFILL,
    DecodeFleet,
    DecodeSession,
    SessionDropped,
    TokenScheduler,
)

PARAMS = init(jax.random.PRNGKey(0), TINY)
_REF_CACHE: dict = {}


def ref_decode(prompt, n):
    """Greedy continuation via the full-context reference forward —
    what every paged/batched/migrated decode must reproduce."""
    key = (tuple(prompt), n)
    if key not in _REF_CACHE:
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = apply(PARAMS, np.asarray([toks], np.int32), TINY)
            t = int(np.asarray(logits[0, -1]).argmax())
            out.append(t)
            toks.append(t)
        _REF_CACHE[key] = out
    return _REF_CACHE[key]


def make_fleet(**kw) -> DecodeFleet:
    kw.setdefault("job", "t/decode")
    kw.setdefault("roles", {"decode": 1})
    kw.setdefault("slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_blocks", 32)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("max_blocks_per_session", 8)
    return DecodeFleet(PARAMS, TINY, **kw)


RNG = np.random.default_rng(7)


def prompts(n, lo=3, hi=12):
    return [RNG.integers(1, 255, size=int(RNG.integers(lo, hi))).tolist()
            for _ in range(n)]


class TestDecodeParity:
    def test_single_session_matches_reference(self):
        fleet = make_fleet()
        try:
            p = [5, 9, 17, 33]
            sess = fleet.submit(p, max_new_tokens=8)
            assert sess.wait(60) == ref_decode(p, 8)
        finally:
            fleet.stop()

    def test_concurrent_sessions_all_match(self):
        """More sessions than slots: the batch continuously re-packs as
        sequences finish, and every output still matches the unbatched
        reference exactly."""
        fleet = make_fleet(slots=3)
        try:
            ps = prompts(8)
            ss = [fleet.submit(p, max_new_tokens=6) for p in ps]
            for p, s in zip(ps, ss):
                assert s.wait(120) == ref_decode(p, 6)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_eos_frees_slot_early(self):
        fleet = make_fleet(eos_id=ref_decode([5, 9, 17, 33], 3)[2])
        try:
            sess = fleet.submit([5, 9, 17, 33], max_new_tokens=50)
            out = sess.wait(60)
            assert out == ref_decode([5, 9, 17, 33], 3)
            # the early finish released everything
            assert fleet.sessions_active() == 0
            assert fleet.kv_blocks()[0] == 0
        finally:
            fleet.stop()

    def test_chunked_prefill_long_prompt(self):
        fleet = make_fleet(prefill_chunk=4, kv_block_size=4,
                           kv_blocks=64, max_blocks_per_session=16)
        try:
            p = RNG.integers(1, 255, size=30).tolist()  # 8 chunks
            sess = fleet.submit(p, max_new_tokens=5)
            assert sess.wait(60) == ref_decode(p, 5)
        finally:
            fleet.stop()


class TestScheduler:
    def test_wfq_favors_high_priority(self):
        """Under prefill contention the high class drains ~4x the low
        class's share (DEFAULT_WFQ_WEIGHTS), without starving low."""
        sched = TokenScheduler()
        order = []
        pend = []
        for i in range(12):
            s = DecodeSession([1] * 8, 4,
                              priority=[PRI_HIGH, PRI_LOW][i % 2], id=i)
            sched.stamp(s)
            pend.append(s)
        while pend:
            s = sched.pick_prefill(pend)
            order.append(s.priority)
            pend.remove(s)
        # first half of service is dominated by the high class
        first = order[:6]
        assert first.count(PRI_HIGH) >= 4
        # but the low class is not starved out of the tail
        assert PRI_LOW in order[:8]

    def test_interleave_budget_protects_decode(self):
        sched = TokenScheduler(decode_per_prefill=3)
        assert sched.allow_prefill(decoding=0, prefill_pending=1)
        assert not sched.allow_prefill(decoding=2, prefill_pending=1)
        for _ in range(3):
            sched.note_decode()
        assert sched.allow_prefill(decoding=2, prefill_pending=1)
        sched.note_prefill()
        assert not sched.allow_prefill(decoding=2, prefill_pending=1)
        assert not sched.allow_prefill(decoding=0, prefill_pending=0)

    def test_priorities_complete_under_load(self):
        fleet = make_fleet(slots=2)
        try:
            ps = prompts(6)
            ss = [fleet.submit(p, max_new_tokens=5,
                               priority=[PRI_HIGH, PRI_NORMAL,
                                         PRI_LOW][i % 3])
                  for i, p in enumerate(ps)]
            for p, s in zip(ps, ss):
                assert s.wait(120) == ref_decode(p, 5)
        finally:
            fleet.stop()


class TestBoundedAdmission:
    def test_oversized_session_rejected_typed(self):
        fleet = make_fleet(kv_blocks=8, max_blocks_per_session=2,
                           kv_block_size=4, max_queued_sessions=2)
        try:
            with pytest.raises(KVPoolExhausted):
                fleet.submit([1] * 20, max_new_tokens=20)
            assert fleet.sessions_active() == 0
        finally:
            fleet.stop()

    def test_pool_pressure_queues_then_drains(self):
        """Sessions beyond pool capacity wait (bounded, no OOM) and
        admit as finishing sessions free blocks — all complete."""
        fleet = make_fleet(kv_blocks=8, kv_block_size=4,
                           max_blocks_per_session=4, slots=4)
        try:
            ps = prompts(6, 3, 6)
            ss = [fleet.submit(p, max_new_tokens=4) for p in ps]
            for p, s in zip(ps, ss):
                assert s.wait(120) == ref_decode(p, 4)
        finally:
            fleet.stop()

    def test_queue_cap_sheds(self):
        fleet = make_fleet(kv_blocks=4, kv_block_size=4,
                           max_blocks_per_session=4,
                           max_queued_sessions=2)
        try:
            # one 16-token reservation takes the whole 4-block pool;
            # one more queues; the next hits the queue cap and sheds
            fleet.submit([1] * 8, max_new_tokens=8)
            fleet.submit([1] * 8, max_new_tokens=8)
            with pytest.raises(KVPoolExhausted):
                for _ in range(8):
                    fleet.submit([1] * 8, max_new_tokens=8)
        finally:
            fleet.stop(drain=False)


class TestLiveResize:
    def test_scale_down_zero_drops_bitwise_stable(self):
        """THE tentpole invariant: a 2→1 scale-down mid-decode drops no
        session and every continuation is token-identical to the
        undisturbed reference (same logical KV gather → same logits)."""
        fleet = make_fleet(roles={"decode": 2}, kv_blocks=64)
        try:
            ps = prompts(6, 6, 10)
            ss = [fleet.submit(p, max_new_tokens=16) for p in ps]
            for s in ss:
                s.wait_first_token(60)
            assert fleet.scale_to(1) == 1
            for p, s in zip(ps, ss):
                assert s.wait(180) == ref_decode(p, 16)
            assert fleet.sessions_failed == 0
            assert fleet.sessions_completed == len(ps)
            assert fleet.migrations >= 1
        finally:
            fleet.stop()

    def test_scale_up_then_down_conserves_sessions(self):
        fleet = make_fleet(roles={"decode": 1})
        try:
            ss = [fleet.submit(p, max_new_tokens=12)
                  for p in prompts(4)]
            assert fleet.scale_to(3) == 3
            assert fleet.scale_to(1) == 1
            for s in ss:
                s.wait(180)
            assert (fleet.sessions_completed + fleet.sessions_failed
                    == fleet.sessions_submitted)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_mid_prefill_export_resumes_prefill(self):
        """REVIEW regression: a session evacuated while its prompt is
        mid-chunked-prefill (cached > 0, no token emitted) must travel
        its partial cache and resume PREFILL on the adopter — the old
        import path forced S_DECODING and tripped over the empty
        ``generated`` history, dropping the session from scale_to."""
        fleet = make_fleet(roles={"decode": 2}, prefill_chunk=2,
                           kv_block_size=4, kv_blocks=64,
                           max_blocks_per_session=32)
        try:
            src, dst = [r for r in fleet._replicas
                        if r.role == "decode"]
            p = RNG.integers(1, 255, size=100).tolist()  # 50 chunks
            sess = None
            deadline = time.time() + 120
            for attempt in range(3):
                cand = DecodeSession(p, 4, id=90_000 + attempt)
                src.submit(cand)
                # wait (without parking the loop) for the first prefill
                # chunk to land, then quiesce: 50 chunks leave a wide
                # window to park with a partial prompt cache
                while (time.time() < deadline and not cand.generated
                       and cand.cached == 0):
                    time.sleep(0.0001)
                assert src.quiesce(30)
                if cand.cached > 0 and not cand.generated:
                    sess = cand  # parked with a partial prompt cache
                    break
                src.resume()  # overshot the prefill window: retry
                cand.wait(60)
            assert sess is not None, "never parked mid-prefill"
            moved = src.export_all()
            src.resume()
            (m, kv), = moved
            assert m is sess and kv is not None
            assert kv["k"].shape[1] == sess.cached < len(p)
            dst.import_session(sess, kv)
            assert sess.state == S_PREFILL  # NOT decode over nothing
            assert sess.wait(120) == ref_decode(p, 4)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_scale_down_during_prefill_zero_drops(self):
        """REVIEW regression, end-to-end: scale_to while prompts are
        still prefilling (no first token awaited) drops nothing and
        every continuation still matches the reference."""
        fleet = make_fleet(roles={"decode": 2}, prefill_chunk=2,
                           kv_block_size=4, kv_blocks=128,
                           max_blocks_per_session=32)
        try:
            ps = prompts(4, 40, 80)
            ss = [fleet.submit(p, max_new_tokens=4) for p in ps]
            assert fleet.scale_to(1) == 1  # mid-prefill for most
            for p, s in zip(ps, ss):
                assert s.wait(180) == ref_decode(p, 4)
            assert fleet.sessions_failed == 0
            assert fleet.sessions_completed == len(ps)
        finally:
            fleet.stop()

    def test_admission_defers_until_scatter_applied(self):
        """REVIEW regression: a session imported with its cache must
        not be slotted before its deferred K/V scatter lands —
        admission skips sids with a pending import, and the drain at
        the next iteration boundary releases them."""
        fleet = make_fleet(roles={"decode": 2}, kv_blocks=8,
                           kv_block_size=8, max_blocks_per_session=8)
        try:
            src, dst = [r for r in fleet._replicas
                        if r.role == "decode"]
            p = RNG.integers(1, 255, size=30).tolist()
            sess = DecodeSession(p, 2, id=91_000)
            src.submit(sess)
            sess.wait_first_token(60)
            assert src.quiesce(30)
            (m, kv), = src.export_all()
            src.resume()
            assert m is sess and kv is not None
            assert dst.quiesce(30)
            dst.import_session(sess, kv)
            assert sess.state == S_DECODING
            with dst._cond:
                dst._admit_locked()
            # scatter still pending: the session must NOT hold a slot
            assert sess.slot is None and sess in dst._queue
            dst._drain_imports()  # loop provably parked (quiesced)
            with dst._cond:
                dst._admit_locked()
            assert sess.slot is not None
            dst.resume()
            assert sess.wait(60) == ref_decode(p, 2)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_can_admit_skips_already_reserved_imports(self):
        """REVIEW regression: a queued session that already owns its
        pool blocks (imported with cache) must not ALSO count its full
        reservation toward queued demand — the double count made fleet
        admission over-conservative after migrations/handoffs."""
        fleet = make_fleet(roles={"decode": 2}, kv_blocks=8,
                           kv_block_size=8, max_blocks_per_session=8)
        try:
            src, dst = [r for r in fleet._replicas
                        if r.role == "decode"]
            p = RNG.integers(1, 255, size=30).tolist()  # 32-tok span
            moved = None
            for attempt in range(3):
                sess = DecodeSession(p, 2, id=92_000 + attempt)
                src.submit(sess)
                sess.wait_first_token(60)
                assert src.quiesce(30)
                moved = src.export_all()
                src.resume()
                if moved:
                    break
                # the last token landed before the park and the session
                # completed — nothing resident to export; retry
                sess.wait(60)
            assert moved, "session never parked mid-decode"
            (m, kv), = moved
            assert dst.quiesce(30)
            dst.import_session(sess, kv)  # 4 blocks reserved, queued
            assert dst.pool.blocks_free() == 4
            # an identical 4-block session fits the remaining half of
            # the pool; the old probe summed the import's 4 blocks on
            # top of its reservation and refused
            assert dst.can_admit(30, 2)
            dst.resume()
            assert sess.wait(60) == ref_decode(p, 2)
        finally:
            fleet.stop()
        """A survivor too full to adopt the cache still adopts the
        SESSION (re-prefill of known history) — capacity pressure
        degrades latency, never correctness."""
        fleet = make_fleet(roles={"decode": 2}, kv_blocks=8,
                           kv_block_size=4, max_blocks_per_session=8)
        try:
            ps = prompts(4, 4, 7)
            ss = [fleet.submit(p, max_new_tokens=10) for p in ps]
            for s in ss:
                s.wait_first_token(60)
            fleet.scale_to(1)
            for p, s in zip(ps, ss):
                assert s.wait(180) == ref_decode(p, 10)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()


class TestRollingReload:
    def test_rolling_reload_live_decode(self):
        """REGRESSION (watch_lineage under live decode): a reload must
        land at an iteration boundary with every in-flight session's
        cache preserved — zero sessions dropped through a rolling
        swap, and sessions keep decoding across it."""
        fleet = make_fleet(roles={"decode": 2})
        try:
            ps = prompts(5, 5, 9)
            ss = [fleet.submit(p, max_new_tokens=14) for p in ps]
            for s in ss:
                s.wait_first_token(60)
            # same values, fresh arrays: output parity proves the swap
            # went through the cached path without disturbing KV state
            p2 = jax.tree.map(lambda a: a * 1.0, PARAMS)
            assert fleet.rolling_reload(p2, generation=3) == 2
            assert fleet.generation == 3
            for p, s in zip(ps, ss):
                assert s.wait(180) == ref_decode(p, 14)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_reload_from_lineage_verified_only(self):
        class FakeCkpt:
            def __init__(self):
                self.restored = None

            def latest_verified_step(self):
                return 5

            def manifest_verified(self, step):
                return True

            def restore(self, template, step=None):
                self.last_restored_step = step
                return {"params": PARAMS}

        fleet = make_fleet()
        try:
            ck = FakeCkpt()
            assert fleet.reload_from_lineage(ck) == 5
            assert fleet.generation == 5
            # not newer → no-op
            assert fleet.reload_from_lineage(ck) is None
        finally:
            fleet.stop()

    def test_reload_skips_unverified(self):
        class BadCkpt:
            def latest_verified_step(self):
                return 9

            def manifest_verified(self, step):
                return False

            def restore(self, template, step=None):  # pragma: no cover
                raise AssertionError("must not restore unverified")

        fleet = make_fleet()
        try:
            assert fleet.reload_from_lineage(BadCkpt()) is None
            assert fleet.generation == 0
        finally:
            fleet.stop()


class TestKillDrill:
    def test_kill_rescues_by_recompute(self):
        """A SIGKILLed replica's device cache is GONE; survivors
        re-prefill each session's known history and continue token-
        identically (greedy decode is deterministic)."""
        fleet = make_fleet(roles={"decode": 2}, kv_blocks=64)
        try:
            ps = prompts(6, 5, 9)
            ss = [fleet.submit(p, max_new_tokens=12) for p in ps]
            for s in ss:
                s.wait_first_token(60)
            victim = next(r.name for r in fleet._replicas
                          if r.sessions_active() > 0)
            rescued = fleet.kill_replica(victim)
            assert rescued >= 1
            for p, s in zip(ps, ss):
                assert s.wait(180) == ref_decode(p, 12)
            assert fleet.sessions_failed == 0
        finally:
            fleet.stop()

    def test_kill_last_replica_fails_typed(self):
        """No survivor: every resident session fails with
        SessionDropped — typed, promptly, never a silent hang."""
        fleet = make_fleet(roles={"decode": 1})
        try:
            ss = [fleet.submit(p, max_new_tokens=30)
                  for p in prompts(3)]
            for s in ss:
                s.wait_first_token(60)
            only = fleet._replicas[0].name
            assert fleet.kill_replica(only) == 0
            for s in ss:
                with pytest.raises(SessionDropped):
                    s.wait(10)
            assert fleet.sessions_failed == len(ss)
        finally:
            fleet.stop()

    def test_abandoned_sessions_free_on_stop(self):
        fleet = make_fleet()
        try:
            ss = [fleet.submit(p, max_new_tokens=50)
                  for p in prompts(2)]
            for s in ss:
                s.wait_first_token(60)
        finally:
            fleet.stop(drain=False)
        for s in ss:
            with pytest.raises(SessionDropped):
                s.wait(10)
        assert fleet.kv_blocks()[0] == 0  # every block returned


class TestDisaggregation:
    def test_prefill_decode_handoff_parity(self):
        fleet = make_fleet(roles={"prefill": 1, "decode": 2})
        try:
            ps = prompts(5, 5, 10)
            ss = [fleet.submit(p, max_new_tokens=8) for p in ps]
            for p, s in zip(ps, ss):
                assert s.wait(120) == ref_decode(p, 8)
            # every session decoded on the decode tier after handoff
            assert all(s.replica.split("/")[-1].startswith("d")
                       for s in ss)
            assert fleet.migrations >= len(ps)
        finally:
            fleet.stop()


class TestStatsAndMetrics:
    def test_fleet_stats_shape(self):
        fleet = make_fleet()
        try:
            ss = [fleet.submit(p, max_new_tokens=8) for p in prompts(4)]
            for s in ss:
                s.wait(120)
            st = fleet.stats(window_s=600)
            assert st.ttft_p99_ms > 0
            assert st.requests_windowed == 4
            assert st.kv_blocks_total == 32
            assert st.replicas_ready == 1
        finally:
            fleet.stop()

    def test_histograms_preregistered(self):
        """The strict exposition parser must see the full TTFT/TPOT
        bucket blocks (every priority class) from scrape #1 — before
        any request has been observed into them."""
        from edl_tpu.observability.metrics import (
            get_registry,
            iter_samples,
            parse_exposition,
        )

        fleet = make_fleet(job="t/prereg")
        try:
            text = get_registry().render()
            parse_exposition(text)  # strict grammar must hold
            samples = list(iter_samples(text))
            names = {s[0] for s in samples}
            for fam in ("edl_serving_ttft_seconds",
                        "edl_serving_tpot_seconds"):
                assert fam + "_bucket" in names
                assert fam + "_count" in names
            for pri in ("high", "normal", "low"):
                assert any(name == "edl_serving_ttft_seconds_count"
                           and labels.get("priority") == pri
                           and labels.get("job") == "t/prereg"
                           for name, labels, _ in samples)
            assert "edl_serving_kv_blocks_total" in names
            assert "edl_serving_sessions_active" in names
        finally:
            fleet.stop()


class TestGenerateEndpoint:
    def test_http_generate_roundtrip(self):
        from edl_tpu.runtime.frontdoor import FleetApp, FrontDoor

        fleet = make_fleet(job="t/genhttp")

        class _NoFleet:
            generation = 0

            def replicas_ready(self):
                return 1

        app = FleetApp(_NoFleet(), row_dim=4, decode_fleet=fleet)
        door = FrontDoor(app, host="127.0.0.1", job="t/genhttp").start()
        try:
            p = [5, 9, 17]
            body = json.dumps({"prompt": p,
                               "max_new_tokens": 6}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{door.port}/generate", data=body,
                headers={"Content-Type": "application/json"})
            resp = urllib.request.urlopen(req, timeout=60)
            out = json.loads(resp.read())
            assert out["tokens"] == ref_decode(p, 6)
            assert resp.headers.get("X-EDL-Session") == str(out["session"])
            assert out["ttft_ms"] > 0
        finally:
            door.stop()
            fleet.stop()

    def test_http_generate_bad_request(self):
        from edl_tpu.runtime.frontdoor import FleetApp, FrontDoor

        fleet = make_fleet(job="t/genbad")

        class _NoFleet:
            generation = 0

            def replicas_ready(self):
                return 1

        app = FleetApp(_NoFleet(), row_dim=4, decode_fleet=fleet)
        door = FrontDoor(app, host="127.0.0.1", job="t/genbad").start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{door.port}/generate",
                data=b"{not json", headers={})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 400
        finally:
            door.stop()
            fleet.stop()


class TestLBAffinity:
    def test_session_pins_and_repins_on_death(self):
        """Pure routing-policy test on LBApp internals: a session block
        sticks to its pinned upstream; when the pin dies the block
        re-pins to a survivor (the decode fleet's handoff makes the
        survivor correct)."""
        from edl_tpu.runtime.lb import LBApp, _Cell, _OutBlock

        lb = LBApp(job="t/aff")

        class FakeUp:
            def __init__(self, name, load):
                self.name = name
                self.load = load
                self.alive = True

            def routable(self):
                return self.alive

            def outstanding(self):
                return self.load

        a, b = FakeUp("a", 5), FakeUp("b", 0)
        lb.upstreams = {"a": a, "b": b}
        blk = _OutBlock(None, None, 1, b"", _Cell())
        blk.session = "s1"
        # first pick: least-outstanding, then pinned
        assert lb._pick_affine(blk).name == "b"
        b.load = 100
        assert lb._pick_affine(blk).name == "b"  # sticky despite load
        # pinned upstream dies → fall back + re-pin
        b.alive = False
        assert lb._pick_affine(blk).name == "a"
        b.alive = True
        assert lb._pick_affine(blk).name == "a"  # re-pinned, stays
        # sessionless blocks are unaffected least-outstanding
        b.load = 0
        blk2 = _OutBlock(None, None, 1, b"", _Cell())
        assert lb._pick_affine(blk2).name == "b"

    def test_affinity_lru_bounded(self):
        from edl_tpu.runtime.lb import LBApp, _Cell, _OutBlock

        lb = LBApp(job="t/afflru")
        lb._affinity_cap = 8

        class FakeUp:
            name = "only"

            def routable(self):
                return True

            def outstanding(self):
                return 0

        lb.upstreams = {"only": FakeUp()}
        for i in range(50):
            blk = _OutBlock(None, None, 1, b"", _Cell())
            blk.session = f"s{i}"
            lb._pick_affine(blk)
        assert len(lb._affinity) == 8


class TestScalerTTFT:
    def test_ttft_breach_grows_and_gates_shrink(self):
        from edl_tpu.api.types import ServingJob, ServingSpec
        from edl_tpu.runtime.serving import FleetStats
        from edl_tpu.scheduler.autoscaler import ServingScaler

        spec = ServingSpec(min_replicas=1, max_replicas=8,
                           slo_p99_ms=0.0, slo_ttft_ms=200.0,
                           decode_slots=4)
        job = ServingJob(name="svc", namespace="t", spec=spec)
        pol = ServingScaler()
        breach = FleetStats(requests_windowed=10, ttft_p99_ms=900.0,
                            queue_depth=8)
        assert pol.decide(job, breach, current=2) > 2
        # inside SLO but not deep inside: hold (headroom hysteresis)
        edge = FleetStats(requests_windowed=10, ttft_p99_ms=150.0)
        assert pol.decide(job, edge, current=2) is None
        # deep headroom + empty queue → shrink one step
        idle = FleetStats(requests_windowed=10, ttft_p99_ms=10.0)
        assert pol.decide(job, idle, current=2) == 1
