"""Calibration plane tests (edl_tpu/observability/calib.py).

Covers the ledger core (EWMA factor, bounded sample rings,
zero-prediction accounting, strict exposition of every
``edl_calibration_*`` series), KV persistence + the job-GC sweep of
``calib/``, the CalibrationFactors read-back hook (caching, clamps,
min-sample gating, dead-coordinator neutrality), the opt-in calibrated
paths in ``choose_shape`` and the goodput allocator, the drift alert
rule fire/resolve cycle, the dashboard/CLI rendering, the cheap
instrumentation sites (trainer resize, scaler plan resolution, goodput
curve), and the HA failover acceptance property (factors readable from
a promoted standby after a primary SIGKILL).  The heavy decode-plane
predictors (kv_move_seconds, spec_accept, interleave_*) are exercised
end-to-end by the CI calib smoke and the bench calibration leg.
"""

from __future__ import annotations

import signal

import jax
import jax.numpy as jnp
import pytest

from edl_tpu.observability import calib
from edl_tpu.observability.calib import (
    CalibrationFactors,
    CalibrationLedger,
    load_factor,
    load_factors,
    nominal_transfer_seconds,
    set_process_calib,
)
from edl_tpu.observability.metrics import MetricsRegistry, parse_exposition
from edl_tpu.observability.scrape import (
    AlertEngine,
    CalibrationDriftRule,
    FleetView,
    default_rules,
    render_calib_dashboard,
    render_fleet_dashboard,
)
from tests.test_scrape import make_scraper


@pytest.fixture(autouse=True)
def _no_process_ledger():
    """Every test starts and ends with the process ledger disarmed —
    an armed ledger left behind would leak records into whichever test
    resizes a trainer next."""
    set_process_calib(None)
    yield
    set_process_calib(None)


def ledger(**kw):
    kw.setdefault("job", "ns/job")
    kw.setdefault("registry", MetricsRegistry())
    return CalibrationLedger(**kw)


# ---------------------------------------------------------------------------
# ledger core
# ---------------------------------------------------------------------------


def test_record_pairs_prediction_with_measurement():
    led = ledger()
    err = led.record("reshard_seconds", 2.0, 3.0, unit="s")
    assert err == pytest.approx(50.0)
    assert led.factor("reshard_seconds") == pytest.approx(1.5)
    assert led.sample_count("reshard_seconds") == 1
    # second sample moves the EWMA alpha of the way toward its factor
    led.record("reshard_seconds", 2.0, 2.0)
    assert led.factor("reshard_seconds") == pytest.approx(
        0.1 * 1.0 + 0.9 * 1.5)
    snap = led.snapshot()["predictors"]["reshard_seconds"]
    assert snap["samples"] == 2 and snap["unit"] == "s"
    assert snap["last_predicted"] == 2.0 and snap["last_measured"] == 2.0
    assert snap["error_pct_p50"] in (0.0, 50.0)  # exact over the ring
    assert led.predictors() == ["reshard_seconds"]
    assert led.factor("never_recorded") is None


def test_sample_ring_is_bounded_but_counters_are_not():
    led = ledger(ring_size=4)
    for i in range(10):
        led.record("p", 1.0, 1.0 + i)  # error i*100%
    assert led.sample_count("p") == 10
    ring = led.samples("p")
    assert len(ring) == 4
    # the ring holds the RECENT pairs (measured 7..10)
    assert [m for _, m, _ in ring] == [7.0, 8.0, 9.0, 10.0]
    # quantiles answer over the ring window, not lifetime
    assert led.error_pct_quantile("p", 0.0) == pytest.approx(600.0)
    assert led.error_pct_quantile("p", 0.99) == pytest.approx(900.0)
    assert led.error_pct_quantile("q", 0.5) is None


def test_zero_predictions_counted_never_divided():
    led = ledger()
    assert led.record("p", 0.0, 5.0) is None
    assert led.record("p", -1.0, 5.0) is None
    assert led.record("p", float("nan"), 5.0) is None
    assert led.record("p", 1.0, float("nan")) is None
    assert led.factor("p") is None and led.sample_count("p") == 0
    snap = led.snapshot()["predictors"]["p"]
    assert snap["zero_predictions"] == 4 and snap["factor"] is None
    # a later honest prediction still calibrates
    assert led.record("p", 1.0, 2.0) == pytest.approx(100.0)
    assert led.factor("p") == pytest.approx(2.0)


def test_exposition_is_strictly_parseable_with_all_series():
    reg = MetricsRegistry()
    led = ledger(registry=reg)
    led.record("reshard_seconds", 1.0, 2.0, unit="s")
    led.record("goodput_curve", 100.0, 90.0, unit="tok/s")
    led.record("goodput_curve", 0.0, 90.0)  # zero-prediction
    text = reg.render()
    series = parse_exposition(text)  # strict parse: raises on violations
    names = {key.split("{", 1)[0] for key in series}
    assert "edl_calibration_samples_total" in names
    assert "edl_calibration_factor" in names
    assert "edl_calibration_error_pct_bucket" in names
    assert "edl_calibration_zero_predictions_total" in names
    assert ('edl_calibration_factor{job="ns/job",'
            'predictor="reshard_seconds"} 2') in text
    assert ('edl_calibration_zero_predictions_total{job="ns/job",'
            'predictor="goodput_curve"} 1') in text


def test_process_ledger_helpers_are_safe_unarmed_and_armed():
    # unarmed: the module helper is a strict no-op
    calib.record("p", 1.0, 2.0)
    assert calib.get_process_calib() is None
    led = set_process_calib(ledger())
    assert calib.get_process_calib() is led
    calib.record("p", 1.0, 2.0)
    assert led.sample_count("p") == 1
    # a bad pair must never raise out of an instrumented hot path
    calib.record("p", "not-a-number", 2.0)
    assert led.sample_count("p") == 1


def test_nominal_transfer_seconds_prices_each_path():
    assert nominal_transfer_seconds(90e9) == pytest.approx(1.0)
    assert nominal_transfer_seconds(0.0, 6.25e9) == pytest.approx(1.0)
    # host fallback: both byte counts ride the host fabric
    assert nominal_transfer_seconds(4e9, 4e9, host=True) == pytest.approx(
        1.0)
    assert nominal_transfer_seconds(0.0) == 0.0


# ---------------------------------------------------------------------------
# KV persistence + GC + read-back
# ---------------------------------------------------------------------------


def test_factor_record_roundtrip_on_py_backend():
    from edl_tpu.coord import PyCoordService

    svc = PyCoordService()
    led = ledger(coord=svc)
    led.record("reshard_seconds", 1.0, 2.0, unit="s", path="ici")
    assert svc.kv_get("calib/ns/job/reshard_seconds") is not None
    doc = load_factor(svc, "ns/job", "reshard_seconds")
    assert doc["factor"] == pytest.approx(2.0)
    assert doc["n"] == 1 and doc["unit"] == "s"
    assert doc["labels"] == {"path": "ici"}
    assert load_factor(svc, "ns/job", "nope") is None
    led.record("kv_move_seconds", 1.0, 1.5)
    assert set(load_factors(svc, "ns/job")) == {"reshard_seconds",
                                                "kv_move_seconds"}
    assert load_factors(svc, "other/job") == {}


def test_calib_prefix_swept_on_job_deletion():
    from edl_tpu.coord import PyCoordService
    from edl_tpu.coord.gc import JOB_KV_PREFIXES, gc_job_kv

    assert "calib/" in JOB_KV_PREFIXES
    svc = PyCoordService()
    doomed = ledger(job="ns/doomed", coord=svc)
    doomed.record("reshard_seconds", 1.0, 2.0)
    doomed.record("goodput_curve", 10.0, 9.0)
    sibling = ledger(job="ns/doomedx", coord=svc)  # prefix-adjacent uid
    sibling.record("reshard_seconds", 1.0, 2.0)
    removed = gc_job_kv(svc, "ns/doomed")
    assert removed == 2
    assert load_factors(svc, "ns/doomed") == {}
    # the adjacent job's record survives — the sweep is uid-exact
    assert set(load_factors(svc, "ns/doomedx")) == {"reshard_seconds"}


def test_factors_readback_caches_gates_and_clamps():
    from edl_tpu.coord import PyCoordService

    svc = PyCoordService()
    led = ledger(job="j", coord=svc)
    for _ in range(3):
        led.record("honest", 1.0, 3.0)
    led.record("thin", 1.0, 5.0)  # one sample only
    for _ in range(3):
        led.record("wild", 1.0, 1000.0)
    clock = [0.0]
    cf = CalibrationFactors(svc, "j", refresh_s=10.0,
                            clock=lambda: clock[0])
    assert cf.factor("honest") == pytest.approx(3.0)
    assert cf.scale("honest", 10.0) == pytest.approx(30.0)
    # below min_samples and unknown predictors answer neutral
    assert cf.factor("thin") == 1.0
    assert cf.factor("missing") == 1.0
    # a wild record clamps instead of multiplying estimates by 1000
    assert cf.factor("wild") == 20.0
    # the cache holds inside refresh_s: new KV state is invisible...
    for _ in range(3):
        led.record("late", 1.0, 2.0)
    assert cf.factor("late") == 1.0
    clock[0] = 11.0  # ...and one refresh later it is
    assert cf.factor("late") == pytest.approx(2.0)


def test_factors_readback_neutral_on_dead_coordinator():
    class Dead:
        def kv_keys(self, prefix):
            raise ConnectionError("coordinator unreachable")

        def kv_get(self, key):
            raise ConnectionError("coordinator unreachable")

    cf = CalibrationFactors(Dead(), "j")
    assert cf.factor("anything") == 1.0
    assert cf.scale("anything", 7.0) == 7.0


# ---------------------------------------------------------------------------
# the opt-in calibrated estimate paths
# ---------------------------------------------------------------------------


def test_choose_shape_accepts_calibration_and_stays_neutral_at_one():
    from edl_tpu.parallel.mesh import make_mesh, tree_shardings
    from edl_tpu.parallel.replan import choose_shape

    devs = jax.devices()[:4]
    tree = {"w": jnp.zeros((16, 32)), "b": jnp.zeros((4,))}
    from edl_tpu.parallel.mesh import MeshShape

    mesh = make_mesh(4, MeshShape(dp=4).to_spec(), devices=devs)
    sh0 = tree_shardings(mesh, tree, "fsdp")
    base_shape, base_plan = choose_shape(tree, sh0, 4, devs, "fsdp")
    asked: list[str] = []

    def factors(predictor):
        asked.append(predictor)
        return 1.0

    shape, plan = choose_shape(tree, sh0, 4, devs, "fsdp",
                               calibration=factors)
    # a neutral factor must not change the choice, and the hook reads
    # the reshard_seconds predictor (the factor the trainer records)
    assert shape == base_shape and plan.bytes_moved == base_plan.bytes_moved
    assert asked == ["reshard_seconds"]

    def broken(predictor):
        raise RuntimeError("kv down")

    shape2, _ = choose_shape(tree, sh0, 4, devs, "fsdp",
                             calibration=broken)
    assert shape2 == base_shape  # exception degrades to neutral

    class FactorsShaped:
        def factor(self, predictor):
            asked.append(f"obj:{predictor}")
            return 1.0

    shape3, _ = choose_shape(tree, sh0, 4, devs, "fsdp",
                             calibration=FactorsShaped())
    assert shape3 == base_shape
    assert asked[-1] == "obj:reshard_seconds"


def test_goodput_step_marginal_scales_only_the_measured_branch():
    from edl_tpu.observability.goodput import ScalingCurve
    from edl_tpu.scheduler.planner import _step_marginal

    c = ScalingCurve()
    c.observe(2, 100.0)
    c.observe(4, 180.0)
    assert _step_marginal(c, 4, 1, 0.0) == pytest.approx(40.0)
    assert _step_marginal(c, 4, 1, 0.0, calib_factor=0.5) == \
        pytest.approx(20.0)
    # the optimistic prior is an exploration bonus, not a curve
    # prediction: the factor must not rename it
    assert _step_marginal(None, 4, 1, 123.0, calib_factor=0.5) == 123.0
    assert _step_marginal(ScalingCurve(), 4, 1, 77.0,
                          calib_factor=0.5) == 77.0


def test_goodput_allocator_threads_the_calibration_factor():
    from tests.test_sched_goodput import curve, curves_for, \
        one_domain_cluster, priced_job

    from edl_tpu.scheduler.planner import scale_all_jobs_goodput

    def jobs():
        return [priced_job("a", 1, 0, 4, 0)]

    cv = curves_for({"default/a": curve({1: 100.0, 2: 200.0, 4: 400.0})})
    base = scale_all_jobs_goodput(jobs(), one_domain_cluster(1, 4), 1.0,
                                  curves=cv)
    assert base.marginals["default/a"] == pytest.approx(100.0)
    scaled = scale_all_jobs_goodput(
        jobs(), one_domain_cluster(1, 4), 1.0, curves=cv,
        calibration=lambda p: 0.5)
    # same grants (one uncontended job), but the marginal that PRICED
    # them carries the measured correction
    assert scaled.diff == base.diff
    assert scaled.marginals["default/a"] == pytest.approx(50.0)

    # a raising / non-positive calibration source degrades to neutral
    def broken(p):
        raise RuntimeError("kv down")

    neutral = scale_all_jobs_goodput(jobs(), one_domain_cluster(1, 4),
                                     1.0, curves=cv, calibration=broken)
    assert neutral.marginals == base.marginals
    zero = scale_all_jobs_goodput(jobs(), one_domain_cluster(1, 4), 1.0,
                                  curves=cv, calibration=lambda p: 0.0)
    assert zero.marginals == base.marginals


# ---------------------------------------------------------------------------
# instrumentation sites (the cheap ones; decode plane rides the CI smoke)
# ---------------------------------------------------------------------------


def test_trainer_resize_records_reshard_calibration():
    from tests.test_prewarm import batch, make_trainer

    led = set_process_calib(ledger(job="t/resize"))
    tr = make_trainer()
    tr.step(batch())
    assert tr.resize(4)
    evt = tr.resize_events[-1]
    # the measured rate rides the event next to the plan-derived bytes
    assert "reshard_gbps" in evt and evt["reshard_gbps"] >= 0.0
    assert led.sample_count("reshard_seconds") == 1
    assert led.factor("reshard_seconds") > 0.0
    _, _, err = led.samples("reshard_seconds")[0]
    assert err >= 0.0


def test_run_report_carries_measured_resize_gbps_field():
    from edl_tpu.runtime.local import RunReport

    assert RunReport().resize_gbps == []


def test_curve_store_records_goodput_curve_predictor():
    from edl_tpu.coord import PyCoordService
    from edl_tpu.observability.goodput import CurveStore

    led = set_process_calib(ledger(job="g/job"))
    store = CurveStore(PyCoordService(), "g/job",
                       registry=MetricsRegistry())
    store.record(4, 1000.0)  # no prior prediction at ws=4: nothing pairs
    assert led.sample_count("goodput_curve") == 0
    store.record(4, 900.0)  # the curve predicted 1000 here
    assert led.sample_count("goodput_curve") == 1
    pred, measured, err = led.samples("goodput_curve")[0]
    assert (pred, measured) == (1000.0, 900.0)
    assert err == pytest.approx(10.0)


def test_token_scheduler_exposes_its_interleave_predictions():
    from edl_tpu.runtime.serving import TokenScheduler

    sched = TokenScheduler()
    assert sched.predicted_decode_ms() is None  # no sample: no prediction
    assert sched.predicted_prefill_ms() is None
    sched.note_decode(10.0)
    sched.note_prefill(40.0)
    assert sched.predicted_decode_ms() == pytest.approx(10.0)
    assert sched.predicted_prefill_ms() == pytest.approx(40.0)


def test_serving_scaler_resolves_plan_predictions_after_settle():
    from edl_tpu.api.types import ServingJob, ServingSpec
    from edl_tpu.runtime.serving import FleetStats
    from edl_tpu.scheduler.autoscaler import ServingScaler

    led = set_process_calib(ledger(job="default/svc"))
    clock = [100.0]
    stats = {"default/svc": FleetStats(
        p50_ms=30.0, p99_ms=80.0, qps=10.0, queue_depth=0,
        replicas_ready=2, replicas_active=2, requests_windowed=20)}
    sc = ServingScaler(stats_for=lambda uid: stats[uid],
                       actuate=lambda uid, n: None,
                       clock=lambda: clock[0])
    sc.on_add(ServingJob(name="svc", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=50.0)))
    assert sc.tick() == {"default/svc": 3}  # breach → plan to 3
    assert led.sample_count("serving_scale_qps") == 0  # not settled yet
    # fleet settles AT the target with a realized window: the plan's
    # predicted qps/p99 pair with what the window measured
    stats["default/svc"] = FleetStats(
        p50_ms=10.0, p99_ms=30.0, qps=12.0, queue_depth=0,
        replicas_ready=3, replicas_active=3, requests_windowed=25)
    clock[0] += sc.calib_settle_s + 1.0
    sc.tick()
    assert led.sample_count("serving_scale_qps") == 1
    assert led.sample_count("serving_scale_p99") == 1
    qp, qm, _ = led.samples("serving_scale_qps")[0]
    assert (qp, qm) == (10.0, 12.0)  # demand carryover vs realized
    pp, pm, _ = led.samples("serving_scale_p99")[0]
    assert (pp, pm) == (50.0, 30.0)  # the SLO the plan promised
    # the pending resolves exactly once
    clock[0] += sc.calib_settle_s + 1.0
    sc.tick()
    assert led.sample_count("serving_scale_qps") == 1


def test_serving_scaler_drops_superseded_predictions():
    from edl_tpu.api.types import ServingJob, ServingSpec
    from edl_tpu.runtime.serving import FleetStats
    from edl_tpu.scheduler.autoscaler import ServingScaler

    led = set_process_calib(ledger(job="default/svc"))
    clock = [100.0]
    stats = {"default/svc": FleetStats(
        p50_ms=30.0, p99_ms=80.0, qps=10.0, queue_depth=0,
        replicas_ready=2, replicas_active=2, requests_windowed=20)}
    sc = ServingScaler(stats_for=lambda uid: stats[uid],
                       actuate=lambda uid, n: None,
                       clock=lambda: clock[0])
    sc.on_add(ServingJob(name="svc", spec=ServingSpec(
        min_replicas=1, max_replicas=8, slo_p99_ms=50.0)))
    assert sc.tick() == {"default/svc": 3}
    # the fleet never reaches the target (stuck at 2, now healthy):
    # the prediction is scored against nothing
    stats["default/svc"] = FleetStats(
        p50_ms=10.0, p99_ms=30.0, qps=10.0, queue_depth=0,
        replicas_ready=2, replicas_active=2, requests_windowed=20)
    clock[0] += sc.calib_settle_s + 1.0
    sc.tick()
    assert led.sample_count("serving_scale_qps") == 0
    assert led.sample_count("serving_scale_p99") == 0


# ---------------------------------------------------------------------------
# scrape plane: summary, drift rule, dashboards
# ---------------------------------------------------------------------------


def _scraped_ledger_view(windows=1):
    """A FleetView over a scraped registry fed by a real ledger, with
    enough sweeps for windowed quantiles to have deltas."""
    reg = MetricsRegistry()
    led = CalibrationLedger(job="j", registry=reg)
    s, clock = make_scraper({"t": reg.render})
    s.sweep()
    clock.advance(1.0)
    led.record("reshard_seconds", 1.0, 1.5, unit="s")
    led.record("goodput_curve", 100.0, 95.0, unit="tok/s")
    s.sweep()
    return FleetView(s, window_s=10.0), led, s, clock


def test_fleetview_calibration_summary_rolls_up_per_predictor():
    view, led, _, _ = _scraped_ledger_view()
    summary = view.calibration_summary()
    assert set(summary) == {"j"}
    assert set(summary["j"]) == {"reshard_seconds", "goodput_curve"}
    rs = summary["j"]["reshard_seconds"]
    assert rs["factor"] == pytest.approx(1.5)
    assert rs["samples"] == 1
    assert rs["error_pct_p50"] is not None  # windowed deltas exist
    # and the full snapshot carries the table for the dashboard
    assert view.snapshot()["calibration"]["j"]["goodput_curve"][
        "factor"] == pytest.approx(0.95)


def test_calibration_drift_rule_fires_after_consecutive_windows():
    reg = MetricsRegistry()
    g = reg.gauge("calibration_factor")
    n = reg.counter("calibration_samples")
    g.set(5.0, job="j", predictor="p")
    n.inc(10, job="j", predictor="p")
    s, clock = make_scraper({"t": reg.render})
    s.sweep()
    view = FleetView(s, window_s=10.0)
    engine = AlertEngine(view, rules=[CalibrationDriftRule(windows=3)],
                         registry=MetricsRegistry())
    engine.evaluate()
    engine.evaluate()
    assert engine.firing() == []  # 2 consecutive windows: not yet
    engine.evaluate()
    firing = engine.firing()
    assert [a.rule for a in firing] == ["calibration_drift"]
    assert firing[0].labels == {"job": "j", "predictor": "p"}
    # the factor returns to band: the streak resets and the alert
    # resolves on the next evaluation
    g.set(1.2, job="j", predictor="p")
    clock.advance(1.0)
    s.sweep()
    engine.evaluate()
    assert engine.firing() == []


def test_calibration_drift_needs_min_samples():
    reg = MetricsRegistry()
    reg.gauge("calibration_factor").set(9.0, job="j", predictor="p")
    reg.counter("calibration_samples").inc(2, job="j", predictor="p")
    s, _ = make_scraper({"t": reg.render})
    s.sweep()
    engine = AlertEngine(FleetView(s),
                         rules=[CalibrationDriftRule(windows=1,
                                                     min_samples=3)],
                         registry=MetricsRegistry())
    engine.evaluate()
    assert engine.firing() == []  # 2 samples: too thin to page anyone


def test_drift_rule_ships_in_default_rules():
    assert any(isinstance(r, CalibrationDriftRule)
               for r in default_rules())


def test_calib_dashboard_renders_factors_and_drift():
    view, _, s, clock = _scraped_ledger_view()
    engine = AlertEngine(view, rules=[CalibrationDriftRule(windows=1)],
                         registry=MetricsRegistry())
    engine.evaluate()
    out = render_calib_dashboard(view, engine)
    assert "reshard_seconds" in out and "goodput_curve" in out
    assert "1.5" in out and "ok" in out
    assert "DRIFT: none firing" in out
    # the fleet dashboard carries the same table as a section
    assert "CALIBRATION" in render_fleet_dashboard(view, engine)
    # an out-of-band predictor renders as DRIFT and the firing alert
    # is listed once the rule trips
    view2_reg = MetricsRegistry()
    led2 = CalibrationLedger(job="j2", registry=view2_reg)
    for _ in range(3):
        led2.record("kv_move_seconds", 1.0, 10.0)
    s2, _ = make_scraper({"t": view2_reg.render})
    s2.sweep()
    view2 = FleetView(s2)
    engine2 = AlertEngine(view2, rules=[CalibrationDriftRule(windows=1)],
                          registry=MetricsRegistry())
    engine2.evaluate()
    out2 = render_calib_dashboard(view2, engine2)
    assert "CALIBRATION DRIFT FIRING (1)" in out2
    assert "kv_move_seconds" in out2


def test_calib_dashboard_empty_view_degrades_gracefully():
    reg = MetricsRegistry()
    s, _ = make_scraper({"t": reg.render})
    s.sweep()
    out = render_calib_dashboard(FleetView(s))
    assert "no calibration series scraped" in out


def test_cli_calib_verb_renders_scraped_factors(capsys):
    from edl_tpu import cli
    from edl_tpu.observability.health import serve_health

    reg = MetricsRegistry()
    led = CalibrationLedger(job="cli/job", registry=reg)
    led.record("reshard_seconds", 1.0, 1.4, unit="s")
    srv = serve_health(0, {}, host="127.0.0.1", registry=reg)
    try:
        port = srv.server_address[1]
        rc = cli.main(["calib", "--scrape-targets", f"127.0.0.1:{port}",
                       "--sweeps", "1", "--check"])
    finally:
        srv.shutdown()
    out = capsys.readouterr().out
    assert rc == 0  # in-band factor: --check stays green
    assert "reshard_seconds" in out and "cli/job" in out
    assert "1.4" in out


# ---------------------------------------------------------------------------
# HA: factors survive a coordinator-primary SIGKILL
# ---------------------------------------------------------------------------


@pytest.mark.multihost
def test_factors_survive_primary_failover(tmp_path):
    """The acceptance property: factor records written against the HA
    pair's primary are readable from the promoted standby after a
    SIGKILL, and the promoted primary accepts new samples (same harness
    as the goodput curve's failover pin)."""
    from edl_tpu.coord import CoordClient, native_available, spawn_ha_pair

    if not native_available():
        pytest.skip("no native coordinator core")
    pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
    c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                    reconnect_window_s=12.0, promote_grace_s=0.2,
                    endpoints=[("127.0.0.1", sb.port)])
    try:
        led = CalibrationLedger(job="ha/job", coord=c,
                                registry=MetricsRegistry())
        led.record("reshard_seconds", 1.0, 2.0, unit="s")
        led.record("goodput_curve", 100.0, 90.0, unit="tok/s")
        pr.process.send_signal(signal.SIGKILL)
        pr.process.wait(timeout=10)
        # the next read transparently fails over and promotes
        survived = load_factors(c, "ha/job")
        assert (c.host, c.port) == ("127.0.0.1", sb.port)
        assert set(survived) == {"reshard_seconds", "goodput_curve"}
        assert survived["reshard_seconds"]["factor"] == pytest.approx(2.0)
        # the promoted primary keeps accepting samples, and the
        # read-back hook prices from the survivor
        led.record("reshard_seconds", 1.0, 2.0)
        led.record("reshard_seconds", 1.0, 2.0)
        cf = CalibrationFactors(c, "ha/job", min_samples=3)
        assert cf.factor("reshard_seconds") == pytest.approx(2.0)
    finally:
        c.close()
        pr.stop()
        sb.stop()
