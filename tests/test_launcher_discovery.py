"""Launcher + discovery tests (reference docker/paddle_k8s, k8s_tools.py)."""

import threading
import time

import pytest

from edl_tpu.api.types import (
    RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_TPU,
    ResourceRequirements, TrainerSpec, TrainingJob, TrainingJobSpec,
)
from edl_tpu.cluster.base import PodPhase
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.coord.service import PyCoordService
from edl_tpu.runtime.discovery import (
    CoordDiscovery, DiscoveryTimeout, PodDiscovery,
)
from edl_tpu.runtime import launcher


def _submit(c, name="j1", lo=3, hi=3):
    job = TrainingJob(
        name=name,
        spec=TrainingJobSpec(
            fault_tolerant=True,
            trainer=TrainerSpec(
                min_instance=lo, max_instance=hi,
                resources=ResourceRequirements(
                    requests={RESOURCE_CPU: "1", RESOURCE_MEMORY: "1G"},
                    limits={RESOURCE_TPU: "1"},
                ),
            ),
        ),
    )
    c.create_resources(job)
    c.reconcile()
    return job


def _cluster():
    c = FakeCluster()
    c.add_node("n0", cpu_milli=64000, memory_mega=64000, tpu_chips=8)
    return c


class TestPodDiscovery:
    def test_count_and_wait(self):
        c = _cluster()
        job = _submit(c)
        d = PodDiscovery(c, job.full_name, poll_s=0.0)
        assert d.count_pods_by_phase(PodPhase.RUNNING) == 3
        assert d.wait_pods_running(3, timeout_s=1.0) == 3

    def test_wait_timeout(self):
        c = _cluster()
        job = _submit(c)
        d = PodDiscovery(c, job.full_name, poll_s=0.01)
        with pytest.raises(DiscoveryTimeout):
            d.wait_pods_running(10, timeout_s=0.05)

    def test_rank_from_sorted_names(self):
        c = _cluster()
        job = _submit(c)
        d = PodDiscovery(c, job.full_name, poll_s=0.0)
        addrs = d.fetch_addresses()
        assert addrs == sorted(addrs) and len(addrs) == 3
        assert d.fetch_rank(addrs[1]) == 1
        with pytest.raises(RuntimeError):
            d.fetch_rank("nonexistent")

    def test_terminating_counted(self):
        c = _cluster()
        job = _submit(c)
        pod = c.list_pods(job_uid=job.full_name)[0]
        pod.deletion_timestamp = True
        d = PodDiscovery(c, job.full_name, poll_s=0.0)
        assert d.count_pods_by_phase(PodPhase.TERMINATING) == 1
        assert d.count_pods_by_phase(PodPhase.RUNNING) == 2


class TestCoordDiscovery:
    def test_rank_stable_under_rejoin(self):
        svc = PyCoordService()
        a = CoordDiscovery(svc, "worker-a", "10.0.0.9")
        b = CoordDiscovery(svc, "worker-b", "10.0.0.1")
        a.join(), b.join()
        assert a.rank_and_world() == (0, 2)
        assert b.rank_and_world() == (1, 2)
        # replacement pod for a rejoins with the same name → same rank,
        # unlike IP-sort (b's lower IP would have stolen rank 0)
        a.leave()
        a2 = CoordDiscovery(svc, "worker-a", "10.0.0.200")
        a2.join()
        assert a2.rank_and_world() == (0, 2)

    def test_epoch_bumps_on_membership_change(self):
        svc = PyCoordService()
        a = CoordDiscovery(svc, "a")
        e0 = a.join()
        b = CoordDiscovery(svc, "b")
        e1 = b.join()
        assert e1 > e0
        b.leave()
        assert a.epoch() > e1

    def test_wait_members(self):
        svc = PyCoordService()
        a = CoordDiscovery(svc, "a")
        a.join()

        def late_join():
            time.sleep(0.05)
            CoordDiscovery(svc, "b").join()

        t = threading.Thread(target=late_join)
        t.start()
        peers = a.wait_members(2, timeout_s=2.0, poll_s=0.01)
        t.join()
        assert [n for n, _ in peers] == ["a", "b"]

    def test_rank_requires_join(self):
        svc = PyCoordService()
        d = CoordDiscovery(svc, "ghost")
        with pytest.raises(RuntimeError):
            d.rank_and_world()

    def test_keepalive_outlives_member_ttl(self):
        """A member inside keepalive() must not expire even when the
        block outlasts the TTL (the launcher runs user entrypoints for
        hours; without background heartbeats the epoch would bump and
        peers would see a phantom scale-down).

        The service runs on a fake clock advanced in sub-TTL steps, with
        a generous real-time window for the beat thread to refresh the
        deadline, so a loaded CI machine can't flake this."""
        now = [0]
        svc = PyCoordService(member_ttl_ms=100, clock=lambda: now[0])
        a = CoordDiscovery(svc, "a")
        epoch_after_join = a.join()
        with a.keepalive(interval_s=0.002):
            for _ in range(10):  # 6 TTLs of fake time in total
                now[0] += 60
                time.sleep(0.05)  # ≥ ~20 beats refresh at the new time
                assert [n for n, _ in a.peers()] == ["a"]
            assert a.epoch() == epoch_after_join
        a.leave()

    def test_no_keepalive_expires_after_ttl(self):
        """Control for the test above: without keepalive the TTL fires
        (deterministic: fake clock, no heartbeats anywhere)."""
        now = [0]
        svc = PyCoordService(member_ttl_ms=100, clock=lambda: now[0])
        a = CoordDiscovery(svc, "a")
        a.join()
        now[0] += 150
        assert a.peers() == []


class TestLauncher:
    def test_classify_exit(self):
        assert launcher.classify_exit(139) == "Segmentation fault (core dumped)"
        assert launcher.classify_exit(136).startswith("Floating point")
        assert launcher.classify_exit(134).startswith("Aborted")
        assert launcher.classify_exit(0) is None
        assert launcher.classify_exit(1) is None

    def test_termination_log(self, tmp_path):
        p = tmp_path / "term.log"
        launcher.write_termination_log(139, str(p))
        assert "Segmentation fault" in p.read_text()
        launcher.write_termination_log(0, str(p / "never"))  # no-op

    def test_check_failed_cnt(self):
        c = _cluster()
        job = _submit(c)
        d = PodDiscovery(c, job.full_name, poll_s=0.0)
        assert not launcher.check_failed_cnt(d, 0)
        # FakeCluster's Job controller re-creates failed pods; count both
        pod = c.list_pods(job_uid=job.full_name)[0]
        pod.phase = PodPhase.FAILED  # fail without reconcile
        assert launcher.check_failed_cnt(d, 0)
        assert not launcher.check_failed_cnt(d, 3)

    def test_run_entry_ok_and_crash(self, tmp_path):
        assert launcher.run_entry("true") == 0
        marker = tmp_path / "ws" ; marker.mkdir()
        code = launcher.run_entry("pwd > out.txt", workspace=str(marker))
        assert code == 0
        assert str(marker) in (marker / "out.txt").read_text()
        assert launcher.run_entry("exit 7") == 7

    def test_start_trainer_end_to_end(self, tmp_path):
        """FT trainer startup against a live coordination server."""
        from edl_tpu.coord.server import spawn_server

        handle = spawn_server(port=0)
        try:
            out = tmp_path / "env.txt"
            code = launcher.start_trainer(
                coord_host="127.0.0.1", coord_port=handle.port,
                entry=f'echo "$EDL_COORD_HOST:$EDL_COORD_PORT '
                      f'$EDL_WORKER_NAME" > {out}',
                worker_name="trainer-0", wait_timeout_s=10.0,
            )
            assert code == 0
            text = out.read_text()
            assert f"127.0.0.1:{handle.port}" in text
            assert "trainer-0" in text
            # worker left membership on exit
            client = handle.client()
            _, members = client.members()
            assert members == []
            client.close()
        finally:
            handle.stop()

    def test_main_dispatch_unknown(self, capsys):
        assert launcher.main(["bogus"]) == 2
        assert launcher.main([]) == 2

    def test_main_trainer_without_coord_env_fails_loudly(self, monkeypatch,
                                                         capsys):
        monkeypatch.delenv("EDL_COORD_ENDPOINT", raising=False)
        monkeypatch.delenv("EDL_COORD_HOST", raising=False)
        assert launcher.main(["start_trainer"]) == 2
        assert "no coordinator address" in capsys.readouterr().err

    def test_resolve_coordinator_endpoint(self):
        r = launcher.resolve_coordinator_endpoint
        assert r({"EDL_COORD_ENDPOINT": "svc:9000"}, 7164) == ("svc", 9000)
        assert r({"EDL_COORD_ENDPOINT": "svc"}, 7164) == ("svc", 7164)
        assert r({"EDL_COORD_HOST": "h"}, 7164) == ("h", 7164)
        # endpoint wins over host
        assert r({"EDL_COORD_ENDPOINT": "a:1", "EDL_COORD_HOST": "b"},
                 7164) == ("a", 1)
        with pytest.raises(ValueError):
            r({}, 7164)

    def test_start_pserver_joins_and_leaves(self):
        from edl_tpu.coord.server import spawn_server

        handle = spawn_server(port=0)
        try:
            client = handle.client()
            seen = []

            def park():
                _, members = client.members()
                seen.append(members)

            code = launcher.start_pserver(
                coord_host="127.0.0.1", coord_port=handle.port,
                worker_name="ps0", wait_timeout_s=10.0, park=park)
            assert code == 0
            assert seen and seen[0][0][0] == "pserver/ps0"
            _, members = client.members()
            assert members == []  # left on exit
            client.close()
        finally:
            handle.stop()


class TestStaticVerbDispatch:
    def test_jobparser_emits_static_verb_for_non_ft(self):
        """The reference switches start_new_trainer vs start_trainer v2 on
        fault_tolerant (pkg/jobparser.go:124); the compiled command must
        switch the same way."""
        from edl_tpu.api.types import (ResourceRequirements, TrainerSpec,
                                       TrainingJob, TrainingJobSpec)
        from edl_tpu.controller.jobparser import parse_to_trainer

        def job(ft):
            return TrainingJob(name="j", spec=TrainingJobSpec(
                fault_tolerant=ft,
                trainer=TrainerSpec(entrypoint="true", min_instance=2,
                                    max_instance=2,
                                    resources=ResourceRequirements())))

        cmd_ft = parse_to_trainer(job(True))["spec"]["template"]["spec"][
            "containers"][0]["command"]
        cmd_static = parse_to_trainer(job(False))["spec"]["template"][
            "spec"]["containers"][0]["command"]
        assert cmd_ft[-1] == "start_trainer"
        assert cmd_static[-1] == "start_static_trainer"

    def test_main_static_trainer_runs_entry_with_rank(self, tmp_path,
                                                      monkeypatch):
        """`launcher start_static_trainer` under the EDL_* env contract:
        barrier on the pod count, rank from the sorted pod list, entry
        exec'd with EDL_TRAINER_ID/TRAINERS/ADDRESSES exported."""
        from edl_tpu.cluster.base import PodPhase
        from edl_tpu.cluster.fake import FakeCluster, FakePod
        from edl_tpu.runtime.discovery import PodDiscovery

        fake = FakeCluster()
        for i in range(2):
            fake._pods[f"j-trainer-{i}"] = FakePod(
                name=f"j-trainer-{i}", job_uid="default/j", role="trainer",
                phase=PodPhase.RUNNING, node="n0")
        monkeypatch.setattr(
            launcher, "_pod_discovery_from_env",
            lambda env: PodDiscovery(fake, "default/j"))
        out = tmp_path / "env.txt"
        monkeypatch.setenv("EDL_JOB_NAME", "j")
        monkeypatch.setenv("EDL_POD_NAME", "j-trainer-1")
        monkeypatch.setenv("EDL_TRAINER_MIN", "2")
        monkeypatch.setenv(
            "EDL_ENTRY",
            f'echo "$EDL_TRAINER_ID/$EDL_TRAINERS $EDL_TRAINER_ADDRESSES"'
            f' > {out}')
        assert launcher.main(["start_static_trainer"]) == 0
        text = out.read_text()
        assert "1/2" in text
        assert "j-trainer-0,j-trainer-1" in text

    def test_main_static_trainer_env_peers_backend(self, tmp_path,
                                                   monkeypatch):
        """EDL_STATIC_PEERS gives the static path a discovery backend
        without a kubernetes client (harness / bare-metal runs): rank
        from the sorted names, addresses from the peer spec."""
        out = tmp_path / "env.txt"
        monkeypatch.setenv("EDL_JOB_NAME", "j")
        monkeypatch.setenv("EDL_POD_NAME", "p-b")
        monkeypatch.setenv("EDL_TRAINER_MIN", "2")
        monkeypatch.setenv("EDL_STATIC_PEERS",
                           "p-b=10.0.0.2,p-a=10.0.0.1")
        monkeypatch.setenv(
            "EDL_ENTRY",
            f'echo "$EDL_TRAINER_ID/$EDL_TRAINERS $EDL_TRAINER_ADDRESSES"'
            f' > {out}')
        assert launcher.main(["start_static_trainer"]) == 0
        assert out.read_text().strip() == "1/2 10.0.0.1,10.0.0.2"

    def test_trainer_manifest_carries_downward_identity(self):
        """EDL_POD_NAME/EDL_POD_IP come from the downward API — HOSTNAME
        is the node's name under hostNetwork and cannot be the identity."""
        from edl_tpu.api.types import (ResourceRequirements, TrainerSpec,
                                       TrainingJob, TrainingJobSpec)
        from edl_tpu.controller.jobparser import parse_to_trainer

        job = TrainingJob(name="j", spec=TrainingJobSpec(
            fault_tolerant=False, host_network=True,
            trainer=TrainerSpec(entrypoint="true", min_instance=1,
                                max_instance=1,
                                resources=ResourceRequirements())))
        env = parse_to_trainer(job)["spec"]["template"]["spec"][
            "containers"][0]["env"]
        by_name = {e["name"]: e for e in env}
        assert by_name["EDL_POD_NAME"]["valueFrom"][
            "fieldRef"]["fieldPath"] == "metadata.name"
        assert by_name["EDL_POD_IP"]["valueFrom"][
            "fieldRef"]["fieldPath"] == "status.podIP"
