"""Transformer core, pallas flash attention (interpret mode), and ring
attention — all validated against the reference attention math on the
virtual CPU mesh."""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.models import mlp, transformer, word2vec
from edl_tpu.ops.flash_attention import attention, reference_attention
from edl_tpu.parallel.compat import set_mesh
from edl_tpu.parallel.mesh import MeshSpec, make_mesh
from edl_tpu.parallel.ring_attention import ring_attention

#: the flash-kernel ring wraps pallas custom-calls in shard_map; the old
#: jax on some worker images miscompiles that composition under jit (its
#: sharding-remover pass replaces the kernel's manual-sharded result with
#: a mismatched shape).  The jnp ring and everything else runs on both —
#: only the pallas-in-shard_map tests need the modern partitioner.
requires_modern_shard_map = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="legacy jax SPMD partitioner miscompiles pallas inside shard_map")


# -- flash attention kernel (pallas interpret mode == runs on CPU) -----------


@pytest.mark.parametrize("causal", [True, False])
def test_flash_kernel_matches_reference(causal):
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, d = 2, 256, 2, 128
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=causal)
    out = attention(q, k, v, causal=causal, use_pallas=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kernel_gradients_match_reference():
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, d = 1, 128, 2, 128
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(attention(q, k, v, use_pallas=True, interpret=True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)


def test_flash_kernel_gqa_native_matches_repeated_reference():
    """GQA without the HBM repeat: the kernel maps each kv head to its
    query group through the block index maps; outputs AND all gradients
    must match the reference computed on explicitly repeated kv heads
    (including dK/dV, whose kernel must sum over the whole group)."""
    key = jax.random.key(2)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, hk, d = 2, 256, 4, 2, 128
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hk, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hk, d), jnp.float32)

    # small blocks force multiple q AND k blocks per head, so the dK/dV
    # kernel's inner-index decomposition (group member x q block) is
    # actually exercised — at the default blocks s=256 degenerates to one
    blocks = dict(block_q=64, block_k=128)

    def f_flash(q, k, v):
        return jnp.sum(attention(q, k, v, use_pallas=True,
                                 interpret=True, **blocks) ** 2)

    def f_ref(q, k, v):
        rep = h // hk
        return jnp.sum(reference_attention(
            q, jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)) ** 2)

    out = attention(q, k, v, use_pallas=True, interpret=True, **blocks)
    ref = reference_attention(q, jnp.repeat(k, h // hk, axis=2),
                              jnp.repeat(v, h // hk, axis=2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)


def test_attention_fallback_on_odd_lengths():
    # s=100 not divisible by 128: silently uses the reference path.
    q = k = v = jnp.ones((1, 100, 2, 64))
    out = attention(q, k, v, use_pallas=True, interpret=True)
    assert out.shape == (1, 100, 2, 64)


def test_remat_policies_match_no_remat():
    # both remat modes are pure memory/FLOPs tradeoffs — loss and grads
    # must match the no-remat step exactly
    import dataclasses

    base = dataclasses.replace(transformer.TINY, remat=False)
    params = transformer.init(jax.random.key(0), base)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0,
                                base.vocab_size, dtype=jnp.int32)
    batch = (tokens, jnp.roll(tokens, -1, axis=1))
    ref_loss, ref_grad = jax.value_and_grad(
        transformer.make_loss_fn(base))(params, batch)
    for pol in ("full", "dots"):
        cfg = dataclasses.replace(base, remat=True, remat_policy=pol)
        loss, grad = jax.value_and_grad(
            transformer.make_loss_fn(cfg))(params, batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5),
            grad, ref_grad)
    with pytest.raises(ValueError, match="remat_policy"):
        # caught at CONSTRUCTION, even with remat off
        dataclasses.replace(base, remat=False, remat_policy="dot")


def test_blocks_halve_to_divisor_keep_kernel_path():
    # 1536 is a multiple of 512 but not of the 1024 default block_k: the
    # blocks must halve to a divisor so the length STAYS on the kernel
    # path (regression: growing the defaults silently sent such lengths
    # to the score-materializing reference path).
    import edl_tpu.ops.flash_attention as fa

    for s in (1536, 1664):
        bq, bk = fa.fit_blocks(s)
        assert s % bq == 0 and s % bk == 0 and bq >= 128 and bk >= 128

    key = jax.random.key(3)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, 1536, 4, 32))
    k = jax.random.normal(kk, (1, 1536, 2, 32))
    v = jax.random.normal(kv, (1, 1536, 2, 32))
    ref = reference_attention(q, jnp.repeat(k, 2, axis=2),
                              jnp.repeat(v, 2, axis=2), causal=True)
    # kernel path must be taken: make the fallback loud
    import unittest.mock as mock
    with mock.patch.object(fa, "reference_attention",
                           side_effect=AssertionError("fell back")):
        out = attention(q, k, v, causal=True, use_pallas=True,
                        interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# -- ring attention ----------------------------------------------------------


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(4, MeshSpec(dp=1, sp=-1))
    key = jax.random.key(2)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, d = 2, 64, 2, 16  # s shards 16 per device over sp=4
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@requires_modern_shard_map
@pytest.mark.parametrize("hk", [4, 2])
def test_ring_flash_attention_matches_reference(hk):
    """The flash-kernel ring (pallas per chunk + lse combine + ring-level
    custom VJP): outputs and all gradients must match full reference
    attention, including GQA (hk < h) where the kv chunks ride the ring
    unrepeated."""
    from edl_tpu.parallel.ring_attention import ring_flash_attention_sharded

    mesh = make_mesh(4, MeshSpec(dp=1, sp=-1))
    key = jax.random.key(3)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, d = 2, 512, 4, 32  # 128 tokens per device over sp=4
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hk, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hk, d), jnp.float32)
    rep = h // hk

    def f_ring(q, k, v):
        out = ring_flash_attention_sharded(q, k, v, causal=True,
                                           interpret=True)
        return jnp.sum(out ** 2), out

    def f_ref(q, k, v):
        out = reference_attention(q, jnp.repeat(k, rep, axis=2),
                                  jnp.repeat(v, rep, axis=2), causal=True)
        return jnp.sum(out ** 2), out

    with set_mesh(mesh):
        (_, out), grads = jax.jit(
            jax.value_and_grad(f_ring, argnums=(0, 1, 2), has_aux=True)
        )(q, k, v)
    (_, ref), ref_grads = jax.value_and_grad(
        f_ref, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    for a, b_ in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=5e-4, rtol=5e-4)


@requires_modern_shard_map
def test_ring_flash_falls_back_on_unaligned_chunks():
    # sc = 64 per device is not 128-aligned: the flash ring must route to
    # the jnp ring (a truncating pallas grid would silently drop rows)
    from edl_tpu.parallel.ring_attention import ring_flash_attention_sharded

    mesh = make_mesh(4, MeshSpec(dp=1, sp=-1))
    key = jax.random.key(4)
    kq, kk, kv = jax.random.split(key, 3)
    b, s, h, hk, d = 1, 256, 2, 1, 32
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, hk, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, hk, d), jnp.float32)
    with set_mesh(mesh):
        out = jax.jit(lambda q, k, v: ring_flash_attention_sharded(
            q, k, v, causal=True, interpret=True))(q, k, v)
    ref = reference_attention(q, jnp.repeat(k, h // hk, axis=2),
                              jnp.repeat(v, h // hk, axis=2), causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# -- transformer core --------------------------------------------------------


def test_transformer_forward_shapes():
    cfg = transformer.TINY
    params = transformer.init(jax.random.key(0), cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = transformer.apply(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_transformer_causality():
    # Changing a future token must not change past logits.
    cfg = transformer.TINY
    params = transformer.init(jax.random.key(0), cfg)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    l1 = transformer.apply(params, t1, cfg)
    l2 = transformer.apply(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_transformer_trains_on_copy_task():
    cfg = transformer.TINY
    params = transformer.init(jax.random.key(0), cfg)
    loss_fn = transformer.make_loss_fn(cfg)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    rng = np.random.default_rng(0)
    seq = rng.integers(1, 200, size=(8, 17)).astype(np.int32)
    batch = (jnp.array(seq[:, :-1]), jnp.array(seq[:, 1:]))

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for i in range(30):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7  # memorizing one batch


def test_transformer_sharded_train_step_on_mesh():
    # Full dp×fsdp×tp train step on the virtual 8-device mesh.
    cfg = transformer.TINY
    mesh = make_mesh(8, MeshSpec(dp=2, fsdp=2, tp=2))
    params = transformer.init(jax.random.key(0), cfg)
    loss_fn = transformer.make_loss_fn(cfg)
    specs = transformer.param_partition_specs(cfg)
    from jax.sharding import NamedSharding

    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(
                                 x, jax.sharding.PartitionSpec))
    params = jax.device_put(params, shardings)
    batch_sh = NamedSharding(mesh, transformer.batch_partition_spec())
    tokens = jax.device_put(jnp.zeros((4, 16), jnp.int32), batch_sh)
    targets = jax.device_put(jnp.ones((4, 16), jnp.int32), batch_sh)

    with set_mesh(mesh):
        # out_shardings pins grads to the param layout (as ElasticTrainer
        # does); without it XLA may legally re-shard outputs.
        loss, grads = jax.jit(
            jax.value_and_grad(loss_fn),
            out_shardings=(None, shardings),
        )(params, (tokens, targets))
    assert np.isfinite(float(loss))
    assert grads["layers"][0]["wq"].sharding.spec == specs["layers"][0]["wq"]


def test_gqa_head_counts():
    cfg = transformer.TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, n_kv_heads=2,
        d_ff=64, dtype=jnp.float32, use_flash=False, remat=False)
    params = transformer.init(jax.random.key(0), cfg)
    assert params["layers"][0]["wk"].shape == (32, 2 * 8)
    logits = transformer.apply(params, jnp.zeros((1, 8), jnp.int32), cfg)
    assert logits.shape == (1, 8, 64)


# -- bert / resnet -----------------------------------------------------------


def test_bert_mlm_trains():
    from edl_tpu.models import bert

    cfg = bert.TINY
    params = bert.init(jax.random.key(0), cfg)
    loss_fn = bert.make_loss_fn(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(4, 200, size=(4, 32)).astype(np.int32)
    mask = (rng.random((4, 32)) < 0.15).astype(np.float32)
    masked = np.where(mask > 0, 3, tokens).astype(np.int32)  # [MASK]=3
    batch = (jnp.array(masked), jnp.array(tokens), jnp.array(mask))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.8


def test_bert_bidirectional():
    # Non-causal: a change at position j affects representations at i < j.
    from edl_tpu.models import bert

    cfg = bert.TINY
    params = bert.init(jax.random.key(0), cfg)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(7)
    h1 = bert.apply(params, t1, cfg)
    h2 = bert.apply(params, t2, cfg)
    assert not np.allclose(np.asarray(h1[0, :10]), np.asarray(h2[0, :10]))


def test_resnet_trains():
    from edl_tpu.models import resnet

    cfg = resnet.TINY
    params = resnet.init(jax.random.key(0), cfg)
    loss_fn = resnet.make_loss_fn(cfg)
    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=8).astype(np.int32)
    batch = (jnp.array(images), jnp.array(labels))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_resnet50_shapes():
    from edl_tpu.models import resnet

    params = resnet.init(jax.random.key(0), resnet.RESNET50)
    # 16 bottlenecks in (3,4,6,3)
    assert sum(len(s) for s in params["stages"]) == 16
    assert params["head"].shape == (2048, 1000)


def test_transformer_ring_attention_on_sp_mesh():
    # sp=2 mesh: the decoder must route through ring attention and match
    # the single-device forward numerically.
    cfg = transformer.TINY
    params = transformer.init(jax.random.key(0), cfg)
    tokens = jnp.arange(64, dtype=jnp.int32).reshape(2, 32) % cfg.vocab_size
    ref = transformer.apply(params, tokens, cfg)

    mesh = make_mesh(8, MeshSpec(dp=1, fsdp=2, tp=2, sp=2))
    from jax.sharding import NamedSharding

    specs = transformer.param_partition_specs(cfg)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda x: isinstance(
                                 x, jax.sharding.PartitionSpec))
    sp_params = jax.device_put(params, shardings)
    sp_tokens = jax.device_put(
        tokens, NamedSharding(mesh, transformer.batch_partition_spec()))
    with set_mesh(mesh):
        out = jax.jit(lambda p, t: transformer.apply(p, t, cfg))(
            sp_params, sp_tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_dryrun_multichip_has_no_remat_warnings():
    """VERDICT r1 #8: the sharded train step must compile without SPMD
    'involuntary full rematerialization' — every such warning is a
    replicate-then-repartition hop that would be real HBM/ICI waste on
    hardware.  Run in a subprocess because the warnings are emitted from
    XLA's C++ logging, not Python."""
    import subprocess
    import sys

    import os
    import subprocess
    import sys

    # make sure XLA's C++ warnings are actually observable — a quieted log
    # level would make the assertion below pass vacuously
    env = dict(os.environ, TF_CPP_MIN_LOG_LEVEL="0")
    for n in (8, 16):
        # one subprocess per size: the virtual device count is fixed at
        # backend init, so the two sizes cannot share a process
        out = subprocess.run(
            [sys.executable, "-c",
             f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=str(Path(__file__).resolve().parent.parent),
        )
        assert out.returncode == 0, (n, out.stderr[-2000:])
        assert "rematerialization" not in out.stderr, (n, out.stderr[-2000:])
    # negative control: with the gather path forced on the same mesh the
    # warning DOES appear, proving the channel is live and the one-hot
    # path is what keeps it clean
    probe = (
        "import __graft_entry__ as g;"
        "from edl_tpu.models import transformer as tfm;"
        "tfm.embed_lookup = (lambda table, tokens, *, one_hot, dtype:"
        " table.astype(dtype)[tokens]);"  # force the gather path
        "g.dryrun_multichip(8)"
    )
    out = subprocess.run(
        [sys.executable, "-c", probe],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "rematerialization" in out.stderr, (
        "warning channel dead: gather on a sharded table should warn")


def test_resnet_bf16_trains_a_step():
    """The bf16 compute path (what RESNET50 uses on TPU) must be
    differentiable end-to-end — the f32-accumulate + downcast conv
    variant broke the conv transpose rule, caught only when the bf16
    config first reached a real train step (bench model_zoo leg)."""
    import dataclasses

    from edl_tpu.models import resnet

    cfg = dataclasses.replace(resnet.TINY, dtype=jnp.bfloat16)
    params = resnet.init(jax.random.key(0), cfg)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    images = jax.random.normal(jax.random.key(1), (2, 32, 32, 3)
                               ).astype(cfg.dtype)
    labels = jnp.array([1, 3], jnp.int32)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(resnet.loss_fn)(
            params, (images, labels), cfg=cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params, opt_state, l1 = step(params, opt_state)
    _, _, l2 = step(params, opt_state)
    assert jnp.isfinite(l1) and jnp.isfinite(l2)


def test_pallas_group_norm_matches_reference():
    """Fused GN kernel (interpret mode) == jnp math, values AND grads."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.ops import group_norm as gn

    b, h, w, c, groups = 3, 6, 5, 16, 4
    key = jax.random.key(0)
    x = jax.random.normal(key, (b, h, w, c), jnp.float32) * 2 + 0.5
    scale = jax.random.normal(jax.random.key(1), (c,), jnp.float32)
    bias = jax.random.normal(jax.random.key(2), (c,), jnp.float32)

    ref = gn.group_norm(x, scale, bias, groups, use_pallas=False)
    out = gn.group_norm(x, scale, bias, groups, interpret=True)
    assert jnp.allclose(out, ref, atol=1e-5), float(
        jnp.max(jnp.abs(out - ref)))

    def loss_ref(x, s, bb):
        return jnp.sum(gn.group_norm(x, s, bb, groups,
                                     use_pallas=False) ** 2)

    def loss_pl(x, s, bb):
        return jnp.sum(gn.group_norm(x, s, bb, groups,
                                     interpret=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    g_pl = jax.grad(loss_pl, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_ in zip(g_ref, g_pl):
        assert jnp.allclose(a, b_, atol=1e-3, rtol=1e-3), float(
            jnp.max(jnp.abs(a - b_)))


def test_pallas_group_norm_bf16_and_resnet_wiring():
    """bf16 activations round-trip; the ResNet _group_norm call site uses
    the dispatcher (CPU → jnp path) and keeps its contract."""
    import jax
    import jax.numpy as jnp

    from edl_tpu.models import resnet
    from edl_tpu.ops import group_norm as gn

    x = jax.random.normal(jax.random.key(0), (2, 4, 4, 8), jnp.bfloat16)
    p = {"scale": jnp.ones((8,), jnp.float32) * 1.5,
         "bias": jnp.zeros((8,), jnp.float32)}
    out = resnet._group_norm(x, p, groups=2)
    assert out.dtype == jnp.bfloat16 and out.shape == x.shape
    ref = gn.group_norm(x, p["scale"], p["bias"], 2, use_pallas=False)
    assert jnp.allclose(out.astype(jnp.float32), ref.astype(jnp.float32),
                        atol=1e-2)


def test_resnet_s2d_stem_trains():
    """The TPU-native s2d stem (RESNET50_TPU's shape family) produces the
    same trunk geometry as conv7+maxpool (H/4) and trains."""
    import jax
    import jax.numpy as jnp
    import optax

    from edl_tpu.models import resnet

    cfg = resnet.ResNetConfig(stage_sizes=(1, 1), width=8, num_classes=10,
                              groups=4, dtype=jnp.float32, stem="s2d")
    params = resnet.init(jax.random.key(0), cfg)
    assert params["stem"].shape == (2, 2, 48, 8)
    imgs = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    labels = jnp.array([1, 7], jnp.int32)
    logits = resnet.apply(params, imgs, cfg)
    assert logits.shape == (2, 10)
    loss, grads = jax.value_and_grad(resnet.make_loss_fn(cfg))(
        params, (imgs, labels))
    assert jnp.isfinite(loss)
    opt = optax.adam(1e-3)
    updates, _ = opt.update(jax.tree.map(lambda g: g, grads),
                            opt.init(params))
    loss2 = resnet.make_loss_fn(cfg)(optax.apply_updates(params, updates),
                                     (imgs, labels))
    assert jnp.isfinite(loss2)
