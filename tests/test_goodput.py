"""Goodput ledger + scaling curve (doc/observability.md §goodput).

Correctness of the chip-second attribution machine — phase-transition
edge cases (overlapping resize+checkpoint, stall during reform, world
death mid-phase), the conservation invariant under a seeded randomized
fault campaign — plus the curve store's coordinator-KV persistence,
including across a primary kill/failover on the HA pair (reusing the
test_coord_ha harness), and the advisory surface the autoscaler logs.
"""

from __future__ import annotations

import random
import signal
import time

import pytest

from edl_tpu.observability import goodput
from edl_tpu.observability.goodput import (
    ALL_PHASES,
    CHECKPOINT_PAUSE,
    COMPILE,
    CurveStore,
    GoodputLedger,
    IDLE,
    PRODUCTIVE,
    QUEUED,
    REFORM_DARK,
    RESHARD,
    STALL,
    ScalingCurve,
)


class Clock:
    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


def make(world_size=2, base=QUEUED):
    clock = Clock()
    return GoodputLedger(job="t", world_size=world_size, base_phase=base,
                         clock=clock), clock


# ---------------------------------------------------------------------------
# phase state machine
# ---------------------------------------------------------------------------

def test_baseline_accrues_to_base_phase_weighted_by_world():
    led, clock = make(world_size=4)
    clock.t = 2.0
    assert led.chip_seconds(QUEUED) == 8.0
    assert led.goodput_fraction() == 0.0
    assert led.conserves(1e-9)


def test_world_size_change_settles_old_rate_first():
    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 1.0
    led.set_world_size(8)
    clock.t = 2.0
    # 1 s @ 2 chips + 1 s @ 8 chips, every one of them productive
    assert led.chip_seconds(PRODUCTIVE) == 10.0
    assert led.conserves(1e-9)


def test_overlapping_resize_inside_checkpoint_pause():
    """The classic overlap: a resize lands while a checkpoint pause is
    open.  The inner (resize) window attributes to reshard; only the
    remainder of the pause attributes to checkpoint_pause — and nothing
    is counted twice (conservation stays exact)."""
    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 1.0
    led.enter(CHECKPOINT_PAUSE)
    clock.t = 1.5
    led.enter(RESHARD)           # resize begins mid-pause
    clock.t = 2.5
    led.exit(RESHARD)
    clock.t = 3.0
    led.exit(CHECKPOINT_PAUSE)
    clock.t = 4.0
    snap = led.snapshot()
    assert snap["chip_seconds"][RESHARD] == 2.0           # 1 s × 2
    assert snap["chip_seconds"][CHECKPOINT_PAUSE] == 2.0  # (.5+.5) × 2
    assert snap["chip_seconds"][PRODUCTIVE] == 4.0        # 1 s + 1 s
    assert led.conserves(1e-9)


def test_stall_during_reform_settles_without_double_count():
    """A stall detected while the process is already in reform dark time
    (the watchdog breach racing a world death): the stall window nests,
    the reform's reset collapses both, and conservation holds."""
    led, clock = make(world_size=2)
    led.reset(REFORM_DARK)
    clock.t = 1.0
    led.enter(STALL)
    clock.t = 2.0
    led.reset(REFORM_DARK)       # the escalation kills → reform continues
    clock.t = 3.0
    led.reset(PRODUCTIVE)
    snap = led.snapshot()
    assert snap["chip_seconds"][STALL] == 2.0
    assert snap["chip_seconds"][REFORM_DARK] == 4.0
    assert led.conserves(1e-9)


def test_world_death_mid_phase_exits_out_of_order():
    """A world that dies mid-checkpoint leaves its phases half-open and
    possibly exits them out of LIFO order; the ledger keeps counting."""
    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 1.0
    led.enter(CHECKPOINT_PAUSE)
    led.enter(RESHARD)
    clock.t = 2.0
    # out-of-order: the OUTER phase is exited first
    assert led.exit(CHECKPOINT_PAUSE)
    clock.t = 3.0
    # death: whatever is still open (reshard) settles into the reset
    led.reset(REFORM_DARK)
    clock.t = 4.0
    snap = led.snapshot()
    assert snap["chip_seconds"][RESHARD] == 4.0  # 1-2 inner + 2-3 (still top)
    assert snap["chip_seconds"][REFORM_DARK] == 2.0
    assert led.conserves(1e-9)


def test_enter_is_idempotent_and_exit_of_absent_is_noop():
    led, clock = make()
    assert led.enter(STALL) is True
    assert led.enter(STALL) is False      # two detectors, one push
    assert led.exit(STALL) is True
    assert led.exit(STALL) is False       # second exit: no-op
    assert led.exit(COMPILE) is False     # never entered
    with pytest.raises(ValueError):
        led.enter("not-a-phase")
    assert led.conserves(1e-9)


def test_note_span_transfers_and_clamps():
    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 2.0  # 4 chip-seconds productive
    moved = led.note_span(COMPILE, 1.0)  # 2 chip-seconds across
    assert moved == 2.0
    # over-reported span: clamped to what the source actually has
    moved = led.note_span(RESHARD, 100.0)
    assert moved == 2.0
    snap = led.snapshot()
    assert snap["chip_seconds"][PRODUCTIVE] == 0.0
    assert snap["chip_seconds"][COMPILE] == 2.0
    assert snap["chip_seconds"][RESHARD] == 2.0
    assert led.conserves(1e-9)  # transfers can never break conservation


def test_conservation_under_seeded_fault_campaign():
    """A seeded randomized campaign of every mutation the runtime can
    throw at the ledger — nested enters, out-of-order exits, mid-phase
    world deaths (reset), retroactive note_spans, world-size changes —
    must keep attributed == integral exactly, at every step.  Three
    seeds; each campaign is deterministic and reproducible."""
    for seed in (0, 7, 1234):
        rng = random.Random(seed)
        led, clock = make(world_size=2)
        for _ in range(1500):
            clock.t += rng.random() * 3.0
            op = rng.randrange(6)
            phase = rng.choice(ALL_PHASES)
            if op == 0:
                led.enter(phase)
            elif op == 1:
                led.exit(phase)
            elif op == 2:
                led.reset(phase)
            elif op == 3:
                led.note_span(phase, rng.random() * 5.0)
            elif op == 4:
                led.set_world_size(rng.randrange(0, 9))
            else:
                led.snapshot()  # readout mid-flight must not perturb
        assert led.conserves(1e-9), (seed, led.snapshot())
        snap = led.snapshot()
        assert snap["attributed_chip_seconds"] == pytest.approx(
            snap["integral_chip_seconds"], abs=1e-6)
        assert all(v >= 0 for v in snap["chip_seconds"].values()), snap


def test_close_freezes_accrual_for_scrapes():
    """A finished job's ledger must stop accruing: the callback gauges
    registered over it would otherwise drift on every scrape, decaying
    the fraction toward zero after the job ended."""
    led, clock = make(world_size=2, base=PRODUCTIVE)
    clock.t = 3.0
    led.close()
    frozen = led.snapshot()
    clock.t = 100.0              # scrapes long after the job finished
    assert led.snapshot() == frozen
    assert led.chip_seconds(PRODUCTIVE) == 6.0
    assert led.goodput_fraction() == 1.0
    led.close()                  # idempotent
    assert led.conserves(1e-9)


def test_mfu_mean_weighted_by_reporting_samples():
    c = ScalingCurve("j")
    for _ in range(10):
        c.observe(2, 100.0)      # no mfu reported
    c.observe(2, 100.0, mfu_pct=50.0)
    c.observe(2, 100.0, mfu_pct=60.0)
    cell = c._cells[(2, "")]
    assert cell["mfu_pct"] == pytest.approx(55.0)  # not diluted by the 10
    rt = ScalingCurve.from_json(c.to_json())
    rt.observe(2, 100.0, mfu_pct=61.0)
    assert rt._cells[(2, "")]["mfu_pct"] == pytest.approx(
        (55.0 * 2 + 61.0) / 3)


def test_goodput_fraction_bounds():
    led, clock = make(world_size=1)
    led.reset(PRODUCTIVE)
    clock.t = 3.0
    led.enter(STALL)
    clock.t = 4.0
    frac = led.goodput_fraction()
    assert 0.0 < frac <= 1.0
    assert frac == pytest.approx(0.75)


# ---------------------------------------------------------------------------
# /metrics + flight-record surfaces
# ---------------------------------------------------------------------------

def test_register_metrics_renders_strict_exposition():
    from edl_tpu.observability.metrics import MetricsRegistry
    from tests.test_observability import parse_prometheus

    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 2.0
    led.enter(STALL)
    clock.t = 3.0
    reg = MetricsRegistry()
    goodput.register_metrics(led, reg)
    series = parse_prometheus(reg.render())
    assert series['edl_goodput_fraction{job="t"}'] == pytest.approx(4 / 6)
    assert series['edl_goodput_chip_seconds{job="t",phase="stall"}'] \
        == pytest.approx(2.0)
    assert series['edl_goodput_lost_seconds{job="t",phase="stall"}'] \
        == pytest.approx(2.0)
    assert series['edl_goodput_world_size{job="t"}'] == 2


def test_flight_record_embeds_ledger_snapshot(tmp_path):
    import json

    from edl_tpu.observability.metrics import dump_flight_record

    led, clock = make(world_size=2)
    led.reset(PRODUCTIVE)
    clock.t = 2.0
    goodput.set_process_ledger(led)
    try:
        path = dump_flight_record(str(tmp_path), "test-stall")
        doc = json.loads(open(path).read())
        assert doc["goodput"]["chip_seconds"]["productive"] == 4.0
        assert doc["goodput"]["job"] == "t"
    finally:
        goodput.set_process_ledger(None)
    # and without a ledger the record simply has no goodput key
    path = dump_flight_record(str(tmp_path), "test-bare")
    assert "goodput" not in json.loads(open(path).read())


def test_watchdog_stall_feeds_process_ledger():
    from edl_tpu.runtime.watchdog import StallWatchdog

    led, lclock = make(world_size=2, base=PRODUCTIVE)
    goodput.set_process_ledger(led)
    try:
        wclock = Clock()
        wd = StallWatchdog(floor_s=0.5, k=2.0, scope="gp-unit",
                           clock=wclock)
        wd.beat(1)
        wclock.t = 2.0
        assert wd.check() is not None
        assert led.current_phase() == STALL
        # the breach retro-attributed the silence already spent
        assert led.chip_seconds(STALL) >= 0.0
        wd.beat(2)                       # hang resolved
        assert led.current_phase() == PRODUCTIVE
        assert led.conserves(1e-6)
    finally:
        goodput.set_process_ledger(None)


# ---------------------------------------------------------------------------
# scaling curve + KV persistence
# ---------------------------------------------------------------------------

def test_curve_aggregation_and_marginals():
    c = ScalingCurve("j")
    c.observe(2, 100.0, shape="dp2", mfu_pct=60.0)
    c.observe(2, 120.0, shape="dp2", mfu_pct=62.0)
    c.observe(4, 180.0, shape="dp4")
    c.observe(4, 150.0, shape="dp2xfsdp2")
    assert c.tokens_per_second(2) == 110.0
    assert c.tokens_per_second(4) == 180.0  # best shape rules
    assert c.marginal_tokens_per_second_per_chip(2) == pytest.approx(55.0)
    assert c.marginal_tokens_per_second_per_chip(4) == pytest.approx(35.0)
    assert c.nearest_world_size(3) == 2
    assert c.nearest_world_size(100) == 4
    assert c.nearest_world_size(1) == 2
    assert c.marginal_tokens_per_second_per_chip(7) is None  # unmeasured
    rt = ScalingCurve.from_json(c.to_json())
    assert rt.summary() == c.summary()
    assert rt.sample_count() == 4


def test_curve_store_roundtrip_on_py_backend():
    from edl_tpu.coord import PyCoordService
    from edl_tpu.observability.metrics import MetricsRegistry

    svc = PyCoordService()
    reg = MetricsRegistry()
    store = CurveStore(svc, "ns/job", registry=reg)
    store.record(2, 1000.0, shape="dp2", mfu_pct=50.0)
    store.record(4, 1800.0, shape="dp4")
    # persisted under the documented key, loadable by a fresh reader
    assert svc.kv_get("goodput-curve/ns/job") is not None
    loaded = goodput.load_curve(svc, "ns/job")
    assert loaded.world_sizes() == [2, 4]
    assert loaded.tokens_per_second(4) == 1800.0
    # curve gauges refreshed on record
    text = reg.render()
    assert ('edl_goodput_curve_tokens_per_second'
            '{job="ns/job",world_size="4"} 1800') in text
    assert 'edl_goodput_marginal_tokens_per_second_per_chip' in text


@pytest.mark.multihost
def test_curve_survives_primary_failover(tmp_path):
    """The acceptance property: curve samples recorded against the HA
    pair's primary are readable from the promoted standby after a
    SIGKILL — the curve rides the replication stream like any KV
    (test_coord_ha harness: spawn_ha_pair + multi-endpoint client)."""
    from edl_tpu.coord import CoordClient, native_available, spawn_ha_pair

    if not native_available():
        pytest.skip("no native coordinator core")
    pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
    c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                    reconnect_window_s=12.0, promote_grace_s=0.2,
                    endpoints=[("127.0.0.1", sb.port)])
    try:
        store = CurveStore(c, "ha/job")
        store.record(2, 900.0, shape="dp2")
        store.record(4, 1500.0, shape="dp4")
        pr.process.send_signal(signal.SIGKILL)
        pr.process.wait(timeout=10)
        # the next read transparently fails over and promotes
        survived = goodput.load_curve(c, "ha/job")
        assert (c.host, c.port) == ("127.0.0.1", sb.port)
        assert survived is not None
        assert survived.world_sizes() == [2, 4]
        assert survived.tokens_per_second(4) == 1500.0
        # and the promoted primary accepts NEW samples onto the curve
        store.record(8, 2100.0, shape="dp8")
        assert goodput.load_curve(c, "ha/job").world_sizes() == [2, 4, 8]
    finally:
        c.close()
        pr.stop()
        sb.stop()


# ---------------------------------------------------------------------------
# autoscaler advisory
# ---------------------------------------------------------------------------

def test_autoscaler_logs_marginal_throughput_advisory():
    """With a curve source configured, every actuated plan logs the
    job's measured marginal tok/s-per-chip at the target.  The packing
    itself now rides the goodput objective (PR 15) — for this lone
    uncontended job both objectives land on the same max-out plan, which
    the baseline-vs-curve comparison pins."""
    from tests.test_autoscaler import cluster_with, mk_job, submit

    from edl_tpu.scheduler.autoscaler import Autoscaler

    curve = ScalingCurve("default/example")
    curve.observe(2, 1000.0)
    curve.observe(8, 3000.0)

    c = cluster_with(cpu_milli=10_000)
    baseline = Autoscaler(cluster_with(cpu_milli=10_000))
    with_curve = Autoscaler(
        c, goodput_curves=lambda uid: curve
        if uid == "default/example" else None)
    job = mk_job("example", lo=2, hi=10)
    submit(baseline.cluster, baseline, mk_job("example", lo=2, hi=10))
    submit(c, with_curve, job)
    t_base = baseline.tick()
    t_curve = with_curve.tick()
    assert t_curve == t_base  # the plan is not perturbed by the curve
    assert with_curve.advisory_history, "no advisory logged"
    adv = with_curve.advisory_history[-1]
    assert adv["job"] == "default/example"
    assert adv["target"] == t_curve["default/example"]
    # target 10 > largest measured 8 → answered from the curve edge
    assert adv["measured_at"] == 8
    assert adv["marginal_tok_s_per_chip"] == pytest.approx(
        (3000.0 - 1000.0) / 6, abs=0.1)
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.observability.metrics import get_registry

    assert get_counters().get("autoscaler_goodput_advisories") >= 1
    gauge = get_registry().gauge("autoscaler_marginal_tokens_per_chip")
    assert {"job": "default/example"} in gauge.label_sets()
    # deleting the job removes its advisory series (no frozen gauges)
    with_curve.on_del(job)
    with_curve.drain_events()
    assert {"job": "default/example"} not in gauge.label_sets()


def test_autoscaler_curve_failure_degrades_to_silence():
    from tests.test_autoscaler import cluster_with, mk_job, submit

    from edl_tpu.scheduler.autoscaler import Autoscaler

    def broken(uid):
        raise RuntimeError("curve store unreachable")

    c = cluster_with(cpu_milli=10_000)
    a = Autoscaler(c, goodput_curves=broken)
    job = mk_job("example", lo=2, hi=10)
    submit(c, a, job)
    target = a.tick()             # plan proceeds; advisory just absent
    assert target
    assert not a.advisory_history
