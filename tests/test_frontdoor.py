"""The async serving front door (runtime/frontdoor.py): keep-alive +
pipelining, the f32 fast path vs the JSON contract, bounded-admission
429s, priority shed order, the ready gate, and the satellite fixes that
ride the same PR (HTTP/1.1 legacy serve_main, shared-condition
``await_all``, batched keepalive, vectorized ``observe_many``)."""

import json
import os
import re
import socket
import threading
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from edl_tpu.models import mlp  # noqa: E402
from edl_tpu.observability.collector import get_counters  # noqa: E402
from edl_tpu.runtime.frontdoor import (  # noqa: E402
    FD_READY,
    RESP_429,
    BatchApp,
    FleetApp,
    FrontDoor,
    build_predict_request,
    format_serving_addr,
    parse_serving_addr,
)
from edl_tpu.runtime.serving import ElasticServer, ServeRequest  # noqa: E402

SIZES = [8, 16, 4]
PARAMS = mlp.init(jax.random.key(0), SIZES)


def make_replica(job, *, max_batch=16, max_queue_ms=1.0, kv=None,
                 replica="r0", hard_cap_rows=4096, soft_cap_rows=0,
                 build_gate=None):
    def build():
        if build_gate is not None:
            build_gate.wait(30)
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job=job, replica=replica,
                   max_batch=max_batch, max_queue_ms=max_queue_ms,
                   hard_cap_rows=hard_cap_rows,
                   soft_cap_rows=soft_cap_rows, kv=kv, addr_ttl_s=5.0)
    door = FrontDoor(app, host="127.0.0.1", job=job).start()
    return app, door


def read_responses(sock, n, timeout=30.0):
    """Read n HTTP responses off one socket; returns list of
    (status, body bytes) in arrival order."""
    sock.settimeout(timeout)
    buf = b""
    out = []
    while len(out) < n:
        idx = buf.find(b"\r\n\r\n")
        if idx < 0:
            buf += sock.recv(1 << 20)
            continue
        head = buf[:idx + 4]
        status = int(head.split(b" ", 2)[1])
        m = re.search(rb"[Cc]ontent-[Ll]ength: (\d+)", head)
        clen = int(m.group(1)) if m else 0
        while len(buf) < idx + 4 + clen:
            buf += sock.recv(1 << 20)
        out.append((status, buf[idx + 4:idx + 4 + clen]))
        buf = buf[idx + 4 + clen:]
    return out


def connect(port):
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


class TestFrontDoor:
    @classmethod
    def setup_class(cls):
        cls.app, cls.door = make_replica("fdtest/pipe")
        assert cls.app.wait_ready(120)

    @classmethod
    def teardown_class(cls):
        cls.door.stop()

    def test_keepalive_pipelining_in_order(self):
        """N pipelined requests over ONE connection come back as N
        in-order responses, each row's output correct — and the door
        saw one connection for all of them."""
        conns_before = self.door.connections
        n = 32
        rows = [np.full((SIZES[0],), i, np.float32) for i in range(n)]
        blob = b"".join(build_predict_request(r) for r in rows)
        s = connect(self.door.port)
        s.sendall(blob)
        resps = read_responses(s, n)
        s.close()
        ref = np.asarray(mlp.apply(PARAMS, np.stack(rows)))
        for i, (status, body) in enumerate(resps):
            assert status == 200
            np.testing.assert_allclose(np.frombuffer(body, "<f4"), ref[i],
                                       atol=1e-5)
        assert self.door.connections == conns_before + 1

    def test_json_contract_matches_f32(self):
        row = np.arange(SIZES[0], dtype=np.float32)
        body = json.dumps({"inputs": row.tolist()}).encode()
        jreq = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body)) + body
        s = connect(self.door.port)
        s.sendall(jreq + build_predict_request(row))
        (st1, b1), (st2, b2) = read_responses(s, 2)
        s.close()
        assert st1 == 200 and st2 == 200
        out_json = np.asarray(json.loads(b1.decode())["outputs"])
        out_f32 = np.frombuffer(b2, "<f4")
        np.testing.assert_allclose(out_json, out_f32, atol=1e-5)

    def test_mixed_pipelining_order_held(self):
        """A JSON request sandwiched between f32 runs: responses come
        back in request order (the pending-ring guarantee)."""
        row = np.ones((SIZES[0],), np.float32)
        body = json.dumps({"inputs": row.tolist()}).encode()
        jreq = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body)) + body
        freq = build_predict_request(row)
        s = connect(self.door.port)
        s.sendall(freq * 3 + jreq + freq * 3)
        resps = read_responses(s, 7)
        s.close()
        assert [st for st, _ in resps] == [200] * 7
        assert b"outputs" in resps[3][1]  # the JSON one is the 4th
        for i in (0, 1, 2, 4, 5, 6):
            assert b"outputs" not in resps[i][1]

    def test_healthz(self):
        s = connect(self.door.port)
        s.sendall(b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
        (status, _), = read_responses(s, 1)
        s.close()
        assert status == 200


def test_ready_gate_503_until_built():
    gate = threading.Event()
    app, door = make_replica("fdtest/gate", build_gate=gate)
    try:
        s = connect(door.port)
        s.sendall(b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
        (status, _), = read_responses(s, 1)
        assert status == 503  # still building
        gate.set()
        assert app.wait_ready(120)
        s.sendall(b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
        (status, _), = read_responses(s, 1)
        assert status == 200
        s.close()
    finally:
        gate.set()
        door.stop()


def test_failed_build_503s_and_wait_ready_false():
    """A replica whose build dies must answer fast 503s — not queue
    rows nothing will ever drain — and ``wait_ready`` must report the
    failure instead of True-on-dead."""
    def broken():
        raise RuntimeError("synthetic build failure")

    app = BatchApp(broken, SIZES[0], job="fdtest/deadbuild")
    door = FrontDoor(app, host="127.0.0.1", job="fdtest/deadbuild").start()
    try:
        assert app.wait_ready(30) is False
        assert app.failed
        s = connect(door.port)
        row = np.zeros((SIZES[0],), np.float32)
        s.sendall(build_predict_request(row))
        t0 = time.perf_counter()
        (status, _), = read_responses(s, 1, timeout=10)
        assert status == 503
        assert time.perf_counter() - t0 < 5.0  # fast, not a hang
        s.close()
    finally:
        door.stop()


def test_drain_never_clobbered_by_reload():
    """A reload must not regate a DRAINING replica back to READY: the
    drain (scale-down in progress) always wins the gate — refused at
    entry, and via the CAS if it lands mid-swap."""
    from edl_tpu.runtime.frontdoor import FD_DRAINING, FD_RELOADING

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job="fdtest/drainrace")
    door = FrontDoor(app, host="127.0.0.1", job="fdtest/drainrace").start()
    try:
        assert app.wait_ready(120)
        app._set_state(FD_DRAINING)
        assert app.swap_weights(PARAMS, 2) is False  # refused at entry
        assert app.state == FD_DRAINING
        # the mid-swap race: a drain that moved the gate first keeps it
        assert app._set_state_if(FD_RELOADING, FD_READY) is False
        assert app.state == FD_DRAINING
    finally:
        door.stop()


def test_huge_and_negative_content_length_rejected():
    """Bounded admission bounds the TRANSPORT too: a Content-Length past
    max_body_bytes is 413'd and the connection closed before anything
    is buffered; a negative Content-Length (would desync the consume
    offsets) is a hard 400; a Transfer-Encoding body (no Content-Length
    boundary to frame by — the chunk stream would be parsed as the next
    request head) is a 411 + close."""
    app, door = make_replica("fdtest/bodycap")
    assert app.wait_ready(120)
    try:
        s = connect(door.port)
        s.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 4294967296\r\n\r\n")
        (st, _), = read_responses(s, 1, timeout=10)
        assert st == 413
        assert s.recv(1 << 16) == b""  # connection closed
        s.close()
        s = connect(door.port)
        s.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: -5\r\n\r\n")
        (st, _), = read_responses(s, 1, timeout=10)
        assert st == 400
        assert s.recv(1 << 16) == b""
        s.close()
        s = connect(door.port)
        s.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"5\r\nhello\r\n0\r\n\r\n")
        (st, _), = read_responses(s, 1, timeout=10)
        assert st == 411
        assert s.recv(1 << 16) == b""
        s.close()
    finally:
        door.stop()


def test_start_surfaces_bind_error():
    """A listener bind failure (port in use) raises from start() with
    the real cause immediately — not a 30 s hang behind a generic
    'failed to start'."""
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]

    class NullApp:
        wants_raw = False

    t0 = time.monotonic()
    try:
        with pytest.raises(RuntimeError, match="failed to start"):
            FrontDoor(NullApp(), host="127.0.0.1", port=port,
                      job="fdtest/bind").start()
        assert time.monotonic() - t0 < 10
    finally:
        blocker.close()


def test_failed_swap_keeps_batcher_alive():
    """Corrupt/incompatible weights must not kill the batcher: the swap
    reports False, the old generation keeps serving, and the failure is
    counted — not a silent READY blackhole."""
    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job="fdtest/badswap")
    door = FrontDoor(app, host="127.0.0.1", job="fdtest/badswap").start()
    try:
        assert app.wait_ready(120)
        c = get_counters()
        fails0 = c.get("serving_reload_failures", job="fdtest/badswap")
        orig = app.server.load_params

        def boom(params):
            raise RuntimeError("synthetic corrupt weights")

        app.server.load_params = boom
        try:
            assert app.swap_weights(PARAMS, 2, timeout_s=10) is False
        finally:
            app.server.load_params = orig
        assert app.state == FD_READY  # regated, not wedged RELOADING
        assert app.generation == 0    # old weights kept
        assert c.get("serving_reload_failures",
                     job="fdtest/badswap") == fails0 + 1
        # the batcher survived: requests still serve
        s = connect(door.port)
        s.sendall(build_predict_request(np.ones((SIZES[0],), np.float32)))
        (st, _), = read_responses(s, 1, timeout=10)
        assert st == 200
        s.close()
    finally:
        door.stop()


def test_pipelined_error_never_overtakes_earlier_response():
    """A malformed request pipelined AFTER a valid one: the 400 waits
    its turn in the slot ring — the client reads [200, 400] in request
    order, then the connection closes."""
    app, door = make_replica("fdtest/errorder")
    assert app.wait_ready(120)
    try:
        s = connect(door.port)
        good = build_predict_request(np.ones((SIZES[0],), np.float32))
        bad = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
               b"Content-Length: -5\r\n\r\n")
        s.sendall(good + bad)
        (st1, _), (st2, _) = read_responses(s, 2, timeout=10)
        assert (st1, st2) == (200, 400)
        assert s.recv(1 << 16) == b""  # closed after the ordered flush
        s.close()
    finally:
        door.stop()


def test_standby_survives_weight_reload():
    """A warm STANDBY replica stays unroutable through a fleet-wide
    rolling weight reload: swap_weights regates to where it came from,
    never silently activating a replica behind the autoscaler's back."""
    from edl_tpu.runtime.frontdoor import FD_DRAINING, FD_STANDBY

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job="fdtest/standby", standby=True)
    door = FrontDoor(app, host="127.0.0.1", job="fdtest/standby").start()
    try:
        assert app.wait_ready(120)
        assert app.state == FD_STANDBY
        assert app.swap_weights(PARAMS, 2)
        assert app.generation == 2
        assert app.state == FD_STANDBY  # reloaded, still gated
        # activate opens the gate (the scale-up adoption)…
        s = connect(door.port)
        s.sendall(b"POST /admin/activate HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 0\r\n\r\n")
        (st, _), = read_responses(s, 1)
        assert st == 200 and app.state == FD_READY
        # …but must NEVER revive a draining replica (409, gate holds)
        app._set_state(FD_DRAINING)
        s.sendall(b"POST /admin/activate HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 0\r\n\r\n")
        (st, _), = read_responses(s, 1)
        s.close()
        assert st == 409 and app.state == FD_DRAINING
    finally:
        door.stop()


class TestOverload:
    @classmethod
    def setup_class(cls):
        cls.app, cls.door = make_replica(
            "fdtest/overload", max_batch=8, max_queue_ms=0.5,
            hard_cap_rows=32, soft_cap_rows=16)
        assert cls.app.wait_ready(120)

    @classmethod
    def teardown_class(cls):
        cls.door.stop()

    def _blast(self, n, priority=None, stall_ms=200):
        """Wedge one iteration, then pipeline ``n`` requests so the
        queue builds past the caps; returns the status tally."""
        self.app._stall_once_ms = stall_ms
        row = np.ones((SIZES[0],), np.float32)
        warm = build_predict_request(row)
        blob = b"".join(build_predict_request(row, priority=priority)
                        for _ in range(n))
        s = connect(self.door.port)
        s.sendall(warm)  # opens the stalled iteration
        time.sleep(0.05)
        s.sendall(blob)
        resps = read_responses(s, n + 1)
        s.close()
        tally = {}
        for st, _ in resps:
            tally[st] = tally.get(st, 0) + 1
        return tally

    def test_backpressure_degrades_to_429(self):
        c = get_counters()
        before = c.get("frontdoor_overload_sheds", job="fdtest/overload",
                       priority="normal")
        tally = self._blast(200)
        # everything answered: the hard cap's worth served, the rest
        # shed fast — never queued to death, never dropped
        assert tally.get(200, 0) >= 1
        assert tally.get(429, 0) >= 1
        assert sum(tally.values()) == 201
        assert c.get("frontdoor_overload_sheds", job="fdtest/overload",
                     priority="normal") > before

    def test_priority_shed_order(self):
        """low sheds at the soft watermark while normal still admits;
        high admits past the hard cap's reserve band."""
        c = get_counters()
        job = "fdtest/overload"
        # the previous test's blast backlog must fully drain first, or
        # this test's counts start from a nonzero queue (load-flaky)
        deadline = time.monotonic() + 20
        while self.app._queued_rows > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert self.app._queued_rows == 0
        self.app._stall_once_ms = 300
        row = np.ones((SIZES[0],), np.float32)
        s = connect(self.door.port)
        # fill to the soft cap with normal traffic (held queued by the
        # wedged iteration)
        s.sendall(build_predict_request(row) * 16)
        time.sleep(0.05)
        low_before = c.get("frontdoor_overload_sheds", job=job,
                           priority="low")
        # now: low must shed (soft cap), normal must still admit,
        # high must still admit
        s.sendall(build_predict_request(row, priority="low"))
        s.sendall(build_predict_request(row, priority="normal"))
        s.sendall(build_predict_request(row, priority="high"))
        resps = read_responses(s, 19)
        s.close()
        statuses = [st for st, _ in resps]
        assert statuses[:16] == [200] * 16
        assert statuses[16] == 429  # low shed at the soft watermark
        assert statuses[17] == 200  # normal admitted under the hard cap
        assert statuses[18] == 200  # high admitted in the reserve band
        assert c.get("frontdoor_overload_sheds", job=job,
                     priority="low") == low_before + 1


def test_json_path_respects_admission_caps():
    """The JSON compatibility contract rides the SAME bounded admission
    as f32: flooding JSON past the hard cap 429s instead of growing the
    queue without bound."""
    import json as _json

    app, door = make_replica("fdtest/jsoncap", max_batch=8,
                             max_queue_ms=0.5, hard_cap_rows=8,
                             soft_cap_rows=4)
    assert app.wait_ready(120)
    try:
        app._stall_once_ms = 300  # wedge so the queue builds
        row = np.ones((SIZES[0],), np.float32)
        body = _json.dumps({"inputs": row.tolist()}).encode()
        jreq = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body)) + body
        s = connect(door.port)
        s.sendall(build_predict_request(row))  # opens the stall
        time.sleep(0.05)
        s.sendall(jreq * 20)
        resps = read_responses(s, 21, timeout=30)
        s.close()
        tally = {}
        for st, _ in resps:
            tally[st] = tally.get(st, 0) + 1
        assert tally.get(429, 0) > 0, tally  # capped, not unbounded
        assert tally.get(200, 0) >= 1, tally
    finally:
        door.stop()


def test_fleet_app_request_timeout_500():
    """A fleet request that never completes 500s after timeout_s
    instead of head-of-line-blocking the keep-alive connection forever
    (the legacy handler's per-request bound, kept)."""
    from edl_tpu.runtime.frontdoor import FleetApp

    class WedgedFleet:
        generation = 1

        def replicas_ready(self):
            return 1

        def submit(self, batch, trace_id=None, parent_span=None):
            return ServeRequest(payload=batch)  # never completed

    app = FleetApp(WedgedFleet(), SIZES[0], timeout_s=0.5)
    door = FrontDoor(app, host="127.0.0.1", job="fdtest/fleettmo").start()
    try:
        s = connect(door.port)
        s.sendall(build_predict_request(np.ones((SIZES[0],), np.float32)))
        t0 = time.monotonic()
        (st, _), = read_responses(s, 1, timeout=15)
        assert st == 500
        assert time.monotonic() - t0 < 10
        s.close()
    finally:
        door.stop()


def test_fleet_app_serves_fleet_with_keepalive():
    """serve_main's async front door: the in-process ServingFleet behind
    FleetApp — JSON contract + f32 + pipelining on one connection."""
    from edl_tpu.runtime.serving import ServingFleet

    fleet = ServingFleet(
        lambda p, b: mlp.apply(p, b[0]), PARAMS,
        example_row=(np.zeros((SIZES[0],), np.float32),),
        job="fdtest/fleet", max_batch_size=4, max_queue_ms=0.5)
    fleet.scale_to(1)
    door = FrontDoor(FleetApp(fleet, SIZES[0]), host="127.0.0.1",
                     job="fdtest/fleet").start()
    try:
        row = np.arange(SIZES[0], dtype=np.float32)
        body = json.dumps({"inputs": row.tolist()}).encode()
        jreq = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body)) + body
        s = connect(door.port)
        s.sendall(build_predict_request(row) * 5 + jreq)
        resps = read_responses(s, 6)
        s.close()
        assert [st for st, _ in resps] == [200] * 6
        payload = json.loads(resps[5][1].decode())
        ref = np.asarray(mlp.apply(PARAMS, row[None, :]))[0]
        np.testing.assert_allclose(np.asarray(payload["outputs"]), ref,
                                   atol=1e-5)
        np.testing.assert_allclose(np.frombuffer(resps[0][1], "<f4"), ref,
                                   atol=1e-5)
    finally:
        door.stop()
        fleet.stop()


def test_legacy_serve_main_http11_keepalive(tmp_path):
    """The satellite: the legacy ThreadingHTTPServer path answers two
    requests over ONE connection (HTTP/1.1 + Content-Length =
    keep-alive), so even the baseline stops paying a handshake per
    request."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", EDL_SERVING_FRONTDOOR="legacy",
               EDL_SERVING_MODEL_DIR=str(tmp_path),
               EDL_SERVING_MODEL="mlp:8,16,4", EDL_SERVING_PORT="0",
               EDL_HEALTH_PORT="-1", EDL_SERVING_RELOAD_POLL_S="0")
    logf = tmp_path / "serve.log"
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "from edl_tpu.runtime.serving import serve_main; serve_main()"],
        stdout=open(logf, "w"), stderr=subprocess.STDOUT, env=env)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            text = logf.read_text()
            m = re.search(r"model server ready.*?port=(\d+)", text)
            if m:
                port = int(m.group(1))
                break
            assert proc.poll() is None, text
            time.sleep(0.2)
        assert port, "server never came up"
        row = list(range(8))
        body = json.dumps({"inputs": row}).encode()
        req = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
               b"Content-Type: application/json\r\n"
               b"Content-Length: %d\r\n\r\n" % len(body)) + body
        s = connect(port)
        s.sendall(req)
        (st1, b1), = read_responses(s, 1)
        # SAME socket, second request: keep-alive held
        s.sendall(req)
        (st2, b2), = read_responses(s, 1)
        s.close()
        assert st1 == 200 and st2 == 200
        assert (json.loads(b1.decode())["outputs"]
                == json.loads(b2.decode())["outputs"])
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()


# -- satellite units ---------------------------------------------------------


def test_serving_addr_value_roundtrip():
    v = format_serving_addr("10.0.0.3:8500", 30.0, "reloading")
    addr, state, expired = parse_serving_addr(v)
    assert addr == "10.0.0.3:8500" and state == "reloading" and not expired
    addr, state, expired = parse_serving_addr(
        format_serving_addr("1.2.3.4:1", -5.0, FD_READY))
    assert expired
    addr, state, _ = parse_serving_addr(b"1.2.3.4:1 -")
    assert addr == "1.2.3.4:1" and state == FD_READY
    assert parse_serving_addr(b"garbage")[0] is None


def test_observe_many_matches_observe():
    from edl_tpu.observability.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h1 = reg.histogram("a_seconds", buckets=(0.01, 0.1, 1.0))
    h2 = reg.histogram("b_seconds", buckets=(0.01, 0.1, 1.0))
    vals = [0.005, 0.01, 0.05, 0.5, 5.0, 0.09]
    for v in vals:
        h1.observe(v, job="x")
    h2.observe_many(np.asarray(vals), job="x")
    assert h1._counts[(("job", "x"),)] == h2._counts[(("job", "x"),)]
    assert h1.sum(job="x") == pytest.approx(h2.sum(job="x"))
    assert h2.count(job="x") == len(vals)


def test_await_all_shared_wait_bounds_wedged_tail():
    """A wedged tail costs ONE deadline wait, not a poll per request:
    2000 never-completing requests must tally within ~the timeout."""
    from edl_tpu.runtime.serving import PoissonTraffic

    traffic = PoissonTraffic.__new__(PoissonTraffic)
    traffic.sent = [ServeRequest(payload=(np.zeros(1),), id=i,
                                 t_enqueue=time.perf_counter())
                    for i in range(2000)]
    for r in traffic.sent[:500]:
        r.complete(np.zeros(1))
    t0 = time.perf_counter()
    tally = traffic.await_all(timeout_s=0.5)
    wall = time.perf_counter() - t0
    assert tally["served"] == 500
    assert tally["timeouts"] == 1500
    assert wall < 2.0, wall  # the old path cost >= 1 ms per wedged req


def test_await_all_wakes_on_late_completion():
    from edl_tpu.runtime.serving import PoissonTraffic

    traffic = PoissonTraffic.__new__(PoissonTraffic)
    traffic.sent = [ServeRequest(payload=(np.zeros(1),), id=i,
                                 t_enqueue=time.perf_counter())
                    for i in range(3)]

    def finish_later():
        time.sleep(0.2)
        for r in traffic.sent:
            r.complete(np.zeros(1))

    threading.Thread(target=finish_later).start()
    t0 = time.perf_counter()
    tally = traffic.await_all(timeout_s=10.0)
    wall = time.perf_counter() - t0
    assert tally["served"] == 3 and tally["timeouts"] == 0
    assert wall < 5.0  # woke on the shared condition, not the deadline


def test_keepalive_prefers_heartbeat_many():
    """CoordDiscovery.keepalive rides the coalesced KEEPALIVE verb when
    the client has one (the batched default the kubelet harnesses now
    inherit), and falls back to per-name HB otherwise."""
    from edl_tpu.runtime.discovery import CoordDiscovery

    class Client:
        def __init__(self, batched):
            self.hb_calls = 0
            self.many_calls = 0
            if not batched:
                self.heartbeat_many = None

        def member_ttl_ms(self):
            return 60

        def heartbeat(self, name):
            self.hb_calls += 1
            return True

        def heartbeat_many(self, names):
            self.many_calls += 1
            return {n: True for n in names}

        def kv_get(self, key):
            return None

    batched = Client(batched=True)
    d = CoordDiscovery(batched, "w0")
    with d.keepalive(interval_s=0.02):
        time.sleep(0.15)
    assert batched.many_calls >= 2
    assert batched.hb_calls == 0

    plain = Client(batched=False)
    d2 = CoordDiscovery(plain, "w1")
    with d2.keepalive(interval_s=0.02):
        time.sleep(0.15)
    assert plain.hb_calls >= 2


def test_make_worker_coord_mux_default(monkeypatch):
    """multihost_worker builds its coordinator client over a CoordMux by
    default (one multiplexed connection per pod process — the scale-out
    wiring the kubelet harnesses were missing); EDL_COORD_MUX=0 opts
    out."""
    pytest.importorskip("edl_tpu.coord.bindings")
    from edl_tpu.coord.client import CoordClient, MuxCoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.runtime.multihost_worker import make_worker_coord

    srv = spawn_server()
    try:
        c = make_worker_coord("127.0.0.1", srv.port)
        assert isinstance(c, MuxCoordClient)
        assert c.ping()
        monkeypatch.setenv("EDL_COORD_MUX", "0")
        c2 = make_worker_coord("127.0.0.1", srv.port)
        assert isinstance(c2, CoordClient)
        assert not isinstance(c2, MuxCoordClient)
        c2.close()
    finally:
        srv.process.kill()


def test_gc_sweeps_serving_addr_prefix():
    from edl_tpu.coord.gc import JOB_KV_PREFIXES

    assert "serving-addr/" in JOB_KV_PREFIXES


# -- request tracing (ISSUE-14): f32↔JSON span parity, the loop-lag probe ----


def _read_raw_responses(sock, n, timeout=30.0):
    """Like read_responses but keeps the raw head bytes (header-contract
    assertions need them)."""
    sock.settimeout(timeout)
    buf = b""
    out = []
    while len(out) < n:
        idx = buf.find(b"\r\n\r\n")
        if idx < 0:
            buf += sock.recv(1 << 20)
            continue
        head = buf[:idx + 4]
        status = int(head.split(b" ", 2)[1])
        m = re.search(rb"\r\n[Cc]ontent-[Ll]ength: (\d+)", head)
        clen = int(m.group(1)) if m else 0
        while len(buf) < idx + 4 + clen:
            buf += sock.recv(1 << 20)
        out.append((status, head, buf[idx + 4:idx + 4 + clen]))
        buf = buf[idx + 4 + clen:]
    return out


def _span_names(trace_id):
    from edl_tpu.observability.tracing import get_tracer

    return sorted({e.name for e in get_tracer().events()
                   if e.trace_id == trace_id})


def test_f32_json_span_parity_and_echo():
    """A traced request gets the SAME front-door phase taxonomy and the
    same header echo whether it arrives on the f32 fast path or the
    JSON slow path — the fast path is not a tracing blind spot
    (ISSUE-14 satellite: today only JSON got the full treatment)."""
    from edl_tpu.observability.tracing import new_trace_id

    app, door = make_replica("fdtest/parity")
    assert app.wait_ready(120)
    try:
        tid_f32, tid_json = new_trace_id(), new_trace_id()
        row = np.ones((SIZES[0],), np.float32)
        s = connect(door.port)
        # f32 fast path, traced
        s.sendall(build_predict_request(row, trace_id=tid_f32))
        status, head, body = _read_raw_responses(s, 1)[0]
        assert status == 200
        assert f"X-EDL-Trace-Id: {tid_f32}".encode() in head, head
        assert len(body) == SIZES[-1] * 4  # still a raw f32 body
        # JSON slow path, traced
        payload = json.dumps({"inputs": row.tolist()}).encode()
        s.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Type: application/json\r\n"
                  b"X-EDL-Trace-Id: " + tid_json.encode() + b"\r\n"
                  b"Content-Length: %d\r\n\r\n" % len(payload) + payload)
        status, head, body = _read_raw_responses(s, 1)[0]
        assert status == 200
        assert f"X-EDL-Trace-Id: {tid_json}".encode() in head
        s.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
                not _span_names(tid_f32) or not _span_names(tid_json)):
            time.sleep(0.05)
        # span PARITY: identical phase taxonomy on both paths
        assert _span_names(tid_f32) == _span_names(tid_json)
        assert _span_names(tid_f32) == [
            "frontdoor.admit", "frontdoor.batch", "frontdoor.forward",
            "frontdoor.parse", "frontdoor.queue", "frontdoor.respond",
            "frontdoor_request"]
        # both landed in the exemplar ring + the histogram's exemplars
        ring_ids = {e["trace_id"] for e in app.exemplars}
        assert {tid_f32, tid_json} <= ring_ids
        hist_ids = {t for t, _v, _ts in
                    app._hist.exemplars(job="fdtest/parity")}
        assert tid_f32 in hist_ids or tid_json in hist_ids
    finally:
        door.stop()


def test_traced_head_neither_cached_nor_armed():
    """Traced heads are unique per request (they embed the id): they
    must not churn the bounded head cache, and must not re-arm the
    fixed-stride parser away from the steady-state head."""
    from edl_tpu.observability.tracing import new_trace_id

    app, door = make_replica("fdtest/headcache")
    assert app.wait_ready(120)
    try:
        row = np.ones((SIZES[0],), np.float32)
        s = connect(door.port)
        # plain → traced → plain, pipelined on one connection
        tid = new_trace_id()
        s.sendall(build_predict_request(row)
                  + build_predict_request(row, trace_id=tid)
                  + build_predict_request(row))
        resps = read_responses(s, 3)
        assert [st for st, _ in resps] == [200] * 3
        cached = list(door.head_cache)
        assert not any(tid.encode() in h for h in cached), cached
        # the armed fast-path head is still the PLAIN steady-state one
        conn = next(iter(door.conns))
        assert conn._fixed is not None
        assert tid.encode() not in conn._fixed[0]
        s.close()
    finally:
        door.stop()


def test_untraced_f32_requests_emit_no_spans():
    """The unsampled steady state pays nothing: plain f32 requests
    leave no frontdoor_request spans behind."""
    from edl_tpu.observability.tracing import get_tracer

    app, door = make_replica("fdtest/quiet")
    assert app.wait_ready(120)
    try:
        before = sum(1 for e in get_tracer().events()
                     if e.name == "frontdoor_request")
        row = np.ones((SIZES[0],), np.float32)
        s = connect(door.port)
        s.sendall(build_predict_request(row) * 8)
        assert [st for st, _ in read_responses(s, 8)] == [200] * 8
        s.close()
        after = sum(1 for e in get_tracer().events()
                    if e.name == "frontdoor_request")
        assert after == before
        assert not any(e.get("replica") == "r0" and False
                       for e in app.exemplars)  # ring untouched by these
    finally:
        door.stop()


def test_loop_lag_probe_histogram_breach_and_flightrec(tmp_path):
    """The loop-lag watchdog: a blocking call on the event-loop thread
    shows up in edl_loop_lag_seconds, counts breaches, and a sustained
    lag dumps a flight record embedding the exemplar ring."""
    from edl_tpu.runtime.frontdoor import LoopLagProbe

    app, door = make_replica("fdtest/lag")
    assert app.wait_ready(120)
    probe = None
    try:
        probe = LoopLagProbe(
            door, "fdtest-lag", interval_s=0.02, breach_s=0.05,
            sustain=2, flight_dir=str(tmp_path),
            exemplars_fn=lambda: list(app.exemplars),
            dump_cooldown_s=0.0).start()
        # wedge DETECTION is armed before the first tick (seeded beat):
        # a loop that wedges immediately is still caught
        assert probe._watchdog._last_beat is not None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and probe.ticks < 3:
            time.sleep(0.02)
        assert probe.ticks >= 3, "probe never ran on the loop"
        # wedge the loop twice: two consecutive breached ticks
        for _ in range(2):
            door.call_soon(time.sleep, 0.12)
            time.sleep(0.15)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and probe.escalations == 0:
            time.sleep(0.05)
        assert probe.breaches >= 2
        assert probe.escalations >= 1
        assert get_counters().get("loop_lag_breaches",
                                  loop="fdtest-lag") >= 2
        recs = [f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-") and "loop-lag" in f]
        assert recs, os.listdir(tmp_path)
        with open(tmp_path / recs[0]) as f:
            doc = json.load(f)
        assert doc["extra"]["loop"] == "fdtest-lag"
        assert "exemplars" in doc["extra"]
        # the lag histogram saw the wedge
        from edl_tpu.observability.metrics import get_registry

        hist = get_registry().histogram("loop_lag_seconds")
        assert hist.count(loop="fdtest-lag") >= 3
        assert hist.sum(loop="fdtest-lag") >= 0.1  # two ~120 ms wedges
    finally:
        if probe is not None:
            probe.stop()
        door.stop()
