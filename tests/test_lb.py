"""The serving load-balancer tier (runtime/lb.py): KV discovery with
ready-gate routing, hedging with first-wins cancellation, connection
pooling, priority shedding, and the killed-replica rescue drill — the
ISSUE-13 satellite checklist, in-process."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from edl_tpu.models import mlp  # noqa: E402
from edl_tpu.observability.collector import get_counters  # noqa: E402
from edl_tpu.runtime.frontdoor import (  # noqa: E402
    FD_READY,
    FD_RELOADING,
    SERVING_ADDR_PREFIX,
    BatchApp,
    FrontDoor,
    build_predict_request,
)
from edl_tpu.runtime.lb import ServingLB  # noqa: E402

from tests.test_frontdoor import connect, read_responses  # noqa: E402

SIZES = [8, 16, 4]
PARAMS = mlp.init(jax.random.key(0), SIZES)


class FakeKV:
    """Thread-safe dict with the coordinator KV verbs discovery and the
    state publisher use."""

    def __init__(self):
        self._d = {}
        self._lock = threading.Lock()

    def kv_set(self, key, value):
        with self._lock:
            self._d[key] = bytes(value)

    def kv_get(self, key):
        with self._lock:
            return self._d.get(key)

    def kv_del(self, key):
        with self._lock:
            return self._d.pop(key, None) is not None

    def kv_keys(self, prefix=""):
        with self._lock:
            return [k for k in self._d if k.startswith(prefix)]


def spin_replica(kv, job, replica, **kw):
    from edl_tpu.runtime.serving import ElasticServer

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job=job, replica=replica, kv=kv,
                   max_batch=kw.pop("max_batch", 16),
                   max_queue_ms=kw.pop("max_queue_ms", 0.5),
                   addr_ttl_s=kw.pop("addr_ttl_s", 5.0), **kw)
    door = FrontDoor(app, host="127.0.0.1", job=f"{job}-{replica}").start()
    assert app.wait_ready(120)
    return app, door


class TestLBTier:
    """Two live replicas + one LB, discovered through the FakeKV the
    replicas publish their ready-gate keys to."""

    JOB = "lbtest/fleet"

    @classmethod
    def setup_class(cls):
        cls.kv = FakeKV()
        cls.app_a, cls.door_a = spin_replica(cls.kv, cls.JOB, "ra")
        cls.app_b, cls.door_b = spin_replica(cls.kv, cls.JOB, "rb")
        cls.lb = ServingLB(
            job=cls.JOB, host="127.0.0.1", kv=cls.kv, pool=2,
            discovery_s=0.1, sweep_ms=3.0, hedge_floor_ms=30.0,
            request_timeout_s=20.0).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sum(1 for u in cls.lb.app.upstreams.values()
                   if u.routable()) == 2:
                break
            time.sleep(0.05)
        assert sum(1 for u in cls.lb.app.upstreams.values()
                   if u.routable()) == 2, cls.lb.app.upstreams

    @classmethod
    def teardown_class(cls):
        cls.lb.stop()
        cls.door_a.stop()
        cls.door_b.stop()

    def _upstream(self, name):
        return self.lb.app.upstreams[name]

    def _send(self, n, sock=None, priority=None):
        row = np.ones((SIZES[0],), np.float32)
        s = sock or connect(self.lb.port)
        s.sendall(b"".join(build_predict_request(row, priority=priority)
                           for _ in range(n)))
        return s

    def test_discovery_published_keys(self):
        keys = self.kv.kv_keys(f"{SERVING_ADDR_PREFIX}{self.JOB}/")
        assert len(keys) == 2

    def test_routes_and_answers(self):
        s = self._send(20)
        resps = read_responses(s, 20)
        s.close()
        assert [st for st, _ in resps] == [200] * 20
        ref = np.asarray(mlp.apply(
            PARAMS, np.ones((1, SIZES[0]), np.float32)))[0]
        np.testing.assert_allclose(np.frombuffer(resps[0][1], "<f4"), ref,
                                   atol=1e-5)

    def test_connection_pool_reuse(self):
        """Hundreds of requests ride the SAME pooled upstream
        connections: the replica doors' accepted-connection count must
        not move while requests pour through."""
        # park the hedger: on a loaded host a burst aging past the
        # 30 ms floor would hedge and double-count requests_served
        saved = (self.lb.app.hedge_floor_ms, self.lb.app.hedge_cap_ms,
                 self.lb.app.hedge_delay_s)
        self.lb.app.hedge_floor_ms = self.lb.app.hedge_cap_ms = 60_000.0
        self.lb.app.hedge_delay_s = 60.0
        try:
            conns_before = (self.door_a.connections
                            + self.door_b.connections)
            served_a = self.app_a.requests_served
            served_b = self.app_b.requests_served
            for _ in range(3):
                s = self._send(100)
                resps = read_responses(s, 100)
                assert [st for st, _ in resps] == [200] * 100
                s.close()
            assert self.door_a.connections + self.door_b.connections \
                == conns_before
            served = (self.app_a.requests_served - served_a
                      + self.app_b.requests_served - served_b)
            assert served == 300  # every request crossed an upstream
        finally:
            (self.lb.app.hedge_floor_ms, self.lb.app.hedge_cap_ms,
             self.lb.app.hedge_delay_s) = saved

    def test_ready_gate_routes_around_reloading(self):
        """A RELOADING replica takes no new traffic; regated, it takes
        traffic again — the rolling-reload invariant."""
        self.app_b._set_state(FD_RELOADING)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and self._upstream("rb").state == FD_READY:
            time.sleep(0.02)
        assert self._upstream("rb").state == FD_RELOADING
        served_b = self.app_b.requests_served
        reqs_b = self._upstream("rb").requests
        s = self._send(60)
        resps = read_responses(s, 60)
        s.close()
        assert [st for st, _ in resps] == [200] * 60
        assert self._upstream("rb").requests == reqs_b
        assert self.app_b.requests_served == served_b
        # regate: traffic returns
        self.app_b._set_state(FD_READY)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and self._upstream("rb").state != FD_READY:
            time.sleep(0.02)
        got = False
        for _ in range(10):  # routing is least-outstanding; nudge it
            s = self._send(40)
            read_responses(s, 40)
            s.close()
            if self._upstream("rb").requests > reqs_b:
                got = True
                break
        assert got, "regated replica never took traffic again"

    def _gate_rb(self, state):
        self.app_b._set_state(state)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and self._upstream("rb").state != state:
            time.sleep(0.02)
        assert self._upstream("rb").state == state

    def test_hedge_fires_and_first_wins(self):
        """An injected straggler iteration on one replica: the LB hedges
        the aged block to the peer (win counted), and the straggler's
        late response is consumed and DISCARDED (lose counted) — first
        wins, nothing errors, nothing duplicates client-side.

        Deterministic steering: ra is wedged via a DIRECT request, rb is
        gated while the LB block is sent (so it lands on ra), then rb is
        regated so the hedge sweep has a target."""
        c = get_counters()
        wins0 = c.get("lb_hedges", job=self.JOB, result="win")
        loses0 = c.get("lb_hedges", job=self.JOB, result="lose")
        row = np.ones((SIZES[0],), np.float32)
        # 1. wedge ra's next iteration, triggered off the LB's path
        self.app_a._stall_once_ms = 1200
        direct = connect(self.door_a.port)
        direct.sendall(build_predict_request(row))
        time.sleep(0.05)  # the wedged iteration is now in progress
        # 2. gate rb so the LB block must land on ra's queue
        self._gate_rb(FD_RELOADING)
        s = self._send(4)
        time.sleep(0.05)
        # 3. regate rb: the hedge sweep now has a fast target
        self._gate_rb(FD_READY)
        resps = read_responses(s, 4, timeout=30)
        s.close()
        assert [st for st, _ in resps] == [200] * 4
        read_responses(direct, 1, timeout=30)
        direct.close()
        # the hedge won (rb answered while ra slept) and ra's late
        # response was consumed + discarded
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and (
                c.get("lb_hedges", job=self.JOB, result="win") == wins0
                or c.get("lb_hedges", job=self.JOB,
                         result="lose") == loses0):
            time.sleep(0.05)
        assert c.get("lb_hedges", job=self.JOB, result="win") > wins0
        assert c.get("lb_hedges", job=self.JOB, result="lose") > loses0

    def test_killed_replica_rescued_zero_errors(self):
        """Abruptly sever a replica mid-burst (its queued work dies with
        its connections): every outstanding block is re-sent to the
        survivor — the client sees 200s only, and rescues are counted."""
        c = get_counters()
        rescues0 = c.get("lb_rescues", job=self.JOB)
        row = np.ones((SIZES[0],), np.float32)
        # park the hedger (floor/cap AND the live delay >> the drill)
        # so the RESCUE path — not a racing hedge — saves the burst
        self.lb.app.hedge_floor_ms = self.lb.app.hedge_cap_ms = 60_000.0
        self.lb.app.hedge_delay_s = 60.0
        # wedge ra off the LB path, gate rb so the burst lands on ra
        # (long enough that the gate waits + discovery sweeps before the
        # sever stay comfortably inside the wedge)
        self.app_a._stall_once_ms = 3000
        direct = connect(self.door_a.port)
        direct.sendall(build_predict_request(row))
        time.sleep(0.05)
        self._gate_rb(FD_RELOADING)
        s = self._send(40)
        time.sleep(0.1)  # the burst is now queued on ra
        self._gate_rb(FD_READY)
        # sever ra's sockets (RST-style: transports abort via the loop)
        door = self.door_a

        def sever():
            for conn in list(door.conns):
                conn.transport.abort()

        door.call_soon(sever)
        resps = read_responses(s, 40, timeout=30)
        s.close()
        assert [st for st, _ in resps] == [200] * 40
        assert c.get("lb_rescues", job=self.JOB) > rescues0
        direct.close()  # severed with the rest of ra's connections

    def test_connection_close_does_not_kill_upstream_pool(self):
        """A client's hop-by-hop ``Connection: close`` is stripped
        before forwarding: the client hop closes, but the pooled
        pipelined upstream connections survive (no rescue storm, no
        redial per close-marked request)."""
        conns_before = self.door_a.connections + self.door_b.connections
        row = np.ones((SIZES[0],), np.float32)
        body = np.ascontiguousarray(row, dtype="<f4").tobytes()
        req = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
               b"Content-Type: application/x-edl-f32\r\n"
               b"Connection: close\r\n"
               b"Content-Length: %d\r\n\r\n" % len(body)) + body
        for _ in range(3):
            s = connect(self.lb.port)
            s.sendall(req)
            (st, b), = read_responses(s, 1)
            assert st == 200
            assert s.recv(1 << 16) == b""  # client hop DID close
            s.close()
        # follow-up traffic still rides the same pooled connections
        s = self._send(20)
        resps = read_responses(s, 20)
        s.close()
        assert [st for st, _ in resps] == [200] * 20
        assert self.door_a.connections + self.door_b.connections \
            == conns_before

    def test_json_forwarded_verbatim(self):
        row = np.arange(SIZES[0], dtype=np.float32)
        body = json.dumps({"inputs": row.tolist()}).encode()
        jreq = (b"POST /predict HTTP/1.1\r\nHost: t\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body)) + body
        s = connect(self.lb.port)
        s.sendall(jreq)
        (st, b), = read_responses(s, 1)
        s.close()
        assert st == 200
        ref = np.asarray(mlp.apply(PARAMS, row[None, :]))[0]
        np.testing.assert_allclose(
            np.asarray(json.loads(b.decode())["outputs"]), ref, atol=1e-5)

    def test_healthz(self):
        s = connect(self.lb.port)
        s.sendall(b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
        (st, _), = read_responses(s, 1)
        s.close()
        assert st == 200

    def test_admin_verbs_not_forwarded(self):
        """The LB is not a transparent proxy for the replica admin
        surface: /admin/* from a client gets a 404 at the LB, never a
        forwarded drill verb."""
        s = connect(self.lb.port)
        s.sendall(b"POST /admin/stall HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 6\r\n\r\n300000")
        (st, _), = read_responses(s, 1)
        s.close()
        assert st == 404
        assert self.app_a._stall_once_ms == 0.0
        assert self.app_b._stall_once_ms == 0.0


class TestLBShedding:
    """Priority shedding against the LB-wide outstanding count (tiny
    caps, one deliberately wedged replica)."""

    JOB = "lbtest/shed"

    @classmethod
    def setup_class(cls):
        cls.kv = FakeKV()
        cls.app, cls.door = spin_replica(cls.kv, cls.JOB, "r0",
                                         max_batch=8)
        cls.lb = ServingLB(
            job=cls.JOB, host="127.0.0.1", kv=cls.kv, pool=1,
            discovery_s=0.1, sweep_ms=5.0, hedge_floor_ms=10_000.0,
            hard_cap_rows=32, soft_cap_rows=16).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not any(
                u.routable() for u in cls.lb.app.upstreams.values()):
            time.sleep(0.05)
        assert any(u.routable() for u in cls.lb.app.upstreams.values())

    @classmethod
    def teardown_class(cls):
        cls.lb.stop()
        cls.door.stop()

    def test_priority_shed_order_under_overload(self):
        c = get_counters()
        row = np.ones((SIZES[0],), np.float32)
        self.app._stall_once_ms = 400
        s = connect(self.lb.port)
        s.sendall(build_predict_request(row) * 16)  # fill to soft cap
        time.sleep(0.1)
        low0 = c.get("lb_overload_sheds", job=self.JOB, priority="low")
        s.sendall(build_predict_request(row, priority="low"))
        s.sendall(build_predict_request(row, priority="normal"))
        s.sendall(build_predict_request(row, priority="high"))
        resps = read_responses(s, 19, timeout=30)
        s.close()
        statuses = [st for st, _ in resps]
        assert statuses[:16] == [200] * 16
        assert statuses[16] == 429  # low shed first
        assert statuses[17] == 200  # normal still admitted
        assert statuses[18] == 200  # high rides the reserve band
        assert c.get("lb_overload_sheds", job=self.JOB,
                     priority="low") == low0 + 1
        # overload degraded in priority order and nothing was dropped:
        # every request got a fast, definitive answer
        assert len(resps) == 19


def test_request_timeout_kills_desynced_upstream_conn():
    """A block expired by the request-timeout last resort must take its
    pipelined upstream connection with it: the wedged replica's eventual
    late responses would otherwise be credited to the NEXT block on the
    FIFO — silently wrong outputs forever.  The client gets a 503, the
    stale connection dies (the fake upstream sees EOF), and the repooled
    fresh connection serves the next request correctly."""
    c = get_counters()
    accepted = []
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    halt = threading.Event()

    def acceptor():
        while not halt.is_set():
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            accepted.append(conn)

    t = threading.Thread(target=acceptor, daemon=True)
    t.start()
    lb = ServingLB(
        job="lbtest/timeout", host="127.0.0.1",
        static_upstreams={"r0": f"127.0.0.1:{srv.getsockname()[1]}"},
        pool=1, sweep_ms=5.0, hedge_floor_ms=60_000.0,
        hedge_cap_ms=60_000.0, request_timeout_s=0.3,
        # the hand-rolled socket upstream can't echo nonces; this test
        # pins the timeout/desync kill, not response integrity
        integrity=False).start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not accepted:
            time.sleep(0.02)
        assert accepted, "LB never dialed the upstream"
        first = accepted[0]
        timeouts0 = c.get("lb_timeouts", job="lbtest/timeout")
        row = np.ones((SIZES[0],), np.float32)
        s = connect(lb.port)
        s.sendall(build_predict_request(row))  # upstream never answers
        (st, _), = read_responses(s, 1, timeout=10)
        assert st == 503  # timed out, not hung
        assert c.get("lb_timeouts", job="lbtest/timeout") == timeouts0 + 1
        # the stale connection is DEAD: the fake upstream reads EOF
        first.settimeout(10)
        first.recv(1 << 16)  # drain the forwarded request bytes
        assert first.recv(1 << 16) == b""  # EOF: the LB killed the conn
        # the pool re-dials; the fresh connection serves correctly
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(accepted) < 2:
            time.sleep(0.02)
        assert len(accepted) >= 2, "LB never repooled after the kill"
        fresh = accepted[-1]
        s.sendall(build_predict_request(row))
        fresh.settimeout(10)
        fresh.recv(1 << 16)
        fresh.sendall(b"HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nhi")
        (st2, body2), = read_responses(s, 1, timeout=10)
        assert (st2, body2) == (200, b"hi")  # right response, right block
        s.close()
    finally:
        halt.set()
        srv.close()
        lb.stop()
        for conn in accepted:
            try:
                conn.close()
            except OSError:
                pass


def test_lb_static_upstreams_no_kv():
    """The LB also runs without a coordinator (static upstream list) —
    the zero-dependency deployment shape."""
    from edl_tpu.runtime.serving import ElasticServer

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    app = BatchApp(build, SIZES[0], job="lbtest/static", replica="r0")
    door = FrontDoor(app, host="127.0.0.1", job="lbtest/static").start()
    assert app.wait_ready(120)
    lb = ServingLB(job="lbtest/static", host="127.0.0.1",
                   static_upstreams={"r0": f"127.0.0.1:{door.port}"},
                   pool=1).start()
    try:
        time.sleep(0.3)
        row = np.ones((SIZES[0],), np.float32)
        s = connect(lb.port)
        s.sendall(build_predict_request(row) * 10)
        resps = read_responses(s, 10)
        s.close()
        assert [st for st, _ in resps] == [200] * 10
    finally:
        lb.stop()
        door.stop()


def test_lb_static_upstream_redialed_after_late_start():
    """A static upstream that was NOT listening when the LB started
    (replica restart window, LB-first boot order) is re-dialed by the
    sweep's pool top-up and becomes routable once it comes up — without
    KV discovery there is no other redial trigger."""
    from edl_tpu.runtime.serving import ElasticServer

    def build():
        return ElasticServer(lambda p, b: mlp.apply(p, b[0]), PARAMS)

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # nothing listens here yet
    lb = ServingLB(job="lbtest/latestart", host="127.0.0.1",
                   static_upstreams={"r0": f"127.0.0.1:{port}"},
                   pool=1, sweep_ms=3.0).start()
    door = None
    try:
        time.sleep(0.7)  # the initial dial has failed by now
        assert not any(u.routable()
                       for u in lb.app.upstreams.values())
        app = BatchApp(build, SIZES[0], job="lbtest/latestart",
                       replica="r0")
        door = FrontDoor(app, host="127.0.0.1", port=port,
                         job="lbtest/latestart").start()
        assert app.wait_ready(120)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if any(u.routable() for u in lb.app.upstreams.values()):
                break
            time.sleep(0.05)
        assert any(u.routable() for u in lb.app.upstreams.values())
        row = np.ones((SIZES[0],), np.float32)
        s = connect(lb.port)
        s.sendall(build_predict_request(row) * 5)
        resps = read_responses(s, 5)
        s.close()
        assert [st for st, _ in resps] == [200] * 5
    finally:
        lb.stop()
        if door is not None:
            door.stop()


def test_unhedged_rescue_duplicate_not_a_hedge_lose():
    """A rescue resend whose ORIGINAL also answered (sever raced the
    response) is a late duplicate, not a hedge-duel loss — only duel
    participants (hedge twins, hedged primaries/rescues) may move the
    win/lose series dashboards read as duel outcomes."""
    from edl_tpu.runtime.lb import LBApp, _Cell, _OutBlock

    app = LBApp(job="lbtest/dup")
    c = get_counters()
    lose0 = c.get("lb_hedges", job="lbtest/dup", result="lose")
    late0 = c.get("lb_late_responses", job="lbtest/dup")

    class _ClosedConn:
        closed = True

    cell = _Cell()
    cell.done = True  # the original already answered the client
    rescue = _OutBlock(_ClosedConn(), None, 3, b"", cell, kind="rescue")
    app.block_done(rescue)
    assert c.get("lb_hedges", job="lbtest/dup", result="lose") == lose0
    assert c.get("lb_late_responses", job="lbtest/dup") == late0 + 3
    # a hedge twin losing the duel IS a duel outcome
    hedge = _OutBlock(_ClosedConn(), None, 2, b"", cell, kind="hedge")
    hedge.hedged = True
    app.block_done(hedge)
    assert c.get("lb_hedges", job="lbtest/dup",
                 result="lose") == lose0 + 2
    assert c.get("lb_late_responses", job="lbtest/dup") == late0 + 3


# -- request tracing at the origin (ISSUE-14) --------------------------------


def _trace_events(tid):
    from edl_tpu.observability.tracing import get_tracer

    return [e for e in get_tracer().events() if e.trace_id == tid]


class TestTraceOrigin:
    """The LB as trace origin: head sampling injects the header, a
    hedge duel yields winner/loser spans stitched cross-tier, and the
    exemplar ring + traces_sampled counters record the keeps."""

    JOB = "lbtrace/fleet"

    @classmethod
    def setup_class(cls):
        cls.kv = FakeKV()
        cls.app_a, cls.door_a = spin_replica(cls.kv, cls.JOB, "ra")
        cls.app_b, cls.door_b = spin_replica(cls.kv, cls.JOB, "rb")
        # trace_sample=1.0: EVERY admitted block head-samples — the
        # deterministic setting tests (and only tests) use
        cls.lb = ServingLB(
            job=cls.JOB, host="127.0.0.1", kv=cls.kv, pool=2,
            discovery_s=0.1, sweep_ms=3.0, hedge_floor_ms=30.0,
            request_timeout_s=20.0, trace_sample=1.0).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and sum(
                1 for u in cls.lb.app.upstreams.values()
                if u.routable()) < 2:
            time.sleep(0.05)
        assert sum(1 for u in cls.lb.app.upstreams.values()
                   if u.routable()) == 2

    @classmethod
    def teardown_class(cls):
        cls.lb.stop()
        cls.door_a.stop()
        cls.door_b.stop()

    def _gate_rb(self, state):
        self.app_b._set_state(state)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline \
                and self.lb.app.upstreams["rb"].state != state:
            time.sleep(0.02)
        assert self.lb.app.upstreams["rb"].state == state

    def test_head_sampling_injects_and_stitches(self):
        """An UNTRACED client request is head-sampled at the LB: a
        trace id is minted, the header injected into the forwarded
        bytes, the replica's door records its phases under the same id
        parented to the LB root, and the echo rides back to the
        client."""
        c = get_counters()
        head0 = c.get("traces_sampled", job=self.JOB, origin="head")
        row = np.ones((SIZES[0],), np.float32)
        s = connect(self.lb.port)
        s.sendall(build_predict_request(row))  # NO client trace header
        resps = read_responses(s, 1)
        s.close()
        assert resps[0][0] == 200
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and c.get(
                "traces_sampled", job=self.JOB, origin="head") == head0:
            time.sleep(0.05)
        assert c.get("traces_sampled", job=self.JOB,
                     origin="head") > head0
        ex = [e for e in self.lb.app.exemplars if e["origin"] == "head"]
        assert ex, list(self.lb.app.exemplars)
        tid = ex[-1]["trace_id"]
        deadline = time.monotonic() + 10
        names = set()
        while time.monotonic() < deadline:
            names = {e.name for e in _trace_events(tid)}
            if "frontdoor_request" in names and "lb_request" in names:
                break
            time.sleep(0.05)
        # the stitched set: LB origin spans AND the door's phases,
        # one trace id across both tiers
        assert {"lb_request", "lb.route", "lb.upstream",
                "frontdoor_request", "frontdoor.forward"} <= names
        # the door root is PARENTED to the LB root (injected
        # X-EDL-Parent-Span), not an orphan stitched only by id
        root = next(e for e in _trace_events(tid)
                    if e.name == "lb_request")
        door_root = next(e for e in _trace_events(tid)
                         if e.name == "frontdoor_request")
        assert door_root.parent_id == root.span_id

    def test_hedged_request_tree_marks_loser_discarded(self):
        """The acceptance shape: a hedged request's stitched tree shows
        the duel — hedge twins as sibling lb.upstream spans, winner
        marked win, the straggler's late response marked discarded —
        rendered by the same code path `edl-tpu trace` uses."""
        from edl_tpu.observability.tracing import (
            get_tracer, new_trace_id, render_trace_tree,
        )

        c = get_counters()
        tid = new_trace_id()
        row = np.ones((SIZES[0],), np.float32)
        # wedge ra via a direct request, steer the traced request onto
        # it, then regate rb as the hedge target (the test_lb steering
        # recipe)
        self.app_a._stall_once_ms = 1200
        direct = connect(self.door_a.port)
        direct.sendall(build_predict_request(row))
        time.sleep(0.05)
        self._gate_rb(FD_RELOADING)
        s = connect(self.lb.port)
        s.sendall(build_predict_request(row, trace_id=tid))
        time.sleep(0.05)
        self._gate_rb(FD_READY)
        resps = read_responses(s, 1, timeout=30)
        s.close()
        assert resps[0][0] == 200
        read_responses(direct, 1, timeout=30)
        direct.close()
        # wait until the duel fully resolved: winner AND discarded loser
        deadline = time.monotonic() + 15
        outcomes = set()
        while time.monotonic() < deadline:
            outcomes = {e.args.get("outcome")
                        for e in _trace_events(tid)
                        if e.name == "lb.upstream"}
            if {"win", "discarded"} <= outcomes:
                break
            time.sleep(0.05)
        assert {"win", "discarded"} <= outcomes, outcomes
        evs = [{"name": e.name, "category": e.category,
                "ts_s": e.start_s, "dur_s": e.duration_s,
                "proc": "inproc", "trace_id": e.trace_id,
                "span_id": e.span_id, "parent_id": e.parent_id,
                "args": dict(e.args)} for e in _trace_events(tid)]
        txt = render_trace_tree(evs, tid)
        assert "lb_request" in txt
        assert "outcome=discarded" in txt
        assert "outcome=win" in txt
        assert "kind=hedge" in txt
        assert "frontdoor_request" in txt
        # the exemplar ring marks it hedged, and the always-keep
        # counter moved even though this was a client-traced request
        ex = [e for e in self.lb.app.exemplars
              if e["trace_id"] == tid]
        assert ex and ex[0]["hedged"] is True
        # histogram exemplar attached for the scrape plane
        ids = {t for t, _v, _ts in
               self.lb.app._hist.exemplars(job=self.JOB)}
        assert tid in ids
        assert get_tracer()  # keep the import referenced

    def test_trace_disabled_lb_injects_nothing(self):
        """trace=False: no ctx, no injection, no spans — the pre-ISSUE
        behavior, selectable per process (EDL_LB_TRACE_SAMPLE=-1)."""
        from edl_tpu.observability.tracing import get_tracer

        kv = FakeKV()
        app, door = spin_replica(kv, "lbtrace/off", "rq")
        lb = ServingLB(job="lbtrace/off", host="127.0.0.1", kv=kv,
                       pool=1, discovery_s=0.1, sweep_ms=3.0,
                       trace=False, trace_sample=1.0).start()
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not any(
                    u.routable() for u in lb.app.upstreams.values()):
                time.sleep(0.05)
            before = len(get_tracer().events())
            row = np.ones((SIZES[0],), np.float32)
            s = connect(lb.port)
            s.sendall(build_predict_request(row) * 4)
            assert [st for st, _ in read_responses(s, 4)] == [200] * 4
            s.close()
            assert get_counters().get("traces_sampled",
                                      job="lbtrace/off",
                                      origin="head") == 0
            new = [e for e in list(get_tracer().events())[before:]
                   if e.name in ("lb_request", "frontdoor_request")
                   and e.args.get("job") == "lbtrace/off"]
            assert new == []
        finally:
            lb.stop()
            door.stop()
