"""Structural-schema pruning: the shipped manifests survive a REAL apiserver.

Round-3 verdict weak #1: the example job used kebab-case keys the CRD's
structural schema did not declare, so a conformant apiserver would prune
them on ``kubectl apply`` and the elastic 2-10 job silently degraded to a
fixed 1/1 job — and the stub apiserver stored dicts verbatim, so no test
could catch it.  Fix is three-sided: the CRD schema declares both
spellings (k8s/crd.yaml), the shipped example/docs use canonical
snake_case, and the stub now prunes per the SHIPPED schema
(tests/k8s_stub.py:prune_per_schema) so any future docs/schema drift
fails here instead of on a cluster.

Reference match: pkg/apis/paddlepaddle/v1/types.go:44-90 — the CRD types
ARE the accepted key set.
"""

from __future__ import annotations

import copy
import pathlib
import re

import pytest
import yaml

from edl_tpu.api import serde

from tests.k8s_stub import load_crd_schemas, prune_per_schema

# fixtures `kube`/`control_plane` come from tests/conftest.py

REPO = pathlib.Path(__file__).resolve().parent.parent
SCHEMA = load_crd_schemas()[("edl.tpu", "trainingjobs")]


def prune_cr(doc: dict) -> dict:
    out = copy.deepcopy(doc)
    props = SCHEMA["properties"]
    for section in ("spec", "status"):
        if section in out:
            out[section] = prune_per_schema(out[section], props[section])
    return out


# ---------------------------------------------------------------- pruner unit

def test_pruner_drops_undeclared_and_keeps_declared():
    doc = {"spec": {"image": "i", "bogus_key": 1,
                    "trainer": {"min_instance": 2, "camelKey": 3,
                                "resources": {"limits": {"cpu": "1"},
                                              "anything": {"x": 1}}},
                    "node_selector": {"pool": "tpu"}}}
    pruned = prune_cr(doc)["spec"]
    assert "bogus_key" not in pruned
    assert pruned["trainer"]["min_instance"] == 2
    assert "camelKey" not in pruned["trainer"]
    # x-kubernetes-preserve-unknown-fields: resources kept verbatim
    assert pruned["trainer"]["resources"]["anything"] == {"x": 1}
    # additionalProperties map: keys preserved
    assert pruned["node_selector"] == {"pool": "tpu"}


def test_pruner_keeps_both_instance_spellings():
    """The schema declares snake AND the reference's kebab spellings, so
    neither is lost on admission (reference example/examplejob.yaml:15-16
    uses min-instance)."""
    doc = {"spec": {"trainer": {"min-instance": 2, "max-instance": 10,
                                "min_instance": 3}}}
    pruned = prune_cr(doc)["spec"]["trainer"]
    assert pruned == {"min-instance": 2, "max-instance": 10,
                      "min_instance": 3}


def test_serde_aliases_and_crd_schema_in_lockstep():
    """Every spelling the client serde accepts must be declared in the
    CRD schema wherever its canonical form is — otherwise the key works
    via `edl-tpu submit` but is apiserver-pruned on `kubectl apply`."""
    def walk(schema, out):
        props = schema.get("properties") or {}
        for k, sub in props.items():
            out.setdefault(k, []).append(props)
            walk(sub, out)
        if isinstance(schema.get("items"), dict):
            walk(schema["items"], out)
    declared: dict[str, list] = {}
    walk(SCHEMA, declared)
    for kebab, snake in serde.KEBAB_ALIASES.items():
        assert snake in declared, snake
        for scope in declared[snake]:
            assert kebab in scope, (
                f"{kebab} missing from a schema scope declaring {snake}")
    # the master-endpoint alias serde reads is declared too
    assert "coord_endpoint" in declared


def test_serde_prefers_snake_when_both_spellings_present():
    t = serde.job_from_dict({
        "kind": "TrainingJob", "metadata": {"name": "j"},
        "spec": {"trainer": {"min-instance": 2, "min_instance": 3,
                             "max-instance": 10}}}).spec.trainer
    assert t.min_instance == 3      # snake wins deterministically
    assert t.max_instance == 10     # kebab alone still accepted


# ------------------------------------------------- shipped manifests survive

def manifest_docs() -> list[tuple[str, dict]]:
    """Every TrainingJob manifest we ship: examples/*.yaml plus every
    ```yaml block in doc/*.md.  A doc edit that introduces an undeclared
    key fails the pruning-equivalence test below."""
    found = []
    for p in sorted((REPO / "examples").glob("*.yaml")):
        doc = yaml.safe_load(p.read_text())
        if isinstance(doc, dict) and doc.get("kind") == "TrainingJob":
            found.append((str(p.relative_to(REPO)), doc))
    for p in sorted((REPO / "doc").glob("*.md")):
        for block in re.findall(r"```yaml\n(.*?)```", p.read_text(), re.S):
            try:
                doc = yaml.safe_load(block)
            except yaml.YAMLError:
                continue
            if isinstance(doc, dict) and doc.get("kind") == "TrainingJob":
                found.append((str(p.relative_to(REPO)), doc))
    return found


def test_manifest_inventory_is_nonempty():
    names = [n for n, _ in manifest_docs()]
    assert any("examplejob" in n for n in names)
    assert any(n.startswith("doc/") for n in names)


@pytest.mark.parametrize("name,doc", manifest_docs())
def test_shipped_manifests_survive_apiserver_pruning(name, doc):
    """Admission pruning must not change what the controller parses out of
    any shipped manifest — in particular the elastic min/max dial."""
    before = serde.job_from_dict(doc)
    after = serde.job_from_dict(prune_cr(doc))
    assert after == before, f"{name}: pruning changed the parsed job"
    # the canonical example is genuinely elastic after pruning
    if "examplejob" in name or "usage" in name:
        assert (after.spec.trainer.min_instance,
                after.spec.trainer.max_instance) == (2, 10), name


# ------------------------------------------------ end-to-end through the stub

def test_shipped_example_elastic_through_pruning_stub(control_plane):
    """kubectl apply -f examples/examplejob.yaml against the PRUNING stub:
    the controller must see min=2/max=10 (round-3 'done' criterion)."""
    cluster, controller, sync, state = control_plane
    doc = yaml.safe_load((REPO / "examples" / "examplejob.yaml").read_text())
    cluster.create_training_job_cr(doc)

    stored = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                                   "example")]
    assert stored["spec"]["trainer"]["min_instance"] == 2  # not pruned

    sync.run_once()
    job = controller.jobs()[0]
    assert (job.spec.trainer.min_instance,
            job.spec.trainer.max_instance) == (2, 10)
    # materialized at min parallelism, i.e. actually elastic-capable
    assert state.jobs[("default", "example-trainer")].spec.parallelism == 2


def test_reference_style_kebab_manifest_through_pruning_stub(control_plane):
    """A reference-ported manifest (kebab keys, example/examplejob.yaml
    style) keeps its elastic dial thanks to the schema aliases."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr({
        "apiVersion": "edl.tpu/v1", "kind": "TrainingJob",
        "metadata": {"name": "ported", "namespace": "default"},
        "spec": {"image": "i", "fault_tolerant": True,
                 "trainer": {"entrypoint": "python t.py",
                             "min-instance": 2, "max-instance": 10,
                             "resources": {"requests": {"cpu": "1",
                                                        "memory": "1Gi"}}}},
    })
    stored = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                                   "ported")]
    assert stored["spec"]["trainer"]["min-instance"] == 2
    sync.run_once()
    job = controller.jobs()[0]
    assert (job.spec.trainer.min_instance,
            job.spec.trainer.max_instance) == (2, 10)


def test_undeclared_key_is_pruned_by_stub(control_plane):
    """Negative control: the stub really prunes — an undeclared spelling
    vanishes on admission and the job falls back to the 1/1 default (the
    exact silent failure mode the schema aliases exist to prevent)."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr({
        "apiVersion": "edl.tpu/v1", "kind": "TrainingJob",
        "metadata": {"name": "oops", "namespace": "default"},
        "spec": {"image": "i",
                 "trainer": {"entrypoint": "python t.py",
                             "minInstances": 2, "maxInstances": 10}},
    })
    stored = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                                   "oops")]
    assert "minInstances" not in stored["spec"]["trainer"]
    sync.run_once()
    job = controller.jobs()[0]
    assert (job.spec.trainer.min_instance,
            job.spec.trainer.max_instance) == (1, 1)
