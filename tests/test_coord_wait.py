"""Event-driven coordination: the long-poll wait path under churn.

PR 3 replaced the fixed-sleep polling loops on the reform critical path
(discovery.wait_stable, the coordinator claim, wait_state) with
WAITEPOCH/KVWAIT long-polls: a waiter parks on the coordination service
and is woken by the join/leave/expiry/KV-set that matters.  These tests
pin the contract on both the pure-Python service and the native TCP
server: correctness under concurrent churn, timeout-vs-fire ordering,
and — the operational point — no thundering-herd re-poll while parked.
"""

from __future__ import annotations

import threading
import time

import pytest

from edl_tpu.coord import PyCoordService, spawn_server
from edl_tpu.runtime.discovery import CoordDiscovery, wait_epoch_change


@pytest.fixture()
def server():
    srv = spawn_server(member_ttl_ms=2000)
    try:
        yield srv
    finally:
        srv.stop()


def _service_and_clients(kind, server, n=1):
    """One mutating handle + n independent waiter handles."""
    if kind == "python":
        s = PyCoordService(member_ttl_ms=2000)
        return s, [s] * n
    return server.client(), [server.client() for _ in range(n)]


@pytest.fixture(params=["python", "native-server"])
def kind(request):
    return request.param


# ---------------------------------------------------------------- basic fire

def test_wait_epoch_fires_on_join(kind, server):
    svc, (waiter,) = _service_and_clients(kind, server)
    svc.join("a")
    known = svc.epoch()
    got = {}

    def park():
        t0 = time.monotonic()
        got["epoch"] = waiter.wait_epoch(known, timeout_s=10.0)
        got["dt"] = time.monotonic() - t0

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.2)
    svc.join("b")
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["epoch"] != known
    # event-driven: woke on the join, not at the 10 s timeout
    assert got["dt"] < 2.0


def test_wait_epoch_timeout_returns_same_epoch(kind, server):
    svc, (waiter,) = _service_and_clients(kind, server)
    svc.join("only")
    known = svc.epoch()
    t0 = time.monotonic()
    assert waiter.wait_epoch(known, timeout_s=0.3) == known
    dt = time.monotonic() - t0
    assert 0.25 <= dt < 2.0  # honored the timeout, did not park forever


def test_kv_wait_fires_on_set_and_on_epoch_move(kind, server):
    svc, (w1, w2) = _service_and_clients(kind, server, n=2)
    svc.join("a")
    known = svc.epoch()
    got = {}

    def park_kv():
        got["kv"] = w1.kv_wait("the-key", timeout_s=10.0)

    def park_epoch():
        got["ep"] = w2.kv_wait("never-set", timeout_s=10.0,
                               known_epoch=known)

    t1 = threading.Thread(target=park_kv)
    t2 = threading.Thread(target=park_epoch)
    t1.start(), t2.start()
    time.sleep(0.2)
    svc.kv_set("the-key", b"payload")
    svc.join("b")  # moves the epoch for the second waiter
    t1.join(timeout=5), t2.join(timeout=5)
    assert got["kv"][0] == b"payload"
    v, ep = got["ep"]
    assert v is None and ep is not None and ep != known


def test_kv_wait_preexisting_key_returns_immediately(kind, server):
    svc, (waiter,) = _service_and_clients(kind, server)
    svc.kv_set("already", b"here")
    t0 = time.monotonic()
    v, _ = waiter.kv_wait("already", timeout_s=10.0)
    assert v == b"here"
    assert time.monotonic() - t0 < 1.0


def test_kv_wait_timeout_vs_fire_ordering(kind, server):
    """A waiter whose timeout lapses BEFORE the fire reports the timeout;
    one still parked AT the fire reports the value — the two outcomes
    never blur even when the fire lands just after a timeout."""
    svc, (w1, w2) = _service_and_clients(kind, server, n=2)
    results = {}

    def short():  # times out at 0.3 s; the set comes at 0.6 s
        results["short"] = w1.kv_wait("ordered", timeout_s=0.3)

    def long():
        results["long"] = w2.kv_wait("ordered", timeout_s=10.0)

    t1, t2 = threading.Thread(target=short), threading.Thread(target=long)
    t1.start(), t2.start()
    time.sleep(0.6)
    svc.kv_set("ordered", b"late")
    t1.join(timeout=5), t2.join(timeout=5)
    assert results["short"][0] is None  # lapsed before the fire
    assert results["long"][0] == b"late"  # parked through it


# --------------------------------------------------------------- churn soak

def test_waiters_survive_concurrent_churn(kind, server):
    """Joins/leaves/kv churn from several threads while waiters are
    parked: every wait returns (no wedge), every fired wait observed a
    real change."""
    svc, waiters = _service_and_clients(kind, server, n=4)
    svc.join("base")
    stop = threading.Event()
    outcomes: list = []
    lock = threading.Lock()

    def churner(i):
        for round_ in range(10):
            svc.join(f"w{i}-{round_}")
            time.sleep(0.01)
            svc.leave(f"w{i}-{round_}")
            svc.kv_set(f"churn/{i}/{round_}", b"x")

    def parked_epoch(w):
        while not stop.is_set():
            known = w.epoch()
            got = w.wait_epoch(known, timeout_s=0.5)
            with lock:
                outcomes.append(("epoch", known, got))

    churners = [threading.Thread(target=churner, args=(i,))
                for i in range(3)]
    parkers = [threading.Thread(target=parked_epoch, args=(w,))
               for w in waiters]
    for t in churners + parkers:
        t.start()
    for t in churners:
        t.join(timeout=30)
    stop.set()
    for t in parkers:
        t.join(timeout=10)
        assert not t.is_alive(), "parked waiter wedged through churn"
    fired = [o for o in outcomes if o[1] != o[2]]
    assert fired, "no waiter ever observed the churn"


def test_wait_epoch_fires_on_ttl_expiry(kind, server):
    """TTL expiry is the one mutation no command announces — parked
    waiters must still notice a dead member within the re-check cadence."""
    svc, (waiter,) = _service_and_clients(kind, server)
    if kind == "python":
        # the python service's injectable clock defaults to monotonic ms —
        # real time passes, so a 2 s TTL genuinely lapses
        svc.join("dies")
        known = svc.epoch()
    else:
        svc.join("dies")
        known = svc.epoch()
    t0 = time.monotonic()
    got = waiter.wait_epoch(known, timeout_s=10.0)
    dt = time.monotonic() - t0
    assert got != known, "TTL expiry never fired the waiter"
    assert dt < 5.0  # TTL (2 s) + recheck cadence, with margin


# ------------------------------------------------------- no thundering herd

def test_parked_waiters_do_not_thundering_herd(server):
    """The operational claim: K parked waiters cost ~K re-parks per
    LONGPOLL_CHUNK_S, not the 20 Hz × K request storm the old sleep-poll
    loops generated.  Measured against the native server's own request
    counter so client-side batching can't fake it."""
    mut = server.client()
    mut.join("a")
    known = mut.epoch()
    waiters = [server.client() for _ in range(4)]
    before = mut.server_metrics()
    threads = [threading.Thread(target=w.wait_epoch, args=(known, 1.8))
               for w in waiters]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    parked_s = time.monotonic() - t0
    after = mut.server_metrics()
    requests = (after["requests_served"] - before["requests_served"]
                - 1)  # the metrics read itself
    # 4 waiters × ~1.8 s parked at ≤1 req/s each (+1 initial park each):
    # anything close to sleep-polling (4 × 20 Hz × 1.8 s = 144) fails
    assert requests <= 20, (requests, parked_s)
    assert after["longpolls_parked"] > before["longpolls_parked"]


def test_server_metrics_counts_fired(server):
    c = server.client()
    c.join("a")
    known = c.epoch()
    w = server.client()
    t = threading.Thread(target=w.wait_epoch, args=(known, 10.0))
    t.start()
    time.sleep(0.2)
    c.join("b")
    t.join(timeout=5)
    m = c.server_metrics()
    assert m["longpolls_fired"] >= 1
    assert m["requests_served"] > 0


# ----------------------------------------------------- discovery integration

def test_wait_members_event_driven(kind, server):
    svc, (waiter,) = _service_and_clients(kind, server)
    d = CoordDiscovery(waiter, "me", "addr0")
    d.join()
    got = {}

    def park():
        got["peers"] = d.wait_members(3, timeout_s=10.0)

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.1)
    svc.join("p1", "addr1")
    svc.join("p2", "addr2")
    t.join(timeout=5)
    assert [n for n, _ in got["peers"]] == ["me", "p1", "p2"]


def test_wait_epoch_change_falls_back_without_longpoll():
    """Duck-typed backends without wait_epoch still work (sleep-poll)."""

    class Minimal:
        def __init__(self):
            self._e = 0

        def epoch(self):
            return self._e

    m = Minimal()

    def bump():
        time.sleep(0.2)
        m._e = 1

    threading.Thread(target=bump).start()
    assert wait_epoch_change(m, 0, timeout_s=5.0, poll_s=0.02) == 1
