"""Endurance soak: 10+ minutes of worker churn + periodic primary kills
against the HA coordinator pair (VERDICT r5 #9, ROADMAP #5).

The HA claim must be SUSTAINED, not a one-shot drill: across the whole
window the multi-endpoint client never sees :class:`CoordUnavailable`,
the killed node is respawned as a standby of whoever got promoted (the
operator/kubelet loop), and at the end

* memory (RSS) of the surviving coordinator processes is bounded,
* the harness process's open-FD count is bounded (no socket leak per
  failover or per churn cycle),
* the coordinator generation count (the fencing token — one bump per
  promotion) matches the kills, i.e. no promotion flapping,
* queue/KV/epoch state is exactly what the acked operations imply.

Duration is ``EDL_HA_SOAK_S`` (default 600 s — slow-marked; CI smoke and
local runs can shrink it).
"""

from __future__ import annotations

import os
import signal
import socket
import threading
import time

import pytest

from edl_tpu.coord import CoordClient, spawn_ha_pair, spawn_server

_DURATION_S = float(os.environ.get("EDL_HA_SOAK_S", "600"))

pytestmark = [pytest.mark.slow, pytest.mark.multihost,
              pytest.mark.timeout_s(_DURATION_S + 240)]


def _rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def _open_fds() -> int:
    return len(os.listdir("/proc/self/fd"))


def _raw(port: int, line: str, timeout: float = 3.0) -> str:
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((line + "\n").encode())
        return s.makefile("rb").readline().decode().strip()


def test_ha_endurance_soak(tmp_path):
    state_a = str(tmp_path / "coord-a.state")
    state_b = str(tmp_path / "coord-b.state")
    pr, sb = spawn_ha_pair(str(tmp_path), member_ttl_ms=8000,
                           repl_lease_ms=1500)
    nodes = {pr.port: pr, sb.port: sb}
    state_of = {pr.port: state_a, sb.port: state_b}
    c = CoordClient("127.0.0.1", pr.port, timeout=3.0,
                    reconnect_window_s=25.0, promote_grace_s=0.3,
                    endpoints=[("127.0.0.1", sb.port)])
    kill_every_s = max(min(_DURATION_S / 8.0, 75.0), 15.0)
    stop = threading.Event()
    waiter_errors: list = []

    def longpoller():
        # a permanently parked wait riding every failover: the re-park
        # path leaks neither FDs nor correctness
        while not stop.is_set():
            try:
                c.kv_wait(f"never/{time.monotonic()}", 0.5)
            except Exception as exc:  # pragma: no cover - failure evidence
                waiter_errors.append(exc)
                return

    deadline = time.monotonic() + _DURATION_S
    kills = 0
    joins = 0
    cycles = 0
    rss_samples: dict[int, list[int]] = {p: [] for p in nodes}
    fd_start = _open_fds()
    fd_samples = [fd_start]
    next_kill = time.monotonic() + kill_every_s
    t = threading.Thread(target=longpoller, daemon=True)
    t.start()
    try:
        while time.monotonic() < deadline:
            cycles += 1
            w = f"w{cycles % 8}"
            c.join(w, f"addr-{cycles % 8}")
            joins += 1
            c.heartbeat(w)
            # bounded KV working set: rotate 16 keys, delete the oldest
            c.kv_set(f"ckpt/{cycles % 16}", f"/gen-{cycles}".encode())
            c.kv_del(f"ckpt/{(cycles + 1) % 16}")
            c.kv_set("sentinel", str(cycles).encode())
            assert c.kv_get("sentinel") == str(cycles).encode()
            if cycles % 7 == 0:
                c.leave(w)
                joins += 1  # a leave bumps the epoch like a join does
            time.sleep(0.05)
            if time.monotonic() >= next_kill and kills < 64:
                next_kill = time.monotonic() + kill_every_s
                victim_port = c.port  # the current primary
                survivor_port = next(p for p in nodes if p != victim_port)
                nodes[victim_port].process.send_signal(signal.SIGKILL)
                nodes[victim_port].process.wait(timeout=10)
                kills += 1
                # the very next op must ride the failover
                assert c.kv_get("sentinel") == str(cycles).encode()
                assert c.port == survivor_port
                # operator loop: respawn the corpse as a standby of the
                # promoted node, on its old endpoint, from its old file
                nodes[victim_port] = spawn_server(
                    port=victim_port, standby=True, member_ttl_ms=8000,
                    state_file=state_of[victim_port], repl_lease_ms=1500)
                assert _raw(survivor_port,
                            f"REPLICATE 127.0.0.1:{victim_port}") == "OK"
                for port, handle in nodes.items():
                    rss_samples[port].append(_rss_kb(handle.process.pid))
                fd_samples.append(_open_fds())
    finally:
        stop.set()
        t.join(timeout=10)
        fence = int(_raw(c.port, "ROLE").split(" ")[2])
        c.close()
        for handle in nodes.values():
            handle.stop()

    assert not waiter_errors, waiter_errors
    assert kills >= 2, f"soak too short to kill twice ({_DURATION_S}s)"
    # generation count bounded: exactly one promotion per kill — no
    # promotion flapping, no spurious depositions
    assert kills <= fence <= kills + 1, (fence, kills)
    # open FDs bounded: failovers and churn must not leak sockets
    assert max(fd_samples) <= fd_start + 24, (fd_start, fd_samples)
    # RSS bounded: no per-cycle/per-failover growth without bound.  Self-
    # relative: the last sample stays within 2x the first (plus 32 MB of
    # slack for allocator noise at small absolute sizes).
    for port, samples in rss_samples.items():
        if len(samples) >= 2:
            assert samples[-1] <= 2 * samples[0] + 32 * 1024, (port, samples)
