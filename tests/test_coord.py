"""Coordination core tests, run against BOTH the pure-Python spec and the
native C++ core through ctypes — the same behavioral contract
(role of the reference master's task queue, docker/paddle_k8s:26-32, and
etcd membership/KV, pkg/jobparser.go:167-184).
"""

import pytest

from edl_tpu.coord import (
    CoordClient,
    LeaseStatus,
    NativeCoordService,
    PyCoordService,
    native_available,
    spawn_server,
)


class FakeClock:
    def __init__(self) -> None:
        self.ms = 1_000_000

    def __call__(self) -> int:
        return self.ms

    def advance(self, ms: int) -> None:
        self.ms += ms


def make_service(kind, **kw):
    clock = FakeClock()
    if kind == "native":
        if not native_available():
            pytest.skip("native coord core unavailable")
        return NativeCoordService(clock=clock, **kw), clock
    return PyCoordService(clock=clock, **kw), clock


@pytest.fixture(params=["python", "native"])
def kind(request):
    return request.param


def test_lease_complete_done(kind):
    s, _ = make_service(kind)
    ids = [s.add_task(f"shard-{i}".encode()) for i in range(3)]
    seen = set()
    for _ in range(3):
        status, tid, payload = s.lease("w0")
        assert status == LeaseStatus.OK
        assert payload.startswith(b"shard-")
        seen.add(tid)
        assert s.complete(tid)
    assert seen == set(ids)
    status, _, _ = s.lease("w0")
    assert status == LeaseStatus.DONE
    assert s.all_done()


def test_timeout_redispatch(kind):
    # The 16 s dead-trainer re-dispatch bound (reference paddle_k8s:30).
    s, clock = make_service(kind, task_timeout_ms=16_000)
    s.add_task(b"t")
    status, tid, _ = s.lease("dead-worker")
    assert status == LeaseStatus.OK
    # Not yet timed out: nothing leasable, but not done either.
    status, _, _ = s.lease("w1")
    assert status == LeaseStatus.EMPTY
    clock.advance(16_001)
    status, tid2, payload = s.lease("w1")
    assert status == LeaseStatus.OK and payload == b"t"
    assert tid2 == tid  # same task, re-dispatched
    assert s.complete(tid2)
    # A duplicate/late completion is rejected once the lease is gone.
    assert not s.complete(tid)
    assert s.all_done()


def test_fail_requeues_then_drops_poison(kind):
    s, _ = make_service(kind)
    s.add_task(b"poison")
    for i in range(3):  # max failures = 3
        status, tid, _ = s.lease("w")
        assert status == LeaseStatus.OK
        assert s.fail(tid)
    status, _, _ = s.lease("w")
    assert status == LeaseStatus.DONE  # dropped, not wedged
    assert s.stats().dropped == 1


def test_release_worker_returns_leases(kind):
    s, _ = make_service(kind)
    s.add_task(b"a")
    s.add_task(b"b")
    s.lease("w0")
    s.lease("w0")
    assert s.release_worker("w0") == 2
    st = s.stats()
    assert st.todo == 2 and st.leased == 0


def test_multi_pass_recycles_tasks(kind):
    s, _ = make_service(kind, passes=2)
    s.add_task(b"x")
    status, tid, _ = s.lease("w")
    s.complete(tid)
    assert s.current_pass() == 0 or s.current_pass() == 1
    # pass 2: the task comes back
    status, tid, payload = s.lease("w")
    assert status == LeaseStatus.OK and payload == b"x"
    s.complete(tid)
    status, _, _ = s.lease("w")
    assert status == LeaseStatus.DONE
    assert s.current_pass() == 1


def test_membership_epochs(kind):
    s, clock = make_service(kind, member_ttl_ms=15_000)
    e1 = s.join("w0", "host0:1")
    e2 = s.join("w1", "host1:1")
    assert e2 > e1
    epoch, members = s.members()
    assert [m[0] for m in members] == ["w0", "w1"]  # name-sorted = ranks
    # heartbeats keep members alive through a TTL window
    clock.advance(10_000)
    assert s.heartbeat("w0")
    assert s.heartbeat("w1")
    clock.advance(10_000)
    assert s.heartbeat("w1")
    clock.advance(6_000)
    # w0 missed its heartbeats: expired, epoch bumps
    epoch2, members2 = s.members()
    assert [m[0] for m in members2] == ["w1"]
    assert epoch2 > epoch
    # graceful leave bumps again
    assert s.leave("w1")
    assert s.epoch() > epoch2
    # re-join after expiry works
    assert not s.heartbeat("w0")
    s.join("w0", "host0:1")
    assert s.members()[1] == [("w0", "host0:1")]


def test_kv_and_cas(kind):
    s, _ = make_service(kind)
    assert s.kv_get("k") is None
    s.kv_set("k", b"v1")
    assert s.kv_get("k") == b"v1"
    # CAS: claim-if-absent (pserver slot semantics)
    assert s.kv_cas("slot/0", b"", b"w0")
    assert not s.kv_cas("slot/0", b"", b"w1")  # already claimed
    assert s.kv_cas("slot/0", b"w0", b"w1")  # handoff with correct expect
    assert s.kv_get("slot/0") == b"w1"
    assert s.kv_keys("slot/") == ["slot/0"]
    assert s.kv_del("k")
    assert s.kv_get("k") is None


def test_empty_payload_task(kind):
    s, _ = make_service(kind)
    tid = s.add_task(b"")
    status, got, payload = s.lease("w")
    assert status == LeaseStatus.OK and got == tid and payload == b""
    assert s.complete(tid)


# ---------------------------------------------------------------------------
# TCP server integration (native binary + Python client)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    h = spawn_server(port=0, task_timeout_ms=300)
    yield h
    h.stop()


def test_server_roundtrip(server):
    c = server.client()
    assert c.ping()
    tid = c.add_task(b"hello \x00 binary")
    status, got, payload = c.lease("w0")
    assert status == LeaseStatus.OK and got == tid
    assert payload == b"hello \x00 binary"
    assert c.complete(tid)
    status, _, _ = c.lease("w0")
    assert status == LeaseStatus.DONE
    c.close()


def test_server_timeout_redispatch_realtime(server):
    import time

    c = server.client()
    tid = c.add_task(b"work")
    status, t1, _ = c.lease("dead")
    assert status == LeaseStatus.OK
    time.sleep(0.4)  # server runs with task_timeout_ms=300
    status, t2, payload = c.lease("alive")
    assert status == LeaseStatus.OK and payload == b"work"
    assert c.complete(t2)
    c.close()


def test_server_membership_and_kv(server):
    c1 = server.client()
    c2 = server.client()
    e1 = c1.join("trainer-0", "10.0.0.1:7164")
    e2 = c2.join("trainer-1", "10.0.0.2:7164")
    assert e2 > e1
    epoch, members = c1.members()
    assert ("trainer-0", "10.0.0.1:7164") in members
    assert ("trainer-1", "10.0.0.2:7164") in members
    assert c1.kv_cas("ckpt/latest", b"", b"step-100")
    assert c2.kv_get("ckpt/latest") == b"step-100"
    assert c2.heartbeat("trainer-1")
    assert c1.leave("trainer-0")
    c1.close()
    c2.close()


def test_server_concurrent_lease_no_double_grant(server):
    import threading

    c = server.client()
    n = 50
    for i in range(n):
        c.add_task(f"task-{i}".encode())
    granted: list[int] = []
    lock = threading.Lock()

    def worker(wid):
        cc = server.client()
        while True:
            status, tid, _ = cc.lease(f"w{wid}")
            if status != LeaseStatus.OK:
                break
            with lock:
                granted.append(tid)
            cc.complete(tid)
        cc.close()

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(granted)[-n:] == sorted(set(granted))[-n:]
    assert len(set(granted)) == len(granted)  # every task granted exactly once
    c.close()


# ---------------------------------------------------------------------------
# Regression tests for review findings
# ---------------------------------------------------------------------------


def test_all_tasks_dropped_multi_pass_terminates(kind):
    # Poison pills across a multi-pass queue must finish, not livelock.
    s, _ = make_service(kind, passes=3)
    s.add_task(b"poison")
    for _ in range(3):
        status, tid, _ = s.lease("w")
        assert status == LeaseStatus.OK
        s.fail(tid)
    status, _, _ = s.lease("w")
    assert status == LeaseStatus.DONE
    assert s.all_done()


def test_zero_task_multi_pass_terminates(kind):
    s, _ = make_service(kind, passes=5)
    status, _, _ = s.lease("w")
    assert status == LeaseStatus.DONE


def test_large_payload_roundtrip(kind):
    # > the bindings' initial 64 KiB buffer: grow-and-retry must kick in.
    s, _ = make_service(kind)
    blob = bytes(range(256)) * 1024  # 256 KiB
    s.kv_set("big", blob)
    assert s.kv_get("big") == blob
    s.add_task(blob)
    status, tid, payload = s.lease("w")
    assert status == LeaseStatus.OK and payload == blob
    assert s.complete(tid)


def test_server_survives_malformed_commands(server):
    import socket

    raw = socket.create_connection(("127.0.0.1", server.port))
    raw.sendall(b"COMPLETE abc\nFAIL 99999999999999999999999\nPING\n")
    f = raw.makefile("rb")
    l1, l2, l3 = f.readline(), f.readline(), f.readline()
    assert l1.startswith(b"ERR")
    assert l2.startswith(b"ERR")
    assert l3.strip() == b"PONG"  # server alive
    raw.close()


def test_server_empty_kv_value(server):
    c = server.client()
    c.kv_set("empty", b"")
    assert c.kv_get("empty") == b""
    assert c.kv_cas("empty2", b"", b"")
    assert c.kv_get("empty2") == b""
    c.close()


def test_server_join_empty_address_roundtrip(server):
    c = server.client()
    c.join("bare-worker")
    _, members = c.members()
    assert ("bare-worker", "") in members
    c.leave("bare-worker")
    c.close()


# ---------------------------------------------------------------------------
# HTTP health endpoint (role of the reference master's :8080,
# docker/paddle_k8s:27-31; round-3 verdict missing #3: the manifests
# advertised a health port nothing served)
# ---------------------------------------------------------------------------


def test_health_endpoint_serves_stats_and_404():
    import json
    import urllib.error
    import urllib.request

    h = spawn_server(port=0, task_timeout_ms=300, health_port=0)
    try:
        assert h.health_port and h.health_port > 0
        c = h.client()
        c.add_task(b"a")
        c.add_task(b"b")
        c.join("w0", "10.0.0.1:1")
        url = f"http://127.0.0.1:{h.health_port}/healthz"
        with urllib.request.urlopen(url, timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read())
        assert doc["status"] == "ok"
        assert doc["tasks"]["todo"] == 2 and doc["tasks"]["done"] == 0
        assert doc["members"] == 1 and doc["epoch"] >= 1
        # the coord protocol still answers on its own port
        assert c.ping()
        # unknown paths are 404, and the server survives them
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{h.health_port}/nope", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        with urllib.request.urlopen(url, timeout=5) as r:
            assert r.status == 200
        c.close()
    finally:
        h.stop()


def test_health_endpoint_disabled_by_default(server):
    # the module-scope server was spawned without health_port: no second
    # banner was parsed and no health listener exists
    assert server.health_port is None


def test_health_port_negative_means_disabled():
    # the CLI/env convention (-1 = disabled) must not hang the spawner
    # waiting for a health banner the binary will never print
    h = spawn_server(port=0, health_port=-1)
    try:
        assert h.health_port is None
        c = h.client()
        assert c.ping()
        c.close()
    finally:
        h.stop()


def test_health_shed_replies_503_not_reset():
    """At the 8-in-flight probe cap the server must shed WITH a minimal
    503 — a bare close reads as connection-reset, which kubelet probes
    count toward the liveness failureThreshold exactly like a wedged
    coordinator (ADVICE r5 item 4)."""
    import socket
    import time
    import urllib.request

    h = spawn_server(port=0, health_port=0)
    try:
        # park 8 idle connections in ServeHealth's read (5 s deadline)
        held = [socket.create_connection(("127.0.0.1", h.health_port))
                for _ in range(8)]
        time.sleep(0.3)  # let the accept loop count them in-flight
        s = socket.create_connection(("127.0.0.1", h.health_port), timeout=3)
        s.sendall(b"GET /healthz HTTP/1.1\r\nHost: x\r\n\r\n")
        s.settimeout(3)
        resp = b""
        try:
            while chunk := s.recv(4096):
                resp += chunk
        except OSError:
            pass
        assert resp.startswith(b"HTTP/1.1 503"), resp
        assert b"overloaded" in resp
        s.close()
        for c in held:
            c.close()
        time.sleep(0.5)  # slots drain
        # overload over: probes are 200 again (overload != wedge)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{h.health_port}/healthz", timeout=5) as r:
            assert r.status == 200
    finally:
        h.stop()


# ---------------------------------------------------------------------------
# Client outage riding: jittered exponential backoff + degraded-mode hooks
# ---------------------------------------------------------------------------


def test_backoff_delay_envelope():
    import random as _random

    from edl_tpu.coord.client import BACKOFF_CAP_S, backoff_delay

    rng = _random.Random(42)
    delays = [backoff_delay(a, rng) for a in range(12)]
    # jitter stays inside (d/2, d] of the exponential envelope
    for attempt, d in enumerate(delays):
        env = min(BACKOFF_CAP_S, 0.05 * 2 ** attempt)
        assert env / 2 < d <= env, (attempt, d)
    # the envelope grows to the cap and never beyond (no hot-spin, no
    # unbounded stall; the huge-attempt form must not overflow either)
    assert max(delays) <= BACKOFF_CAP_S
    assert backoff_delay(10_000, rng) <= BACKOFF_CAP_S
    # early retries are fast: a blip costs tens of ms, not 0.3 s
    assert delays[0] < 0.06


def test_client_degraded_hook_fires_during_outage(server):
    """Kill nothing: dial a dead port.  The first-connect loop rides the
    window; the degraded hook is the per-retry signal a trainer uses to
    hold at a step boundary instead of treating the outage as fatal."""
    import socket as _socket
    import time

    from edl_tpu.coord.client import CoordClient

    # a port with nothing behind it (bind+close = likely free, refused)
    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        CoordClient("127.0.0.1", dead_port, timeout=1.0,
                    reconnect_window_s=0.8)
    # the dial loop honored the window (with backoff, not a busy loop)
    assert 0.7 < time.monotonic() - t0 < 10.0

    # live server: break the connection under the client and watch the
    # degraded → recovered transition fire exactly once each
    c = server.client()
    events = []
    c.on_degraded = lambda attempt, elapsed: events.append(("deg", attempt))
    c.on_recovered = lambda outage: events.append(("rec", outage))
    # sever the live connection out from under the client (close() alone
    # would not: the makefile reader still holds the fd open)
    c._sock.shutdown(_socket.SHUT_RDWR)
    assert c.ping()  # rides the reconnect window transparently
    kinds = [k for k, _ in events]
    assert "deg" in kinds and "rec" in kinds
    assert kinds.index("deg") < kinds.index("rec")
    c.close()
