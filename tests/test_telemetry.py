"""Unified telemetry plane, end to end.

Exposition conformance over every backend that serves ``/metrics``
(native C++ coordinator, Python registry route, PyCoordService gauges),
and the cross-process span story: a supervised world restart under an
injected stall must leave behind per-process trace files that merge into
ONE job timeline — the root reform span decomposing into the child's
named startup phases — plus a flight record and a scrape-able supervisor.

The strict text-format parser lives in tests/test_observability.py (one
oracle, every route held to it).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from tests.test_observability import parse_prometheus


# ---------------------------------------------------------------------------
# exposition conformance per backend
# ---------------------------------------------------------------------------

def _scrape(port: int, path: str = "/metrics") -> tuple[str, str]:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode(), r.headers["Content-Type"]


def test_native_server_metrics_exposition_conforms():
    """The C++ coordinator's /metrics speaks the same format as every
    Python route — one scrape config covers both backends."""
    from edl_tpu.coord.server import spawn_server

    h = spawn_server(health_port=0)
    try:
        c = h.client()
        c.join("w0", "addr0")
        c.add_task(b"payload")
        body, ctype = _scrape(h.health_port)
        assert "version=0.0.4" in ctype
        series = parse_prometheus(body)
        assert series["edl_coord_requests_total"] >= 2
        assert series['edl_coord_queue_tasks{state="todo"}'] == 1
        assert series["edl_coord_members"] == 1
        assert series["edl_coord_membership_epoch"] == 1
        assert "edl_coord_longpolls_parked_total" in series
        c.close()
    finally:
        h.stop()


def test_py_coord_service_metrics_match_native_names():
    """PyCoordService.register_metrics serves the same series names the
    native server exposes, so dashboards are backend-agnostic.  The
    parity set is pinned EXACTLY against server.cc's MetricsBody names —
    a rename on either side fails here, not in a dashboard."""
    from edl_tpu.coord import PyCoordService
    from edl_tpu.observability.metrics import MetricsRegistry

    svc = PyCoordService()
    svc.join("a")
    svc.add_task(b"x")
    reg = MetricsRegistry()
    svc.register_metrics(reg)
    series = parse_prometheus(reg.render())
    # name-for-name with server.cc MetricsBody()
    for native_name in ("edl_coord_requests_total",
                        "edl_coord_longpolls_parked_total",
                        "edl_coord_longpolls_fired_total",
                        "edl_coord_pass",
                        "edl_coord_membership_epoch",
                        "edl_coord_members",
                        'edl_coord_queue_tasks{state="todo"}',
                        'edl_coord_queue_tasks{state="leased"}',
                        'edl_coord_queue_tasks{state="done"}',
                        'edl_coord_queue_tasks{state="dropped"}'):
        assert native_name in series, (native_name, sorted(series))
    assert series['edl_coord_queue_tasks{state="todo"}'] == 1
    assert series["edl_coord_members"] == 1
    assert series["edl_coord_membership_epoch"] == 1
    svc.lease("a")
    assert parse_prometheus(reg.render())[
        'edl_coord_queue_tasks{state="leased"}'] == 1


def test_controller_style_process_serves_both_routes():
    """A controller-shaped process (serve_health + registry): /healthz
    and /metrics from one port, both conformant."""
    from edl_tpu.observability.collector import Collector, get_counters
    from edl_tpu.observability.health import serve_health

    from tests.test_observability import _cluster, _job

    cluster = _cluster()
    cluster.create_resources(_job("j1"))
    cluster.reconcile()
    import io

    Collector(cluster, out=io.StringIO()).run_once()
    get_counters().inc("controller_probe")
    srv = serve_health(0, {"alive": lambda: True}, host="127.0.0.1")
    try:
        port = srv.server_address[1]
        body, _ = _scrape(port)
        series = parse_prometheus(body)
        assert series["edl_cluster_submitted_jobs"] == 1
        assert series['edl_cluster_running_trainers{job="default/j1"}'] == 2
        assert series["edl_controller_probe_total"] >= 1
        health, _ = _scrape(port, "/healthz")
        assert json.loads(health)["alive"] is True
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# span propagation across a supervised world restart (single worker — no
# multiprocess CPU collectives needed; same pattern as test_stall_eviction)
# ---------------------------------------------------------------------------

def _tele_init_state():
    return {"step": np.zeros((), np.int32)}


def _tele_load_state(path: str):
    from edl_tpu.runtime.multihost import load_numpy_tree

    return load_numpy_tree(path, _tele_init_state())


def _tele_train_world(world, state, should_stop, *, marker="",
                      done_at=20, wedge_at=6, heartbeat=None):
    """Beats per step; wedges once at ``wedge_at`` (the supervisor's
    watchdog SIGKILL ends it); the post-reform run drains to done_at."""
    import time as _time

    step = int(state["step"])
    while step < done_at:
        if should_stop():
            return {"step": np.asarray(step, np.int32)}, True
        step += 1
        if heartbeat is not None:
            heartbeat(step)
        _time.sleep(0.12)
        if step == wedge_at and not os.path.exists(marker):
            open(marker, "w").close()
            _time.sleep(600)
    return {"step": np.asarray(step, np.int32)}, False


@pytest.mark.timeout_s(240)
def test_span_ids_survive_supervised_world_restart(tmp_path, monkeypatch):
    """kill→reform under the supervisor: the merged job timeline contains
    root reform spans whose trace ids the world children's named startup
    phases carry (parented to the root), the supervisor served live
    /metrics with the reform counters, and the stall escalation left a
    flight record."""
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.observability.tracing import Tracer, get_tracer
    from edl_tpu.runtime.multihost import run_elastic_worker, save_numpy_tree

    traces = tmp_path / "traces"
    monkeypatch.setenv("EDL_MH_TRACE", str(traces))
    get_tracer().clear()  # the supervisor dump must be this run's story
    handle = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
    client = CoordClient("127.0.0.1", handle.port)
    scraped: dict = {}

    def scrape_during_run() -> None:
        # find the supervisor's OS-assigned metrics port via the address
        # file, then scrape while the job is still running
        deadline = time.monotonic() + 120
        addr_file = tmp_path / "metrics-addr-w0"
        while time.monotonic() < deadline:
            if addr_file.exists():
                host, _, port = addr_file.read_text().partition(":")
                try:
                    body, ctype = _scrape(int(port))
                    scraped["series"] = parse_prometheus(body)
                    scraped["ctype"] = ctype
                    health, _ = _scrape(int(port), "/healthz")
                    scraped["health"] = json.loads(health)
                    return
                except OSError:
                    pass
            time.sleep(0.2)

    scraper = threading.Thread(target=scrape_during_run, daemon=True)
    scraper.start()
    try:
        outcome = run_elastic_worker(
            client, "w0",
            init_state=_tele_init_state,
            train_world=functools.partial(
                _tele_train_world, marker=str(tmp_path / "wedged")),
            save_state=save_numpy_tree,
            load_state=_tele_load_state,
            ckpt_dir=str(tmp_path),
            settle_s=0.1,
            warm_spawn=False,
            reform_grace_s=2.0,
            stall_floor_s=1.5, stall_k=6.0,
            metrics_port=0,
        )
        scraper.join(timeout=10)
        assert outcome.step == 20

        # -- merged job timeline: one reform = one span tree ---------------
        files = sorted(str(p) for p in traces.glob("trace-*.json"))
        # supervisor + at least two worlds (pre- and post-reform)
        assert any("trace-w0.json" in f for f in files), files
        assert sum("world" in f for f in files) >= 2, files
        merged = Tracer.merge_files(files, str(tmp_path / "merged.json"))
        slices = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        roots = [e for e in slices if e["name"] == "reform"]
        assert len(roots) >= 2  # initial form + post-stall reform
        phase_names = {"world_start.spawn_imports",
                       "world_start.coordinator_handshake",
                       "world_start.device_acquire",
                       "world_start.restore"}
        by_trace: dict[str, set] = {}
        for e in slices:
            tid = e["args"].get("trace_id")
            if tid:
                by_trace.setdefault(tid, set()).add(e["name"])
        # every root's trace id is carried by child startup phases from a
        # DIFFERENT process (pid differs), parented to that root's span
        for root in roots:
            tid = root["args"]["trace_id"]
            assert phase_names <= by_trace[tid], (tid, by_trace[tid])
            children = [e for e in slices
                        if e["args"].get("trace_id") == tid
                        and e["name"] in phase_names]
            assert all(c["pid"] != root["pid"] for c in children)
            assert {c["args"].get("parent_id") for c in children} \
                == {root["args"]["span_id"]}
            # plan span parents to the same root inside the supervisor
            plans = [e for e in slices
                     if e["name"] == "reform.plan"
                     and e["args"].get("trace_id") == tid]
            assert plans and plans[0]["args"]["parent_id"] \
                == root["args"]["span_id"]

        # -- the world child printed its machine-parseable phase line ------
        import bench

        # the child logs went to THIS test's stdout, not a file; read the
        # per-world trace args instead: every phase span carries phase=
        recs = [e for e in slices if e["name"].startswith("world_start.")]
        assert {e["args"]["phase"] for e in recs} >= {
            "coordinator_handshake", "device_acquire", "restore"}
        assert bench._parse_world_phases(
            "[w0] world_phases epoch=1 restore_s=0.5")  # parser sanity

        # -- supervisor /metrics was live mid-run --------------------------
        assert scraped, "scraper never reached the supervisor's /metrics"
        assert "version=0.0.4" in scraped["ctype"]
        assert scraped["health"]["supervisor"] is True
        assert "edl_coord_requests_total" in scraped["series"]

        # -- stall escalation left a flight record in the ckpt dir ---------
        recs = [f for f in os.listdir(tmp_path)
                if f.startswith("flightrec-") and "stall" in f]
        assert recs, os.listdir(tmp_path)
        doc = json.loads((tmp_path / recs[0]).read_text())
        assert doc["reason"] == "stall-multihost"
        assert any(e["name"] == "stall_detected"
                   for e in doc["trace_events"])
    finally:
        client.close()
        handle.stop()
