"""Formation barrier, straggler eviction, and the eviction-aware
keepalive — the protocol pieces that keep a wedged-but-heartbeating
member from stalling world formation forever.

Fast and deterministic: everything runs against an in-process
PyCoordService (same API as the native server), no jax, no subprocesses.
The end-to-end stall drill (wedged world child → watchdog kill → epoch
rebuild) lives in tests/test_multihost.py.
"""

from __future__ import annotations

import time

import pytest

from edl_tpu.coord import PyCoordService
from edl_tpu.runtime.discovery import CoordDiscovery
from edl_tpu.runtime.multihost import (
    ElasticWorld,
    FormationTimeout,
    StragglerTracker,
    WorkerEvicted,
)


def make_worlds(coord, names, settle_s=0.05):
    worlds = {n: ElasticWorld(coord, n, settle_s=settle_s, poll_s=0.01)
              for n in names}
    for w in worlds.values():
        w.join()
    return worlds


def plan_all(worlds, exclude=()):
    """Every (non-wedged) supervisor plans + arrives at the barrier."""
    plans = {}
    for n, w in worlds.items():
        if n in exclude:
            continue
        plans[n] = w.plan(min_members=1, formation_budget_s=10.0)
        w.mark_formed(plans[n].epoch)
    return plans


def test_formation_timeout_is_bounded_and_typed():
    coord = PyCoordService()
    w = ElasticWorld(coord, "w0", settle_s=0.05, poll_s=0.01)
    w.join()
    t0 = time.monotonic()
    with pytest.raises(FormationTimeout):
        w.plan(min_members=3, formation_budget_s=0.3)
    assert time.monotonic() - t0 < 2.0  # budget, not the 120 s default
    assert issubclass(FormationTimeout, TimeoutError)  # old callers ok


def test_straggler_evicted_after_repeated_frozen_barrier():
    """w2 joins membership (keepalive-alive) but never plans: its barrier
    marker stays frozen across consecutive same-epoch failures, so the
    lowest-ranked arrived member evicts it and the next plan excludes it."""
    from edl_tpu.observability.collector import get_counters

    coord = PyCoordService()
    worlds = make_worlds(coord, ["w0", "w1", "w2"])
    # strike_interval_s=0: the test drives failures back-to-back; the
    # time floor has its own test below
    trackers = {n: StragglerTracker(worlds[n], evict_after=2,
                                    strike_interval_s=0.0)
                for n in ("w0", "w1")}
    before = get_counters().get("members_evicted")

    # attempt 1: w0/w1 arrive, w2 never does; the world dies (init
    # timeout against the absent peer).  First failure only baselines.
    plans = plan_all(worlds, exclude=("w2",))
    assert plans["w0"].world_size == 3  # w2 still in everyone's plan
    epoch1 = plans["w0"].epoch
    for n in ("w0", "w1"):
        assert trackers[n].note_failure(plans[n]) == []

    # attempt 2 at the same epoch: markers re-written by w0/w1, w2 frozen
    plans = plan_all(worlds, exclude=("w2",))
    assert plans["w0"].epoch == epoch1  # membership never moved
    for n in ("w0", "w1"):
        trackers[n].note_failure(plans[n])
    # attempt 3: strike threshold crossed — w0 (lowest arrived) evicts
    plans = plan_all(worlds, exclude=("w2",))
    evicted = trackers["w0"].note_failure(plans["w0"])
    assert evicted == ["w2"]
    assert trackers["w1"].note_failure(plans["w1"]) == []  # not the actor
    assert get_counters().get("members_evicted") == before + 1

    # membership moved past the straggler; the next plan excludes it
    _, members = coord.members()
    assert "w2" not in {n for n, _ in members}
    p = worlds["w0"].plan(min_members=1, formation_budget_s=10.0)
    assert p.members == ("w0", "w1")

    # the evicted member itself gets a typed verdict, not a stale world
    with pytest.raises(WorkerEvicted):
        worlds["w2"].wait_stable(min_members=1, timeout_s=1.0)


def test_strike_time_floor_protects_slow_replanners():
    """A locally crash-looping child (instant exits) fires note_failure
    rapidly; a healthy peer needs real time to notice the death and
    re-plan.  The strike_interval_s floor means back-to-back failures
    land at most ONE strike per interval — no false eviction."""
    fake_now = [0.0]
    coord = PyCoordService()
    worlds = make_worlds(coord, ["w0", "w1"])
    tracker = StragglerTracker(worlds["w0"], evict_after=2,
                               strike_interval_s=20.0,
                               clock=lambda: fake_now[0])
    # w1 planned once (baseline marker), then goes quiet while w0's
    # child crash-loops 5 times within a second
    plans = plan_all(worlds)
    assert tracker.note_failure(plans["w0"]) == []  # baseline
    for _ in range(5):
        fake_now[0] += 0.2
        plans = plan_all(worlds, exclude=("w1",))
        assert tracker.note_failure(plans["w0"]) == []  # floored: no evict
    assert tracker._strikes.get("w1", 0) == 1  # one strike, not five
    # only once real re-arrival time has elapsed does the second strike
    # land — and with it the (evict_after=2) eviction
    fake_now[0] += 25.0
    plans = plan_all(worlds, exclude=("w1",))
    assert tracker.note_failure(plans["w0"]) == ["w1"]


def test_fresh_start_amnesty_clears_own_eviction():
    """A restarted worker under an evicted name must not be locked out
    forever: clear_eviction (run_elastic_worker's first act) lifts the
    marker, after which join + wait_stable work normally — while the
    OLD wedged incarnation's keepalive keeps declining rejoin right up
    to that restart."""
    coord = PyCoordService()
    evictor = ElasticWorld(coord, "w0")
    evictor.join()
    evictor.evict("w1", reason="wedged")
    assert "w1" in evictor.evicted_names()

    # the fresh incarnation (new process, same stable name)
    reborn = ElasticWorld(coord, "w1", settle_s=0.05, poll_s=0.01)
    assert reborn.clear_eviction() is True
    assert reborn.clear_eviction() is False  # idempotent: already lifted
    reborn.join()
    epoch, names = reborn.wait_stable(min_members=2, timeout_s=5.0)
    assert "w1" in names  # fully back in the job


def test_crash_pruned_by_ttl_never_reaches_eviction():
    """A crashed peer leaves membership via the TTL → the epoch moves →
    strikes reset (consecutive-same-epoch accounting): eviction stays
    reserved for wedged-but-heartbeating members."""
    coord = PyCoordService()
    worlds = make_worlds(coord, ["w0", "w1"])
    tracker = StragglerTracker(worlds["w0"], evict_after=2)
    plans = plan_all(worlds)
    assert tracker.note_failure(plans["w0"]) == []  # baseline
    coord.leave("w1")  # the TTL-prune/clean-leave of a CRASHED peer
    p2 = worlds["w0"].plan(min_members=1, formation_budget_s=10.0)
    assert p2.epoch != plans["w0"].epoch
    worlds["w0"].mark_formed(p2.epoch)
    # failure at the NEW epoch re-baselines instead of striking
    assert tracker.note_failure(p2) == []
    _, members = coord.members()
    assert {n for n, _ in members} == {"w0"}  # w1 pruned, w0 untouched


def test_eviction_marker_blocks_keepalive_rejoin():
    """The eviction must survive the victim's own keepalive: heartbeat
    expiry normally triggers a rejoin; the marker overrules it."""
    coord = PyCoordService(member_ttl_ms=200)
    disc = CoordDiscovery(coord, "w-straggler")
    disc.join()
    evictor = ElasticWorld(coord, "w0")
    evictor.join()
    with disc.keepalive(interval_s=0.05):
        time.sleep(0.2)  # keepalive humming
        _, members = coord.members()
        assert "w-straggler" in {n for n, _ in members}
        evictor.evict("w-straggler", reason="test")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not disc.evicted:
            time.sleep(0.05)
        assert disc.evicted
        # the rejoin was declined: the straggler stays OUT of membership
        time.sleep(0.3)  # several would-be rejoin intervals
        _, members = coord.members()
        assert "w-straggler" not in {n for n, _ in members}


def test_keepalive_still_rejoins_without_marker():
    """Regression guard for the rejoin path the eviction check rides on:
    a plain expiry (no marker) must still rejoin."""
    coord = PyCoordService(member_ttl_ms=150)
    disc = CoordDiscovery(coord, "w0")
    disc.join()
    with disc.keepalive(interval_s=0.05):
        coord.leave("w0")  # simulate a server-side prune
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            _, members = coord.members()
            if "w0" in {n for n, _ in members}:
                break
            time.sleep(0.05)
        _, members = coord.members()
        assert "w0" in {n for n, _ in members}
        assert not disc.evicted


# ---------------------------------------------------------------------------
# Supervisor escalation, end to end on ONE worker (no collectives needed):
# the world child wedges mid-step → the supervisor's StallWatchdog kills
# it → the epoch rebuilds → the job completes.  Runs in tier-1: a
# single-process world avoids the multiprocess-CPU-collectives support
# the heavier drills in tests/test_multihost.py require.
# ---------------------------------------------------------------------------

import os

import numpy as np

import pytest as _pytest


def _wedge_init_state():
    return {"step": np.zeros((), np.int32)}


def _wedge_load_state(path: str):
    from edl_tpu.runtime.multihost import load_numpy_tree

    return load_numpy_tree(path, _wedge_init_state())


def _wedge_train_world(world, state, should_stop, *, marker="",
                       done_at=30, wedge_at=8, heartbeat=None):
    """Picklable world body: beats per step, wedges once at ``wedge_at``
    (forever — only the supervisor's SIGKILL ends it), and on the rerun
    (marker exists) drains to ``done_at``.

    Steps are paced SLOWER than the supervisor's 0.1 s heartbeat poll so
    several distinct beats are observed and the EWMA settles — detection
    itself arms at the first observed beat, but a well-fed EWMA makes
    the asserted deadline/latency numbers deterministic."""
    import time as _time

    step = int(state["step"])
    while step < done_at:
        if should_stop():
            return {"step": np.asarray(step, np.int32)}, True
        step += 1
        if heartbeat is not None:
            heartbeat(step)
        _time.sleep(0.15)
        if step == wedge_at and not os.path.exists(marker):
            open(marker, "w").close()
            _time.sleep(600)  # the silent hang; no beat ever again
    return {"step": np.asarray(step, np.int32)}, False  # drained


@_pytest.mark.timeout_s(240)
def test_supervisor_watchdog_kills_wedged_child_and_world_rebuilds(tmp_path):
    from edl_tpu.coord.client import CoordClient
    from edl_tpu.coord.server import spawn_server
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.runtime.multihost import run_elastic_worker, save_numpy_tree
    import functools

    counters = get_counters()
    before_stalls = counters.get("stalls_detected", scope="multihost")
    before_reforms = counters.get("world_reforms")
    handle = spawn_server(member_ttl_ms=3000, task_timeout_ms=4000)
    client = CoordClient("127.0.0.1", handle.port)
    try:
        t0 = time.monotonic()
        outcome = run_elastic_worker(
            client, "w0",
            init_state=_wedge_init_state,
            train_world=functools.partial(
                _wedge_train_world, marker=str(tmp_path / "wedged")),
            save_state=save_numpy_tree,
            load_state=_wedge_load_state,
            ckpt_dir=str(tmp_path),
            settle_s=0.1,
            warm_spawn=False,       # fewer processes; determinism
            reform_grace_s=2.0,     # single member: epoch never moves
            stall_floor_s=1.5, stall_k=6.0,
        )
        wall = time.monotonic() - t0
        # the hang was detected (not ridden out): the wedge sleeps 600 s,
        # the whole drill — two world bootstraps included — finished in
        # a fraction of that
        assert wall < 200, wall
        assert os.path.exists(tmp_path / "wedged")  # the wedge happened
        assert counters.get("stalls_detected", scope="multihost") \
            == before_stalls + 1
        # the kill became the already-handled reform, and the rebuilt
        # epoch finished the job from the last published generation
        assert counters.get("world_reforms") >= before_reforms + 1
        assert outcome.step == 30
        assert outcome.state_path and os.path.exists(outcome.state_path)
        assert int(_wedge_load_state(outcome.state_path)["step"]) == 30
    finally:
        client.close()
        handle.stop()
