"""Elastic inference serving (doc/serving.md): continuous batching,
hint→prewarm scale-up behind the ready gate, graceful drain, rolling
weight reloads from the checkpoint lineage, SLO-driven autoscaling, the
ServingJob control-plane lifecycle, and job-scoped coordinator-KV GC."""

from __future__ import annotations

import threading
import time

import jax
import numpy as np
import pytest

from edl_tpu.api.types import (
    JobPhase,
    ResourceRequirements,
    ServingJob,
    ServingSpec,
)
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.models import mlp
from edl_tpu.observability.collector import get_counters
from edl_tpu.runtime.serving import (
    ElasticServer,
    PoissonTraffic,
    ServingFleet,
    ServingReplica,
)
from edl_tpu.scheduler.autoscaler import ServingScaler

PARAMS = mlp.init(jax.random.key(0), [16, 32, 4])


def apply_fn(p, b):
    return mlp.apply(p, b[0])


def make_fleet(job="t/svc", **kw) -> ServingFleet:
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("max_queue_ms", 1.0)
    kw.setdefault("drain_timeout_s", 5.0)
    return ServingFleet(apply_fn, PARAMS,
                        example_row=(np.zeros((16,), np.float32),),
                        job=job, **kw)


def row(i: int) -> tuple:
    return (np.full((16,), i % 7, np.float32),)


def expected(x_row: np.ndarray, params=PARAMS) -> np.ndarray:
    return np.asarray(mlp.apply(params, x_row[None, :]))[0]


# ---------------------------------------------------------- ElasticServer

def test_elastic_server_forward_parity_and_reload():
    srv = ElasticServer(apply_fn, PARAMS, initial_world_size=1)
    batch = (np.random.default_rng(0).normal(size=(8, 16))
             .astype(np.float32),)
    srv.warmup(batch)
    out = np.asarray(srv.serve(batch))
    assert np.allclose(out, np.asarray(mlp.apply(PARAMS, batch[0])))
    # weight swap: outputs flip to the new generation's
    p2 = jax.tree.map(lambda a: a + 1.0, PARAMS)
    srv.load_params(p2)
    out2 = np.asarray(srv.serve(batch))
    assert np.allclose(out2, np.asarray(mlp.apply(p2, batch[0])))


def test_elastic_server_resize_preserves_outputs():
    """A serving replica is elastic like a trainer: the mesh resizes
    live (same _MeshBundle machinery) and the forward outputs are
    unchanged — no checkpoint round-trip, no weight loss."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    srv = ElasticServer(apply_fn, PARAMS, initial_world_size=1)
    batch = (np.ones((8, 16), np.float32),)
    srv.warmup(batch)
    before = np.asarray(srv.serve(batch))
    assert srv.resize(2)
    assert srv.world_size == 2
    assert np.allclose(np.asarray(srv.serve(batch)), before, atol=1e-5)


# ---------------------------------------------------- continuous batching

def test_replica_batches_a_burst_into_few_iterations():
    built = []

    def build():
        s = ElasticServer(apply_fn, PARAMS, initial_world_size=1)
        built.append(s)
        return s

    r = ServingReplica("t/r0", build,
                       example_batch=(np.zeros((8, 16), np.float32),),
                       max_batch_size=8, max_queue_ms=5.0, job="t/cb")
    r.start()
    assert r.wait_ready(60)
    from edl_tpu.runtime.serving import ServeRequest

    reqs = [ServeRequest(payload=row(i), id=i,
                         t_enqueue=time.perf_counter()) for i in range(24)]
    for q in reqs:
        r.submit(q)
    for i, q in enumerate(reqs):
        got = np.asarray(q.wait(10))
        assert np.allclose(got, expected(row(i)[0])), i  # per-row correct
    # 24 requests over batch-8 admission: packed, not one-per-iteration
    assert r.iterations <= 6, r.iterations
    assert r.requests_served == 24
    r.stop(drain=True)


def test_lone_request_is_not_held_for_a_full_batch():
    fleet = make_fleet(job="t/lone")
    try:
        fleet.scale_to(1)
        req = fleet.submit(row(3))
        got = np.asarray(req.wait(10))
        assert np.allclose(got, expected(row(3)[0]))
        assert req.latency_s < 1.0  # admission window is ms-scale
    finally:
        fleet.stop()


# ------------------------------------------------- scale up/down + drain

def test_hint_then_scale_up_is_a_prewarm_hit():
    fleet = make_fleet(job="t/hint")
    try:
        fleet.scale_to(1)
        c0 = get_counters().get("serving_prewarm_hits", job="t/hint")
        fleet.hint(2)  # the autoscaler's plan hint: build starts NOW
        fleet.scale_to(2)  # actuation adopts the hint-built replica
        assert fleet.prewarm_hits == 1
        assert get_counters().get("serving_prewarm_hits",
                                  job="t/hint") == c0 + 1
        assert fleet.replicas_ready() == 2
    finally:
        fleet.stop()


def test_scale_down_drains_without_dropping():
    fleet = make_fleet(job="t/drain")
    try:
        fleet.scale_to(2)
        reqs = [fleet.submit(row(i)) for i in range(64)]
        fleet.scale_to(1)  # drains the departing replica's queue first
        for i, q in enumerate(reqs):
            np.asarray(q.wait(10))  # every single one served
        assert fleet.replicas_active() == 1
        assert get_counters().get("serving_dropped_requests",
                                  job="t/drain") == 0
    finally:
        fleet.stop()


def test_forced_stop_counts_drops_and_surfaces_them():
    from edl_tpu.runtime.serving import RequestDropped

    fleet = make_fleet(job="t/forced", max_queue_ms=50.0)
    fleet.scale_to(1)
    c0 = get_counters().get("serving_dropped_requests", job="t/forced")
    reqs = [fleet.submit(row(i)) for i in range(32)]
    fleet.stop(drain=False)  # the UNgraceful path
    outcomes = []
    for q in reqs:
        try:
            q.wait(5)
            outcomes.append("served")
        except RequestDropped:
            outcomes.append("dropped")
    dropped = outcomes.count("dropped")
    assert dropped == get_counters().get("serving_dropped_requests",
                                         job="t/forced") - c0
    # a dropped request FAILS its future loudly — it never hangs a caller


# ------------------------------------------------------- rolling reloads

def test_rolling_reload_under_traffic_swaps_all_and_drops_nothing():
    fleet = make_fleet(job="t/reload")
    try:
        fleet.scale_to(2)
        p2 = jax.tree.map(lambda a: a * 2.0, PARAMS)
        traffic = PoissonTraffic(fleet, row, qps=250, seed=3)
        done = []
        t = threading.Thread(target=lambda: done.append(
            fleet.rolling_reload(p2, generation=5)))
        th = threading.Thread(target=lambda: traffic.run(0.8))
        th.start()
        time.sleep(0.2)
        t.start()
        th.join()
        t.join()
        tally = traffic.await_all()
        assert tally["dropped"] == 0 and tally["errors"] == 0, tally
        assert done == [2]  # every replica swapped, one at a time
        assert fleet.generation == 5
        # post-reload answers come from generation 5's weights
        req = fleet.submit(row(1))
        assert np.allclose(np.asarray(req.wait(5)),
                           expected(row(1)[0], p2))
        assert get_counters().get("serving_reloads", job="t/reload") >= 2
    finally:
        fleet.stop()


def test_reload_from_checkpoint_lineage(tmp_path):
    """The deployed reload driver: generation N+1 appears in the elastic
    checkpoint lineage (verified manifest) → the fleet rolls onto it;
    a generation it already serves is a no-op."""
    from edl_tpu.runtime.checkpoint import ElasticCheckpointer

    ckpt = ElasticCheckpointer(tmp_path / "lineage", max_to_keep=3)
    ckpt.save(1, {"params": PARAMS})
    fleet = make_fleet(job="t/lineage", kv=None)
    try:
        fleet.scale_to(1)
        fleet.generation = 1
        assert fleet.reload_from_lineage(ckpt) is None  # already current
        p2 = jax.tree.map(lambda a: a + 3.0, PARAMS)
        ckpt.save(2, {"params": p2})
        assert fleet.reload_from_lineage(ckpt) == 2
        req = fleet.submit(row(2))
        assert np.allclose(np.asarray(req.wait(5)),
                           expected(row(2)[0], p2))
    finally:
        fleet.stop()
        ckpt.close()


def test_reload_skips_unverified_generation(tmp_path):
    """PR 17 satellite: the fleet must NEVER load a generation whose
    manifest does not carry (or forges) the verified bit.  A forged
    manifest skips with a counter and the serving generation stands; a
    later honestly-verified generation rolls on normally."""
    import json

    from edl_tpu.runtime.checkpoint import ElasticCheckpointer

    ckpt = ElasticCheckpointer(tmp_path / "lineage", max_to_keep=4)
    ckpt.save(1, {"params": PARAMS})
    p2 = jax.tree.map(lambda a: a + 3.0, PARAMS)
    ckpt.save(2, {"params": p2})
    # forge generation 2: strip the verified bit, leave the files (and
    # their CRCs) intact — latest_verified_step alone would take it
    mpath = ckpt._manifest_path(2)
    forged = json.loads(mpath.read_text())
    del forged["verified"]
    mpath.write_text(json.dumps(forged))

    fleet = make_fleet(job="t/unverified", kv=None)
    try:
        fleet.scale_to(1)
        fleet.generation = 1
        before = get_counters().get("serving_reload_skipped_unverified")
        assert fleet.reload_from_lineage(ckpt) is None
        assert fleet.generation == 1  # the fleet never moved
        assert get_counters().get(
            "serving_reload_skipped_unverified") == before + 1
        # generation 3 lies DEEPER: verified bit intact but the leaf
        # hashes disagree with the stored bytes — restore() falls back
        # past it, and publishing the fallback tree under generation 3
        # is refused too
        p3 = jax.tree.map(lambda a: a + 7.0, PARAMS)
        ckpt.save(3, {"params": p3})
        mpath = ckpt._manifest_path(3)
        lied = json.loads(mpath.read_text())
        leaf = sorted(lied["leaves"])[0]
        lied["leaves"][leaf] = f"{0:016x}"
        mpath.write_text(json.dumps(lied))
        assert fleet.reload_from_lineage(ckpt) is None
        assert fleet.generation == 1
        assert get_counters().get(
            "serving_reload_skipped_unverified") == before + 2
        # an honest generation 4 ships
        p4 = jax.tree.map(lambda a: a * 2.0, PARAMS)
        ckpt.save(4, {"params": p4})
        assert fleet.reload_from_lineage(ckpt) == 4
        req = fleet.submit(row(1))
        assert np.allclose(np.asarray(req.wait(5)),
                           expected(row(1)[0], p4))
    finally:
        fleet.stop()
        ckpt.close()


def test_generation_published_to_coordinator_kv():
    from edl_tpu.coord import PyCoordService

    kv = PyCoordService()
    fleet = make_fleet(job="t/gen", kv=kv)
    try:
        fleet.scale_to(1)
        fleet.rolling_reload(jax.tree.map(lambda a: a + 1, PARAMS), 9)
        assert kv.kv_get("serving-gen/t/gen") == b"9"
    finally:
        fleet.stop()


# ------------------------------------------------------- the SLO policy

def _job(lo=1, hi=8, slo=50.0, qps_target=0.0, batch=8) -> ServingJob:
    return ServingJob(name="svc", spec=ServingSpec(
        min_replicas=lo, max_replicas=hi, slo_p99_ms=slo,
        target_qps_per_replica=qps_target, max_batch_size=batch))


def _stats(p99=10.0, qps=10.0, depth=0, active=2, windowed=20):
    from edl_tpu.runtime.serving import FleetStats

    return FleetStats(p50_ms=p99 / 3, p99_ms=p99, qps=qps,
                      queue_depth=depth, replicas_ready=active,
                      replicas_active=active, requests_windowed=windowed)


def test_policy_grows_on_p99_breach_and_holds_inside_slo():
    sc = ServingScaler()
    job = _job(slo=50.0)
    assert sc.decide(job, _stats(p99=80.0, active=2), 2) == 3
    assert sc.decide(job, _stats(p99=30.0, depth=1, active=2), 2) is None


def test_policy_breach_with_deep_backlog_adds_proportionally():
    sc = ServingScaler()
    job = _job(slo=50.0, batch=8)
    # queue of 64 ≈ 8 batches over 2 replicas → grow by more than one
    assert sc.decide(job, _stats(p99=90.0, depth=64, active=2), 2) == 4


def test_policy_qps_target_scales_by_throughput():
    sc = ServingScaler()
    job = _job(slo=0.0, qps_target=30.0)
    assert sc.decide(job, _stats(p99=1.0, qps=100.0, active=2), 2) == 4
    # and caps at max_replicas
    job2 = _job(hi=3, slo=0.0, qps_target=10.0)
    assert sc.decide(job2, _stats(qps=500.0, active=2), 2) == 3


def test_policy_shrinks_only_with_headroom_and_empty_queue():
    sc = ServingScaler()
    job = _job(lo=1, slo=50.0)
    assert sc.decide(job, _stats(p99=5.0, depth=0, active=3), 3) == 2
    assert sc.decide(job, _stats(p99=5.0, depth=4, active=3), 3) is None
    assert sc.decide(job, _stats(p99=30.0, depth=0, active=3), 3) is None
    assert sc.decide(job, _stats(p99=5.0, depth=0, active=1), 1) is None
    # a cold window (no requests) decides nothing
    assert sc.decide(job, _stats(windowed=0), 3) is None


def test_tick_hints_before_actuating_and_respects_cooldown():
    clock = [100.0]
    calls: list[str] = []
    stats = {"default/svc": _stats(p99=80.0, active=2)}
    sc = ServingScaler(stats_for=lambda uid: stats[uid],
                       actuate=lambda uid, n: calls.append(f"act:{n}"),
                       clock=lambda: clock[0])
    sc.hint_sink = lambda uid, n: calls.append(f"hint:{n}")
    sc.on_add(_job())
    out = sc.tick()
    assert out == {"default/svc": 3}
    assert calls == ["hint:3", "act:3"]  # hint FIRST — the head start
    # breach persists inside the up-cooldown: suppressed, no thrash
    stats["default/svc"] = _stats(p99=80.0, active=3)
    assert sc.tick() == {}
    clock[0] += 10.0
    assert sc.tick() == {"default/svc": 4}
    # shrink waits out the longer down-cooldown
    stats["default/svc"] = _stats(p99=2.0, active=4)
    clock[0] += 10.0
    assert sc.tick() == {}
    clock[0] += sc.scale_down_cooldown_s
    assert sc.tick() == {"default/svc": 3}


def test_scaler_drives_a_live_fleet_through_a_burst():
    """Closed loop: Poisson burst → p99 breaches → scaler hints+scales
    the real fleet → burst absorbed with zero drops."""
    fleet = make_fleet(job="default/svc", slo_p99_ms=60.0, max_queue_ms=0.5)
    try:
        fleet.scale_to(1)
        job = _job(lo=1, hi=3, slo=60.0)
        sc = ServingScaler(
            stats_for=lambda uid: fleet.stats(window_s=2.0),
            actuate=lambda uid, n: fleet.scale_to(n))
        sc.hint_sink = lambda uid, n: fleet.hint(n)
        sc.on_add(job)
        traffic = PoissonTraffic(fleet, row, qps=400, seed=7)
        th = threading.Thread(target=lambda: traffic.run(2.0))
        th.start()
        grew = False
        for _ in range(40):
            time.sleep(0.05)
            if sc.tick():
                grew = True
        th.join()
        tally = traffic.await_all(timeout_s=30)
        assert tally["dropped"] == 0 and tally["errors"] == 0, tally
        assert grew or fleet.stats().p99_ms <= 60.0
    finally:
        fleet.stop()


# ------------------------------------------- control plane + phases + GC

def _cluster(nodes=4) -> FakeCluster:
    c = FakeCluster()
    for i in range(nodes):
        c.add_node(f"n{i}", cpu_milli=8000, memory_mega=32000)
    return c


def _serving_job(name="svc", lo=2, hi=6) -> ServingJob:
    return ServingJob(name=name, spec=ServingSpec(
        min_replicas=lo, max_replicas=hi, slo_p99_ms=50.0,
        resources=ResourceRequirements(requests={"cpu": "1"})))


def test_controller_lifecycle_on_fake_cluster():
    from edl_tpu.controller.controller import Controller

    cluster = _cluster()
    ctl = Controller(cluster, updater_convert_seconds=0.05,
                     updater_confirm_seconds=0.05)
    try:
        job = _serving_job()
        u = ctl.submit(job)
        deadline = time.monotonic() + 10
        while u.phase != JobPhase.RUNNING and time.monotonic() < deadline:
            time.sleep(0.02)
        assert u.phase == JobPhase.RUNNING
        pods = cluster.list_pods(job_uid="default/svc", role="server")
        assert len(pods) == 2
        # serving jobs register with the SLO scaler, NOT the trainer
        # packing loop
        assert "default/svc" in ctl.serving_scaler.jobs
        assert "default/svc" not in ctl.autoscaler.jobs
        # per-role status carries a SERVER row from live pods
        from edl_tpu.controller.updater import compute_replica_statuses

        rows = {s.resource_type: s
                for s in compute_replica_statuses(cluster, "default/svc")}
        assert rows["SERVER"].state.value == "Running"
        assert len(rows["SERVER"].resource_states) == 2
        # the replica dial scales the group (SCALING phase surfaces)
        cluster.update_trainer_parallelism(job, 4)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if len(cluster.list_pods(job_uid="default/svc",
                                     role="server")) == 4:
                break
            time.sleep(0.02)
        assert len(cluster.list_pods(job_uid="default/svc",
                                     role="server")) == 4
        ctl.delete(job)
        assert cluster.list_pods(job_uid="default/svc") == []
    finally:
        ctl.stop()


def test_failed_server_pod_is_replaced():
    """ReplicaSet semantics: a crashed server is replaced, the job never
    statically fails (replaceable_on_failure)."""
    cluster = _cluster()
    job = _serving_job(lo=2, hi=2)
    job.image = "img"
    cluster.create_resources(job)
    pods = cluster.list_pods(job_uid="default/svc", role="server")
    assert len(pods) == 2
    cluster.kill_pod(pods[0].name)
    live = [p for p in cluster.list_pods(job_uid="default/svc",
                                         role="server")
            if p.phase.value == "Running"]
    assert len(live) == 2  # replacement spawned


def test_job_deletion_sweeps_job_scoped_coordinator_kv():
    """The GC satellite: goodput-curve/vw-map/vw-cursor/serving-gen keys
    outlive every reform and failover but NOT the job — controller
    delete sweeps exactly the deleted job's keys."""
    from edl_tpu.controller.controller import Controller
    from edl_tpu.coord import PyCoordService
    from edl_tpu.coord.gc import JOB_KV_PREFIXES, gc_job_kv

    coord = PyCoordService()
    for prefix in JOB_KV_PREFIXES:
        coord.kv_set(f"{prefix}default/svc", b"x")
        coord.kv_set(f"{prefix}default/other", b"y")
    # the survivor sharing a NAME PREFIX with the victim must survive the
    # sweep (exact-uid scoping, not startswith)
    coord.kv_set("vw-map/default/svc2", b"z")

    cluster = _cluster()
    ctl = Controller(cluster, updater_convert_seconds=0.05,
                     updater_confirm_seconds=0.05,
                     coord_for=lambda job: coord)
    try:
        job = _serving_job()
        ctl.submit(job)
        ctl.delete(job)
        for prefix in JOB_KV_PREFIXES:
            assert coord.kv_get(f"{prefix}default/svc") is None, prefix
            assert coord.kv_get(f"{prefix}default/other") == b"y", prefix
        assert coord.kv_get("vw-map/default/svc2") == b"z"
    finally:
        ctl.stop()
    # direct-call form (prune path / operator tooling)
    coord.kv_set("goodput-curve/j", b"x")
    coord.kv_set("vw-cursor/j", b"x")
    assert gc_job_kv(coord, "j") == 2
    assert gc_job_kv(coord, "j") == 0  # idempotent


def test_serving_cr_drives_controller_through_stub_apiserver(control_plane):
    """Deployed path: `kubectl apply` a ServingJob CR → sync loop →
    controller materializes the server ReplicaSet + Service → pods come
    up → the CR's recorded status reaches Running → delete tears down."""
    cluster, controller, sync, state = control_plane
    from tests.k8s_stub import make_pod

    cr = {
        "apiVersion": "edl.tpu/v1",
        "kind": "ServingJob",
        "metadata": {"name": "svc1", "namespace": "default"},
        "spec": {
            "image": "edl-tpu/serve:latest",
            "server": {"minReplicas": 2, "max-replicas": 4,
                       "slo_p99_ms": 50,
                       "resources": {"requests": {"cpu": "1"}}},
        },
    }
    cluster.create_serving_job_cr(cr)
    sync.run_once()
    assert ("default", "svc1-server") in state.replicasets
    assert ("default", "svc1-serve") in state.services
    # kubelet: server pods come up Running
    for i in range(2):
        state.pods.append(make_pod(
            f"svc1-server-{i}", phase="Running", node="a0",
            labels={"edl-tpu-serving": "svc1"}, cpu="1"))
    deadline = time.monotonic() + 15
    recorded = None
    while time.monotonic() < deadline:
        sync.run_once()
        obj = state.custom_objects.get(
            ("edl.tpu", "default", "servingjobs", "svc1"))
        recorded = (obj or {}).get("status")
        if recorded and recorded.get("phase") == "Running":
            break
        time.sleep(0.05)
    assert recorded and recorded["phase"] == "Running", recorded
    server_rows = [r for r in recorded["replica_statuses"]
                   if r["resource_type"] == "SERVER"]
    assert server_rows and server_rows[0]["state"] == "Running"
    # kubectl delete sj svc1 → full teardown
    cluster.delete_serving_job_cr("svc1")
    sync.run_once()
    assert ("default", "svc1-server") not in state.replicasets


# --------------------------------------------------------------- metrics

def test_serving_series_render_under_the_strict_parser():
    from edl_tpu.observability.metrics import get_registry
    from tests.test_observability import parse_prometheus

    fleet = make_fleet(job="t/metrics")
    try:
        fleet.scale_to(1)
        for i in range(12):
            fleet.submit(row(i)).wait(10)
        series = parse_prometheus(get_registry().render())
        assert series['edl_serving_requests_total{job="t/metrics"}'] >= 12
        assert series['edl_serving_replicas_ready{job="t/metrics"}'] == 1
        # the ms-scale histogram actually resolves ms latencies: at
        # least one strictly-sub-DEFAULT-bucket boundary carries counts
        key = ('edl_serving_request_seconds_bucket'
               '{job="t/metrics",le="0.0005"}')
        assert key in series
        assert series['edl_serving_request_seconds_count'
                      '{job="t/metrics"}'] >= 12
    finally:
        fleet.stop()
