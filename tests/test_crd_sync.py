"""The deployed control plane: TrainingJob CRs drive the controller.

Round-2 verdict's top gap: ``edl-tpu controller`` on a real cluster must
watch TrainingJob custom objects and manage them — the reference's core
informer loop (reference pkg/controller.go:79-161) — and write phase +
replica statuses back into the CR's status subresource
(reference pkg/updater/trainingJobUpdater.go:295-307).  Here the real
:class:`K8sCluster` CR methods and :class:`TrainingJobSyncLoop` run
end-to-end against the stub apiserver: apply a CR → the controller
materializes pods; kubelet-simulated pods go Running → recorded status
says Running; edit the spec → controller sees the update; delete the CR →
full teardown.
"""

from __future__ import annotations

import time

from edl_tpu.controller.controller import Controller
from edl_tpu.controller.sync import TrainingJobSyncLoop

from tests.k8s_stub import StubState, make_pod

# fixtures: `kube` and `control_plane` live in tests/conftest.py (shared
# with test_crd_pruning.py)


def cr_manifest(name="job1", lo=2, hi=4, fault_tolerant=True):
    """What a user would `kubectl apply` (shape of k8s/crd.yaml +
    examples/examplejob.yaml; reference example/examplejob.yaml)."""
    return {
        "apiVersion": "edl.tpu/v1",
        "kind": "TrainingJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "image": "edl-tpu-job:latest",
            "fault_tolerant": fault_tolerant,
            "trainer": {
                "entrypoint": "python train.py",
                "min_instance": lo,
                "max_instance": hi,
                "resources": {
                    "requests": {"cpu": "1", "memory": "1Gi"},
                    "limits": {"cpu": "1", "memory": "1Gi",
                               "google.com/tpu": "1"},
                },
            },
        },
    }


def run_trainer_pods(state: StubState, name: str, n: int) -> None:
    """The kubelet's role: the trainer Job's pods come up Running."""
    state.pods = [p for p in state.pods
                  if (p.metadata.labels or {}).get("edl-tpu-job") != name]
    for i in range(n):
        state.pods.append(make_pod(
            f"{name}-trainer-{i}", phase="Running", node="a0",
            labels={"edl-tpu-job": name}, cpu="1", memory="1Gi", tpu=1))


def wait_phase(sync: TrainingJobSyncLoop, state: StubState, name: str,
               phase: str, timeout: float = 15.0) -> dict:
    """Tick the sync loop until the CR's *recorded* status shows phase."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sync.run_once()
        cr = state.custom_objects.get(
            ("edl.tpu", "default", "trainingjobs", name))
        if cr is not None and (cr.get("status") or {}).get("phase") == phase:
            return cr
        time.sleep(0.05)
    raise TimeoutError(
        f"CR {name} never reached recorded phase {phase}; "
        f"have {(cr or {}).get('status')!r}")


def test_cr_lifecycle_end_to_end(control_plane):
    cluster, controller, sync, state = control_plane

    # kubectl apply -f examplejob.yaml
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()

    # the controller materialized the trainer group (onAdd semantics,
    # reference pkg/controller.go:110-148)
    assert ("default", "job1-trainer") in state.jobs
    assert controller.jobs() and controller.jobs()[0].name == "job1"

    # pods come up → the RECORDED CR status reaches Running with per-pod
    # replica statuses (kubectl get tj shows it; VERDICT r2 missing #2)
    run_trainer_pods(state, "job1", 2)
    cr = wait_phase(sync, state, "job1", "Running")
    trainer_rs = [rs for rs in cr["status"]["replica_statuses"]
                  if rs["resource_type"] == "TRAINER"][0]
    assert trainer_rs["state"] == "Running"
    assert set(trainer_rs["resource_states"]) == {
        "job1-trainer-0", "job1-trainer-1"}

    # spec edit (kubectl apply again): controller.modify sees the new max
    edited = cr_manifest("job1", lo=2, hi=8)
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "job1", edited)
    sync.run_once()
    assert controller.jobs()[0].spec.trainer.max_instance == 8

    # kubectl delete tj job1 → full teardown (onDelete, reference
    # pkg/controller.go:156-161 + Gen-2 deleteTrainingJob)
    cluster.delete_training_job_cr("job1")
    sync.run_once()
    assert controller.jobs() == []
    assert ("default", "job1-trainer") not in state.jobs
    assert not state.replicasets
    # loop bookkeeping is clean: a re-apply is a fresh add
    assert sync._jobs == {} and sync._seen_specs == {}


def test_invalid_cr_gets_failed_status_once(control_plane):
    cluster, controller, sync, state = control_plane

    # elastic (min<max) without fault_tolerant is invalid
    # (reference pkg/jobparser.go:66-68)
    cluster.create_training_job_cr(
        cr_manifest("badjob", lo=1, hi=4, fault_tolerant=False))
    sync.run_once()
    cr = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                               "badjob")]
    assert cr["status"]["phase"] == "Failed"
    assert "fault_tolerant" in cr["status"]["reason"]
    assert controller.jobs() == []  # never reached the registry

    # unchanged invalid spec is not re-submitted every tick
    sync.run_once()
    assert controller.jobs() == []

    # fixing the spec turns it into a normal add
    fixed = cr_manifest("badjob", lo=1, hi=4, fault_tolerant=True)
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "badjob", fixed)
    sync.run_once()
    assert [j.name for j in controller.jobs()] == ["badjob"]


def test_status_verb_reads_recorded_status(control_plane, capsys):
    cluster, controller, sync, state = control_plane
    from edl_tpu.cli import format_status

    cluster.create_training_job_cr(cr_manifest("job2", lo=1, hi=2))
    sync.run_once()
    run_trainer_pods(state, "job2", 1)
    wait_phase(sync, state, "job2", "Running")
    out = format_status(cluster, "default", "job2")
    assert "recorded by controller" in out
    assert "Running" in out and "job2-trainer-0" in out


def test_malformed_cr_rejected_does_not_block_tick(control_plane):
    """The CRD schema's preserve-unknown-fields admits shapes the parser
    cannot (a string where a map belongs, explicit nulls).  Such a CR must
    get a Failed status — and must never abort the tick for other CRs."""
    cluster, controller, sync, state = control_plane
    bad = cr_manifest("mangled")
    bad["spec"]["trainer"]["resources"] = "2cpu"  # string, not a map
    cluster.create_training_job_cr(bad)
    null_field = cr_manifest("nullfield")
    null_field["spec"]["trainer"]["min_instance"] = None
    cluster.create_training_job_cr(null_field)
    cluster.create_training_job_cr(cr_manifest("zz-good", lo=1, hi=2))

    sync.run_once()
    for name in ("mangled", "nullfield"):
        cr = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                                   name)]
        assert cr["status"]["phase"] == "Failed", name
        assert cr["status"]["reason"].startswith("invalid spec"), name
    # the good CR (sorted after both bad ones) was still dispatched
    assert [j.name for j in controller.jobs()] == ["zz-good"]
    # and the bad ones are not re-dispatched every tick
    sync.run_once()
    assert [j.name for j in controller.jobs()] == ["zz-good"]


def test_cr_in_other_namespace_is_managed(control_plane):
    """The watch is cluster-wide (reference NamespaceAll informer,
    pkg/controller.go:83); the CR lands in its manifest's namespace and
    status writes back there."""
    cluster, controller, sync, state = control_plane
    cr = cr_manifest("nsjob", lo=1, hi=2)
    cr["metadata"]["namespace"] = "team-a"
    cluster.create_training_job_cr(cr)
    assert ("edl.tpu", "team-a", "trainingjobs", "nsjob") in \
        state.custom_objects
    sync.run_once()
    assert ("team-a", "nsjob-trainer") in state.jobs
    state.pods.append(make_pod("nsjob-trainer-0", namespace="team-a",
                               phase="Running", node="a0",
                               labels={"edl-tpu-job": "nsjob"},
                               cpu="1", memory="1Gi", tpu=1))
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        sync.run_once()
        obj = state.custom_objects[("edl.tpu", "team-a", "trainingjobs",
                                    "nsjob")]
        if (obj.get("status") or {}).get("phase") == "Running":
            break
        time.sleep(0.05)
    assert obj["status"]["phase"] == "Running"


def test_controller_restart_adopts_running_jobs(control_plane):
    """A controller restart re-submits every listed CR; the job's
    resources still exist — that is ADOPTION (409 tolerated), not a
    create failure, and the healthy job must keep its Running status."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    run_trainer_pods(state, "job1", 2)
    wait_phase(sync, state, "job1", "Running")
    controller.stop()

    # the controller process restarts: fresh registry, fresh sync state,
    # same apiserver contents
    controller2 = Controller(cluster, updater_convert_seconds=0.05,
                             updater_confirm_seconds=0.05)
    sync2 = TrainingJobSyncLoop(cluster, controller2, poll_seconds=0.05)
    try:
        cr = wait_phase(sync2, state, "job1", "Running")
        assert "create failed" not in (cr["status"].get("reason") or "")
        assert [j.name for j in controller2.jobs()] == ["job1"]
        assert ("default", "job1-trainer") in state.jobs  # still there
    finally:
        controller2.stop()


def test_orphaned_resources_swept_after_restart(control_plane):
    """`kubectl delete tj` while the controller is down must not leak the
    trainer group forever: the CR is the source of truth, so a group
    without a CR is torn down by the sync loop's orphan sweep — but only
    after the grace window: teardown is irreversible, so the first ticks
    after a controller start are LOG-ONLY (advisor r3: a single-tick sweep
    destroyed running work on controller upgrade)."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    assert ("default", "job1-trainer") in state.jobs
    controller.stop()

    # controller down; the user deletes the CR out-of-band
    del state.custom_objects[("edl.tpu", "default", "trainingjobs", "job1")]

    controller2 = Controller(cluster, updater_convert_seconds=0.05,
                             updater_confirm_seconds=0.05)
    sync2 = TrainingJobSyncLoop(cluster, controller2, poll_seconds=0.05,
                                orphan_grace_ticks=3)
    try:
        for _ in range(2):  # inside the grace window: nothing destroyed
            sync2.run_once()
            assert ("default", "job1-trainer") in state.jobs
        sync2.run_once()  # third consecutive CR-less tick: swept
        assert ("default", "job1-trainer") not in state.jobs
        assert not state.replicasets and not state.services
    finally:
        controller2.stop()


def test_orphan_strikes_reset_when_cr_reappears(control_plane):
    """A CR applied moments after its resources (or a transient LIST
    blip) must clear the strike counter — no teardown later."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    controller.stop()
    saved = state.custom_objects.pop(
        ("edl.tpu", "default", "trainingjobs", "job1"))

    controller2 = Controller(cluster, updater_convert_seconds=0.05,
                             updater_confirm_seconds=0.05)
    sync2 = TrainingJobSyncLoop(cluster, controller2, poll_seconds=0.05,
                                orphan_grace_ticks=3)
    try:
        sync2.run_once()
        sync2.run_once()  # 2 strikes accrued
        state.custom_objects[
            ("edl.tpu", "default", "trainingjobs", "job1")] = saved
        sync2.run_once()  # CR back: strikes reset, job adopted
        assert sync2._orphan_strikes == {}
        for _ in range(4):
            sync2.run_once()
        assert ("default", "job1-trainer") in state.jobs
    finally:
        controller2.stop()


def test_in_process_submitted_job_never_swept(control_plane):
    """A job submitted straight into the controller registry (the pre-CR
    flow: tests, demos, legacy tooling) has no CR by design — the sweep
    must treat it as owned work, not garbage (advisor r3 medium)."""
    from edl_tpu.api.serde import job_from_dict

    cluster, controller, sync, state = control_plane
    controller.submit(job_from_dict(cr_manifest("direct", lo=1, hi=2)))
    assert ("default", "direct-trainer") in state.jobs
    for _ in range(5):  # well past any grace window
        sync.run_once()
    assert ("default", "direct-trainer") in state.jobs


def test_gc_orphans_off_is_log_only(control_plane):
    """--no-gc-orphans: the sweep reports orphans but never deletes."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    controller.stop()
    del state.custom_objects[("edl.tpu", "default", "trainingjobs", "job1")]

    controller2 = Controller(cluster, updater_convert_seconds=0.05,
                             updater_confirm_seconds=0.05)
    sync2 = TrainingJobSyncLoop(cluster, controller2, poll_seconds=0.05,
                                gc_orphans=False, orphan_grace_ticks=2)
    try:
        for _ in range(6):
            sync2.run_once()
        assert ("default", "job1-trainer") in state.jobs
    finally:
        controller2.stop()


def test_orphan_sweep_covers_other_namespaces(control_plane):
    """The sweep is cluster-wide like the watch: an orphaned group in a
    non-default namespace is torn down too."""
    cluster, controller, sync, state = control_plane
    cr = cr_manifest("nsjob", lo=1, hi=2)
    cr["metadata"]["namespace"] = "team-a"
    cluster.create_training_job_cr(cr)
    sync.run_once()
    assert ("team-a", "nsjob-trainer") in state.jobs
    controller.stop()

    del state.custom_objects[("edl.tpu", "team-a", "trainingjobs", "nsjob")]
    controller2 = Controller(cluster, updater_convert_seconds=0.05,
                             updater_confirm_seconds=0.05)
    sync2 = TrainingJobSyncLoop(cluster, controller2, poll_seconds=0.05,
                                orphan_grace_ticks=2)
    try:
        sync2.run_once()  # strike 1: log-only
        assert ("team-a", "nsjob-trainer") in state.jobs
        sync2.run_once()  # strike 2: swept
        assert ("team-a", "nsjob-trainer") not in state.jobs
    finally:
        controller2.stop()


def test_invalid_spec_edit_surfaces_reason_keeps_running(control_plane):
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    run_trainer_pods(state, "job1", 2)
    wait_phase(sync, state, "job1", "Running")

    # edit to an invalid spec: min > max
    bad = cr_manifest("job1", lo=6, hi=4)
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "job1", bad)
    sync.run_once()
    sync.run_once()
    cr = state.custom_objects[("edl.tpu", "default", "trainingjobs", "job1")]
    # still Running under the last valid spec, but the rejection is visible
    assert cr["status"]["phase"] == "Running"
    assert "spec update rejected" in cr["status"]["reason"]
    assert controller.jobs()[0].spec.trainer.max_instance == 4

    # reverting to a valid spec clears the reason
    good = cr_manifest("job1", lo=2, hi=8)
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "job1", good)
    sync.run_once()
    sync.run_once()
    cr = state.custom_objects[("edl.tpu", "default", "trainingjobs", "job1")]
    assert "rejected" not in (cr["status"].get("reason") or "")
    assert controller.jobs()[0].spec.trainer.max_instance == 8


def test_list_verb_shows_recorded_phases(control_plane, capsys):
    cluster, controller, sync, state = control_plane
    from edl_tpu.cli import format_job_list

    cluster.create_training_job_cr(cr_manifest("job1", lo=2, hi=4))
    sync.run_once()
    run_trainer_pods(state, "job1", 2)
    wait_phase(sync, state, "job1", "Running")
    out = format_job_list(cluster)
    lines = out.splitlines()
    assert lines[0].split()[:4] == ["NAMESPACE", "NAME", "KIND", "PHASE"]
    row = [l for l in lines if " job1 " in f" {l} "][0]
    assert "Running" in row and "2" in row and "4" in row
    assert "TrainingJob" in row


def test_allow_multi_domain_flip_rejected_in_place(control_plane):
    """The flag is baked into running pods' labels and the mesh's current
    placement: an in-place flip is rejected with a visible reason (change
    it by delete + resubmit, like pod-template fields)."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("job1", lo=1, hi=2))
    sync.run_once()
    run_trainer_pods(state, "job1", 1)
    wait_phase(sync, state, "job1", "Running")

    flipped = cr_manifest("job1", lo=1, hi=2)
    flipped["spec"]["trainer"]["allow_multi_domain"] = True
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "job1", flipped)
    sync.run_once()
    sync.run_once()
    cr = state.custom_objects[("edl.tpu", "default", "trainingjobs", "job1")]
    assert "allow_multi_domain is immutable" in cr["status"]["reason"]
    assert controller.jobs()[0].spec.trainer.allow_multi_domain is False


def test_sync_loop_thread_and_autoscaler_integration(control_plane):
    """The deployed wiring: background sync thread + autoscaler loop; an
    elastic job scales up to its max on an idle cluster through the SAME
    path a real deployment uses (CR → sync → registry → planner →
    parallelism write)."""
    cluster, controller, sync, state = control_plane
    controller.autoscaler.loop_seconds = 0.05
    controller.start()
    sync.poll_seconds = 0.05
    sync.start()
    try:
        cluster.create_training_job_cr(cr_manifest("job3", lo=2, hi=4))
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            job = state.jobs.get(("default", "job3-trainer"))
            n = job.spec.parallelism if job is not None else 0
            # the kubelet mirror: parallelism -> that many Running pods
            if job is not None:
                run_trainer_pods(state, "job3", n)
            if n == 4:
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("autoscaler never scaled job3 to max via CR path")
    finally:
        sync.stop()


# -- streaming watch (the reference informer's event-driven ListWatch,
#    pkg/controller.go:87-107; round-3 verdict optional #9) -----------------


def test_watch_events_drive_add_update_delete(control_plane):
    """With watch=True the loop reacts to CR events without a fresh LIST:
    one anchoring run_once, then add/edit/delete arrive purely through
    the stub apiserver's event stream."""
    cluster, controller, sync, state = control_plane
    sync.watch = True
    sync.run_once()  # anchors the resourceVersion
    assert sync._last_rv is not None

    cluster.create_training_job_cr(cr_manifest("wjob", lo=2, hi=4))
    sync._watch_window(0.3)
    assert [j.name for j in controller.jobs()] == ["wjob"]
    assert ("default", "wjob-trainer") in state.jobs

    edited = cr_manifest("wjob", lo=2, hi=8)
    cluster._custom.replace_namespaced_custom_object(
        "edl.tpu", "v1", "default", "trainingjobs", "wjob", edited)
    sync._watch_window(0.3)
    assert controller.jobs()[0].spec.trainer.max_instance == 8

    cluster.delete_training_job_cr("wjob")
    sync._watch_window(0.3)
    assert controller.jobs() == []
    assert ("default", "wjob-trainer") not in state.jobs


def test_watch_status_writeback_without_list(control_plane):
    """Phase transitions have no CR event; the watch path flushes the
    recorded status from the registry (no O(cluster) LIST needed)."""
    cluster, controller, sync, state = control_plane
    sync.watch = True
    cluster.create_training_job_cr(cr_manifest("wjob", lo=1, hi=2))
    sync.run_once()
    run_trainer_pods(state, "wjob", 1)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        sync._write_back_statuses()  # the watch window's flush
        cr = state.custom_objects[("edl.tpu", "default", "trainingjobs",
                                   "wjob")]
        if (cr.get("status") or {}).get("phase") == "Running":
            break
        time.sleep(0.05)
    assert cr["status"]["phase"] == "Running"


def test_watch_410_compaction_falls_back_to_list(control_plane):
    """An apiserver compaction invalidates the anchored rv: the stream
    raises 410 Gone and the loop must re-anchor with a fresh LIST rather
    than die or spin."""
    import pytest as _pytest

    from tests.k8s_stub import ApiException

    cluster, controller, sync, state = control_plane
    sync.watch = True
    sync.run_once()
    stale_rv = sync._last_rv
    cluster.create_training_job_cr(cr_manifest("wjob", lo=1, hi=2))
    state.compact_custom_events()
    with _pytest.raises(ApiException) as exc:
        sync._watch_window(0.3)
    assert exc.value.status == 410
    # the thread body answers by re-listing; emulate one loop turn
    sync._last_rv = None
    sync.run_once()
    assert [j.name for j in controller.jobs()] == ["wjob"]
    assert sync._last_rv is not None and sync._last_rv != stale_rv


def test_watch_thread_end_to_end(control_plane):
    """The deployed wiring: background sync thread in watch mode —
    create/edit/delete through the apiserver only, verify the controller
    followed, and that full LISTs happened once per resync window, not
    once per tick."""
    cluster, controller, sync, state = control_plane
    sync.watch = True
    sync.poll_seconds = 0.1
    sync.resync_every = 50

    lists = {"n": 0}
    orig = cluster.list_training_job_crs_with_rv

    def counting():
        lists["n"] += 1
        return orig()

    cluster.list_training_job_crs_with_rv = counting
    sync.start()
    try:
        cluster.create_training_job_cr(cr_manifest("wjob", lo=2, hi=4))
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not controller.jobs():
            time.sleep(0.02)
        assert [j.name for j in controller.jobs()] == ["wjob"]
        cluster.delete_training_job_cr("wjob")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and controller.jobs():
            time.sleep(0.02)
        assert controller.jobs() == []
    finally:
        sync.stop()
    # event-driven: far fewer LISTs than loop turns (>= ~40 turns ran)
    assert lists["n"] <= 3, lists["n"]


def test_watch_mode_under_cr_churn(control_plane):
    """Event-driven correctness at modest scale: 20 CRs created, half
    edited, a third deleted — all through watch events with a single
    anchoring LIST — must converge the registry to exactly the surviving
    set with the edited specs, no event lost or double-applied."""
    cluster, controller, sync, state = control_plane
    sync.watch = True
    sync.run_once()  # anchor

    for i in range(20):
        cluster.create_training_job_cr(cr_manifest(f"churn-{i:02d}",
                                                   lo=1, hi=2))
    sync._watch_window(0.5)
    assert len(controller.jobs()) == 20

    for i in range(0, 20, 2):  # edit every even job's max
        cluster._custom.replace_namespaced_custom_object(
            "edl.tpu", "v1", "default", "trainingjobs", f"churn-{i:02d}",
            cr_manifest(f"churn-{i:02d}", lo=1, hi=6))
    for i in range(0, 20, 3):  # delete every third
        cluster.delete_training_job_cr(f"churn-{i:02d}")
    sync._watch_window(0.5)

    alive = {j.name: j for j in controller.jobs()}
    expected = {f"churn-{i:02d}" for i in range(20) if i % 3 != 0}
    assert set(alive) == expected
    for name, job in alive.items():
        i = int(name.split("-")[1])
        assert job.spec.trainer.max_instance == (6 if i % 2 == 0 else 2), name
    # torn-down groups are gone; survivors' groups exist
    for i in range(20):
        present = ("default", f"churn-{i:02d}-trainer") in state.jobs
        assert present == (i % 3 != 0), i


def test_status_patch_backoff_isolates_failing_job(control_plane):
    """A store that 500s for ONE job must not be hammered every window for
    that job while others proceed (the reference informer's rate-limited
    workqueue discipline, reference pkg/controller.go:87-107)."""
    cluster, controller, sync, state = control_plane
    cluster.create_training_job_cr(cr_manifest("goodjob", lo=1, hi=2))
    cluster.create_training_job_cr(cr_manifest("badjob", lo=1, hi=2))
    sync.run_once()
    run_trainer_pods(state, "goodjob", 1)
    run_trainer_pods(state, "badjob", 1)

    calls = {"goodjob": 0, "badjob": 0}
    orig = cluster.patch_training_job_status

    def flaky(name, status, namespace=None):
        calls[name] += 1
        if name == "badjob":
            raise RuntimeError("apiserver 500")
        return orig(name, status, namespace=namespace)

    cluster.patch_training_job_status = flaky
    # tick until the healthy job's RECORDED status reaches Running, then
    # keep ticking so the failing job sees plenty of windows — all of
    # which must land inside its first backoff interval
    deadline = time.monotonic() + 10
    windows = 0
    while time.monotonic() < deadline:
        sync.run_once()
        windows += 1
        cr = state.custom_objects.get(
            ("edl.tpu", "default", "trainingjobs", "goodjob"))
        if windows >= 10 and (cr.get("status") or {}).get("phase") == "Running":
            break
        time.sleep(0.02)
    assert (cr.get("status") or {}).get("phase") == "Running"
    assert calls["goodjob"] >= 1
    # ≥10 windows ran in well under the 1 s backoff base: the failing job
    # must have been tried once (maybe twice across a status change), not
    # once per window
    assert windows >= 10
    assert calls["badjob"] <= 3, calls

    # after the deadline passes the patch retries (and now succeeds);
    # clearing the recorded deadline stands in for waiting out the 1 s base
    sync._patch_backoff.clear()
    cluster.patch_training_job_status = orig
    sync.run_once()
    cr = state.custom_objects.get(
        ("edl.tpu", "default", "trainingjobs", "badjob"))
    assert (cr.get("status") or {}).get("phase")


def test_watch_flag_flips_off_without_watch_surface():
    """watch=True against a store with no watch surface must degrade to
    true poll-list cadence, not silently stretch reconcile latency to the
    resync interval (advisor r4)."""

    class ListOnlyStore:
        def list_training_job_crs(self):
            return []

        def patch_training_job_status(self, name, status, namespace=None):
            return True

    sync = TrainingJobSyncLoop(ListOnlyStore(), controller=None,
                               watch=True)
    assert sync.watch is False
