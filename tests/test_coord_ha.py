"""Coordinator HA: replicated control plane with fenced failover.

The paper's control plane (master + etcd) outlives any one process; our
single coordinator was the SPOF (ROADMAP #5).  These tests pin the HA
contract on BOTH backends (native edl-coord-server pair and in-process
PyCoordService pair):

* every acked mutation is on the standby before the client hears OK
  (stream-before-ack, the replication twin of persist-before-ack);
* a standby answers every client verb — reads and long-polls included —
  with the fencing error until promoted;
* promotion picks the standby with the highest durably-held stream
  position, under a token that beats every token seen;
* a deposed primary (GC-pause shape) fences ITSELF before serving stale
  state, and clients observe ``coord_fencing_rejects``;
* the multi-endpoint client fails over transparently — in-flight
  long-polls re-park on the new primary — and raises a typed
  :class:`CoordUnavailable` within its deadline budget when every
  endpoint is down, instead of riding the outage forever.
"""

from __future__ import annotations

import json
import signal
import socket
import threading
import time

import pytest

from edl_tpu.coord import (
    CoordClient,
    CoordFenced,
    CoordUnavailable,
    NativeCoordService,
    PyCoordService,
    native_available,
    spawn_ha_pair,
    spawn_server,
)
from edl_tpu.observability.collector import get_counters

pytestmark = pytest.mark.multihost


def _kill9(handle) -> None:
    handle.process.send_signal(signal.SIGKILL)
    handle.process.wait(timeout=10)


def _wait_stopped(pid: int, timeout_s: float = 5.0) -> None:
    """Block until the kernel reports the process stopped ('T' state).
    SIGSTOP delivery is asynchronous to the sender under load — issuing
    the next client op before the stop lands lets the 'paused' primary
    serve it and no failover happens (observed flake)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(")", 1)[1].split()[0] == "T":
                return
        time.sleep(0.01)
    raise TimeoutError(f"pid {pid} never stopped")


def _raw(port: int, line: str, timeout: float = 3.0) -> str:
    """One command over a fresh socket — bypasses the client's failover
    so a fenced node's own answer is observable."""
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall((line + "\n").encode())
        return s.makefile("rb").readline().decode().strip()


def _ha_client(primary, standby, **kw):
    kw.setdefault("timeout", 2.0)
    kw.setdefault("reconnect_window_s", 12.0)
    kw.setdefault("promote_grace_s", 0.2)
    return CoordClient("127.0.0.1", primary.port,
                       endpoints=[("127.0.0.1", standby.port)], **kw)


# ---------------------------------------------------------------------------
# Python backend: in-process pair
# ---------------------------------------------------------------------------

class TestPyBackend:
    def _pair(self):
        pr = PyCoordService()
        sb = PyCoordService(role="standby")
        pr.add_replica(sb)
        return pr, sb

    def test_stream_before_ack_and_promotion(self):
        pr, sb = self._pair()
        pr.add_task(b"shard-0")
        pr.join("w0", "a0")
        pr.kv_set("ckpt/1", b"/gen-1")
        # everything acked on the primary is already on the standby
        assert sb.promote(1) == 1
        assert sb.kv_get("ckpt/1") == b"/gen-1"
        assert sb.stats().todo == 1
        epoch, members = sb.members()
        assert (epoch, members) == (1, [("w0", "a0")])
        # failover is invisible to membership: heartbeat accepted, no
        # rejoin, no epoch bump
        assert sb.heartbeat("w0")
        assert sb.epoch() == 1

    def test_standby_rejects_reads_writes_and_waits(self):
        _pr, sb = self._pair()
        for op in (lambda: sb.kv_get("k"),
                   lambda: sb.kv_set("k", b"v"),
                   lambda: sb.epoch(),
                   lambda: sb.members(),
                   lambda: sb.stats(),
                   lambda: sb.lease("w"),
                   lambda: sb.wait_epoch(0, 0.05),
                   lambda: sb.kv_wait("k", 0.05)):
            with pytest.raises(CoordFenced):
                op()
        assert sb.fencing_rejects >= 8

    def test_deposed_primary_self_fences_on_stream(self):
        pr, sb = self._pair()
        pr.kv_set("k", b"v")
        sb.promote(1)
        # the GC-pause shape: the old primary wakes and writes — its
        # stream is rejected with the newer fence and it fences itself;
        # the mutation is never acked (the client's retry lands on the
        # promoted standby)
        with pytest.raises(CoordFenced):
            pr.kv_set("k", b"stale")
        assert pr.role == "fenced"
        with pytest.raises(CoordFenced):
            pr.kv_get("k")
        with pytest.raises(CoordFenced):
            pr.wait_epoch(0, 0.05)
        assert sb.kv_get("k") == b"v"

    def test_lease_guard_fences_reads_without_a_mutation(self):
        # reads alone must discover the deposition: the replication lease
        # forces a heartbeat exchange once stale, and the newer fence
        # fences the old primary BEFORE it hands out stale epoch/KV
        pr = PyCoordService(repl_lease_s=0.05)
        sb = PyCoordService(role="standby")
        pr.add_replica(sb)
        pr.kv_set("k", b"v")
        sb.promote(1)
        time.sleep(0.1)  # lease goes stale (the simulated pause)
        with pytest.raises(CoordFenced):
            pr.kv_get("k")
        assert pr.role == "fenced"

    def test_promote_requires_winning_token(self):
        pr, sb = self._pair()
        pr.kv_set("k", b"v")
        sb.promote(3)
        with pytest.raises(CoordFenced):
            sb.promote(2)  # re-promote with a losing token: refused
        assert sb.promote(5) == 5  # ratchet up is idempotent-safe
        # a standby that saw fence 5 via a later stream refuses 5
        sb2 = PyCoordService(role="standby")
        sb.add_replica(sb2)
        sb.kv_set("k2", b"v2")
        assert sb2.fence == 5
        with pytest.raises(CoordFenced):
            sb2.promote(5)

    def test_parked_longpoll_wakes_fenced(self):
        pr, sb = self._pair()
        pr.join("w0")
        sb.promote(1)
        out = []

        def waiter():
            try:
                pr.wait_epoch(1, 10.0)
            except CoordFenced:
                out.append("fenced")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        # the deposed primary discovers the fence on its next exchange;
        # _self_fence must wake the parked waiter promptly
        with pytest.raises(CoordFenced):
            pr.kv_set("k", b"v")
        t.join(timeout=5)
        assert out == ["fenced"]

    def test_stale_rejector_does_not_depose_rightful_primary(self):
        # a misconfigured replica that believes it is primary at an OLDER
        # fence rejects our stream — but its token loses, so the rightful
        # primary must keep serving (a config error must not become a
        # total control-plane outage)
        pr, sb = self._pair()
        pr.kv_set("k", b"v")
        sb.promote(1)          # sb is the rightful fence-1 primary now
        stale = PyCoordService()  # role primary, fence 0
        sb.add_replica(stale)
        sb.kv_set("k2", b"v2")  # stream rejected by the stale "primary"
        assert sb.role == "primary" and sb.kv_get("k2") == b"v2"
        assert sb.repl_errors >= 1

    def test_fenced_mirror_regains_standby_on_stream(self):
        pr, sb = self._pair()
        pr.kv_set("k", b"v")
        sb.promote(1)
        with pytest.raises(CoordFenced):
            pr.kv_set("k", b"stale")  # deposed: pr self-fences
        assert pr.role == "fenced"
        # the operator loop re-attaches the corpse as sb's mirror: the
        # first accepted stream demotes fenced -> standby (redundancy is
        # back), and it is promotable again after sb dies
        sb.add_replica(pr)
        sb.kv_set("k3", b"v3")
        assert pr.role == "standby"
        assert pr.promote(2) == 2
        assert pr.kv_get("k3") == b"v3"

    def test_unreachable_standby_degrades_not_blocks(self):
        class Dead:
            def sync_from(self, *a):
                raise OSError("unreachable")

            def repl_heartbeat(self, *a):
                raise OSError("unreachable")

        pr = PyCoordService(repl_lease_s=0.0)
        pr.add_replica(Dead())
        pr.kv_set("k", b"v")  # a dead standby must not take down the job
        assert pr.kv_get("k") == b"v"
        assert pr.repl_errors >= 1

    def test_strict_lease_suspends_without_standby_and_recovers(self):
        class Flaky:
            def __init__(self):
                self.up = True

            def sync_from(self, *a):
                if not self.up:
                    raise OSError("unreachable")

            def repl_heartbeat(self, *a):
                if not self.up:
                    raise OSError("unreachable")

        flaky = Flaky()
        pr = PyCoordService(repl_lease_s=0.0, repl_lease_strict=True)
        pr.add_replica(flaky)
        pr.kv_set("k", b"v")
        flaky.up = False
        # CONSISTENT mode: no reachable standby past the lease -> suspend
        # (reads included), but the role is untouched...
        with pytest.raises(CoordFenced):
            pr.kv_get("k")
        assert pr.role == "primary"
        # ...so serving resumes the moment the standby answers again
        flaky.up = True
        assert pr.kv_get("k") == b"v"

    def test_dual_primary_equal_fence_receiver_yields(self):
        # two clients raced PROMOTE onto two standbys with the SAME
        # token: equal fences can never depose each other through the
        # stale-rejector rule, so the first exchange makes the RECEIVER
        # yield — one deterministic survivor
        a = PyCoordService(role="standby")
        b = PyCoordService(role="standby")
        a.promote(1)
        b.promote(1)
        a.add_replica(b)
        # add_replica's catch-up stream hits b while b is still primary:
        # b (the receiver) yields — and the NEXT stream finds a fenced
        # mirror and demotes it to standby, so the loser converges into
        # a's replica instead of lingering as a corpse
        a.kv_set("k", b"v")
        assert a.role == "primary" and b.role == "standby"
        assert a.kv_get("k") == b"v"
        a.kv_set("k2", b"v2")  # a keeps serving as the single survivor
        assert a.role == "primary"
        # and b is a faithful mirror again: promotable with b's state
        assert b.promote(2) == 2
        assert b.kv_get("k2") == b"v2"


# ---------------------------------------------------------------------------
# Snapshot format parity: one format, both backends
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not native_available(), reason="no native core")
class TestSnapshotParity:
    def _populate(self, svc):
        svc.add_task(b"shard-0")
        svc.add_task(b"shard-1")
        st, tid, _ = svc.lease("w0")
        svc.complete(tid, "w0")
        svc.join("w0", "addr-0")
        svc.join("w1", "addr-1")
        svc.kv_set("ckpt/2", b"/gen-2")

    def test_python_blob_restores_into_native(self):
        py = PyCoordService()
        self._populate(py)
        native = NativeCoordService()
        assert native.restore_repl(py.snapshot(include_members=True))
        assert native.kv_get("ckpt/2") == b"/gen-2"
        assert native.stats().todo == 1 and native.stats().done == 1
        epoch, members = native.members()
        assert members == [("w0", "addr-0"), ("w1", "addr-1")]
        assert epoch == py.epoch()

    def test_empty_fields_survive_the_stream(self):
        # empty binary fields frame as "-": a bare trailing space would
        # be dropped by the stream parser — an empty-addr member (the
        # common join(name) case), an empty KV value, and an empty task
        # payload must all survive replication on both backends
        py = PyCoordService()
        py.join("w0")                      # address ""
        py.kv_set("flag", b"")
        py.add_task(b"")
        native = NativeCoordService()
        assert native.restore_repl(py.snapshot(include_members=True))
        assert native.members()[1] == [("w0", "")]
        assert native.kv_get("flag") == b""
        st, _tid, payload = native.lease("w")
        assert st.name == "OK" and payload == b""
        # and back: native blob into a python standby
        py2 = PyCoordService(role="standby")
        py2.sync_from(0, 9, native.snapshot(include_members=True))
        py2.promote(1)
        assert py2.members()[1] == [("w0", "")]
        assert py2.kv_get("flag") == b""

    def test_torn_blob_rejected_without_ratcheting_position(self):
        sb = PyCoordService(role="standby")
        pr = PyCoordService()
        pr.add_replica(sb)
        pr.kv_set("k", b"v")
        good = sb.stream_version()
        with pytest.raises(ValueError):
            sb.sync_from(5, 99, "EDLCOORD1\ntruncated")  # no terminator
        # a torn stream must not ratchet the fence or advertise a
        # position this node does not hold
        assert sb.fence == 0 and sb.stream_version() == good
        assert sb.promote(1) == 1
        assert sb.kv_get("k") == b"v"  # last good mirror intact

    def test_native_blob_restores_into_python(self):
        native = NativeCoordService()
        self._populate(native)
        py = PyCoordService(role="standby")
        py.sync_from(0, 7, native.snapshot(include_members=True))
        py.promote(1)
        assert py.kv_get("ckpt/2") == b"/gen-2"
        assert py.stats().todo == 1 and py.stats().done == 1
        assert py.members()[1] == [("w0", "addr-0"), ("w1", "addr-1")]


# ---------------------------------------------------------------------------
# Native backend: real server pair over TCP
# ---------------------------------------------------------------------------

class TestNativePair:
    def test_failover_preserves_state_and_membership(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), member_ttl_ms=5000,
                               repl_lease_ms=1000)
        c = _ha_client(pr, sb)
        try:
            c.add_task(b"shard-0")
            c.kv_set("ckpt/1", b"/gen-1")
            assert c.join("w0", "a0") == 1
            before = get_counters().get("coord_failovers")
            _kill9(pr)
            # the next call transparently fails over AND promotes
            assert c.kv_get("ckpt/1") == b"/gen-1"
            assert (c.host, c.port) == ("127.0.0.1", sb.port)
            assert get_counters().get("coord_failovers") == before + 1
            # queue + membership + epoch all survived: no rejoin storm
            assert c.stats().todo == 1
            assert c.heartbeat("w0")
            assert c.epoch() == 1
            assert _raw(sb.port, "ROLE").startswith("OK primary 1 ")
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_longpoll_reparks_on_promoted_standby(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), member_ttl_ms=5000,
                               repl_lease_ms=1000)
        c = _ha_client(pr, sb)
        fired = []
        try:
            c.join("w0", "a0")
            t = threading.Thread(
                target=lambda: fired.append(c.wait_epoch(1, 20.0)))
            t.start()
            time.sleep(0.3)  # the wait is parked on the primary
            _kill9(pr)
            # a second client's join on the promoted standby must wake
            # the re-parked wait with the new epoch
            c2 = _ha_client(sb, sb)
            try:
                c2.join("w1", "a1")
            finally:
                c2.close()
            t.join(timeout=15)
            assert fired == [2], fired
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_gc_paused_primary_comes_back_fenced(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), member_ttl_ms=10000,
                               repl_lease_ms=300)
        c = _ha_client(pr, sb)
        try:
            c.kv_set("k", b"v")
            c.join("w0", "a0")
            # GC-style pause: the primary freezes, the client times out
            # and promotes the standby
            pr.process.send_signal(signal.SIGSTOP)
            _wait_stopped(pr.process.pid)
            assert c.kv_get("k") == b"v"  # served by the new primary
            assert (c.host, c.port) == ("127.0.0.1", sb.port)
            # the stale primary resumes with an expired replication
            # lease: its FIRST verb re-verifies against the standby,
            # discovers the newer fence, and self-fences — writes, reads
            # and long-polls all refuse before any stale state escapes
            pr.process.send_signal(signal.SIGCONT)
            time.sleep(0.1)
            assert _raw(pr.port, "KVSET k 646561").startswith("ERR fenced")
            assert _raw(pr.port, "KVGET k").startswith("ERR fenced")
            assert _raw(pr.port, "WAITEPOCH 0 100").startswith("ERR fenced")
            assert _raw(pr.port, "ROLE").startswith("OK fenced")
            # a client pinned to the fenced node observes the typed
            # reject counter and a bounded typed failure
            before = get_counters().get("coord_fencing_rejects")
            c_stale = CoordClient("127.0.0.1", pr.port, timeout=1.0,
                                  reconnect_window_s=0.8)
            t0 = time.monotonic()
            with pytest.raises(CoordUnavailable):
                c_stale.kv_get("k")
            assert time.monotonic() - t0 < 2 * 0.8 + 1.0
            assert get_counters().get("coord_fencing_rejects") > before
            c_stale.close()
            # truth lives with the promoted standby
            assert c.kv_get("k") == b"v"
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_all_endpoints_dead_returns_within_twice_budget(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path))
        budget = 1.5
        c = _ha_client(pr, sb, reconnect_window_s=budget)
        try:
            c.kv_set("k", b"v")
            _kill9(pr)
            _kill9(sb)
            t0 = time.monotonic()
            with pytest.raises(CoordUnavailable):
                c.kv_get("k")
            assert time.monotonic() - t0 < 2 * budget
            # the constructor honors the same typed bound
            t0 = time.monotonic()
            with pytest.raises(CoordUnavailable):
                CoordClient("127.0.0.1", pr.port, timeout=1.0,
                            reconnect_window_s=budget,
                            endpoints=[("127.0.0.1", sb.port)])
            assert time.monotonic() - t0 < 2 * budget + 1.0
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_second_standby_catches_up_after_outage(self, tmp_path):
        # per-replica stream positions: standby B missing a SYNC while A
        # acked it must still receive its catch-up (from the keeper
        # thread) once it returns — else promoting B later would silently
        # lose acked state
        sb1 = spawn_server(standby=True,
                           state_file=str(tmp_path / "b1.state"))
        sb2 = spawn_server(standby=True,
                           state_file=str(tmp_path / "b2.state"))
        pr = spawn_server(
            state_file=str(tmp_path / "a.state"),
            replicate_to=f"127.0.0.1:{sb1.port},127.0.0.1:{sb2.port}",
            repl_lease_ms=600)
        c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                        reconnect_window_s=8.0)
        try:
            c.kv_set("k", b"v1")
            _kill9(sb2)
            c.kv_set("k", b"v2")  # sb1 acks; sb2 is down
            sv = int(_raw(pr.port, "ROLE").split(" ")[3])
            assert int(_raw(sb1.port, "ROLE").split(" ")[3]) == sv
            sb2b = spawn_server(port=sb2.port, standby=True,
                                state_file=str(tmp_path / "b2.state"))
            deadline = time.monotonic() + 10
            caught_up = -1
            while time.monotonic() < deadline:
                caught_up = int(_raw(sb2.port, "ROLE").split(" ")[3])
                if caught_up >= sv:
                    break
                time.sleep(0.1)
            assert caught_up >= sv, (caught_up, sv)
            assert _raw(sb2.port, "PROMOTE 1").startswith("OK 1 ")
            assert _raw(sb2.port, "KVGET k") == "OK " + b"v2".hex()
            sb2b.stop()
        finally:
            c.close()
            pr.stop()
            sb1.stop()
            sb2.stop()

    def test_respawned_old_primary_rejoins_as_standby(self, tmp_path):
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=500)
        c = _ha_client(pr, sb)
        try:
            c.kv_set("k", b"v1")
            old_port = pr.port
            _kill9(pr)
            assert c.kv_get("k") == b"v1"  # failover + promotion
            # respawn the dead node as a STANDBY of the new primary on
            # its old endpoint, re-attach via REPLICATE, and verify the
            # next mutation streams to it
            pr2 = spawn_server(port=old_port, standby=True,
                               state_file=str(tmp_path / "coord-a.state"),
                               repl_lease_ms=500)
            assert _raw(c.port, f"REPLICATE 127.0.0.1:{old_port}") == "OK"
            c.kv_set("k", b"v2")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                role = _raw(old_port, "ROLE").split(" ")
                if int(role[3]) >= 2:  # caught up past the first stream
                    break
                time.sleep(0.05)
            assert role[1] == "standby" and role[2] == "1", role
            # second failover: back onto the respawned node
            _kill9(sb)
            assert c.kv_get("k") == b"v2"
            assert (c.host, c.port) == ("127.0.0.1", old_port)
            assert _raw(old_port, "ROLE").startswith("OK primary 2 ")
            pr2.stop()
        finally:
            c.close()
            pr.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# Replication-stream crash injection (satellite: crash_on_persist "N:repl")
# ---------------------------------------------------------------------------

class TestStrictMode:
    def test_suspended_primary_is_routed_around(self, tmp_path):
        # strict pair, asymmetric outage: the standby dies, so the
        # primary suspends (nothing un-mirrored may be acked) and its
        # ROLE reports "suspended" — the client must not re-target it
        # forever, and once a mirror is back the client promotes IT
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=400)
        # restart the pair strict (spawn_ha_pair has no strict knob: the
        # scenario needs the primary strict, which is enough)
        pr.stop()
        pr = spawn_server(state_file=str(tmp_path / "coord-a.state"),
                          replicate_to=f"127.0.0.1:{sb.port}",
                          repl_lease_ms=400, repl_lease_strict=True)
        c = _ha_client(pr, sb, reconnect_window_s=4.0)
        try:
            c.kv_set("k", b"v1")  # mirrored, acked
            _kill9(sb)
            # no mirror: strict refuses the ack; with no promotable
            # candidate the call fails typed and budget-bounded
            t0 = time.monotonic()
            with pytest.raises(CoordUnavailable):
                c.kv_set("k", b"v2")
            assert time.monotonic() - t0 < 2 * 4.0 + 1.0
            time.sleep(0.5)  # lease lapses -> ROLE reports suspended
            assert _raw(pr.port, "ROLE").startswith("OK suspended")
            # a mirror returns (respawned from its file, holding every
            # acked op): the client promotes IT around the suspended
            # primary and the job resumes
            sb2 = spawn_server(port=sb.port, standby=True,
                               state_file=str(tmp_path / "coord-b.state"))
            c.kv_set("k", b"v3")
            assert (c.host, c.port) == ("127.0.0.1", sb.port)
            assert c.kv_get("k") == b"v3"
            # the suspended ex-primary deposes at its next lease probe
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if _raw(pr.port, "ROLE").startswith("OK fenced"):
                    break
                time.sleep(0.1)
            assert _raw(pr.port, "ROLE").startswith("OK fenced")
            sb2.stop()
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_health_sweep_epoch_bump_reaches_the_standby(self, tmp_path):
        import urllib.request

        # a /healthz-probe TTL sweep bumps the epoch with no client
        # command in flight; the bump must stream to the mirror before a
        # failover can serve a regressed epoch / resurrected member
        pr, sb = spawn_ha_pair(str(tmp_path), member_ttl_ms=300,
                               repl_lease_ms=60000, health_port=0)
        c = _ha_client(pr, sb)
        try:
            c.join("w0", "a0")          # epoch 1, mirrored
            time.sleep(0.5)             # TTL lapses, nobody heartbeats
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{pr.health_port}/healthz",
                    timeout=5) as r:
                assert b'"epoch":2' in r.read()  # the sweep bumped it
            _kill9(pr)
            assert c.epoch() == 2       # the promoted mirror agrees
            _e, members = c.members()
            assert members == []        # the expired member stayed dead
        finally:
            c.close()
            pr.stop()
            sb.stop()


class TestReplCrashInjection:
    def test_primary_dies_streaming_before_ack(self, tmp_path):
        # the primary exits after the SYNC is on the wire but before the
        # client is acked: the standby must come to own that exact state,
        # and the client's at-least-once retry converges on it
        pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000,
                               crash_on_persist="2:repl")
        c = _ha_client(pr, sb)
        try:
            c.kv_set("k1", b"v1")          # stream 1, acked
            c.kv_set("k2", b"v2")          # stream 2: primary dies unacked
            pr.process.wait(timeout=10)
            assert pr.process.returncode == 137
            # the retry rode the failover; both writes visible on the
            # promoted standby
            assert c.kv_get("k1") == b"v1"
            assert c.kv_get("k2") == b"v2"
            assert _raw(sb.port, "ROLE").startswith("OK primary")
        finally:
            c.close()
            pr.stop()
            sb.stop()

    def test_standby_persists_before_acking(self, tmp_path):
        # the STANDBY dies after persisting the streamed state but before
        # acking: restarted from its own file, it must own exactly the
        # position it persisted — the promotion-safety half of the claim
        # ("never promotes with a version it hasn't durably persisted")
        sb = spawn_server(standby=True,
                          state_file=str(tmp_path / "sb.state"),
                          crash_on_persist="1:repl")
        pr = spawn_server(state_file=str(tmp_path / "pr.state"),
                          replicate_to=f"127.0.0.1:{sb.port}",
                          repl_lease_ms=1000)
        c = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                        reconnect_window_s=5.0)
        try:
            c.kv_set("k", b"v")  # standby persists the stream, then dies
            sb.process.wait(timeout=10)
            assert sb.process.returncode == 137
            # the primary never heard the ack — it served anyway
            # (availability) and will catch the standby up on respawn
            assert c.kv_get("k") == b"v"
            sb2 = spawn_server(standby=True,
                               state_file=str(tmp_path / "sb.state"))
            role = _raw(sb2.port, "ROLE").split(" ")
            assert role[1] == "standby" and int(role[3]) >= 1, role
            # what it persisted is what it serves after promotion
            assert _raw(sb2.port, "PROMOTE 1").startswith("OK 1 ")
            assert _raw(sb2.port, "KVGET k") == "OK " + b"v".hex()
            sb2.stop()
        finally:
            c.close()
            pr.stop()
            sb.stop()


# ---------------------------------------------------------------------------
# Fault engine: HA KillCoordinator drill (failover observed, zero reforms)
# ---------------------------------------------------------------------------

def test_ha_kill_coordinator_drill(tmp_path):
    from edl_tpu.runtime.faults import (
        FaultContext, FaultPlan, FaultPlanEngine, KillCoordinator,
    )

    pr, sb = spawn_ha_pair(str(tmp_path), repl_lease_ms=1000)
    c = _ha_client(pr, sb)
    try:
        c.kv_set("k", b"v")
        ctx = FaultContext(coord=c, ha=True,
                           kill_primary=lambda: _kill9(pr))
        engine = FaultPlanEngine(
            FaultPlan([KillCoordinator(at_step=1)]), ctx)
        before_reforms = get_counters().total("world_reforms")
        engine(step=1)
        # drive the client so the failover actually happens, then let the
        # engine observe it
        deadline = time.monotonic() + 15
        while not engine.quiescent() and time.monotonic() < deadline:
            assert c.kv_get("k") == b"v"
            engine.tick()
            time.sleep(0.05)
        assert engine.recovered == ["kill_coordinator"]
        assert get_counters().total("world_reforms") == before_reforms
        assert get_counters().total("coord_ha_reform_leaks") == 0
    finally:
        c.close()
        pr.stop()
        sb.stop()


# ---------------------------------------------------------------------------
# Supervisor integration: endpoint-set publication
# ---------------------------------------------------------------------------

def test_supervisor_publishes_endpoint_set(tmp_path):
    # the multihost supervisor publishes its client's endpoint SET so
    # tooling/late joiners discover the standbys; pinned here without
    # spawning worlds by exercising the same code path the supervisor
    # runs (multihost.run_elastic_worker writes _COORD_ENDPOINTS_KEY)
    from edl_tpu.runtime import multihost

    pr, sb = spawn_ha_pair(str(tmp_path))
    c = _ha_client(pr, sb)
    try:
        eps = getattr(c, "endpoints")
        c.kv_set(multihost._COORD_ENDPOINTS_KEY, json.dumps(
            [f"{h}:{p}" for h, p in eps]).encode())
        raw = c.kv_get(multihost._COORD_ENDPOINTS_KEY)
        assert json.loads(raw.decode()) == [
            f"127.0.0.1:{pr.port}", f"127.0.0.1:{sb.port}"]
        # a client that knows only ONE address discovers the full set at
        # construction — the reason the supervisor publishes it
        c_single = CoordClient("127.0.0.1", pr.port, timeout=2.0,
                               reconnect_window_s=12.0,
                               promote_grace_s=0.2)
        assert ("127.0.0.1", sb.port) in c_single.endpoints
        # and it survives the failover it describes: the death of the
        # only address it was configured with
        _kill9(pr)
        assert c_single.kv_get(
            multihost._COORD_ENDPOINTS_KEY) is not None
        assert (c_single.host, c_single.port) == ("127.0.0.1", sb.port)
        c_single.close()
        raw = c.kv_get(multihost._COORD_ENDPOINTS_KEY)
        assert f"127.0.0.1:{sb.port}" in json.loads(raw.decode())
    finally:
        c.close()
        pr.stop()
        sb.stop()
