"""In-memory stub of the ``kubernetes`` client package.

Role of the reference's generated fake clientset
(reference pkg/client/clientset/versioned/fake/fake_trainingjob.go:29-124):
an object-tracker-backed API surface so the real :class:`K8sCluster` method
bodies execute in tests without an apiserver.  The stub models exactly what
those bodies touch — typed nodes/pods with attribute access, batch Jobs with
resourceVersion semantics (including 409 on stale replaces), ReplicaSets and
Services — plus a conflict-injection hook for the autoscaler's retry path.

Install with :func:`install` (returns the shared state) and pass
``sys.modules`` patching to the ``stub_kubernetes`` fixture in
tests/test_k8s_cluster.py; nothing here imports edl_tpu.
"""

from __future__ import annotations

import copy
import functools
import pathlib
import types
from dataclasses import dataclass, field
from typing import Any, Optional

#: The CRD manifest the stub enforces — the SHIPPED one, so a schema/docs
#: mismatch is caught by tests instead of surfacing as silent field loss on
#: a real cluster (round-3 verdict weak #1: the stub stored dicts verbatim,
#: which is exactly why the kebab-case pruning bug was untestable).
CRD_PATH = pathlib.Path(__file__).resolve().parent.parent / "k8s" / "crd.yaml"


def prune_per_schema(value: Any, schema: Any) -> Any:
    """Structural-schema pruning, as a conformant apiserver performs on
    admission: object fields not declared in ``properties`` are silently
    dropped unless the schema opts out with
    ``x-kubernetes-preserve-unknown-fields``.  An object value whose schema
    declares neither ``properties`` nor ``additionalProperties`` loses ALL
    its fields — that default matters, because keeping them would hide
    exactly the schema-drift class this stub exists to catch."""
    if not isinstance(schema, dict):
        # no schema at this node at all → everything below is unspecified
        return {} if isinstance(value, dict) else value
    if schema.get("x-kubernetes-preserve-unknown-fields"):
        return value
    if isinstance(value, dict):
        props = schema.get("properties")
        if props is not None:
            return {k: prune_per_schema(v, props[k])
                    for k, v in value.items() if k in props}
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            return {k: prune_per_schema(v, addl) for k, v in value.items()}
        if addl:  # additionalProperties: true
            return value
        return {}
    if isinstance(value, list):
        return [prune_per_schema(v, schema.get("items")) for v in value]
    return value


@functools.lru_cache(maxsize=None)
def load_crd_schemas(path: pathlib.Path = CRD_PATH) -> dict:
    """(group, plural) → served-version openAPIV3Schema from a CRD manifest."""
    import yaml

    out: dict = {}
    if not path.exists():  # pragma: no cover - repo layout changed
        return out
    for doc in yaml.safe_load_all(path.read_text()):
        if not doc or doc.get("kind") != "CustomResourceDefinition":
            continue
        spec = doc.get("spec") or {}
        group = spec.get("group", "")
        plural = (spec.get("names") or {}).get("plural", "")
        for v in spec.get("versions") or []:
            if v.get("served"):
                schema = (v.get("schema") or {}).get("openAPIV3Schema")
                if schema:
                    out[(group, plural)] = schema
    return out


class ApiException(Exception):
    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"({status}) {reason}")
        self.status = status
        self.reason = reason


class _Obj:
    """Attribute bag with dict-style construction (role of the kubernetes
    client's typed models, which the real code reads via attributes)."""

    def __init__(self, **kw: Any):
        for k, v in kw.items():
            setattr(self, k, v)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"_Obj({self.__dict__!r})"


def make_node(name: str, cpu: str = "8", memory: str = "16Gi",
              tpu: int = 0, labels: Optional[dict] = None) -> _Obj:
    alloc = {"cpu": cpu, "memory": memory}
    if tpu:
        alloc["google.com/tpu"] = str(tpu)
    return _Obj(
        metadata=_Obj(name=name, labels=dict(labels or {})),
        status=_Obj(allocatable=alloc),
    )


def make_pod(name: str, namespace: str = "default", phase: str = "Running",
             node: Optional[str] = None, labels: Optional[dict] = None,
             cpu: str = "0", memory: str = "0", tpu: int = 0,
             terminating: bool = False) -> _Obj:
    limits = {"cpu": cpu, "memory": memory}
    if tpu:
        limits["google.com/tpu"] = str(tpu)
    container = _Obj(resources=_Obj(
        requests={"cpu": cpu, "memory": memory}, limits=limits))
    return _Obj(
        metadata=_Obj(name=name, namespace=namespace,
                      labels=dict(labels or {}),
                      deletion_timestamp=("now" if terminating else None)),
        spec=_Obj(node_name=node, containers=[container],
                  init_containers=[]),
        status=_Obj(phase=phase),
    )


@dataclass
class StubState:
    """The 'etcd' behind the stub apiserver."""

    nodes: list = field(default_factory=list)
    pods: list = field(default_factory=list)
    #: (namespace, name) → Job object (spec.parallelism,
    #: metadata.resource_version as int, metadata.labels)
    jobs: dict = field(default_factory=dict)
    replicasets: dict = field(default_factory=dict)
    services: dict = field(default_factory=dict)
    #: (group, namespace, plural, name) → custom-object dict (the
    #: TrainingJob CR store; role of the reference's object-tracker-backed
    #: fake clientset, pkg/client/.../fake/fake_trainingjob.go:29-124)
    custom_objects: dict = field(default_factory=dict)
    #: (group, plural) → structural schema, enforced (pruning) on custom-
    #: object create/replace/status-patch exactly as a real apiserver would
    crd_schemas: dict = field(default_factory=load_crd_schemas)
    #: next N replace_namespaced_job calls fail 409 (concurrent-writer
    #: simulation for the ConflictError mapping test)
    conflicts_to_inject: int = 0
    #: monotonic collection resourceVersion for custom objects; every
    #: mutation bumps it and appends to the event log the watch serves
    custom_rv: int = 0
    #: [(rv, "ADDED"|"MODIFIED"|"DELETED", object snapshot)]
    custom_events: list = field(default_factory=list)
    #: events at/below this rv have been compacted away — a watch asking
    #: to resume below it gets 410 Gone (etcd compaction semantics)
    custom_compacted_rv: int = 0

    def record_custom_event(self, typ: str, obj: dict) -> None:
        self.custom_rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.custom_rv)
        self.custom_events.append((self.custom_rv, typ, copy.deepcopy(obj)))

    def compact_custom_events(self) -> None:
        """Simulate etcd compaction: the watch window is gone; resuming
        from any pre-compaction rv must 410 (the informer's re-list path)."""
        self.custom_compacted_rv = self.custom_rv
        self.custom_events.clear()

    # mutation helpers the real apiserver would do itself
    def put_job(self, namespace: str, name: str, parallelism: int,
                labels: Optional[dict] = None) -> None:
        self.jobs[(namespace, name)] = _Obj(
            metadata=_Obj(name=name, namespace=namespace,
                          labels=dict(labels or {}), resource_version=1),
            spec=_Obj(parallelism=parallelism),
        )


class _CoreV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def list_node(self):
        return _Obj(items=list(self._s.nodes))

    def list_pod_for_all_namespaces(self, field_selector: str = ""):
        items = self._s.pods
        if "status.phase!=Succeeded" in (field_selector or ""):
            items = [p for p in items
                     if p.status.phase not in ("Succeeded", "Failed")]
        return _Obj(items=list(items))

    def list_namespaced_pod(self, namespace: str,
                            label_selector: Optional[str] = None):
        items = [p for p in self._s.pods if p.metadata.namespace == namespace]
        if label_selector:
            key, _, value = label_selector.partition("=")
            items = [p for p in items
                     if (p.metadata.labels or {}).get(key) == value
                     or (not value and key in (p.metadata.labels or {}))]
        return _Obj(items=items)

    def create_namespaced_service(self, namespace: str, manifest: dict):
        self._s.services[(namespace, manifest["metadata"]["name"])] = manifest

    def delete_namespaced_service(self, name: str, namespace: str):
        if (namespace, name) not in self._s.services:
            raise ApiException(404, f"service {name}")
        del self._s.services[(namespace, name)]


class _BatchV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def _get(self, namespace: str, name: str) -> _Obj:
        try:
            return self._s.jobs[(namespace, name)]
        except KeyError:
            raise ApiException(404, f"job {name}") from None

    def read_namespaced_job(self, name: str, namespace: str) -> _Obj:
        # a fresh copy each read: mutating the returned object must not
        # write through to the 'server' (the real client deserializes)
        return copy.deepcopy(self._get(namespace, name))

    def replace_namespaced_job(self, name: str, namespace: str, body: _Obj):
        if self._s.conflicts_to_inject > 0:
            self._s.conflicts_to_inject -= 1
            # a concurrent writer bumped the version since our read
            cur = self._get(namespace, name)
            cur.metadata.resource_version += 1
            raise ApiException(409, "resourceVersion conflict")
        cur = self._get(namespace, name)
        if body.metadata.resource_version != cur.metadata.resource_version:
            raise ApiException(409, "resourceVersion conflict")
        body = copy.deepcopy(body)
        body.metadata.resource_version += 1
        self._s.jobs[(namespace, name)] = body

    def create_namespaced_job(self, namespace: str, manifest: dict):
        name = manifest["metadata"]["name"]
        if (namespace, name) in self._s.jobs:
            raise ApiException(409, f"job {name} exists")
        self._s.put_job(namespace, name,
                        manifest["spec"].get("parallelism", 0),
                        manifest["metadata"].get("labels"))

    def list_namespaced_job(self, namespace: str):
        return _Obj(items=[j for (ns, _), j in self._s.jobs.items()
                           if ns == namespace])

    def list_job_for_all_namespaces(self):
        return _Obj(items=list(self._s.jobs.values()))

    def delete_namespaced_job(self, name: str, namespace: str,
                              propagation_policy: str = ""):
        if (namespace, name) not in self._s.jobs:
            raise ApiException(404, f"job {name}")
        del self._s.jobs[(namespace, name)]


class _CustomObjectsApi:
    """CRD verbs the real K8sCluster CR methods touch.  Custom objects are
    plain dicts, as in the real kubernetes client."""

    def __init__(self, state: StubState):
        self._s = state

    def _key(self, group, namespace, plural, name):
        return (group, namespace, plural, name)

    def _admit(self, group: str, plural: str, body: dict) -> dict:
        """Apiserver admission: prune spec/status per the structural schema
        (apiVersion/kind/metadata are typed fields, kept as-is)."""
        schema = self._s.crd_schemas.get((group, plural))
        obj = copy.deepcopy(body)
        if schema is not None:
            props = schema.get("properties") or {}
            for section in ("spec", "status"):
                if section in obj:
                    obj[section] = prune_per_schema(
                        obj[section], props.get(section, {}))
        return obj

    def create_namespaced_custom_object(self, group, version, namespace,
                                        plural, body):
        name = (body.get("metadata") or {}).get("name", "")
        key = self._key(group, namespace, plural, name)
        if key in self._s.custom_objects:
            raise ApiException(409, f"{plural} {name} exists")
        obj = self._admit(group, plural, body)
        obj.setdefault("metadata", {})
        obj["metadata"].setdefault("namespace", namespace)
        obj["metadata"]["generation"] = 1
        self._s.record_custom_event("ADDED", obj)
        self._s.custom_objects[key] = obj
        return copy.deepcopy(obj)

    def list_namespaced_custom_object(self, group, version, namespace,
                                      plural):
        items = [copy.deepcopy(o)
                 for (g, ns, pl, _), o in sorted(self._s.custom_objects.items())
                 if (g, ns, pl) == (group, namespace, plural)]
        return {"items": items,
                "metadata": {"resourceVersion": str(self._s.custom_rv)}}

    def list_cluster_custom_object(self, group, version, plural):
        items = [copy.deepcopy(o)
                 for (g, _, pl, _), o in sorted(self._s.custom_objects.items())
                 if (g, pl) == (group, plural)]
        return {"items": items,
                "metadata": {"resourceVersion": str(self._s.custom_rv)}}

    def get_namespaced_custom_object(self, group, version, namespace,
                                     plural, name):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        return copy.deepcopy(self._s.custom_objects[key])

    def replace_namespaced_custom_object(self, group, version, namespace,
                                         plural, name, body):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        old = self._s.custom_objects[key]
        obj = self._admit(group, plural, body)
        obj.setdefault("metadata", {})
        gen = (old.get("metadata") or {}).get("generation", 1)
        # the apiserver bumps generation only on spec change (status
        # subresource writes go through patch_..._status below)
        if obj.get("spec") != old.get("spec"):
            gen += 1
        obj["metadata"]["generation"] = gen
        obj.setdefault("status", copy.deepcopy(old.get("status") or {}))
        self._s.record_custom_event("MODIFIED", obj)
        self._s.custom_objects[key] = obj
        return copy.deepcopy(obj)

    def patch_namespaced_custom_object_status(self, group, version,
                                              namespace, plural, name, body):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        obj = self._s.custom_objects[key]
        obj["status"] = self._admit(group, plural,
                                    {"status": (body or {}).get("status")
                                     or {}}).get("status", {})
        self._s.record_custom_event("MODIFIED", obj)
        return copy.deepcopy(obj)

    def delete_namespaced_custom_object(self, group, version, namespace,
                                        plural, name):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        self._s.record_custom_event("DELETED", self._s.custom_objects[key])
        del self._s.custom_objects[key]


class _AppsV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def create_namespaced_replica_set(self, namespace: str, manifest: dict):
        self._s.replicasets[(namespace, manifest["metadata"]["name"])] = manifest

    def _get(self, namespace: str, name: str) -> dict:
        try:
            return self._s.replicasets[(namespace, name)]
        except KeyError:
            raise ApiException(404, f"replicaset {name}") from None

    def read_namespaced_replica_set(self, name: str, namespace: str) -> _Obj:
        m = self._get(namespace, name)
        return _Obj(
            metadata=_Obj(name=name,
                          resource_version=(m.get("metadata") or {})
                          .get("resourceVersion", 0)),
            spec=_Obj(replicas=(m.get("spec") or {}).get("replicas", 0)))

    def replace_namespaced_replica_set(self, name: str, namespace: str,
                                       body: _Obj):
        """The serving replica dial (K8sCluster ServingJob actuation) —
        same optimistic-concurrency semantics as the trainer Job."""
        m = self._get(namespace, name)
        meta = m.setdefault("metadata", {})
        if self._s.conflicts_to_inject > 0:
            self._s.conflicts_to_inject -= 1
            meta["resourceVersion"] = meta.get("resourceVersion", 0) + 1
            raise ApiException(409, "resourceVersion conflict")
        if body.metadata.resource_version != meta.get("resourceVersion", 0):
            raise ApiException(409, "resourceVersion conflict")
        m.setdefault("spec", {})["replicas"] = body.spec.replicas
        meta["resourceVersion"] = meta.get("resourceVersion", 0) + 1

    def delete_namespaced_replica_set(self, name: str, namespace: str,
                                      propagation_policy: str = ""):
        if (namespace, name) not in self._s.replicasets:
            raise ApiException(404, f"replicaset {name}")
        del self._s.replicasets[(namespace, name)]


class _Watch:
    """Role of ``kubernetes.watch.Watch`` for the custom-object
    collection: replays the event log past ``resource_version``, then
    tails it until ``timeout_seconds`` (the server-side watch timeout the
    real apiserver enforces).  A resume rv at/below the compaction point
    raises 410 Gone, as etcd compaction does."""

    def __init__(self, state: StubState):
        self._s = state
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def stream(self, func, *args, resource_version="0",
               timeout_seconds=30, **kwargs):
        import time

        rv = int(resource_version or 0)
        if rv < self._s.custom_compacted_rv:
            raise ApiException(410, "too old resource version (compacted)")
        deadline = time.monotonic() + float(timeout_seconds)
        while not self._stopped and time.monotonic() < deadline:
            for erv, typ, obj in list(self._s.custom_events):
                if erv > rv:
                    rv = erv
                    yield {"type": typ, "object": copy.deepcopy(obj)}
            time.sleep(0.01)


# -- HTTP wire mode ----------------------------------------------------------
#
# The in-memory module above exercises K8sCluster's method BODIES; the
# HTTP mode exercises its method bodies THROUGH REAL SOCKETS (VERDICT r5
# #7): the same schema-enforcing StubState served by a threaded HTTP
# apiserver, with a kubernetes-shaped client module whose API classes
# serialize every call over the wire.  What this adds over in-memory:
# watch streams arrive as bytes on a live connection (flushed
# incrementally, ended by the server-side timeout), 410 Gone is a real
# HTTP status the client maps back to ApiException, and 409 conflicts
# cross the wire before the autoscaler's retry loop sees them.

def to_wire(v: Any) -> Any:
    """JSON-encode the stub's value graph; _Obj nodes become tagged dicts
    so attribute access survives the round trip."""
    if isinstance(v, _Obj):
        return {"__obj__": {k: to_wire(x) for k, x in v.__dict__.items()}}
    if isinstance(v, dict):
        return {k: to_wire(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [to_wire(x) for x in v]
    return v


def from_wire(v: Any) -> Any:
    if isinstance(v, dict):
        if set(v) == {"__obj__"}:
            return _Obj(**{k: from_wire(x) for k, x in v["__obj__"].items()})
        return {k: from_wire(x) for k, x in v.items()}
    if isinstance(v, list):
        return [from_wire(x) for x in v]
    return v


class StubApiServer:
    """The stub apiserver behind a real HTTP listener.

    * ``POST /call`` — one API method call: ``{"api": "core|batch|apps|
      custom", "method": ..., "args": [...], "kwargs": {...}}`` → 200
      ``{"result": ...}``; an :class:`ApiException` maps to its real
      HTTP status with ``{"error": {"status", "reason"}}`` in the body.
    * ``GET /watch?resource_version=N&timeout_seconds=T`` — the custom-
      object watch as a line-delimited JSON stream, flushed per event,
      closed at the server-side timeout; a compacted rv answers 410
      before any event flows (etcd semantics, now with a status line).
    """

    def __init__(self, state: StubState) -> None:
        import http.server
        import json
        import threading

        apis = {"core": _CoreV1Api(state), "batch": _BatchV1Api(state),
                "apps": _AppsV1Api(state), "custom": _CustomObjectsApi(state)}

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # keep test output clean
                pass

            def _json(self, status: int, doc: dict) -> None:
                body = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                if self.path != "/call":
                    self._json(404, {"error": {"status": 404,
                                               "reason": self.path}})
                    return
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n).decode())
                api = apis.get(req.get("api"))
                method = getattr(api, req.get("method", ""), None)
                if api is None or method is None:
                    self._json(404, {"error": {
                        "status": 404,
                        "reason": f"{req.get('api')}.{req.get('method')}"}})
                    return
                try:
                    result = method(*from_wire(req.get("args") or []),
                                    **from_wire(req.get("kwargs") or {}))
                except ApiException as exc:
                    self._json(exc.status, {"error": {
                        "status": exc.status, "reason": exc.reason}})
                    return
                except Exception as exc:  # stub bug: surface it loudly
                    self._json(500, {"error": {"status": 500,
                                               "reason": repr(exc)}})
                    return
                self._json(200, {"result": to_wire(result)})

            def do_GET(self) -> None:
                import time
                import urllib.parse

                parsed = urllib.parse.urlparse(self.path)
                if parsed.path != "/watch":
                    self._json(404, {"error": {"status": 404,
                                               "reason": self.path}})
                    return
                q = urllib.parse.parse_qs(parsed.query)
                rv = int((q.get("resource_version") or ["0"])[0] or 0)
                timeout = float((q.get("timeout_seconds") or ["30"])[0])
                if rv < state.custom_compacted_rv:
                    self._json(410, {"error": {
                        "status": 410,
                        "reason": "too old resource version (compacted)"}})
                    return
                # stream: headers now, one JSON line per event, flushed —
                # HTTP/1.0 connection-close delimits the body, so the
                # client sees the stream end exactly at the timeout
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                deadline = time.monotonic() + timeout
                try:
                    while time.monotonic() < deadline:
                        for erv, typ, obj in list(state.custom_events):
                            if erv > rv:
                                rv = erv
                                line = json.dumps(
                                    {"type": typ, "object": obj})
                                self.wfile.write(line.encode() + b"\n")
                                self.wfile.flush()
                        time.sleep(0.01)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # watcher hung up (Watch.stop)

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="stub-apiserver", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class _HTTPApi:
    """Client-side proxy: every attribute is a method that POSTs the
    call over the wire and raises :class:`ApiException` on an API-error
    status, exactly as the real kubernetes client surfaces them."""

    def __init__(self, base_url: str, api: str) -> None:
        self._base = base_url
        self._api = api

    def __getattr__(self, method: str):
        import json
        import urllib.error
        import urllib.request

        def call(*args, **kwargs):
            body = json.dumps({"api": self._api, "method": method,
                               "args": to_wire(list(args)),
                               "kwargs": to_wire(kwargs)}).encode()
            req = urllib.request.Request(
                self._base + "/call", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return from_wire(json.loads(r.read().decode()
                                                ).get("result"))
            except urllib.error.HTTPError as exc:
                try:
                    err = json.loads(exc.read().decode()).get("error") or {}
                except ValueError:
                    err = {}
                raise ApiException(err.get("status", exc.code),
                                   err.get("reason", "")) from None

        return call


class _HTTPWatch:
    """Client half of the watch stream: a chunk-at-a-time GET whose
    line-delimited events are yielded as they arrive on the socket."""

    def __init__(self, base_url: str) -> None:
        self._base = base_url
        self._resp = None
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True
        resp = self._resp
        if resp is not None:
            try:
                resp.close()
            except OSError:
                pass

    def stream(self, func, *args, resource_version="0",
               timeout_seconds=30, **kwargs):
        import json
        import urllib.error
        import urllib.request

        url = (f"{self._base}/watch?resource_version={resource_version}"
               f"&timeout_seconds={timeout_seconds}")
        try:
            # socket inactivity timeout ABOVE the server-side window: the
            # server closing the stream at its timeout is the normal end
            self._resp = urllib.request.urlopen(
                url, timeout=float(timeout_seconds) + 10)
        except urllib.error.HTTPError as exc:
            try:
                err = json.loads(exc.read().decode()).get("error") or {}
            except ValueError:
                err = {}
            raise ApiException(err.get("status", exc.code),
                               err.get("reason", "")) from None
        try:
            for line in self._resp:
                if self._stopped:
                    break
                line = line.strip()
                if line:
                    yield json.loads(line.decode())
        except OSError:
            if not self._stopped:
                raise
        finally:
            # generator close (K8sCluster.watch's finally → w.stop(), or
            # a bare stream.close()) must release the socket NOW — not
            # at GC — and end the server handler's streaming loop
            # instead of leaving it writing until its timeout
            self.stop()


def build_http_module(base_url: str) -> types.ModuleType:
    """A ``kubernetes``-shaped module whose every API call crosses real
    sockets to a :class:`StubApiServer` (same attribute surface as
    :func:`build_module`)."""
    kubernetes = types.ModuleType("kubernetes")
    client = types.ModuleType("kubernetes.client")
    config = types.ModuleType("kubernetes.config")
    exceptions = types.ModuleType("kubernetes.client.exceptions")
    watch = types.ModuleType("kubernetes.watch")

    exceptions.ApiException = ApiException
    client.exceptions = exceptions
    client.CoreV1Api = lambda: _HTTPApi(base_url, "core")
    client.BatchV1Api = lambda: _HTTPApi(base_url, "batch")
    client.AppsV1Api = lambda: _HTTPApi(base_url, "apps")
    client.CustomObjectsApi = lambda: _HTTPApi(base_url, "custom")
    config.load_kube_config = lambda *_a, **_k: None
    config.load_incluster_config = lambda: None
    watch.Watch = lambda: _HTTPWatch(base_url)
    kubernetes.client = client
    kubernetes.config = config
    kubernetes.watch = watch
    return kubernetes


def build_module(state: StubState) -> types.ModuleType:
    """A module object that satisfies every ``kubernetes.*`` attribute
    K8sCluster touches."""
    kubernetes = types.ModuleType("kubernetes")
    client = types.ModuleType("kubernetes.client")
    config = types.ModuleType("kubernetes.config")
    exceptions = types.ModuleType("kubernetes.client.exceptions")
    watch = types.ModuleType("kubernetes.watch")

    exceptions.ApiException = ApiException
    client.exceptions = exceptions
    client.CoreV1Api = lambda: _CoreV1Api(state)
    client.BatchV1Api = lambda: _BatchV1Api(state)
    client.AppsV1Api = lambda: _AppsV1Api(state)
    client.CustomObjectsApi = lambda: _CustomObjectsApi(state)
    config.load_kube_config = lambda *_a, **_k: None
    config.load_incluster_config = lambda: None
    watch.Watch = lambda: _Watch(state)
    kubernetes.client = client
    kubernetes.config = config
    kubernetes.watch = watch
    return kubernetes
