"""In-memory stub of the ``kubernetes`` client package.

Role of the reference's generated fake clientset
(reference pkg/client/clientset/versioned/fake/fake_trainingjob.go:29-124):
an object-tracker-backed API surface so the real :class:`K8sCluster` method
bodies execute in tests without an apiserver.  The stub models exactly what
those bodies touch — typed nodes/pods with attribute access, batch Jobs with
resourceVersion semantics (including 409 on stale replaces), ReplicaSets and
Services — plus a conflict-injection hook for the autoscaler's retry path.

Install with :func:`install` (returns the shared state) and pass
``sys.modules`` patching to the ``stub_kubernetes`` fixture in
tests/test_k8s_cluster.py; nothing here imports edl_tpu.
"""

from __future__ import annotations

import copy
import functools
import pathlib
import types
from dataclasses import dataclass, field
from typing import Any, Optional

#: The CRD manifest the stub enforces — the SHIPPED one, so a schema/docs
#: mismatch is caught by tests instead of surfacing as silent field loss on
#: a real cluster (round-3 verdict weak #1: the stub stored dicts verbatim,
#: which is exactly why the kebab-case pruning bug was untestable).
CRD_PATH = pathlib.Path(__file__).resolve().parent.parent / "k8s" / "crd.yaml"


def prune_per_schema(value: Any, schema: Any) -> Any:
    """Structural-schema pruning, as a conformant apiserver performs on
    admission: object fields not declared in ``properties`` are silently
    dropped unless the schema opts out with
    ``x-kubernetes-preserve-unknown-fields``.  An object value whose schema
    declares neither ``properties`` nor ``additionalProperties`` loses ALL
    its fields — that default matters, because keeping them would hide
    exactly the schema-drift class this stub exists to catch."""
    if not isinstance(schema, dict):
        # no schema at this node at all → everything below is unspecified
        return {} if isinstance(value, dict) else value
    if schema.get("x-kubernetes-preserve-unknown-fields"):
        return value
    if isinstance(value, dict):
        props = schema.get("properties")
        if props is not None:
            return {k: prune_per_schema(v, props[k])
                    for k, v in value.items() if k in props}
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            return {k: prune_per_schema(v, addl) for k, v in value.items()}
        if addl:  # additionalProperties: true
            return value
        return {}
    if isinstance(value, list):
        return [prune_per_schema(v, schema.get("items")) for v in value]
    return value


@functools.lru_cache(maxsize=None)
def load_crd_schemas(path: pathlib.Path = CRD_PATH) -> dict:
    """(group, plural) → served-version openAPIV3Schema from a CRD manifest."""
    import yaml

    out: dict = {}
    if not path.exists():  # pragma: no cover - repo layout changed
        return out
    for doc in yaml.safe_load_all(path.read_text()):
        if not doc or doc.get("kind") != "CustomResourceDefinition":
            continue
        spec = doc.get("spec") or {}
        group = spec.get("group", "")
        plural = (spec.get("names") or {}).get("plural", "")
        for v in spec.get("versions") or []:
            if v.get("served"):
                schema = (v.get("schema") or {}).get("openAPIV3Schema")
                if schema:
                    out[(group, plural)] = schema
    return out


class ApiException(Exception):
    def __init__(self, status: int, reason: str = ""):
        super().__init__(f"({status}) {reason}")
        self.status = status
        self.reason = reason


class _Obj:
    """Attribute bag with dict-style construction (role of the kubernetes
    client's typed models, which the real code reads via attributes)."""

    def __init__(self, **kw: Any):
        for k, v in kw.items():
            setattr(self, k, v)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"_Obj({self.__dict__!r})"


def make_node(name: str, cpu: str = "8", memory: str = "16Gi",
              tpu: int = 0, labels: Optional[dict] = None) -> _Obj:
    alloc = {"cpu": cpu, "memory": memory}
    if tpu:
        alloc["google.com/tpu"] = str(tpu)
    return _Obj(
        metadata=_Obj(name=name, labels=dict(labels or {})),
        status=_Obj(allocatable=alloc),
    )


def make_pod(name: str, namespace: str = "default", phase: str = "Running",
             node: Optional[str] = None, labels: Optional[dict] = None,
             cpu: str = "0", memory: str = "0", tpu: int = 0,
             terminating: bool = False) -> _Obj:
    limits = {"cpu": cpu, "memory": memory}
    if tpu:
        limits["google.com/tpu"] = str(tpu)
    container = _Obj(resources=_Obj(
        requests={"cpu": cpu, "memory": memory}, limits=limits))
    return _Obj(
        metadata=_Obj(name=name, namespace=namespace,
                      labels=dict(labels or {}),
                      deletion_timestamp=("now" if terminating else None)),
        spec=_Obj(node_name=node, containers=[container],
                  init_containers=[]),
        status=_Obj(phase=phase),
    )


@dataclass
class StubState:
    """The 'etcd' behind the stub apiserver."""

    nodes: list = field(default_factory=list)
    pods: list = field(default_factory=list)
    #: (namespace, name) → Job object (spec.parallelism,
    #: metadata.resource_version as int, metadata.labels)
    jobs: dict = field(default_factory=dict)
    replicasets: dict = field(default_factory=dict)
    services: dict = field(default_factory=dict)
    #: (group, namespace, plural, name) → custom-object dict (the
    #: TrainingJob CR store; role of the reference's object-tracker-backed
    #: fake clientset, pkg/client/.../fake/fake_trainingjob.go:29-124)
    custom_objects: dict = field(default_factory=dict)
    #: (group, plural) → structural schema, enforced (pruning) on custom-
    #: object create/replace/status-patch exactly as a real apiserver would
    crd_schemas: dict = field(default_factory=load_crd_schemas)
    #: next N replace_namespaced_job calls fail 409 (concurrent-writer
    #: simulation for the ConflictError mapping test)
    conflicts_to_inject: int = 0
    #: monotonic collection resourceVersion for custom objects; every
    #: mutation bumps it and appends to the event log the watch serves
    custom_rv: int = 0
    #: [(rv, "ADDED"|"MODIFIED"|"DELETED", object snapshot)]
    custom_events: list = field(default_factory=list)
    #: events at/below this rv have been compacted away — a watch asking
    #: to resume below it gets 410 Gone (etcd compaction semantics)
    custom_compacted_rv: int = 0

    def record_custom_event(self, typ: str, obj: dict) -> None:
        self.custom_rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.custom_rv)
        self.custom_events.append((self.custom_rv, typ, copy.deepcopy(obj)))

    def compact_custom_events(self) -> None:
        """Simulate etcd compaction: the watch window is gone; resuming
        from any pre-compaction rv must 410 (the informer's re-list path)."""
        self.custom_compacted_rv = self.custom_rv
        self.custom_events.clear()

    # mutation helpers the real apiserver would do itself
    def put_job(self, namespace: str, name: str, parallelism: int,
                labels: Optional[dict] = None) -> None:
        self.jobs[(namespace, name)] = _Obj(
            metadata=_Obj(name=name, namespace=namespace,
                          labels=dict(labels or {}), resource_version=1),
            spec=_Obj(parallelism=parallelism),
        )


class _CoreV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def list_node(self):
        return _Obj(items=list(self._s.nodes))

    def list_pod_for_all_namespaces(self, field_selector: str = ""):
        items = self._s.pods
        if "status.phase!=Succeeded" in (field_selector or ""):
            items = [p for p in items
                     if p.status.phase not in ("Succeeded", "Failed")]
        return _Obj(items=list(items))

    def list_namespaced_pod(self, namespace: str,
                            label_selector: Optional[str] = None):
        items = [p for p in self._s.pods if p.metadata.namespace == namespace]
        if label_selector:
            key, _, value = label_selector.partition("=")
            items = [p for p in items
                     if (p.metadata.labels or {}).get(key) == value
                     or (not value and key in (p.metadata.labels or {}))]
        return _Obj(items=items)

    def create_namespaced_service(self, namespace: str, manifest: dict):
        self._s.services[(namespace, manifest["metadata"]["name"])] = manifest

    def delete_namespaced_service(self, name: str, namespace: str):
        if (namespace, name) not in self._s.services:
            raise ApiException(404, f"service {name}")
        del self._s.services[(namespace, name)]


class _BatchV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def _get(self, namespace: str, name: str) -> _Obj:
        try:
            return self._s.jobs[(namespace, name)]
        except KeyError:
            raise ApiException(404, f"job {name}") from None

    def read_namespaced_job(self, name: str, namespace: str) -> _Obj:
        # a fresh copy each read: mutating the returned object must not
        # write through to the 'server' (the real client deserializes)
        return copy.deepcopy(self._get(namespace, name))

    def replace_namespaced_job(self, name: str, namespace: str, body: _Obj):
        if self._s.conflicts_to_inject > 0:
            self._s.conflicts_to_inject -= 1
            # a concurrent writer bumped the version since our read
            cur = self._get(namespace, name)
            cur.metadata.resource_version += 1
            raise ApiException(409, "resourceVersion conflict")
        cur = self._get(namespace, name)
        if body.metadata.resource_version != cur.metadata.resource_version:
            raise ApiException(409, "resourceVersion conflict")
        body = copy.deepcopy(body)
        body.metadata.resource_version += 1
        self._s.jobs[(namespace, name)] = body

    def create_namespaced_job(self, namespace: str, manifest: dict):
        name = manifest["metadata"]["name"]
        if (namespace, name) in self._s.jobs:
            raise ApiException(409, f"job {name} exists")
        self._s.put_job(namespace, name,
                        manifest["spec"].get("parallelism", 0),
                        manifest["metadata"].get("labels"))

    def list_namespaced_job(self, namespace: str):
        return _Obj(items=[j for (ns, _), j in self._s.jobs.items()
                           if ns == namespace])

    def list_job_for_all_namespaces(self):
        return _Obj(items=list(self._s.jobs.values()))

    def delete_namespaced_job(self, name: str, namespace: str,
                              propagation_policy: str = ""):
        if (namespace, name) not in self._s.jobs:
            raise ApiException(404, f"job {name}")
        del self._s.jobs[(namespace, name)]


class _CustomObjectsApi:
    """CRD verbs the real K8sCluster CR methods touch.  Custom objects are
    plain dicts, as in the real kubernetes client."""

    def __init__(self, state: StubState):
        self._s = state

    def _key(self, group, namespace, plural, name):
        return (group, namespace, plural, name)

    def _admit(self, group: str, plural: str, body: dict) -> dict:
        """Apiserver admission: prune spec/status per the structural schema
        (apiVersion/kind/metadata are typed fields, kept as-is)."""
        schema = self._s.crd_schemas.get((group, plural))
        obj = copy.deepcopy(body)
        if schema is not None:
            props = schema.get("properties") or {}
            for section in ("spec", "status"):
                if section in obj:
                    obj[section] = prune_per_schema(
                        obj[section], props.get(section, {}))
        return obj

    def create_namespaced_custom_object(self, group, version, namespace,
                                        plural, body):
        name = (body.get("metadata") or {}).get("name", "")
        key = self._key(group, namespace, plural, name)
        if key in self._s.custom_objects:
            raise ApiException(409, f"{plural} {name} exists")
        obj = self._admit(group, plural, body)
        obj.setdefault("metadata", {})
        obj["metadata"].setdefault("namespace", namespace)
        obj["metadata"]["generation"] = 1
        self._s.record_custom_event("ADDED", obj)
        self._s.custom_objects[key] = obj
        return copy.deepcopy(obj)

    def list_namespaced_custom_object(self, group, version, namespace,
                                      plural):
        items = [copy.deepcopy(o)
                 for (g, ns, pl, _), o in sorted(self._s.custom_objects.items())
                 if (g, ns, pl) == (group, namespace, plural)]
        return {"items": items,
                "metadata": {"resourceVersion": str(self._s.custom_rv)}}

    def list_cluster_custom_object(self, group, version, plural):
        items = [copy.deepcopy(o)
                 for (g, _, pl, _), o in sorted(self._s.custom_objects.items())
                 if (g, pl) == (group, plural)]
        return {"items": items,
                "metadata": {"resourceVersion": str(self._s.custom_rv)}}

    def get_namespaced_custom_object(self, group, version, namespace,
                                     plural, name):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        return copy.deepcopy(self._s.custom_objects[key])

    def replace_namespaced_custom_object(self, group, version, namespace,
                                         plural, name, body):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        old = self._s.custom_objects[key]
        obj = self._admit(group, plural, body)
        obj.setdefault("metadata", {})
        gen = (old.get("metadata") or {}).get("generation", 1)
        # the apiserver bumps generation only on spec change (status
        # subresource writes go through patch_..._status below)
        if obj.get("spec") != old.get("spec"):
            gen += 1
        obj["metadata"]["generation"] = gen
        obj.setdefault("status", copy.deepcopy(old.get("status") or {}))
        self._s.record_custom_event("MODIFIED", obj)
        self._s.custom_objects[key] = obj
        return copy.deepcopy(obj)

    def patch_namespaced_custom_object_status(self, group, version,
                                              namespace, plural, name, body):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        obj = self._s.custom_objects[key]
        obj["status"] = self._admit(group, plural,
                                    {"status": (body or {}).get("status")
                                     or {}}).get("status", {})
        self._s.record_custom_event("MODIFIED", obj)
        return copy.deepcopy(obj)

    def delete_namespaced_custom_object(self, group, version, namespace,
                                        plural, name):
        key = self._key(group, namespace, plural, name)
        if key not in self._s.custom_objects:
            raise ApiException(404, f"{plural} {name}")
        self._s.record_custom_event("DELETED", self._s.custom_objects[key])
        del self._s.custom_objects[key]


class _AppsV1Api:
    def __init__(self, state: StubState):
        self._s = state

    def create_namespaced_replica_set(self, namespace: str, manifest: dict):
        self._s.replicasets[(namespace, manifest["metadata"]["name"])] = manifest

    def delete_namespaced_replica_set(self, name: str, namespace: str,
                                      propagation_policy: str = ""):
        if (namespace, name) not in self._s.replicasets:
            raise ApiException(404, f"replicaset {name}")
        del self._s.replicasets[(namespace, name)]


class _Watch:
    """Role of ``kubernetes.watch.Watch`` for the custom-object
    collection: replays the event log past ``resource_version``, then
    tails it until ``timeout_seconds`` (the server-side watch timeout the
    real apiserver enforces).  A resume rv at/below the compaction point
    raises 410 Gone, as etcd compaction does."""

    def __init__(self, state: StubState):
        self._s = state
        self._stopped = False

    def stop(self) -> None:
        self._stopped = True

    def stream(self, func, *args, resource_version="0",
               timeout_seconds=30, **kwargs):
        import time

        rv = int(resource_version or 0)
        if rv < self._s.custom_compacted_rv:
            raise ApiException(410, "too old resource version (compacted)")
        deadline = time.monotonic() + float(timeout_seconds)
        while not self._stopped and time.monotonic() < deadline:
            for erv, typ, obj in list(self._s.custom_events):
                if erv > rv:
                    rv = erv
                    yield {"type": typ, "object": copy.deepcopy(obj)}
            time.sleep(0.01)


def build_module(state: StubState) -> types.ModuleType:
    """A module object that satisfies every ``kubernetes.*`` attribute
    K8sCluster touches."""
    kubernetes = types.ModuleType("kubernetes")
    client = types.ModuleType("kubernetes.client")
    config = types.ModuleType("kubernetes.config")
    exceptions = types.ModuleType("kubernetes.client.exceptions")
    watch = types.ModuleType("kubernetes.watch")

    exceptions.ApiException = ApiException
    client.exceptions = exceptions
    client.CoreV1Api = lambda: _CoreV1Api(state)
    client.BatchV1Api = lambda: _BatchV1Api(state)
    client.AppsV1Api = lambda: _AppsV1Api(state)
    client.CustomObjectsApi = lambda: _CustomObjectsApi(state)
    config.load_kube_config = lambda *_a, **_k: None
    config.load_incluster_config = lambda: None
    watch.Watch = lambda: _Watch(state)
    kubernetes.client = client
    kubernetes.config = config
    kubernetes.watch = watch
    return kubernetes
