"""Elastic runtime units: mesh construction, trainer resize/reshard,
task-lease data, checkpoint restore across mesh sizes.

Runs on the virtual 8-device CPU mesh (tests/conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from edl_tpu.coord import PyCoordService
from edl_tpu.models import mlp
from edl_tpu.parallel.mesh import MeshSpec, dp_sharding, make_mesh, tree_shardings
from edl_tpu.runtime.checkpoint import ElasticCheckpointer
from edl_tpu.runtime.data import ShardRegistry, TaskLeaseBatches
from edl_tpu.runtime.elastic import ElasticTrainer


def synthetic_classification(n=512, dim=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3
    y = rng.integers(0, classes, size=n)
    x = centers[y] + rng.normal(size=(n, dim))
    return x.astype(np.float32), y.astype(np.int32)


# -- mesh --------------------------------------------------------------------


def test_make_mesh_prefix_and_axes():
    m = make_mesh(4, MeshSpec(dp=-1))
    assert m.size == 4 and m.shape["dp"] == 4
    m2 = make_mesh(8, MeshSpec(dp=2, tp=-1))
    assert m2.shape["dp"] == 2 and m2.shape["tp"] == 4


def test_mesh_spec_validation():
    with pytest.raises(ValueError):
        make_mesh(6, MeshSpec(dp=4))  # wants exactly 4
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=-1).resolve(8)  # two wildcards
    with pytest.raises(ValueError):
        make_mesh(99)  # more than available


def test_fsdp_sharding_picks_divisible_dim():
    m = make_mesh(8, MeshSpec(dp=1, fsdp=-1))
    params = {"w": jnp.zeros((16, 10)), "b": jnp.zeros((3,))}
    sh = tree_shardings(m, params, "fsdp")
    assert sh["w"].spec == jax.sharding.PartitionSpec("fsdp", None)
    assert sh["b"].spec == jax.sharding.PartitionSpec()  # 3 not divisible


# -- elastic trainer ---------------------------------------------------------


def make_trainer(n0=2, kind="replicated", spec=None):
    params = mlp.init(jax.random.key(0), [16, 32, 4])
    return ElasticTrainer(
        mlp.loss_fn, params, optax.adam(1e-2),
        spec=spec or MeshSpec(dp=-1),
        param_sharding=kind, initial_world_size=n0,
    )


def test_training_reduces_loss():
    x, y = synthetic_classification()
    t = make_trainer(n0=2)
    first = t.step((x[:64], y[:64]))
    for i in range(30):
        lo = (i * 64) % 448
        t.step((x[lo:lo + 64], y[lo:lo + 64]))
    assert t.eval_loss((x, y)) < first * 0.7


def test_resize_mid_training_preserves_state_and_learning():
    x, y = synthetic_classification()
    t = make_trainer(n0=2)
    for i in range(10):
        lo = (i * 64) % 448
        t.step((x[lo:lo + 64], y[lo:lo + 64]))
    loss_before = t.eval_loss((x, y))
    step_before = t.state.step

    t.resize(8)  # grow 2 → 8
    assert t.world_size == 8
    # state survives byte-for-byte: eval loss unchanged after reshard
    assert abs(t.eval_loss((x, y)) - loss_before) < 1e-5
    assert t.state.step == step_before

    for i in range(20):
        lo = (i * 64) % 448
        t.step((x[lo:lo + 64], y[lo:lo + 64]))
    assert t.eval_loss((x, y)) < loss_before

    t.resize(4)  # shrink 8 → 4 keeps learning too
    loss_8 = t.eval_loss((x, y))
    for i in range(10):
        lo = (i * 64) % 448
        t.step((x[lo:lo + 64], y[lo:lo + 64]))
    assert t.eval_loss((x, y)) <= loss_8 * 1.05
    assert t.resizes == 2


def test_fsdp_trainer_matches_replicated():
    x, y = synthetic_classification(n=256)
    t_rep = make_trainer(n0=4)
    t_fsdp = make_trainer(n0=4, kind="fsdp", spec=MeshSpec(dp=1, fsdp=-1))
    for i in range(5):
        lo = i * 32
        l1 = t_rep.step((x[lo:lo + 32], y[lo:lo + 32]))
        l2 = t_fsdp.step((x[lo:lo + 32], y[lo:lo + 32]))
        assert abs(l1 - l2) < 1e-4  # same math, different layout


def test_step_cache_no_recompile_on_oscillation():
    t = make_trainer(n0=2)
    x, y = synthetic_classification(n=128)
    t.step((x[:64], y[:64]))
    t.resize(4)
    t.step((x[:64], y[:64]))
    t.resize(2)
    t.resize(4)
    # keyed by (size, device ids): oscillation reuses both entries
    assert {k[0] for k in t._step_cache} == {2, 4}
    assert len(t._step_cache) == 2


def test_step_cache_hit_reuses_exact_mesh_and_shardings():
    """Resize down then back up: the cache hit must hand back shardings
    bound to the SAME Mesh object the cached step function was compiled
    against — size-only keying rebuilt 'equal' shardings over a fresh
    Mesh and trained through a stale-mesh executable."""
    t = make_trainer(n0=4)
    x, y = synthetic_classification(n=128)
    t.step((x[:64], y[:64]))
    first_mesh = t.mesh
    first_shardings = t._param_shardings
    t.resize(2)
    assert t.mesh is not first_mesh
    t.resize(4)  # back to a previously-seen size → cache hit
    assert t.mesh is first_mesh
    assert t._param_shardings is first_shardings
    # every staged sharding really is bound to the live mesh
    import jax

    for sh in jax.tree.leaves(t._param_shardings):
        assert sh.mesh is t.mesh
    loss = t.step((x[:64], y[:64]))  # and it still trains
    assert loss == loss  # not NaN


def test_resize_failure_rolls_back_and_keeps_training(monkeypatch):
    """Transactional resize: a device_put failure mid-resize (the OOM
    shape) leaves the previous mesh fully live — the trainer keeps
    stepping, the failure is counted, and a later retry succeeds."""
    from edl_tpu.observability.collector import get_counters
    from edl_tpu.runtime import elastic as elastic_mod

    t = make_trainer(n0=4)
    x, y = synthetic_classification(n=128)
    l0 = t.step((x[:64], y[:64]))
    before_mesh = t.mesh
    before_failed = get_counters().get("resizes_failed")

    calls = []
    real = elastic_mod._reshard

    def failing_reshard(tree, shardings):
        calls.append(1)
        if len(calls) == 2:  # params staged OK, opt-state put blows up
            raise RuntimeError("injected: RESOURCE_EXHAUSTED during reshard")
        return real(tree, shardings)

    monkeypatch.setattr(elastic_mod, "_reshard", failing_reshard)
    assert t.resize(8) is False
    assert t.mesh is before_mesh and t.world_size == 4  # rolled back
    assert t.resizes_failed == 1 and t.resizes == 0
    assert get_counters().get("resizes_failed") == before_failed + 1
    # the old world still trains — state was never half-moved
    l1 = t.step((x[:64], y[:64]))
    assert np.isfinite(l1) and l1 <= l0 * 2
    # and the retry (injection cleared) commits normally
    monkeypatch.setattr(elastic_mod, "_reshard", real)
    assert t.resize(8) is True
    assert t.world_size == 8 and t.resizes == 1
    assert np.isfinite(t.step((x[:64], y[:64])))


def test_resize_compile_failure_rolls_back(monkeypatch):
    """A compile error while staging the new world must also roll back
    (and must NOT poison the step cache for the retry)."""
    t = make_trainer(n0=4)
    x, y = synthetic_classification(n=128)
    t.step((x[:64], y[:64]))

    def exploding_compile(bundle):
        raise RuntimeError("injected: XLA compile failed")

    monkeypatch.setattr(t, "_compile_step", exploding_compile)
    assert t.resize(2) is False
    assert t.world_size == 4 and t.resizes_failed == 1
    assert {k[0] for k in t._step_cache} == {4}  # no poisoned entry
    monkeypatch.undo()
    assert t.resize(2) is True
    assert np.isfinite(t.step((x[:64], y[:64])))


# -- task-lease data ---------------------------------------------------------


def test_task_lease_batches_cover_dataset_once():
    coord = PyCoordService()
    reg = ShardRegistry()
    x, y = synthetic_classification(n=256)
    reg.add_arrays(coord, (x, y), num_shards=8)
    seen = 0
    for bx, by in TaskLeaseBatches(coord, "w0", reg.fetch, batch_size=32):
        assert bx.shape == (32, 16)
        seen += bx.shape[0]
    assert seen == 256
    assert coord.all_done()


def test_task_lease_batches_two_workers_partition_work():
    import threading

    coord = PyCoordService()
    reg = ShardRegistry()
    x, y = synthetic_classification(n=256)
    reg.add_arrays(coord, (x, y), num_shards=8)
    counts = {"w0": 0, "w1": 0}

    def run(w):
        for bx, _ in TaskLeaseBatches(coord, w, reg.fetch, batch_size=32,
                                      poll_seconds=0.005):
            counts[w] += bx.shape[0]

    threads = [threading.Thread(target=run, args=(w,)) for w in counts]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Dynamic leasing guarantees exactly-once coverage, not an even split —
    # a fast worker may legitimately drain every shard.
    assert counts["w0"] + counts["w1"] == 256
    assert coord.all_done()


def test_stale_worker_completion_rejected_after_redispatch():
    # A straggler's late complete() must not void the new holder's lease.
    clock_ms = [0]
    coord = PyCoordService(task_timeout_ms=16_000, clock=lambda: clock_ms[0])
    coord.add_task(b"t")
    _, tid, _ = coord.lease("straggler")
    clock_ms[0] += 16_001
    status, tid2, _ = coord.lease("fresh")
    assert tid2 == tid
    assert not coord.complete(tid, "straggler")  # rejected: lease moved
    assert coord.complete(tid2, "fresh")
    assert coord.all_done()


def test_dead_worker_shard_is_redispatched():
    clock_ms = [1_000_000]
    coord = PyCoordService(task_timeout_ms=16_000, clock=lambda: clock_ms[0])
    reg = ShardRegistry()
    x, y = synthetic_classification(n=64)
    reg.add_arrays(coord, (x, y), num_shards=2)
    # dead worker leases a shard and vanishes
    status, tid, _ = coord.lease("dead")
    # the 16 s re-dispatch bound (reference paddle_k8s:30)
    clock_ms[0] += 16_001
    seen = 0
    for bx, _ in TaskLeaseBatches(coord, "alive", reg.fetch, batch_size=32):
        seen += bx.shape[0]
    assert seen == 64  # nothing lost
    assert coord.all_done()


# -- checkpoint across mesh sizes --------------------------------------------


def test_checkpoint_restore_onto_different_mesh(tmp_path):
    x, y = synthetic_classification(n=128)
    t = make_trainer(n0=2)
    for i in range(5):
        t.step((x[:64], y[:64]))
    loss = t.eval_loss((x, y))

    ckpt = ElasticCheckpointer(tmp_path / "ckpt")
    ckpt.save(t.state.step, {"params": t.state.params,
                             "opt_state": t.state.opt_state})

    # fresh trainer on a DIFFERENT mesh size restores the state
    t2 = make_trainer(n0=8)
    restored = ckpt.restore(
        {"params": t2.state.params, "opt_state": t2.state.opt_state}
    )
    t2.state.params = restored["params"]
    t2.state.opt_state = restored["opt_state"]
    assert abs(t2.eval_loss((x, y)) - loss) < 1e-5
    # and keeps training
    l0 = t2.eval_loss((x, y))
    for i in range(10):
        t2.step((x[:64], y[:64]))
    assert t2.eval_loss((x, y)) < l0
    ckpt.close()


def test_file_shard_store_round_trip(tmp_path):
    """Shard files on storage (the reference's RecordIO chunks): write
    once, lease file payloads, stream back exactly the original rows."""
    import json

    import numpy as np

    from edl_tpu.coord.service import PyCoordService
    from edl_tpu.runtime.data import (FileShardStore, ShardRegistry,
                                      fetch_payload)

    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.float32)
    paths = FileShardStore.write_shards(str(tmp_path), (x, y), 3)
    assert len(paths) == 3 and all(p.endswith(".npz") for p in paths)
    coord = PyCoordService()
    FileShardStore.enqueue(coord, paths)
    rows = []
    while True:
        status, tid, payload = coord.lease("w0")
        if status.name != "OK":
            break
        sx, sy = fetch_payload(payload)
        assert sx.shape[0] == sy.shape[0]
        rows.extend(sy.tolist())
        coord.complete(tid, "w0")
    assert sorted(rows) == y.tolist()  # every row exactly once
    # dispatch still resolves in-memory payloads through the registry
    reg = ShardRegistry()
    reg.register_arrays((x, y), 2)
    got = fetch_payload(json.dumps({"shard": 0}).encode(), registry=reg)
    assert got[0].shape[0] == 5


def test_ensure_seeded_survives_dead_seeder():
    """The seeding claim is renewable and takeover-able: a seeder that
    died after claiming (even mid-dataset-write) cannot hang the job —
    a live worker takes the stale claim over and seeds idempotently."""
    from edl_tpu.coord.service import PyCoordService
    from edl_tpu.runtime.data import ensure_seeded

    coord = PyCoordService()
    seeded_by = []

    def seed(name):
        def fn(beat):
            beat()  # liveness renewal during the 'write'
            coord.add_task(b"t0")
            coord.add_task(b"t1")
            seeded_by.append(name)
        return fn

    # w0 claims then DIES before enqueueing anything (stale marker,
    # untouched queue)
    assert coord.kv_cas("data-seeder", b"", b"seeding:w0:0")
    ensure_seeded(coord, "w1", seed("w1"), stale_ms=1, poll_s=0.01)
    assert seeded_by == ["w1"]
    assert coord.kv_get("data-seeder") == b"seeded"
    s = coord.stats()
    assert s.todo == 2
    # later joiners see 'seeded' and do nothing
    ensure_seeded(coord, "w2", seed("w2"))
    assert seeded_by == ["w1"]


def test_ensure_seeded_does_not_steal_live_claim():
    """A FRESH claim (the seeder is alive, mid-write) must not be taken
    over; the waiter blocks until the flip."""
    import threading
    import time

    from edl_tpu.coord.service import PyCoordService
    from edl_tpu.runtime.data import ensure_seeded

    coord = PyCoordService()
    now = int(time.time() * 1000)
    assert coord.kv_cas("data-seeder", b"", f"seeding:w0:{now}".encode())
    stolen = []
    t = threading.Thread(
        target=lambda: (ensure_seeded(coord, "w1",
                                      lambda beat: stolen.append(1),
                                      stale_ms=60_000, poll_s=0.01)),
        daemon=True)
    t.start()
    time.sleep(0.2)
    assert not stolen and t.is_alive()  # waiting, not stealing
    coord.kv_set("data-seeder", b"seeded")  # the live seeder finishes
    t.join(timeout=5)
    assert not t.is_alive() and not stolen


def test_prune_generations(tmp_path):
    """Old state generations (files, Orbax dirs, KV pointers, per-epoch
    claims) are GC'd past the keep window; recent ones and 'final' stay."""
    import os

    from edl_tpu.coord.service import PyCoordService
    from edl_tpu.runtime.multihost import prune_generations

    coord = PyCoordService()
    for gen in range(1, 9):
        coord.kv_set(f"ckpt/{gen}", f"gen-{gen}".encode())
        coord.kv_set(f"ckpt-writer/{gen}", b"w0")
        coord.kv_set(f"jax-coordinator/{gen}", b"h:1")
        (tmp_path / f"gen-{gen}.npz").write_bytes(b"x")
        (tmp_path / f"result-w0-{gen}.json").write_text("{}")
    os.makedirs(tmp_path / "gen-2" / "0")  # an Orbax-style gen dir
    (tmp_path / "final.npz").write_bytes(b"x")

    pruned = prune_generations(coord, str(tmp_path), upto_gen=8, keep=3)
    assert pruned > 0
    kept = set(p.name for p in tmp_path.iterdir())
    assert "final.npz" in kept
    # exactly the `keep` newest generations survive
    assert {"gen-6.npz", "gen-7.npz", "gen-8.npz"} <= kept
    assert not any(n in kept for n in ("gen-1.npz", "gen-2", "gen-5.npz"))
    # per-epoch result reports are bounded by the same window
    assert "result-w0-8.json" in kept and "result-w0-2.json" not in kept
    assert coord.kv_get("ckpt/5") is None
    assert coord.kv_get("ckpt/6") is not None
    assert coord.kv_get("jax-coordinator/3") is None
    # idempotent / concurrency-safe: a second pruner is a no-op
    assert prune_generations(coord, str(tmp_path), upto_gen=8, keep=3) == 0


def test_elastic_resize_with_transformer():
    """The elastic machinery with the flagship ARCHITECTURE (TINY dims):
    GQA attention + RoPE + RMSNorm + SwiGLU params reshard across resizes
    with state preserved byte-for-byte and learning intact — the MLP
    tests prove the mechanism, this proves it on the model family the
    bench measures."""
    import dataclasses

    from edl_tpu.models import transformer as tfm

    cfg = dataclasses.replace(tfm.TINY, max_seq_len=32)
    params = tfm.init(jax.random.key(0), cfg)
    loss_fn = tfm.make_loss_fn(cfg)
    rng = np.random.default_rng(0)
    # a learnable synthetic language: next token = (token + 1) % vocab
    tokens = rng.integers(0, cfg.vocab_size, size=(512, 32)).astype(np.int32)
    targets = ((tokens + 1) % cfg.vocab_size).astype(np.int32)

    t = ElasticTrainer(loss_fn, params, optax.adam(1e-2),
                       spec=MeshSpec(dp=-1), initial_world_size=2)
    first = t.step((tokens[:64], targets[:64]))
    for i in range(10):
        lo = (i * 64) % 448
        t.step((tokens[lo:lo + 64], targets[lo:lo + 64]))
    loss_before = t.eval_loss((tokens[:128], targets[:128]))

    before = jax.tree.map(lambda a: np.asarray(a), t.state.params)
    t.resize(8)
    assert t.world_size == 8
    # reshard is exact: every parameter byte-identical across the resize
    after = jax.tree.map(lambda a: np.asarray(a), t.state.params)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: bool(np.array_equal(a, b)), before, after))

    for i in range(15):
        lo = (i * 64) % 448
        t.step((tokens[lo:lo + 64], targets[lo:lo + 64]))
    t.resize(4)
    for i in range(15):
        lo = (i * 64) % 448
        t.step((tokens[lo:lo + 64], targets[lo:lo + 64]))
    final = t.eval_loss((tokens[:128], targets[:128]))
    assert final < loss_before < first  # learned through both resizes


def test_eval_loss_matches_train_objective_and_survives_resize():
    """The eval path (round-3 verdict weak #6: compiled per mesh size,
    asserted by nothing): eval_loss computes the same objective as the
    train step WITHOUT touching params or optimizer state, agrees with a
    direct loss_fn evaluation, and recompiles correctly across a resize."""
    x, y = synthetic_classification()
    t = make_trainer(n0=2)
    batch = (x[:64], y[:64])

    before = jax.tree.map(np.asarray, t.state.params)
    ev = t.eval_loss(batch)
    direct = float(mlp.loss_fn(t.state.params, batch))
    assert ev == pytest.approx(direct, rel=1e-5)
    # eval mutated nothing: params bit-identical, step counter unmoved
    after = jax.tree.map(np.asarray, t.state.params)
    assert all(np.array_equal(a, b) for a, b in
               zip(jax.tree.leaves(before), jax.tree.leaves(after)))
    assert t.state.step == 0

    # train reduces the metric eval reports
    for i in range(30):
        t.step((x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16]))
    assert t.eval_loss(batch) < ev

    # resize: the eval fn is rebuilt for the new mesh and stays consistent
    t.resize(4)
    ev4 = t.eval_loss(batch)
    assert ev4 == pytest.approx(
        float(mlp.loss_fn(t.state.params, batch)), rel=1e-5)
    t.resize(1)
    assert t.eval_loss(batch) == pytest.approx(ev4, rel=1e-4)


def test_mid_world_generation_ordering_and_prune(tmp_path):
    """Mid-world generations (multihost.publish_mid_state): rank between
    their world's start generation and the next boundary, newest-mid wins
    after a crash, a clean teardown gen still beats every mid, and both
    the per-epoch keep-window and the global GC prune them."""
    from edl_tpu.runtime.multihost import ElasticWorld, prune_generations

    coord = PyCoordService()
    ew = ElasticWorld(coord, "w0")

    # world start: gen 3 published (cold or inherited)
    coord.kv_set("ckpt/3", b"gen-3.npz")
    assert ew.latest_state(99) == (3, "gen-3.npz")

    # in-world mids at steps 20/40/60: newest wins; keep-window prunes
    for step in (20, 40, 60):
        p = tmp_path / f"mid-3-{step}.npz"
        p.write_bytes(b"x")
        ew.publish_mid_state(3, step, lambda p=p: str(p))
    assert ew.latest_state(99) == (3, str(tmp_path / "mid-3-60.npz"))
    # keep=2: the step-20 mid (pointer AND file) is gone
    assert coord.kv_get("ckpt-mid/3/20") is None
    assert not (tmp_path / "mid-3-20.npz").exists()
    assert coord.kv_get("ckpt-mid/3/40") is not None

    # a clean teardown publishes gen 4 — it beats every mid of epoch 3
    coord.kv_set("ckpt/4", b"gen-4.npz")
    assert ew.latest_state(99) == (4, "gen-4.npz")
    # but an epoch bound below 4 still resolves the newest mid
    assert ew.latest_state(3) == (3, str(tmp_path / "mid-3-60.npz"))

    # global GC: mids age out with their epoch
    for gen in range(4, 9):
        coord.kv_set(f"ckpt/{gen}", f"gen-{gen}".encode())
    prune_generations(coord, str(tmp_path), upto_gen=8, keep=3)
    assert coord.kv_get("ckpt-mid/3/60") is None
    assert not (tmp_path / "mid-3-60.npz").exists()


def test_should_respawn_warm_predicate():
    """Warm-respawn pacing (review r4): after warm_delay on the warm path;
    plus the cold-bootstrap allowance when the live child was a cold spawn
    (its own jax import is still in flight at warm_delay)."""
    from edl_tpu.runtime.multihost import _should_respawn_warm

    assert not _should_respawn_warm(1.9, was_warm=True, warm_delay_s=2.0)
    assert _should_respawn_warm(2.0, was_warm=True, warm_delay_s=2.0)
    # cold child: the 2 s mark is mid-import — hold off
    assert not _should_respawn_warm(2.0, was_warm=False, warm_delay_s=2.0)
    assert not _should_respawn_warm(9.9, was_warm=False, warm_delay_s=2.0)
    assert _should_respawn_warm(10.0, was_warm=False, warm_delay_s=2.0)


# -- checkpoint integrity: corruption detection + fallback restore -----------


def _ckpt_with_steps(tmp_path, steps=(1, 2, 3)):
    import numpy as np

    ck = ElasticCheckpointer(tmp_path / "ickpt", max_to_keep=len(steps) + 1)
    for s in steps:
        ck.save(s, {"w": np.full(16, float(s), np.float32),
                    "step": np.asarray(s, np.int32)})
    return ck


def _largest_file(ck, step):
    files = [p for p in ck._step_dir(step).rglob("*") if p.is_file()]
    return max(files, key=lambda p: (p.stat().st_size, str(p)))


def _like():
    import numpy as np

    return {"w": np.zeros(16, np.float32), "step": np.asarray(0, np.int32)}


def test_restore_falls_back_on_flipped_bytes(tmp_path, caplog):
    """A bit-flipped newest step fails the integrity manifest; restore()
    transparently returns the previous verified step with a warning."""
    from edl_tpu.observability.collector import get_counters

    ck = _ckpt_with_steps(tmp_path)
    victim = _largest_file(ck, 3)
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))
    assert not ck.verify(3)
    assert ck.latest_verified_step() == 2

    before = get_counters().get("recoveries_completed",
                                type="corrupt_checkpoint")
    with caplog.at_level("WARNING"):
        out = ck.restore(_like())
    assert int(out["step"]) == 2
    assert float(out["w"][0]) == 2.0
    assert any("integrity" in r.message or "falling back" in r.message
               for r in caplog.records)
    assert get_counters().get("recoveries_completed",
                              type="corrupt_checkpoint") == before + 1
    ck.close()


def test_restore_falls_back_on_truncated_file(tmp_path):
    """A torn write (truncated file, the power-loss shape) is caught the
    same way — sizes are part of the manifest."""
    ck = _ckpt_with_steps(tmp_path)
    victim = _largest_file(ck, 3)
    data = victim.read_bytes()
    victim.write_bytes(data[: len(data) // 2])
    assert not ck.verify(3)
    out = ck.restore(_like())
    assert int(out["step"]) == 2
    ck.close()


def test_restore_explicit_step_also_falls_back(tmp_path):
    """Asking for a specific corrupted step still degrades gracefully to
    an older verified one instead of crashing the reform."""
    ck = _ckpt_with_steps(tmp_path)
    victim = _largest_file(ck, 2)
    victim.write_bytes(b"")
    out = ck.restore(_like(), step=2)
    assert int(out["step"]) == 1
    ck.close()


def test_restore_raises_when_every_step_corrupt(tmp_path):
    from edl_tpu.runtime.checkpoint import CheckpointCorruption

    ck = _ckpt_with_steps(tmp_path, steps=(1, 2))
    for s in (1, 2):
        _largest_file(ck, s).write_bytes(b"garbage")
    with pytest.raises(CheckpointCorruption):
        ck.restore(_like())
    ck.close()


def test_disk_full_save_degrades_and_recovers(tmp_path):
    """ENOSPC at the persist boundary: best_effort saves skip-and-log
    instead of crashing, and the first subsequent success is counted as
    the disk_full recovery transition."""
    import numpy as np

    from edl_tpu.observability.collector import get_counters

    ck = ElasticCheckpointer(tmp_path / "dfull")
    tree = {"w": np.ones(4, np.float32)}
    assert ck.save(1, tree)
    ck.inject_save_failures(2)
    before = get_counters().get("recoveries_completed", type="disk_full")
    assert ck.save(2, tree, best_effort=True) is False
    assert ck.save(3, tree, best_effort=True) is False
    # non-best-effort callers still see the raw error
    ck.inject_save_failures(1)
    with pytest.raises(OSError):
        ck.save(4, tree)
    assert ck.save(5, tree, best_effort=True) is True
    assert get_counters().get("recoveries_completed",
                              type="disk_full") == before + 1
    # the failed steps were never persisted; the good ones were
    assert sorted(ck._mgr.all_steps()) == [1, 5]
    out = ck.restore({"w": np.zeros(4, np.float32)})
    assert float(out["w"][0]) == 1.0
    ck.close()
