"""Manifest serde + CLI tests (reference example/examplejob.yaml shape,
cmd/edl/edl.go flag surface)."""

import io
import sys

import pytest

from edl_tpu.api.serde import (
    job_from_dict, job_from_yaml, job_to_dict, job_to_yaml, load_job_file,
)
from edl_tpu.api.types import RESOURCE_TPU
from edl_tpu import cli

EXAMPLE_YAML = """
apiVersion: edl.tpu/v1
kind: TrainingJob
metadata:
  name: example
  namespace: default
spec:
  fault_tolerant: true
  passes: 2
  trainer:
    entrypoint: "python train.py"
    workspace: "/workspace"
    min-instance: 2
    max-instance: 10
    resources:
      requests:
        cpu: "4"
        memory: "8G"
      limits:
        cpu: "4"
        memory: "8G"
        google.com/tpu: "4"
    topology: 2x2
  pserver:
    min-instance: 0
    max-instance: 0
  master:
    etcd_endpoint: ""
"""


class TestSerde:
    def test_round_trip(self):
        job = job_from_yaml(EXAMPLE_YAML)
        assert job.name == "example"
        assert job.spec.fault_tolerant
        assert job.spec.trainer.min_instance == 2
        assert job.spec.trainer.max_instance == 10
        assert job.elastic()
        assert job.tpu_chips_per_trainer() == 4  # topology 2x2
        assert str(job.spec.trainer.topology) == "2x2"
        assert job.spec.trainer.resources.limits[RESOURCE_TPU].value() == 4

        job2 = job_from_dict(job_to_dict(job))
        assert job2.spec.trainer.min_instance == 2
        assert str(job2.spec.trainer.topology) == "2x2"
        assert job_to_yaml(job2)  # serializes cleanly

    def test_kebab_and_snake_equivalent(self):
        a = job_from_dict({"metadata": {"name": "j"},
                           "spec": {"trainer": {"min-instance": 3,
                                                "max-instance": 5}}})
        b = job_from_dict({"metadata": {"name": "j"},
                           "spec": {"trainer": {"min_instance": 3,
                                                "max_instance": 5}}})
        assert (a.spec.trainer.min_instance, a.spec.trainer.max_instance) == \
               (b.spec.trainer.min_instance, b.spec.trainer.max_instance)

    def test_wrong_kind_rejected(self):
        with pytest.raises(ValueError):
            job_from_dict({"kind": "Deployment"})

    def test_load_file(self, tmp_path):
        p = tmp_path / "job.yaml"
        p.write_text(EXAMPLE_YAML)
        assert load_job_file(str(p)).name == "example"


class TestCli:
    def test_validate_ok(self, tmp_path, capsys):
        p = tmp_path / "job.yaml"
        p.write_text(EXAMPLE_YAML)
        assert cli.main(["validate", str(p)]) == 0
        out = capsys.readouterr().out
        assert "example" in out and "fault_tolerant: true" in out

    def test_validate_rejects_elastic_without_ft(self, tmp_path, capsys):
        # elastic requires fault_tolerant (reference pkg/jobparser.go:66-68)
        bad = EXAMPLE_YAML.replace("fault_tolerant: true",
                                   "fault_tolerant: false")
        p = tmp_path / "bad.yaml"
        p.write_text(bad)
        assert cli.main(["validate", str(p)]) == 1
        assert "INVALID" in capsys.readouterr().err

    def test_submit_and_delete_fake(self, tmp_path):
        p = tmp_path / "job.yaml"
        p.write_text(EXAMPLE_YAML)
        assert cli.main(["submit", "--fake", str(p)]) == 0
        assert cli.main(["delete", "--fake", "example"]) == 0

    def test_collector_fake(self, capsys):
        assert cli.main(["collector", "--fake", "--interval", "0",
                         "--samples", "2"]) == 0
        out = capsys.readouterr().out
        assert out.count("\n") == 3  # header + 2 samples

    def test_parser_flags_match_reference(self):
        p = cli.build_parser()
        args = p.parse_args(["controller", "--fake",
                             "--max-load-desired", "0.9"])
        assert args.max_load_desired == 0.9
        assert args.loop_seconds == 5.0  # reference pkg/autoscaler.go:31


def test_undeclared_kebab_key_warns_loudly(caplog):
    """A kebab spelling of a real field that is NOT a declared alias
    (e.g. 'etcd-endpoint') would be silently dropped on the submit path
    (and apiserver-pruned on the kubectl path) — the parser must warn so
    the degradation surfaces instead of the job quietly using defaults
    (advisor r4, serde.py)."""
    import logging

    from edl_tpu.api import serde

    doc = {
        "apiVersion": serde.API_VERSION,
        "kind": "TrainingJob",
        "metadata": {"name": "j"},
        "spec": {
            "trainer": {"min-instance": 1, "max-instance": 2},
            "master": {"etcd-endpoint": "http://coord:8080"},
        },
    }
    with caplog.at_level(logging.WARNING, logger="edl_tpu.serde"):
        job = serde.job_from_dict(doc)
    # declared aliases still work silently
    assert job.spec.trainer.min_instance == 1
    assert job.spec.trainer.max_instance == 2
    # the undeclared kebab key is ignored BUT warned about
    assert job.spec.master.etcd_endpoint == ""
    assert any("etcd-endpoint" in r.message and "etcd_endpoint" in r.message
               for r in caplog.records)
