"""The full stack meets in one running system.

Round-4 verdict's top gap: the control plane never executed the pod
entrypoint it ships.  Here it does — the same wiring a real deployment
uses, with every piece live:

  kubectl-apply a TrainingJob CR (stub apiserver)
    → TrainingJobSyncLoop diffs it in         (controller/sync.py)
    → Controller materializes the job          (controller/controller.py)
    → FakeCluster creates coordinator + trainer pods
    → ProcessKubelet execs each pod's MANIFEST command
      (`python -m edl_tpu.runtime.launcher start_trainer`,
       `python -m edl_tpu.coord.server` — compiled by
       controller/jobparser.py, the commands the shipped YAML runs;
       reference parity: pkg/jobparser.go:124 exec'd by
       docker/paddle_k8s:119-141, created by pkg/controller.go:134-147)
    → launcher waits for the coordinator, joins membership, execs the
      user entrypoint (supervised multihost worker)
    → workers form a 2-world and train from the shared task queue
    → the autoscaler grows the job 2 → 4 (world reforms larger)
    → kill -9 one pod's process group (the Job controller replaces the
      pod, the replacement rejoins a reformed 4-world)
    → the queue drains exactly once, workers exit 0, pods Succeed,
      and the CR status shows the lifecycle throughout.

The autoscaler is started only after the initial 2-world forms —
otherwise it grows parallelism to 4 during the workers' jax bootstrap
and the first world simply forms at 4, which proves less (the grow
must reform a LIVE world).

CPU-only: the worker processes run jax on CPU — the same supervised
world code path a TPU pod runs (tests/test_multihost.py proves the
device-backed side separately).
"""

from __future__ import annotations

import glob
import os
import re
import socket
import time

import pytest

from edl_tpu.cluster.exec_kubelet import ProcessKubelet
from edl_tpu.cluster.fake import FakeCluster
from edl_tpu.controller.controller import Controller
from edl_tpu.controller.sync import TrainingJobSyncLoop

pytestmark = [pytest.mark.slow, pytest.mark.timeout_s(840)]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def e2e_cr(name: str, port: int, ckpt_dir: str, lo=2, hi=4) -> dict:
    """The manifest a user would kubectl-apply.  The entrypoint is the
    supervised elastic worker, addressed through the env contract the
    launcher exports (EDL_COORD_HOST/PORT, EDL_WORKER_NAME — role of the
    PADDLE_INIT_* contract, reference pkg/jobparser.go:263-311)."""
    entry = (
        "python -m edl_tpu.runtime.multihost_worker"
        " --coord $EDL_COORD_HOST:$EDL_COORD_PORT"
        " --name $EDL_WORKER_NAME"
        f" --ckpt-dir {ckpt_dir}"
        " --min-members $EDL_TRAINER_MIN"
        " --settle-s 0.3 --heartbeat-timeout-s 5 --model mlp"
    )
    return {
        "apiVersion": "edl.tpu/v1",
        "kind": "TrainingJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "image": "edl-tpu-job:latest",
            "fault_tolerant": True,
            "port": port,
            "trainer": {
                "entrypoint": entry,
                "min_instance": lo,
                "max_instance": hi,
                "env": {"EDL_MH_CKPT_EVERY": "25"},
                "resources": {
                    "requests": {"cpu": "500m", "memory": "256Mi"},
                    "limits": {"cpu": "1", "memory": "512Mi",
                               "google.com/tpu": "1"},
                },
            },
        },
    }


@pytest.mark.needs_multiprocess_collectives
def test_cr_to_supervised_world_end_to_end(kube, tmp_path):
    k8s_mod, state = kube
    cr_store = k8s_mod.K8sCluster(kubeconfig="ignored")

    fake = FakeCluster()
    fake.add_node("host0", cpu_milli=16000, memory_mega=16000, tpu_chips=8)

    controller = Controller(fake, autoscaler_loop_seconds=0.3,
                            updater_convert_seconds=0.5,
                            updater_confirm_seconds=0.2)
    sync = TrainingJobSyncLoop(cr_store, controller, poll_seconds=0.2)

    work = str(tmp_path)
    kubelet = ProcessKubelet(fake, work, env_overrides={
        # harness knobs only: CPU backend, test sizing, free health port
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PALLAS_AXON_POOL_IPS": "",
        "EDL_MH_DIE_WITH_PARENT": "1",
        "EDL_MH_EXAMPLES": str(192 * 1024),
        "EDL_MH_SHARDS": "96",
        "EDL_MH_BATCH": "32",
        "EDL_MH_STEP_SLEEP": "0.04",
        "EDL_HEALTH_PORT": "0",
        "EDL_COORD_MEMBER_TTL_MS": "3000",
        "EDL_COORD_TASK_TIMEOUT_MS": "4000",
        # 1-core host: concurrent warm-spawn preloads contend with the
        # critical path (see multihost_worker warm_spawn rationale)
        "EDL_MH_WARM_SPAWN": "0",
    })

    port = free_port()
    name = "e2e"
    phases_seen: set[str] = set()
    coord_stats = None

    def cr_status() -> dict:
        cr = state.custom_objects.get(
            ("edl.tpu", "default", "trainingjobs", name))
        return (cr or {}).get("status") or {}

    def trainer_logs() -> list[str]:
        return sorted(glob.glob(
            os.path.join(work, "logs", f"{name}-trainer-*.log")))

    def logged_worlds() -> list[tuple[int, int, int]]:
        """(epoch, world, step) from every trainer log ever written —
        scanning files, not live pods: a drained pod's evidence counts."""
        entries = []
        for path in trainer_logs():
            for m in re.finditer(
                    r"entering world epoch=(\d+) world=(\d+) at step=(\d+)",
                    open(path).read()):
                entries.append((int(m.group(1)), int(m.group(2)),
                                int(m.group(3))))
        entries.sort()
        return entries

    def poll_coord():
        nonlocal coord_stats
        try:
            from edl_tpu.coord.client import CoordClient

            c = CoordClient("127.0.0.1", port, timeout=2.0)
            coord_stats = c.stats()
            c.close()
        except OSError:
            pass

    def wait_until(cond, what: str, timeout_s: float):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            phases_seen.add(cr_status().get("phase", ""))
            poll_coord()
            if cond():
                return
            time.sleep(0.25)
        raise TimeoutError(
            f"never reached: {what}; phases={phases_seen}; "
            f"worlds={logged_worlds()}; live={kubelet.live_pods()}")

    sync.start()  # the autoscaler starts LATER (see module docstring)
    try:
        # kubectl apply -f e2e.yaml
        cr_store.create_training_job_cr(e2e_cr(name, port,
                                               os.path.join(work, "ckpt")))

        # the sync loop submitted it; the controller materialized
        # coordinator + 2 trainer pods; the kubelet exec'd the shipped
        # commands; a 2-world formed and started training
        wait_until(lambda: any(w == 2 for _e, w, _s in logged_worlds()),
                   "initial 2-world forms", 180)
        wait_until(lambda: any("] step " in open(p).read()
                               for p in trainer_logs()),
                   "training underway", 60)

        # NOW let the autoscaler see the elastic job: it grows 2 → 4 on
        # the idle cluster and the LIVE world reforms at 4
        controller.start()
        wait_until(lambda: any(w == 4 for _e, w, _s in logged_worlds()),
                   "world grows to 4", 180)

        # kill -9 one trainer's process group mid-training: a dead
        # trainer is a non-event (reference docker/paddle_k8s:119-141) —
        # the Job controller replaces the pod and the replacement's
        # worker rejoins a reformed 4-world
        live = [p for p in kubelet.live_pods() if "-trainer-" in p]
        assert live, "job drained before the kill phase — enlarge workload"
        before_logs = set(trainer_logs())
        victim = live[0]
        assert kubelet.signal_pod(victim)
        wait_until(lambda: victim not in kubelet.live_pods(),
                   "victim process dies", 30)

        def replaced_and_reformed():
            for p in set(trainer_logs()) - before_logs:
                if re.search(r"entering world epoch=\d+ world=4",
                             open(p).read()):
                    return True
            return False

        wait_until(replaced_and_reformed,
                   "pod replaced and 4-world reforms", 240)

        # drain: the queue empties exactly once, workers exit 0, pods
        # Succeed, the CR records it
        wait_until(lambda: cr_status().get("phase") == "Succeeded",
                   "CR status Succeeded", 600)

        # exactly-once accounting (read live while the coordinator ran)
        assert coord_stats is not None
        assert coord_stats.done == 96, coord_stats
        assert coord_stats.todo == 0 and coord_stats.dropped == 0

        # every world entered at a non-decreasing step: each reform
        # resumed from persisted state, never cold-started (continuity)
        worlds = logged_worlds()
        assert {w for _e, w, _s in worlds} >= {2, 4}
        steps = [s for _e, _w, s in worlds]
        assert steps == sorted(steps), worlds

        # the CR surfaced the lifecycle (reference printer columns)
        assert "Running" in phases_seen
        assert "Succeeded" in phases_seen

        # kubectl delete tj e2e → full teardown, coordinator included
        cr_store.delete_training_job_cr(name)
        wait_until(lambda: controller.jobs() == [] and
                   not kubelet.live_pods(), "full teardown", 60)
    finally:
        sync.stop()
        controller.stop()
        kubelet.stop()


def test_static_non_ft_job_runs_through_kubelet(tmp_path):
    """A NON-fault-tolerant job through the same deployed path: the
    jobparser emits `launcher start_static_trainer`, the kubelet execs
    it with the job's peer set, every pod computes its rank from the
    sorted pod list, runs the entry, and the job Succeeds (role of the
    reference's start_trainer v2, docker/paddle_k8s:143-226)."""
    from edl_tpu.api.serde import job_from_dict
    from edl_tpu.api.types import JobPhase
    from edl_tpu.controller.controller import Controller

    fake = FakeCluster()
    fake.add_node("host0", cpu_milli=16000, memory_mega=16000, tpu_chips=8)
    controller = Controller(fake, updater_convert_seconds=0.3,
                            updater_confirm_seconds=0.2)
    work = str(tmp_path)
    kubelet = ProcessKubelet(fake, work, env_overrides={
        "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
    })
    ranks = os.path.join(work, "ranks")
    os.makedirs(ranks, exist_ok=True)
    job = job_from_dict({
        "apiVersion": "edl.tpu/v1", "kind": "TrainingJob",
        "metadata": {"name": "static"},
        "spec": {
            "image": "edl-tpu-job:latest",
            "fault_tolerant": False,
            "trainer": {
                # each pod records its rank/world and peer list, then
                # exits 0 — the work-queue Job completes
                "entrypoint": (
                    f'echo "$EDL_TRAINER_ID/$EDL_TRAINERS '
                    f'$EDL_TRAINER_ADDRESSES" '
                    f'> {ranks}/$EDL_POD_NAME && sleep 0.5'),
                "min_instance": 3, "max_instance": 3,
                "resources": {"requests": {"cpu": "500m",
                                           "memory": "256Mi"},
                              "limits": {"cpu": "1", "memory": "512Mi",
                                         "google.com/tpu": "1"}},
            },
        },
    })
    try:
        controller.submit(job)
        updater = controller.get_updater(job)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if updater.job.status.phase in (JobPhase.SUCCEEDED,
                                            JobPhase.FAILED):
                break
            time.sleep(0.25)
        assert updater.job.status.phase == JobPhase.SUCCEEDED, (
            updater.job.status)
        files = sorted(os.listdir(ranks))
        assert len(files) == 3, files
        seen = {}
        for f in files:
            rank_world, peers = open(os.path.join(ranks, f)
                                     ).read().split(" ", 1)
            rank, world = rank_world.split("/")
            seen[f] = (int(rank), int(world), peers.strip())
        # ranks are exactly 0..2, every pod agrees on world and peers
        assert sorted(r for r, _w, _p in seen.values()) == [0, 1, 2]
        assert {w for _r, w, _p in seen.values()} == {3}
        assert len({p for _r, _w, p in seen.values()}) == 1
        # rank = index of my pod in the shared sorted peer list
        peers = next(iter(seen.values()))[2].split(",")
        for f, (rank, _w, _p) in seen.items():
            assert peers[rank] == f
    finally:
        controller.stop()
        kubelet.stop()


_SOAK_S = float(os.environ.get("EDL_KUBELET_SOAK_S", "600"))


@pytest.mark.needs_multiprocess_collectives
@pytest.mark.timeout_s(_SOAK_S + 480)
def test_kubelet_endurance_soak(kube, tmp_path):
    """Endurance churn under the deployed exec path (VERDICT r5 #9's
    kubelet half): repeated trainer-pod kills and autoscaler-driven
    resizes on a cadence for ``EDL_KUBELET_SOAK_S`` (default 600 s),
    asserting at the end

    * the harness process leaks no FDs per churn cycle,
    * the coordinator pod's RSS is bounded (no per-reform growth),
    * the checkpoint dir is bounded (generation GC kept up),
    * zero lost generations: every world entered at a non-decreasing
      step — each reform resumed from persisted state,
    * the workers' goodput ledgers still CONSERVE after the whole
      schedule (the `goodput_ledger conserves=1` line each supervisor
      prints at graceful teardown).
    """
    import random
    import signal as _signal

    k8s_mod, state = kube
    cr_store = k8s_mod.K8sCluster(kubeconfig="ignored")
    fake = FakeCluster()
    fake.add_node("host0", cpu_milli=16000, memory_mega=16000, tpu_chips=8)
    controller = Controller(fake, autoscaler_loop_seconds=0.3,
                            updater_convert_seconds=0.5,
                            updater_confirm_seconds=0.2)
    sync = TrainingJobSyncLoop(cr_store, controller, poll_seconds=0.2)
    work = str(tmp_path)
    kubelet = ProcessKubelet(fake, work, term_grace_s=25.0, env_overrides={
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PALLAS_AXON_POOL_IPS": "",
        "EDL_MH_DIE_WITH_PARENT": "1",
        # sized to outlast the window: the soak ends by CR delete, not
        # by drain (a drained queue would idle the churn's second half).
        # 1M rows ≈ 64 MB per pod (every worker derives the same split
        # in-process) and 32k global steps × 0.08 s ≫ the default 600 s
        # window even split over 4 workers
        "EDL_MH_EXAMPLES": str(1024 * 1024),
        "EDL_MH_SHARDS": "2048",
        "EDL_MH_BATCH": "32",
        "EDL_MH_STEP_SLEEP": "0.08",
        "EDL_HEALTH_PORT": "0",
        "EDL_COORD_MEMBER_TTL_MS": "3000",
        "EDL_COORD_TASK_TIMEOUT_MS": "4000",
        "EDL_MH_WARM_SPAWN": "0",
    })
    port = free_port()
    name = "soak"
    ckpt_dir = os.path.join(work, "ckpt")

    def trainer_logs() -> list[str]:
        return sorted(glob.glob(
            os.path.join(work, "logs", f"{name}-trainer-*.log")))

    def log_text() -> str:
        return "".join(open(p).read() for p in trainer_logs())

    def logged_worlds() -> list[tuple[int, int, int]]:
        entries = []
        for path in trainer_logs():
            for m in re.finditer(
                    r"entering world epoch=(\d+) world=(\d+) at step=(\d+)",
                    open(path).read()):
                entries.append((int(m.group(1)), int(m.group(2)),
                                int(m.group(3))))
        entries.sort()
        return entries

    def open_fds() -> int:
        return len(os.listdir("/proc/self/fd"))

    def rss_kb(pid: int) -> int:
        try:
            with open(f"/proc/{pid}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    rng = random.Random(20260804)
    sync.start()
    try:
        cr_store.create_training_job_cr(e2e_cr(name, port, ckpt_dir,
                                               lo=2, hi=4))
        deadline = time.monotonic() + 240
        while not any(w >= 2 for _e, w, _s in logged_worlds()):
            assert time.monotonic() < deadline, "initial world never formed"
            time.sleep(0.5)
        controller.start()  # autoscaler live: idle capacity → grow to 4

        # steady-state baselines AFTER bring-up (compile, pod spawns)
        fds_base = open_fds()
        coord_pod = [p for p in kubelet.live_pods()
                     if "-coordinator-" in p]
        coord_pid = kubelet.pid_of(coord_pod[0]) if coord_pod else None
        rss_base = rss_kb(coord_pid) if coord_pid else 0

        t_end = time.monotonic() + _SOAK_S
        kill_every = min(max(_SOAK_S / 8.0, 25.0), 90.0)
        toggle_every = min(max(_SOAK_S / 6.0, 35.0), 120.0)
        next_kill = time.monotonic() + kill_every
        next_toggle = time.monotonic() + toggle_every
        contended = False
        kills = toggles = 0
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now >= next_kill:
                live = [p for p in kubelet.live_pods() if "-trainer-" in p]
                if live:  # kill → Job controller replaces → world reforms
                    kubelet.signal_pod(rng.choice(live), _signal.SIGKILL)
                    kills += 1
                next_kill = now + kill_every
            if now >= next_toggle:
                # toggle a competing workload: the autoscaler shrinks
                # the job under contention, grows it back on release —
                # the resize half of the churn
                if contended:
                    for i in range(4):
                        fake.remove_system_pod(f"burst-{i}")
                else:
                    for i in range(4):
                        fake.add_system_pod(f"burst-{i}", "host0",
                                            cpu_request_milli=2000,
                                            memory_request_mega=100)
                contended = not contended
                toggles += 1
                next_toggle = now + toggle_every
            time.sleep(0.5)
        assert kills >= 2 and toggles >= 1, (kills, toggles)

        # bounded resources at the END of the window, while still live
        assert open_fds() <= fds_base + 32, (fds_base, open_fds())
        if coord_pid and rss_kb(coord_pid) > 0:
            rss_end = rss_kb(coord_pid)
            assert rss_end <= rss_base * 3 + 100_000, (rss_base, rss_end)
        try:
            ents = os.listdir(ckpt_dir)
        except OSError:
            ents = []
        # generation GC kept up: gens/mids/results bounded, not one per
        # membership change accumulated across the whole churn window
        per_gen = [e for e in ents if e.startswith(("gen-", "mid-",
                                                    "result-"))]
        assert len(per_gen) <= 40, sorted(per_gen)

        # graceful end: delete the CR; SIGTERMed supervisors leave,
        # publish their final generation, and print the goodput line
        cr_store.delete_training_job_cr(name)
        deadline = time.monotonic() + 180
        while controller.jobs() or kubelet.live_pods():
            assert time.monotonic() < deadline, kubelet.live_pods()
            time.sleep(0.5)

        # zero lost generations: every world ever entered resumed at a
        # step >= the one before it (sorted by epoch) — a reform that
        # cold-started or rewound would break the ordering
        worlds = logged_worlds()
        assert len(worlds) >= 3, worlds
        steps = [s for _e, _w, s in worlds]
        assert steps == sorted(steps), worlds
        assert any(w == 4 for _e, w, _s in worlds), worlds  # resizes ran

        # the ledger still conserves after the whole fault schedule
        lines = re.findall(r"goodput_ledger .*", log_text())
        assert lines, "no supervisor printed its goodput ledger"
        for line in lines:
            assert "conserves=1" in line, line
            m = re.search(r"fraction=([0-9.]+)", line)
            assert m and 0.0 <= float(m.group(1)) <= 1.0, line
    finally:
        sync.stop()
        controller.stop()
        kubelet.stop()


@pytest.mark.needs_multiprocess_collectives
def test_coordinator_pod_respawn_preserves_state(tmp_path):
    """kill -9 the coordinator POD mid-training: the ReplicaSet analogue
    respawns it on the same state volume (PVC semantics), the workers
    redial, and the job still drains exactly-once (role of the etcd
    sidecar's persistence, reference pkg/jobparser.go:167-184 — here
    CI-locked, not just demonstrated)."""
    import signal

    from edl_tpu.api.serde import job_from_dict
    from edl_tpu.api.types import JobPhase
    from edl_tpu.controller.controller import Controller
    from edl_tpu.coord.client import CoordClient, CoordError

    fake = FakeCluster()
    fake.add_node("host0", cpu_milli=16000, memory_mega=16000, tpu_chips=8)
    controller = Controller(fake, updater_convert_seconds=0.3,
                            updater_confirm_seconds=0.2)
    work = str(tmp_path)
    kubelet = ProcessKubelet(fake, work, env_overrides={
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PALLAS_AXON_POOL_IPS": "",
        "EDL_MH_DIE_WITH_PARENT": "1",
        "EDL_MH_EXAMPLES": str(32 * 1024),
        "EDL_MH_SHARDS": "64",
        "EDL_MH_BATCH": "32",
        "EDL_MH_STEP_SLEEP": "0.04",
        "EDL_HEALTH_PORT": "0",
        "EDL_COORD_MEMBER_TTL_MS": "3000",
        "EDL_COORD_TASK_TIMEOUT_MS": "4000",
        "EDL_MH_WARM_SPAWN": "0",
    })
    port = free_port()
    # the SAME manifest shape as the headline e2e (reuse, not a third
    # hand-built copy); min==max 2 keeps it a fixed-size FT job
    job = job_from_dict(e2e_cr("ckill", port,
                               os.path.join(work, "ckpt"), lo=2, hi=2))

    def tlog_text():
        return "".join(open(p).read() for p in glob.glob(
            os.path.join(work, "logs", "ckill-trainer-*.log")))

    def raw_stats(timeout_s=10.0):
        """One UNFILTERED snapshot (retrying only connection setup) —
        the monotonicity assertion below must see whatever the live
        coordinator actually reports, not a max-filtered view."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                c = CoordClient("127.0.0.1", port, timeout=2.0)
                s = c.stats()
                c.close()
                return s
            except (OSError, CoordError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)

    try:
        controller.submit(job)
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if "step 20 " in tlog_text():
                break
            time.sleep(0.3)
        assert "step 20 " in tlog_text(), "training never started"

        # record real progress BEFORE the kill, then kill -9 the
        # coordinator pod's process group
        deadline = time.monotonic() + 180
        while raw_stats().done == 0:
            assert time.monotonic() < deadline, "no shard ever completed"
            time.sleep(0.3)
        done_before = raw_stats().done
        assert done_before > 0
        coord_pod = [p for p in kubelet.live_pods()
                     if "-coordinator-" in p][0]
        assert kubelet.signal_pod(coord_pod, signal.SIGKILL)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            live = [p for p in kubelet.live_pods() if "-coordinator-" in p]
            if live and live != [coord_pod]:
                break
            time.sleep(0.25)
        live = [p for p in kubelet.live_pods() if "-coordinator-" in p]
        assert live and live != [coord_pod], "coordinator never respawned"

        # the respawned coordinator restored the queue from the job
        # volume: the UNFILTERED first reachable snapshot must show the
        # pre-kill completions — a coordinator that lost its state would
        # report done back at 0 and re-dispatch finished work
        after = raw_stats(timeout_s=30.0)
        assert after.done >= done_before, (after, done_before)

        # wait for the FULL drain while the coordinator is guaranteed
        # alive (workers only exit after the queue is done, so observing
        # done==64 here cannot race the post-success teardown), THEN for
        # the phase machine to record the success
        updater = controller.get_updater(job)
        final = after
        deadline = time.monotonic() + 420
        while final.done < 64 and time.monotonic() < deadline:
            time.sleep(0.3)
            final = raw_stats(timeout_s=30.0)
        assert final.done == 64 and final.dropped == 0, final
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if updater.job.status.phase in (JobPhase.SUCCEEDED,
                                            JobPhase.FAILED):
                break
            time.sleep(0.3)
        assert updater.job.status.phase == JobPhase.SUCCEEDED, (
            updater.job.status)
    finally:
        controller.stop()
        kubelet.stop()
