"""Quantity parsing/scaling parity with k8s resource.Quantity
(assertions from reference pkg/autoscaler_internal_test.go:96-101 et al.)."""

from fractions import Fraction

import pytest

from edl_tpu.api.quantity import MEGA, MILLI, Quantity


def test_trainer_request_limit_units():
    # reference autoscaler_internal_test.go:96-101
    assert Quantity("1k").milli_value() == 1_000_000
    assert Quantity("100Mi").scaled_value(MEGA) == 105
    assert Quantity("10").value() == 10


def test_plain_and_milli():
    assert Quantity("1").milli_value() == 1000
    assert Quantity("250m").milli_value() == 250
    assert Quantity("1.5").milli_value() == 1500
    assert Quantity("2500m").value() == 3  # rounds up like k8s Value()


def test_binary_suffixes():
    assert Quantity("1Ki").exact == 1024
    assert Quantity("10Mi").scaled_value(MEGA) == 11  # 10.48576 MB rounds up
    assert Quantity("1Gi").scaled_value(MEGA) == 1074


def test_decimal_suffixes_and_exponent():
    assert Quantity("1M").exact == 10**6
    assert Quantity("2e3").exact == 2000
    assert Quantity("1E").exact == 10**18


def test_small_quantities():
    assert Quantity("100n").exact == Fraction(100, 10**9)
    assert Quantity("1u").milli_value() == 1  # rounds up to one milli


def test_arithmetic_and_comparison():
    assert Quantity("1") + Quantity("500m") == Quantity("1500m")
    assert Quantity("2") - Quantity("1") == Quantity("1")
    assert Quantity("1") < Quantity("10")
    assert Quantity("1024") > Quantity("1Ki") - Quantity("1")
    assert Quantity("1Ki") == Quantity("1024")
    assert sorted([Quantity("10"), Quantity("1"), Quantity("2")])[0] == Quantity("1")


def test_zero_and_bool():
    assert Quantity("0").is_zero()
    assert not Quantity("0")
    assert Quantity("1m")


def test_negative():
    assert Quantity("-1500m").value() == -2  # rounds away from zero
    assert Quantity("-1").milli_value() == -1000


def test_invalid():
    for bad in ["", "abc", "1x", "--1", "1.2.3"]:
        with pytest.raises(ValueError):
            Quantity(bad)


def test_str_roundtrip():
    for s in ["1", "250m", "1024", "1500m"]:
        assert Quantity(str(Quantity(s))) == Quantity(s)
